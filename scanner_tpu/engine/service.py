"""Distributed master/worker services.

Capability parity: reference scanner/engine/master.{h,cpp} +
worker.{h,cpp} + rpc.proto — dynamic task distribution (NextWork/
FinishedWork), worker liveness pinger with strike-out removal, per-task
timeout, job blacklisting after repeated task failures, elastic worker join,
client watchdog, progress reporting.

Differences from the reference, chosen deliberately:
  * Fully pull-based: the master never dials workers.  Workers heartbeat and
    pull tasks; a joining worker starts pulling immediately (elastic join
    without the reference's unstarted_workers dance, master.cpp:514-560).
  * The job spec travels as one cloudpickle blob (graph + resolved
    PerfParams), so there are no op/kernel registration RPCs
    (ListLoadedOps etc., worker.cpp:882-937) — the graph is self-contained.
  * Bulk data never crosses RPC: workers read/write shared storage, master
    owns all metadata writes — same storage-mediated data plane as the
    reference (SURVEY §2.7).
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

import cloudpickle

from ..common import CacheMode, JobException, PerfParams, ScannerException
from ..storage import Database, make_storage
from ..storage import metadata as md
from ..storage.items import seal_blob
from ..util import clocksync as _clocksync
from ..util import coststats as _coststats
from ..util import faults as _faults
from ..util import health as _health
from ..util import memstats as _memstats
from ..util import metrics as _mx
from ..util import tracing as _tracing
from ..util.log import get_logger
from ..util.metrics import MetricsServer, merge_snapshots
from ..util.profiler import Profiler
from . import controller as _controller
from . import framecache as _framecache
from . import gang as _gang
from . import journal as _journal
from . import rpc
from . import shardmap as _shardmap
from .evaluate import TaskEvaluator
from .executor import _M_TASK_LATENCY, LocalExecutor, TaskItem

PING_INTERVAL = 1.0          # worker heartbeat period
# per-call deadline for heartbeat/ping RPCs.  Deliberately ~2x the ping
# period instead of the 30s client default: a HUNG (accepting but not
# answering) master would otherwise pin the worker's heartbeat thread
# for 30s per call — long past WORKER_STALE_AFTER — and a healthy
# worker would be removed as stale purely because its liveness reports
# were stuck behind a slow peer.
PING_TIMEOUT = 2 * PING_INTERVAL
WORKER_STALE_AFTER = 6.0     # master: no heartbeat -> worker removed
MAX_TASK_FAILURES = 3        # reference master.cpp:2131 blacklist threshold
# transient (storage/RPC) task failures requeue WITHOUT counting a
# blacklist strike — a flaky dependency must not blacklist a healthy
# job.  But "transient" failures that never stop are not transient:
# past this many per task, they start counting strikes like any other
# failure so a dead storage backend still terminates the bulk.
MAX_TRANSIENT_FAILURES = 25
MASTER_SERVICE = "scanner.Master"
WORKER_SERVICE = "scanner.Worker"

# The wire contract of every registered RPC handler (both services):
# the client-side deadline a caller should use, and whether the handler
# is IDEMPOTENT — safe to blind-retry because a duplicate delivery
# cannot double-apply (non-idempotent methods mutate queue/strike/
# profile state and must only ride the UNAVAILABLE-only retry path,
# where the request provably never reached the server).  scanner-check
# SC307 enforces that this table and the registered handler dicts stay
# in sync; new handlers must be classified here to land.  Every
# idempotent=False entry additionally routes through the master's
# generation-fence wrapper (`Master._fenced`) so a superseded master
# cannot accept mutations — scanner-check SC312 keeps the table and
# the wrapped registrations in sync both directions.
# (NewJob stays classified non-idempotent: the admission-token dedupe
# makes a RETRY safe end-to-end, but only when the caller re-presents
# the token — the blind transport-level retry this flag governs does.)
RPC_CONTRACTS = {
    "Ping":             {"timeout_s": PING_TIMEOUT, "idempotent": True},
    "RegisterWorker":   {"timeout_s": 30.0, "idempotent": False},
    "UnregisterWorker": {"timeout_s": PING_TIMEOUT, "idempotent": True},
    "Heartbeat":        {"timeout_s": PING_TIMEOUT, "idempotent": True},
    "NewJob":           {"timeout_s": 120.0, "idempotent": False},
    "GetJob":           {"timeout_s": 30.0, "idempotent": True},
    "NextWork":         {"timeout_s": 30.0, "idempotent": False},
    "StartedWork":      {"timeout_s": 30.0, "idempotent": False},
    "EvalDone":         {"timeout_s": 30.0, "idempotent": True},
    "FinishedWork":     {"timeout_s": 30.0, "idempotent": False},
    # coalesced completion path (engine/shardmap.py): many FinishedWork
    # payloads in one RPC, one journal group-commit — the worker-side
    # batcher a per-shard fan-out needs to keep RPC volume flat
    "FinishedWorkBatch": {"timeout_s": 30.0, "idempotent": False},
    "FailedWork":       {"timeout_s": 30.0, "idempotent": False},
    "GetJobStatus":     {"timeout_s": 30.0, "idempotent": True},
    # the versioned shard map (engine/shardmap.py): served by every
    # shard so clients/workers can resolve routing from any of them
    "GetShardMap":      {"timeout_s": 30.0, "idempotent": True},
    "GetMetrics":       {"timeout_s": 30.0, "idempotent": True},
    "GetHealth":        {"timeout_s": 30.0, "idempotent": True},
    "PokeWatchdog":     {"timeout_s": 30.0, "idempotent": True},
    "PostProfile":      {"timeout_s": 30.0, "idempotent": False},
    "GetProfiles":      {"timeout_s": 30.0, "idempotent": True},
    "ShipSpans":        {"timeout_s": 30.0, "idempotent": False},
    "GetTrace":         {"timeout_s": 30.0, "idempotent": True},
    "ShipMemoryReport": {"timeout_s": 30.0, "idempotent": False},
    "GetMemoryReport":  {"timeout_s": 30.0, "idempotent": True},
    "GetCompileLedger": {"timeout_s": 30.0, "idempotent": True},
    # gang control plane (engine/gang.py): both mutate scheduling
    # state (ack bookkeeping / abort+requeue), so both are fenced —
    # and additionally fenced by (gang_id, epoch): a stale-epoch
    # report answers {"gang_stale": True} instead of being applied.
    # scanner-check SC313 pins every Gang* entry to this shape.
    "GangMemberDone":   {"timeout_s": 30.0, "idempotent": False},
    "GangFailed":       {"timeout_s": 30.0, "idempotent": False},
    "Shutdown":         {"timeout_s": PING_TIMEOUT, "idempotent": True},
}

# Every master RPC a sharded deployment routes per-shard via the shard
# map AND that mutates control-plane state.  scanner-check SC316 pins
# this tuple to the RPC_CONTRACTS idempotent=False set and to the
# `_fenced(...)`-wrapped registrations (extending SC312), both
# directions: a mutating RPC missing here would dodge the stale-map /
# generation fence audit, and an entry here that is not registered
# fenced would let a stale map route a mutation past a failover.
SHARD_ROUTED_RPCS = (
    "RegisterWorker", "NewJob", "NextWork", "StartedWork",
    "FinishedWork", "FinishedWorkBatch", "FailedWork", "PostProfile",
    "ShipSpans", "ShipMemoryReport", "GangMemberDone", "GangFailed",
)

# OOM forensic reports retained on the master (newest win): enough for
# a post-mortem across a worker fleet's pressure event, bounded so a
# flapping job cannot grow master memory
MAX_MEMORY_REPORTS = 16

# cross-host trace assembly bounds: spans kept per bulk on the master
# (overflow counts into the GetTrace/status `spans_dropped` field), the
# straggler top-N surfaced on /statusz + GetJobStatus, and how many
# RECENT bulks keep their full span store — a long-lived master serving
# many bulks must not retain 500k dicts per historical bulk forever
# (the straggler aggregates, which are tiny, are kept for all history)
MAX_BULK_SPANS = 500_000
STRAGGLER_TOP_N = 10
# per-gang straggler attribution rows retained per bulk (newest last);
# part of the straggler aggregates, so they survive compaction
MAX_GANG_SKEW_ROWS = 16
SPAN_HISTORY_BULKS = 4

_mlog = get_logger("master")
_wlog = get_logger("worker")

# control-plane telemetry (docs/observability.md).  The point-in-time
# gauges are refreshed by the master's 0.5s scan loop; the counters are
# bumped inline by the RPC handlers.
_M_WORKERS = _mx.registry().gauge(
    "scanner_tpu_master_workers_active",
    "Workers currently registered and heartbeating.")
_M_HB_AGE = _mx.registry().gauge(
    "scanner_tpu_worker_heartbeat_age_seconds",
    "Seconds since each worker's last heartbeat (master view).",
    labels=["worker"])
_M_TASKS_QUEUED = _mx.registry().gauge(
    "scanner_tpu_master_tasks_queued",
    "Tasks of the active bulk job waiting in the master queue.")
_M_TASKS_OUTSTANDING = _mx.registry().gauge(
    "scanner_tpu_master_tasks_outstanding",
    "Tasks currently assigned to workers (active bulk job).")
_M_TASKS_DONE = _mx.registry().counter(
    "scanner_tpu_master_tasks_completed_total",
    "Tasks completed across all bulk jobs this master served.")
_M_TASK_RETRIES = _mx.registry().counter(
    "scanner_tpu_task_retries_total",
    "Tasks re-queued after a failure or a started-task timeout.")
_M_REVOCATIONS = _mx.registry().counter(
    "scanner_tpu_task_revocations_total",
    "Task attempts revoked (timeout or stale-worker requeue).")
_M_STRIKES = _mx.registry().counter(
    "scanner_tpu_blacklist_strikes_total",
    "Task failures counted toward a job's blacklist threshold.")
_M_TRANSIENT = _mx.registry().counter(
    "scanner_tpu_transient_retries_total",
    "Worker-reported transient (storage/RPC) task failures requeued "
    "without a blacklist strike.")
_M_DRAINS = _mx.registry().counter(
    "scanner_tpu_worker_drains_total",
    "Workers that deregistered via SIGTERM drain (finish in-flight "
    "tasks, stop pulling, UnregisterWorker).")
_M_PREEMPTIONS = _mx.registry().counter(
    "scanner_tpu_worker_preemptions_total",
    "Preemption notices this worker received (spot/preemptible TPU "
    "reclaim, or the worker.preempt chaos site): each one starts a "
    "routine drain with the master fencing assignment first.")
_M_PREEMPT_NOTICES = _mx.registry().counter(
    "scanner_tpu_worker_preempt_notices_total",
    "Preemption notices the master observed on worker heartbeats "
    "(master view; survives the preempted worker's exit) — assignment "
    "to the worker is fenced from the first notice.")
_M_ADMISSION_PAUSED = _mx.registry().gauge(
    "scanner_tpu_master_admission_paused",
    "1 while the master's job admission is paused by the "
    "admission_pause remediation playbook (sustained backpressure "
    "shed); NewJob answers a retryable admission_paused reply.")
_M_JOBS_BLACKLISTED = _mx.registry().counter(
    "scanner_tpu_jobs_blacklisted_total",
    "Jobs removed from their bulk after repeated task failures.")
_M_ADMISSION_DEDUP = _mx.registry().counter(
    "scanner_tpu_admission_dedup_total",
    "NewJob admissions deduplicated by client-minted admission token: "
    "a retry after an ambiguous timeout (or across a master restart) "
    "returned the already-admitted bulk id instead of double-running "
    "the bulk.")


def _is_transient_failure(exc: BaseException) -> bool:
    """Failures caused by the environment rather than the task itself —
    storage errors (including crc-detected item corruption), RPC/
    transport errors, timeouts.  The worker tags FailedWork with this so
    the master requeues without a blacklist strike: a flaky dependency
    must not blacklist a healthy job, while a deterministic kernel bug
    still strikes out after MAX_TASK_FAILURES."""
    import grpc

    from ..common import StorageException
    from ..parallel.distributed import RendezvousError
    if _memstats.is_oom(exc):
        # device memory exhaustion: the pressure came from co-scheduled
        # work, not this task — requeue strike-free (the failed attempt
        # freed its staged buffers on the way out)
        return True
    # a failed jax.distributed rendezvous means the PEER SET changed
    # (a member died, a coordinator moved) — the task is fine; the
    # gang re-forms on the remaining capacity strike-free
    return isinstance(exc, (StorageException, rpc.RpcError, grpc.RpcError,
                            ConnectionError, TimeoutError,
                            RendezvousError))


# ---------------------------------------------------------------------------
# Master
# ---------------------------------------------------------------------------

@dataclass
class _WorkerInfo:
    worker_id: int
    address: str
    last_seen: float
    active: bool = True
    # host:port this worker's gang member runner would serve the
    # jax.distributed coordinator at if elected member 0 (advertised at
    # registration; empty = the worker cannot coordinate a gang)
    gang_address: str = ""
    # spot/preemptible reclaim notice seen on a heartbeat: assignment
    # to this worker is FENCED (NextWork answers wait) while its drain
    # completes — requeues of whatever it cannot finish stay strike-free
    preempting: bool = False
    # alert rule names this worker reported firing on its last
    # heartbeat — the cross-node signal feed for the remediation
    # controller (stage_backpressure lives in worker processes; the
    # master's local health engine cannot see it)
    firing: Set[str] = field(default_factory=set)


@dataclass
class _Gang:
    """One co-scheduled task group (docs/robustness.md §Gang
    scheduling): the member set, its rendezvous wiring, and the
    (gang_id, epoch) fence every gang RPC must present.  Lives in
    `_BulkJob.gangs` from formation until member 0's FinishedWork is
    accepted or the gang aborts — after either, every late report with
    this (gang_id, epoch) is NACKed (`gang_stale`)."""

    gang_id: int
    epoch: int
    key: Tuple[int, int]                 # the (job, task) the gang runs
    attempt: int
    members: List[int]                   # worker ids; members[0] is the
    coordinator: str                     # jax coordinator (its address)
    formed_at: float
    roles_handed: Set[int] = field(default_factory=set)
    acks: Set[int] = field(default_factory=set)   # non-0 members done
    # sharded gangs: member rank -> its shard digest, recorded from
    # GangMemberDone acks that beat the writer's FinishedWork; the
    # commit fold cross-checks them against the digests the writer
    # assembled from (count_shard_fold)
    shard_digests: Dict[int, int] = field(default_factory=dict)
    trace_parent: str = ""               # gang root span traceparent


@dataclass
class _BulkJob:
    bulk_id: int
    spec_blob: bytes                    # graph + resolved perf + cache mode
    task_timeout: float
    # write the table megafile every N completed tasks so a master crash
    # mid-bulk loses at most N tasks of metadata (reference checkpoint
    # every N jobs, master.cpp:1100-1113); 0 disables
    checkpoint_frequency: int = 0
    # Per-job deques + a round-robin ring of job ids: NextWork pops are
    # O(1) (the reference shards tasks for the same reason,
    # master.cpp:1558-1607), and a sticky job bound to another worker is
    # skipped as a WHOLE job — a single shared deque would make every
    # other worker rescan that job's (possibly 10^5) queued tasks per
    # poll, starving later jobs behind it
    queue: Dict[int, Deque[int]] = field(default_factory=dict)
    job_rr: Deque[int] = field(default_factory=deque)
    # (job, task) -> (worker id, clock start, attempt id, started,
    # eval_done).  The `started` flag records whether StartedWork arrived
    # for this attempt: a timeout revocation of a task that only WAITED in
    # a worker's queue is a scheduling artifact and must not count toward
    # job blacklisting.  The attempt id
    # makes assignments distinguishable: after a timeout revocation the
    # same worker may legitimately be re-assigned the task while its stale
    # attempt still runs, and only the *current* attempt's completion may
    # count (reference master.cpp:2111 stop_job_on_worker kills the stale
    # attempt instead; here it reports and is ignored).  `eval_done` means
    # the task is parked in the worker's save stage: it stays outstanding
    # (timeout/fault tracking) but no longer counts against the worker's
    # NextWork window (`held`).
    outstanding: Dict[Tuple[int, int],
                      Tuple[int, float, int, bool, bool]] = \
        field(default_factory=dict)
    next_attempt: int = 0
    # stateful task affinity (PerfParams.stateful_task_affinity + an
    # unbounded-state op in the graph): each job's tasks go, in order,
    # to one worker (reference save_coordinator worker.cpp:373-415);
    # rebound when that worker dies
    sticky: bool = False
    sticky_worker: Dict[int, int] = field(default_factory=dict)
    # worker id -> the sticky job it is currently draining; NextWork
    # serves this job to exhaustion before the ring hands the worker
    # another sticky job — interleaving two chained jobs on one
    # single-instance evaluator would reset kernel streams on every
    # switch and carry-miss every task
    sticky_cur: Dict[int, int] = field(default_factory=dict)
    # per-worker count of outstanding assignments (kept in sync with
    # `outstanding` so the NextWork window check is O(1))
    held: Dict[int, int] = field(default_factory=dict)
    done: Set[Tuple[int, int]] = field(default_factory=set)
    # per-job done-task counts, maintained where done.add happens: the
    # 4 Hz GetJobStatus poll must stay O(jobs) under the control-plane
    # lock, not O(total_tasks)
    job_done: Dict[int, int] = field(default_factory=dict)
    failures: Dict[Tuple[int, int], int] = field(default_factory=dict)
    # transient (storage/RPC) failures per task: requeued strike-free up
    # to MAX_TRANSIENT_FAILURES, then they fall through to `failures`
    transient_failures: Dict[Tuple[int, int], int] = \
        field(default_factory=dict)
    blacklisted_jobs: Set[int] = field(default_factory=set)
    total_tasks: int = 0
    # counters so the finish check is O(1) per FinishedWork (a set
    # comprehension over 10^5-10^6 tasks per completion would be
    # quadratic): tasks in blacklisted jobs, and done-tasks among them
    blacklisted_task_total: int = 0
    done_in_blacklisted: int = 0
    job_tasks: Dict[int, Set[Tuple[int, int]]] = field(default_factory=dict)
    # job idx -> output table names, resolved at admission so completion
    # commits never deserialize the graph under the control-plane lock
    job_sink_names: Dict[int, List[str]] = field(default_factory=dict)
    # job idx -> custom sink streams (finished() barrier on completion)
    job_custom_sinks: Dict[int, list] = field(default_factory=dict)
    job_output_rows: Dict[int, int] = field(default_factory=dict)
    committed_jobs: Set[int] = field(default_factory=set)
    finished: bool = False
    error: str = ""
    profiles: List[dict] = field(default_factory=list)
    # distributed tracing (util/tracing.py): the job's trace_id (from
    # the submitting client's traceparent, or minted at admission), the
    # master-side parent span id new assign spans chain under, the
    # assembled cross-host span store (workers ShipSpans into it), and
    # the incrementally-maintained straggler aggregates — per-stage
    # duration stats plus a bounded min-heap of the slowest task spans
    # ((duration, seq, job, task, node, span_id); seq breaks duration
    # ties so heterogenous payloads never reach tuple comparison)
    trace_id: str = ""
    trace_parent: str = ""
    spans: List[dict] = field(default_factory=list)
    span_drops: int = 0
    span_stats: Dict[str, List[float]] = field(default_factory=dict)
    # per-op roofline aggregates from op.efficiency span events
    # ([eff_sum, n, memory_bound_n] per evaluate:<op> span name) — the
    # straggler summary joins them so a slow stage is attributable to
    # *inefficient* (low eff) vs *overloaded* (high eff, long queue)
    eff_stats: Dict[str, List[float]] = field(default_factory=dict)
    slowest: List[Tuple] = field(default_factory=list)
    slow_seq: int = 0
    # live-status bookkeeping: output rows per task (from the admission
    # job geometry) and cumulative rows through each pipeline stage
    # transition the master observes (NextWork->StartedWork = loaded,
    # EvalDone = evaluated, FinishedWork = saved).  GetJobStatus and
    # /statusz derive per-stage fps and the ETA from these — one source
    # of truth for the client progress bar and the endpoint.
    admitted_at: float = field(default_factory=time.time)
    task_rows: Dict[Tuple[int, int], int] = field(default_factory=dict)
    stage_rows: Dict[str, int] = field(
        default_factory=lambda: {"load": 0, "evaluate": 0, "save": 0})
    # tasks already counted per stage: a retried attempt's second
    # StartedWork/EvalDone must not double-count its rows, or the
    # load/evaluate fps would read (retries+1)x the save fps on a flaky
    # cluster ('save' dedupes via `done`)
    stage_seen: Dict[str, Set[Tuple[int, int]]] = field(
        default_factory=lambda: {"load": set(), "evaluate": set()})

    # client-minted admission token (NewJob dedupe): persisted with the
    # checkpoint/journal so a retried NewJob returns this bulk's id
    # even across a master restart
    admission_token: str = ""
    # wall-clock end of the bulk; 0 while running.  Status fps/elapsed
    # freeze here so querying a historical bulk an hour later does not
    # decay its throughput toward zero.
    finished_at: float = 0.0
    # active-done count when this _BulkJob object started serving (0 at
    # admission; the restored done-count after a master restart).  The
    # ETA divides post-start progress by post-start elapsed — dividing
    # checkpoint-restored completions by seconds-since-recovery would
    # report a completion rate off by orders of magnitude.
    done_at_start: int = 0
    # gang scheduling (PerfParams.gang_hosts > 0): each task is
    # co-scheduled onto a gang of up to gang_hosts live workers
    # instead of answering independent pulls.  `gang_epoch` is the
    # bulk-wide monotonic fence — minted fresh per formation, bumped
    # again on every abort, restored >= its journaled high-water mark
    # across a master failover — so a completion from a superseded
    # gang can never double-commit.  `gang_forming` is the pool of
    # workers waiting for the next formation (joined-order), and
    # `gang_aborted_keys` marks tasks whose re-formation counts as a
    # reform in the metrics.
    gang_hosts: int = 0
    # mesh-partitioned gang evaluation (engine/gang.py sharded members):
    # decided once per bulk from PerfParams.gang_sharded AND the
    # master's [gang] sharded config, and carried on every role reply so
    # all members of a gang run the same mode; gang_halo rides along
    # the same way ([gang] halo_exchange)
    gang_sharded: bool = True
    gang_halo: bool = True
    next_gang_id: int = 0
    gang_epoch: int = 0
    gangs: Dict[int, _Gang] = field(default_factory=dict)
    gang_by_task: Dict[Tuple[int, int], int] = field(default_factory=dict)
    gang_forming: Dict[int, float] = field(default_factory=dict)
    gang_forming_since: float = 0.0
    gang_aborted_keys: Set[Tuple[int, int]] = field(default_factory=set)
    # scan-loop watchdog clock: since when the fleet has had live
    # workers but ZERO gang-capable ones (no gang_address — e.g. the
    # whole fleet runs SCANNER_TPU_GANG=0) while this gang bulk still
    # has work; 0 = capable capacity exists.  Past no_workers_timeout
    # the bulk fails loudly instead of waiting forever on formations
    # that can never happen.
    gang_incapable_since: float = 0.0
    # gangs retired by an accepted member-0 completion (gang_id ->
    # epoch, insertion-bounded): a surviving member's ack that lands
    # AFTER the single writer committed is acknowledged quietly
    # instead of counting as a stale-epoch NACK — it is the normal
    # tail of a healthy gang, not fence traffic
    gang_retired: Dict[int, int] = field(default_factory=dict)
    # cross-host time plane (util/clocksync.py): node -> the worker's
    # most recent advertised {offset, uncertainty, at}, refreshed from
    # heartbeats and from the clock field on every ShipSpans /
    # FinishedWork batch.  GetTrace rebases that node's spans onto
    # master time with it (unless raw_clocks / rebase disabled); the
    # barrier-skew fold corrects member arrival stamps with it.
    clock_offsets: Dict[str, dict] = field(default_factory=dict)
    # (gang_id, epoch) -> in-flight barrier-arrival fold: per-member
    # offset-corrected arrival stamps from absorbed gang.barrier spans.
    # Once all `num` members reported, the max-min skew is observed
    # into the skew histogram and an attribution row is appended.
    gang_arrivals: Dict[Tuple[int, int], dict] = field(
        default_factory=dict)
    # bounded ring of per-gang straggler attribution rows (newest
    # last): gang/epoch, the slowest member's node, its lag vs the
    # median arrival, and whether the gang step was barrier-bound or
    # collective-bound.  Part of the straggler aggregates — survives
    # compaction.
    gang_skew_rows: List[dict] = field(default_factory=list)
    # retention: when this bulk ages out of the last-N history ring its
    # heavy scheduling state (done set, task_rows, per-task maps, the
    # span store) is dropped and status queries serve from this frozen
    # snapshot — Client.stragglers/GetTrace keep working post-completion
    # (aggregates survive compaction; raw spans do not)
    compacted: bool = False
    status_frozen: Optional[dict] = None

    def count_stage(self, stage: str, key: Tuple[int, int]) -> None:
        if key not in self.stage_seen[stage]:
            self.stage_seen[stage].add(key)
            self.stage_rows[stage] += self.task_rows.get(key, 0)

    def mark_finished(self) -> None:
        self.finished = True
        if not self.finished_at:
            self.finished_at = time.time()

    def compact(self, frozen_status: dict) -> None:
        """Drop the heavy per-task state of a finished bulk that aged
        out of the history ring; a long-lived master serving thousands
        of bulks keeps only the tiny straggler aggregates + a frozen
        status per historical bulk instead of 10^5-task done-sets and
        span stores."""
        self.compacted = True
        self.status_frozen = frozen_status
        self.spans = []
        self.done = set()
        self.task_rows = {}
        self.job_tasks = {}
        self.queue = {}
        self.job_rr = deque()
        self.outstanding = {}
        self.held = {}
        self.failures = {}
        self.transient_failures = {}
        self.stage_seen = {"load": set(), "evaluate": set()}
        self.sticky_worker = {}
        self.sticky_cur = {}
        self.gangs = {}
        self.gang_by_task = {}
        self.gang_forming = {}
        self.gang_retired = {}
        self.gang_aborted_keys = set()
        # raw spans are gone, so the per-node rebase map and any
        # incomplete barrier folds go with them; the finished
        # gang_skew_rows are aggregates and stay
        self.clock_offsets = {}
        self.gang_arrivals = {}
        # profiles are deliberately KEPT: GetProfiles / Client.trace
        # device lanes retained them for all history before compaction
        # existed, and they are per-worker (bounded per bulk), not
        # per-task

    def q_push(self, key: Tuple[int, int], front: bool = False) -> None:
        j, t = key
        dq = self.queue.get(j)
        if dq is None:
            dq = self.queue[j] = deque()
            self.job_rr.append(j)
        if front:
            # requeued (revoked/failed/worker-death) task: re-insert in
            # TASK ORDER — sticky chains want the job's deque ascending,
            # and several requeues arriving ascending would reverse at
            # the head with a plain appendleft.  Requeues are rare; the
            # O(n) re-sort is fine.
            if dq and t > dq[0]:
                items = sorted(set(dq) | {t})
                dq.clear()
                dq.extend(items)
            else:
                dq.appendleft(t)
        else:
            dq.append(t)

    def q_count(self) -> int:
        return sum(len(dq) for dq in self.queue.values())

    def q_has_work(self) -> bool:
        return any(self.queue.values())


class Master:
    """The cluster control plane; also the single metadata writer."""

    def __init__(self, db_path: str, port: int = 0,
                 no_workers_timeout: float = 30.0,
                 enable_watchdog: bool = False,
                 storage_type: str = "posix",
                 metrics_port: Optional[int] = None,
                 metrics_host: str = "0.0.0.0",
                 # remediation (engine/controller.py): True builds an
                 # AutoscaleConfig from the [remediation] bounds, or
                 # pass a config; scale_actuator is the pluggable
                 # replica setter (deploy.Cluster.scale in prod, a
                 # callback in tests; None = audit-only, the desired
                 # count still lands on the autoscale gauge)
                 autoscale=None,
                 scale_actuator=None,
                 # sharded control plane (engine/shardmap.py): this
                 # master's shard id and the deployment's shard count
                 # (None = the [control] shards config default).  All
                 # durable control state — generation claims,
                 # checkpoints, journals — scopes under the shard's
                 # namespace; shard 0 of a 1-shard deployment is the
                 # classic single master, bit-for-bit.
                 shard_id: int = 0,
                 num_shards: Optional[int] = None,
                 advertise_host: str = "localhost"):
        self.db = Database(make_storage(storage_type, db_path=db_path))
        self.no_workers_timeout = no_workers_timeout
        self.shard_id = max(0, int(shard_id))
        self.num_shards = max(1, int(
            num_shards if num_shards is not None
            else _shardmap.num_shards()))
        self._advertise_host = advertise_host
        # the newest shard-map epoch this master has observed — the
        # fence `_fenced` NACKs stale-map mutations against; 0 until a
        # map exists (single-shard deployments never publish one)
        self._map_epoch = 0
        self._shard_map: Optional[_shardmap.ShardMap] = None
        _shardmap.note_identity(self.shard_id, self.num_shards)
        self.enable_watchdog = enable_watchdog
        # master-side span sink (export drained into each bulk's span
        # store): admission/assignment spans are the cross-host glue
        # between the client's root span and worker task spans
        self.tracer = _tracing.Tracer(node="master", export=True)
        self._lock = threading.RLock()
        self._admit_lock = threading.Lock()
        self._workers: Dict[int, _WorkerInfo] = {}
        self._next_worker_id = 0
        self._next_bulk_id = 0
        self._bulk: Optional[_BulkJob] = None
        self._history: Dict[int, _BulkJob] = {}
        # cluster-level clock-offset map (node -> the newest advertised
        # estimate, from heartbeats): seeds each bulk's rebase map so a
        # bulk admitted after the fleet converged starts corrected
        self._clock_offsets: Dict[str, dict] = {}
        # OOM forensic reports shipped by workers (ShipMemoryReport),
        # newest-last, bounded — served back by GetMemoryReport next to
        # this process's own memstats view
        self._mem_reports: Deque[dict] = deque(maxlen=MAX_MEMORY_REPORTS)
        self._last_poke = time.time()
        self._no_worker_since = time.time()
        self._cleared_bulk_id: Optional[int] = None
        self._shutdown = threading.Event()
        # durable control plane (engine/journal.py): claim a monotonic
        # master generation via storage CAS — every mutating RPC reply
        # is stamped with it, checkpoint/journal paths are scoped by
        # it, and a master that sees a newer claim fences itself.
        self.generation = _journal.claim_generation(
            self.db.backend, shard=self.shard_id)
        self._fence = threading.Event()
        self._journal: Optional[_journal.BulkJournal] = (
            _journal.BulkJournal(self.db.backend, self.generation,
                                 shard=self.shard_id)
            if _journal.enabled() else None)
        # NewJob admission-token dedupe: token -> bulk_id, bounded by
        # the insertion ring (a retry after an ambiguous timeout — or
        # across a master restart, via the journaled admit record —
        # returns the existing bulk instead of double-running it)
        self._admission_tokens: Dict[str, int] = {}
        self._admission_token_ring: Deque[str] = deque()
        # a forced-generation (SCANNER_TPU_MASTER_GENERATION) master
        # may already be stale at startup: fence BEFORE recovery so it
        # neither adopts nor persists anything
        self._check_fence()
        # resume an interrupted bulk BEFORE serving RPCs: workers that
        # re-register see the restored bulk as active and pull its
        # remaining tasks (reference recover_and_init_database,
        # master.cpp:1311 + checkpoint master.cpp:1100-1113)
        if not self._fence.is_set():
            self._recover_bulk()
        # every idempotent=False (mutating) handler routes through the
        # generation fence (scanner-check SC312 pins this wrapping to
        # the RPC_CONTRACTS table, both directions)
        self._server = rpc.RpcServer(MASTER_SERVICE, {
            "Ping": self._rpc_ping,
            "RegisterWorker": self._fenced(self._rpc_register_worker),
            "UnregisterWorker": self._rpc_unregister_worker,
            "Heartbeat": self._rpc_heartbeat,
            "NewJob": self._fenced(self._rpc_new_job),
            "GetJob": self._rpc_get_job,
            "NextWork": self._fenced(self._rpc_next_work),
            "StartedWork": self._fenced(self._rpc_started_work),
            "EvalDone": self._rpc_eval_done,
            "FinishedWork": self._fenced(self._rpc_finished_work),
            "FinishedWorkBatch": self._fenced(
                self._rpc_finished_work_batch),
            "FailedWork": self._fenced(self._rpc_failed_work),
            "GetJobStatus": self._rpc_job_status,
            "GetShardMap": self._rpc_get_shard_map,
            "GetMetrics": self._rpc_get_metrics,
            "GetHealth": self._rpc_get_health,
            "PokeWatchdog": self._rpc_poke,
            "PostProfile": self._fenced(self._rpc_post_profile),
            "GetProfiles": self._rpc_get_profiles,
            "ShipSpans": self._fenced(self._rpc_ship_spans),
            "GetTrace": self._rpc_get_trace,
            "ShipMemoryReport": self._fenced(
                self._rpc_ship_memory_report),
            "GangMemberDone": self._fenced(self._rpc_gang_member_done),
            "GangFailed": self._fenced(self._rpc_gang_failed),
            "GetMemoryReport": self._rpc_get_memory_report,
            "GetCompileLedger": self._rpc_get_compile_ledger,
            "Shutdown": self._rpc_shutdown,
        }, port=port, tracer=self.tracer)
        self.port = self._server.port
        self._server.start()
        # sharded deployments publish this shard's address into the
        # durable map (epoch bump — the signal every map holder
        # refreshes on).  A fenced master publishes nothing: its
        # successor owns the shard's map entry now.
        self.advertise_address = f"{advertise_host}:{self.port}"
        if self.num_shards > 1 and not self._fence.is_set():
            try:
                self._adopt_shard_map(_shardmap.register_shard(
                    self.db.backend, self.shard_id,
                    self.advertise_address, self.num_shards))
            except Exception:  # noqa: BLE001 — map publish is not
                # worth failing startup over; the scan loop retries
                _mlog.exception("shard-map publish failed at startup")
        # /metrics + /healthz + /statusz — strictly opt-in: no listener
        # exists unless metrics_port is given (0 = ephemeral port, see
        # .metrics_server.port)
        self.metrics_server: Optional[MetricsServer] = None
        if metrics_port is not None:
            self.metrics_server = MetricsServer(
                port=metrics_port, statusz=self._statusz,
                healthz=lambda: {"role": "master"}, host=metrics_host)
        # the health/SLO engine (util/health.py): worker-liveness and
        # latency-burn rules read series this process maintains, so the
        # master always evaluates them — /healthz, GetJobStatus and
        # GetHealth report the roll-up
        _health.ensure_started()
        # remediation (engine/controller.py): the master owns the
        # admission gate and the autoscaler, so it binds their actions
        # here; the scan loop ticks the controller (hysteresis holds)
        # and feeds worker-reported alerts + the autoscale observation.
        # All of it is inert under SCANNER_TPU_REMEDIATION=0.
        self._admission_paused: Optional[str] = None
        self._worker_firing: Set[str] = set()
        self.autoscaler: Optional[_controller.Autoscaler] = None
        if autoscale:
            cfg = autoscale if isinstance(
                autoscale, _controller.AutoscaleConfig) else \
                _controller.AutoscaleConfig(
                    *_controller.autoscale_bounds())
            self.autoscaler = _controller.Autoscaler(
                cfg, actuator=scale_actuator)
        if _controller.ensure_started() is not None:
            _controller.register_action("pause_admission",
                                        self._pause_admission)
            _controller.register_action("resume_admission",
                                        self._resume_admission)
            _controller.register_action("autoscale",
                                        self._autoscale_nudge)
        self._scan_thread = threading.Thread(
            target=self._scan_loop, name="master-scan", daemon=True)
        self._scan_thread.start()

    # -- generation fence (engine/journal.py) -------------------------------

    def _fenced(self, fn):
        """Generation-fence guard every mutating (idempotent=False)
        master handler routes through (scanner-check SC312): a fenced
        — superseded — master accepts ZERO mutations, and live replies
        are stamped with this master's generation so workers can latch
        it and NACK anything older."""
        def guard(req: dict) -> dict:
            if self._fence.is_set():
                _journal.count_stale_rejection("master")
                return {"error": "master fenced: generation "
                                 f"{self.generation} superseded",
                        "fenced": True, "generation": self.generation}
            # the map-epoch fence (engine/shardmap.py): a caller that
            # routed with an older shard map than this master has seen
            # is NACKed so it refreshes and re-routes — a stale map can
            # never push a mutation past a shard failover.  Requests
            # with no map_epoch stamp (legacy / single-shard callers)
            # always pass.
            me = req.get("map_epoch") if isinstance(req, dict) else None
            if me is not None and int(me) < self._map_epoch:
                _shardmap.count_stale_map_rejection()
                return {"error": f"stale shard map (epoch {int(me)} < "
                                 f"{self._map_epoch})",
                        "stale_map": True,
                        "map_epoch": self._map_epoch,
                        "generation": self.generation}
            reply = fn(req)
            if isinstance(reply, dict):
                reply.setdefault("generation", self.generation)
                if self.num_shards > 1:
                    reply.setdefault("map_epoch", self._map_epoch)
            return reply
        guard.__name__ = getattr(fn, "__name__", "handler")
        return guard

    def _check_fence(self) -> bool:
        """One storage poll: has a newer generation been claimed?  Run
        at startup and by the scan loop (~2 s cadence) — path scoping
        already protects storage structurally, this closes the RPC
        window too."""
        if self._fence.is_set():
            return True
        try:
            newest = _journal.highest_claimed(self.db.backend,
                                              shard=self.shard_id)
        except Exception:  # noqa: BLE001 — a flaky storage poll must
            return False   # not fence a healthy master
        if newest > self.generation:
            self._fence_out(newest)
            return True
        return False

    # -- shard map (engine/shardmap.py) -------------------------------------

    def _adopt_shard_map(self, smap: _shardmap.ShardMap) -> None:
        self._shard_map = smap
        self._map_epoch = max(self._map_epoch, smap.epoch)
        _shardmap.note_map_epoch(self._map_epoch)

    def _refresh_shard_map(self) -> None:
        """One storage poll for a newer map epoch (scan-loop cadence,
        next to the generation-fence poll): a peer shard's failover
        re-publish bumps the epoch, and adopting it here arms the
        stale-map fence against pre-failover routing."""
        if self.num_shards <= 1:
            return
        try:
            smap = _shardmap.load(self.db.backend)
        except Exception:  # noqa: BLE001 — a flaky poll keeps the
            return         # current map; next tick retries
        if smap is not None and smap.epoch > self._map_epoch:
            self._adopt_shard_map(smap)

    def _rpc_get_shard_map(self, req: dict) -> dict:
        """The versioned shard map, served by every shard: clients and
        workers resolve routing from any live master."""
        if self.num_shards > 1 and (
                self._shard_map is None
                or len(self._shard_map.shards) < self.num_shards):
            # startup race: peers registered AFTER this shard adopted
            # its own publish — re-poll inline (bounded: only while
            # the map is still missing members) so a resolver dialing
            # any one shard sees the full membership
            self._refresh_shard_map()
        smap = self._shard_map
        return {"epoch": self._map_epoch,
                "shard_id": self.shard_id,
                "num_shards": self.num_shards,
                "shards": {str(k): v for k, v in
                           (smap.shards if smap else {}).items()},
                "generation": self.generation}

    def _fence_out(self, newest: int) -> None:
        self._fence.set()
        _mlog.error(
            "master generation %d FENCED: generation %d has been "
            "claimed on this db — rejecting all mutating RPCs, "
            "persistence stopped (a successor owns the bulk now)",
            self.generation, newest)

    def _journal_append(self, recs) -> None:
        """Durably journal control-plane events.  Callers invoke this
        OUTSIDE self._lock (storage writes must not stall heartbeats)
        and BEFORE acking the RPC that caused them (write-ahead: an
        acked completion is never lost).  A fenced master journals
        nothing."""
        if not recs or self._journal is None or self._fence.is_set():
            return
        try:
            self._journal.append(*recs)
        except Exception:  # noqa: BLE001 — durability is best-effort
            # past the checkpoint floor: a journal write failure must
            # not fail the task completion that triggered it
            _mlog.exception("bulk journal append failed (recovery "
                            "falls back to the checkpoint window)")

    # -- rpc handlers -------------------------------------------------------

    def _rpc_ping(self, req: dict) -> dict:
        return {"ok": True}

    def _rpc_register_worker(self, req: dict) -> dict:
        with self._lock:
            wid = self._next_worker_id
            self._next_worker_id += 1
            self._workers[wid] = _WorkerInfo(
                wid, req.get("address", ""), time.time(),
                gang_address=str(req.get("gang_address", "") or ""))
        _mlog.info("worker %d registered (%s)", wid, req.get("address", ""))
        return {"worker_id": wid}

    def _rpc_unregister_worker(self, req: dict) -> dict:
        """Graceful worker departure (SIGTERM drain): deactivate NOW
        instead of waiting WORKER_STALE_AFTER for the stale scan, and
        requeue anything it still held (a drained worker finished its
        in-flight tasks first, so normally nothing)."""
        wid = req.get("worker_id")
        recs: List[dict] = []
        with self._lock:
            w = self._workers.get(wid)
            if w is not None and w.active:
                w.active = False
                # deactivation is volatile liveness, but the requeue
                # counts transient failures (strike/blacklist
                # escalation — replayed durable state): a superseded
                # master must not keep reshaping it (SC402)
                if not self._fence.is_set():
                    self._requeue_worker_tasks(wid, recs=recs)
                _M_DRAINS.inc()
                _mlog.info("worker %d deregistered (drain)", wid)
        self._journal_append(recs)
        return {"ok": True}

    def _rpc_heartbeat(self, req: dict) -> dict:
        # clock-sync exchange (util/clocksync.py): t1 = arrival stamp,
        # t2 = reply-build stamp, echoed with the worker's t0 so it can
        # compute offset/RTT.  The worker advertises its converged
        # estimate on the NEXT beat ("clock"); the master publishes it
        # as the per-node offset gauges and keeps it for trace rebase.
        t1 = time.time()
        wid = req["worker_id"]
        if req.get("slim"):
            # the heartbeat fold (engine/shardmap.py): a multi-shard
            # worker sends ONE full beat (clock sync, firing alerts,
            # gang liveness) to the shard whose bulk it is working and
            # a slim liveness-only beat to every other shard — per-
            # (worker, shard) RPC volume stays one beat, but the
            # payload fan-out is coalesced away
            with self._lock:
                w = self._workers.get(wid)
                if w is None or not w.active:
                    return {"reregister": True, "active_bulk": None,
                            "generation": self.generation}
                w.last_seen = time.time()
                bulk = self._bulk
                active = bulk.bulk_id \
                    if bulk and not bulk.finished else None
            _shardmap.count_coalesced("Heartbeat")
            return {"reregister": False, "active_bulk": active,
                    "generation": self.generation, "slim": True}
        recs: List[dict] = []
        with self._lock:
            w = self._workers.get(wid)
            if w is None or not w.active:
                # stale worker rejoining after removal: re-register
                return {"reregister": True, "active_bulk": None}
            w.last_seen = time.time()
            # preemption notice: fence assignment NOW — the worker's
            # drain completes on its own clock, but no new task may be
            # handed to reclaimed capacity in the meantime.  A gang
            # this worker belongs to cannot survive the reclaim: abort
            # it immediately so the epoch bumps and the task re-forms
            # on capacity that is staying.
            if req.get("preempting") and not w.preempting:
                w.preempting = True
                _M_PREEMPT_NOTICES.inc()
                _mlog.warning(
                    "worker %d advertised preemption: assignment "
                    "fenced, drain in progress", wid)
                # the abort mutates durable gang state (journaled):
                # a fenced master marks the worker preempting (volatile
                # assignment fence) but leaves gang scheduling to the
                # successor that owns the bulk now (SC402)
                cur = self._bulk
                if cur is not None and not cur.finished \
                        and not self._fence.is_set():
                    for g in list(cur.gangs.values()):
                        if wid in g.members:
                            self._abort_gang_locked(cur, g, "preempted",
                                                    recs)
                    cur.gang_forming.pop(wid, None)
            # firing alert names ride every beat (tiny: a sorted list
            # of rule-name strings) — the scan loop folds them into
            # cluster-level remediation transitions
            w.firing = set(req.get("firing") or ())
            bulk = self._bulk
            active = bulk.bulk_id \
                if bulk and not bulk.finished else None
            # gang liveness rides the beat: the worker compares its
            # in-flight member runs against this list and reaps a
            # runner whose gang was aborted underneath it — survivors
            # blocked in a dead collective tear down in seconds
            # instead of burning the whole member timeout
            gang_ids = None
            if bulk is not None and bulk.gang_hosts \
                    and not bulk.finished:
                gang_ids = sorted(
                    g.gang_id for g in bulk.gangs.values()
                    if wid in g.members)
            # the worker's advertised clock estimate: publish the
            # gauges and retain per node for GetTrace rebase / the
            # barrier-skew fold (node label matches its span stamps)
            est = req.get("clock")
            if est and _clocksync.enabled():
                node = f"worker{wid}"
                self._clock_offsets[node] = dict(est)
                if bulk is not None and not bulk.compacted:
                    bulk.clock_offsets[node] = dict(est)
                _clocksync.publish(node, est)
        # a preemption-triggered gang abort is journaled like any other
        # scheduling mutation (outside the lock, before the ack)
        self._journal_append(recs)
        # the generation rides every beat so workers latch the newest
        # master even between assignments (Heartbeat itself stays
        # idempotent — no fence guard needed to read liveness)
        reply = {"reregister": False, "active_bulk": active,
                 "generation": self.generation}
        if gang_ids is not None:
            reply["gangs"] = gang_ids
        # four-timestamp stamps for the NTP exchange; echoing t0 keeps
        # the worker side stateless across beats
        if "t0" in req:
            reply["t0"] = req["t0"]
            reply["t1"] = t1
            reply["t2"] = time.time()
        return reply

    def _rpc_new_job(self, req: dict) -> dict:
        """Admit a bulk job: resolve perf, create output tables, build the
        task queue (reference master.cpp:1367 process_job).  The admission
        lock serializes concurrent NewJob calls end-to-end — prepare()
        mutates database metadata and must not interleave."""
        token = req.get("token") or ""
        with self._admit_lock:
            with self._lock:
                # idempotent admission: a client retrying NewJob after
                # an ambiguous timeout (or across a master restart —
                # tokens ride the checkpoint/journal) gets the bulk it
                # already admitted, never a double-run.  Checked under
                # the admission lock so a retry racing the original
                # admission blocks until the token is recorded.
                if token and token in self._admission_tokens:
                    _M_ADMISSION_DEDUP.inc()
                    bid = self._admission_tokens[token]
                    _mlog.info("NewJob token %s deduplicated to "
                               "bulk %d", token[:12], bid)
                    return {"bulk_id": bid, "dedup": True}
                if req.get("resolve"):
                    # lookup-only probe (client ride-through after a
                    # failover): an unknown token must NOT admit a
                    # fresh bulk as a side effect — the client decides
                    # what to do with a lost bulk, not this handler
                    return {"error": "unknown admission token",
                            "unknown_token": True}
                if self._admission_paused:
                    # load shedding (admission_pause playbook): answer
                    # retryable instead of queueing work onto a
                    # backpressured cluster — ClusterClient.run retries
                    # with the hinted delay until resume or deadline
                    return {"error": "admission paused: "
                                     f"{self._admission_paused}",
                            "admission_paused": True,
                            "retry_after": 1.0}
                if self._bulk is not None and not self._bulk.finished:
                    return {"error": "a bulk job is already active"}
            # one trace_id per job: the submitting client's context (the
            # rpc:NewJob server span, re-established by the RPC glue) —
            # or a fresh trace when the caller is untraced, so worker
            # spans still assemble under ONE id either way
            tctx = _tracing.current_context()
            trace_id = tctx.trace_id if tctx else _tracing.new_trace_id()
            trace_parent = tctx.span_id if tctx else ""
            spec = cloudpickle.loads(req["spec"])
            outputs = spec["outputs"]
            perf: PerfParams = spec["perf"]
            cache_mode = CacheMode(spec["cache_mode"])
            ex = LocalExecutor(self.db)
            try:
                info, jobs = ex.prepare(outputs, perf, cache_mode)
            except Exception as e:  # noqa: BLE001
                return {"error": f"{type(e).__name__}: {e}"}
            gang_hosts = max(0, int(getattr(perf, "gang_hosts", 0) or 0))
            sticky = bool(getattr(perf, "stateful_task_affinity", False)
                          and any(n.spec is not None
                                  and getattr(n.spec, "unbounded_state",
                                              False)
                                  for n in info.ops))
            if gang_hosts:
                # a gang task is one synchronized program, not a chain
                # of per-worker state carries: gang mode wins
                sticky = False
            with self._lock:
                bulk = _BulkJob(
                    bulk_id=self._next_bulk_id,
                    spec_blob=cloudpickle.dumps(
                        {"outputs": outputs, "perf": perf,
                         "cache_mode": cache_mode.value}),
                    task_timeout=float(getattr(perf, "task_timeout", 0.0)),
                    checkpoint_frequency=int(
                        getattr(perf, "checkpoint_frequency", 0) or 0),
                    sticky=sticky, gang_hosts=gang_hosts,
                    gang_sharded=bool(
                        getattr(perf, "gang_sharded", True))
                    and _gang.sharded_enabled(),
                    gang_halo=_gang.halo_enabled(),
                    admission_token=token,
                    trace_id=trace_id, trace_parent=trace_parent)
                self._next_bulk_id += 1
                if token:
                    self._record_admission_token_locked(
                        token, bulk.bulk_id)
                for job in jobs:
                    if job.skipped:
                        continue
                    tasks = {(job.job_idx, t) for t in range(len(job.tasks))}
                    bulk.job_tasks[job.job_idx] = tasks
                    for t, (s, e) in enumerate(job.tasks):
                        bulk.task_rows[(job.job_idx, t)] = e - s
                    bulk.job_sink_names[job.job_idx] = [
                        d.name for d, _c, _k, _e in job.sink_tables.values()]
                    bulk.job_custom_sinks[job.job_idx] = \
                        list(job.custom_sinks.values())
                    bulk.job_output_rows[job.job_idx] = job.jr.output_rows
                    bulk.queue[job.job_idx] = deque(
                        sorted(t for _j, t in tasks))
                    bulk.job_rr.append(job.job_idx)
                    bulk.total_tasks += len(tasks)
                if bulk.total_tasks == 0:
                    bulk.mark_finished()
            # persist admission state BEFORE publishing the bulk
            # (outside the control-plane lock; still under the
            # admission lock): the checkpoint write resets the journal
            # for the new bulk, and a worker must not be able to
            # complete — and journal — a task that reset would then
            # delete.  A master crash mid-bulk resumes from here.
            if not bulk.finished:
                self._persist_bulk_checkpoint(bulk)  # scanner-check: disable=SC405 admission lock (not the control-plane lock) serializes admission storage end-to-end by design — heartbeats never wait on it
            with self._lock:
                self._bulk = bulk
                self._no_worker_since = time.time()
                self._history[bulk.bulk_id] = bulk
                self._trim_history_locked()
                _mlog.info(
                    "bulk %d admitted: %d jobs, %d tasks",
                    bulk.bulk_id, len(bulk.job_tasks), bulk.total_tasks)
            return {"bulk_id": bulk.bulk_id}

    def _rpc_get_job(self, req: dict) -> dict:
        with self._lock:
            bulk = self._history.get(req["bulk_id"])
            if bulk is None:
                return {"error": "unknown bulk job"}
            return {"spec": bulk.spec_blob}

    def _touch_worker(self, wid) -> None:
        w = self._workers.get(wid)
        if w is not None and w.active:
            w.last_seen = time.time()

    def _rpc_next_work(self, req: dict) -> dict:
        wid = req["worker_id"]
        bulk_id = req["bulk_id"]
        window = int(req.get("window") or 0)
        recs: List[dict] = []
        try:
            return self._next_work_impl(wid, bulk_id, window, recs)
        finally:
            # a gang formation is a scheduling mutation: its journal
            # record is durable before the role reply acks it (the
            # lock is released by the time this runs)
            self._journal_append(recs)

    def _next_work_impl(self, wid, bulk_id: int, window: int,
                        recs: List[dict]) -> dict:
        with self._lock:
            self._touch_worker(wid)
            bulk = self._bulk
            if bulk is None or bulk.bulk_id != bulk_id or bulk.finished:
                return {"status": "none"}
            w = self._workers.get(wid)
            if w is None or not w.active:
                return {"status": "none"}
            if w.preempting:
                # assignment fence: reclaimed capacity gets nothing new
                # while its drain completes (the worker's own drain
                # stops pulls too — this covers the notice->drain race
                # and externally-observed preemptions)
                return {"status": "wait"}
            if bulk.gang_hosts > 0:
                # gang mode: pulls feed the formation pool instead of
                # popping independent tasks (docs/robustness.md §Gang
                # scheduling)
                return self._gang_next_work_locked(bulk, wid, recs)
            if window:
                # per-worker in-flight window: don't let one node's
                # loaders hoard the queue while its siblings idle
                if bulk.held.get(wid, 0) >= window and bulk.q_has_work():
                    return {"status": "wait"}
            # round-robin over jobs; a sticky (stateful-affinity) job
            # bound to a live other worker is skipped as a whole, so it
            # can never starve later jobs for this worker
            got = None
            if bulk.sticky:
                # finish the worker's current chained job before taking
                # another: job switches reset the evaluator's kernel
                # streams and would carry-miss every task
                jc = bulk.sticky_cur.get(wid)
                dq = bulk.queue.get(jc) if jc is not None else None
                if dq and jc not in bulk.blacklisted_jobs \
                        and bulk.sticky_worker.get(jc) == wid:
                    while dq and got is None:
                        t = dq.popleft()
                        if (jc, t) not in bulk.done:
                            got = (jc, t)
                    if not dq:
                        bulk.queue.pop(jc, None)
                elif jc is not None:
                    bulk.sticky_cur.pop(wid, None)
            for _ in range(len(bulk.job_rr)) if got is None else ():
                j = bulk.job_rr.popleft()
                dq = bulk.queue.get(j)
                if not dq or j in bulk.blacklisted_jobs:
                    bulk.queue.pop(j, None)   # drop from the ring
                    continue
                if bulk.sticky:
                    bw = bulk.sticky_worker.get(j)
                    w2 = self._workers.get(bw) if bw is not None else None
                    if w2 is None or not w2.active:
                        bulk.sticky_worker[j] = wid  # bind (or re-bind)
                        bulk.sticky_cur[wid] = j
                    elif bw != wid:
                        bulk.job_rr.append(j)
                        continue
                    else:
                        bulk.sticky_cur[wid] = j
                while dq and got is None:
                    t = dq.popleft()
                    if (j, t) not in bulk.done:
                        got = (j, t)
                if dq:
                    bulk.job_rr.append(j)
                else:
                    bulk.queue.pop(j, None)
                if got is not None:
                    break
            if got is not None:
                j, t = got
                attempt = bulk.next_attempt
                bulk.next_attempt += 1
                bulk.outstanding[(j, t)] = (wid, time.time(), attempt,
                                            False, False)
                bulk.held[wid] = bulk.held.get(wid, 0) + 1
                _mlog.debug("task (%d,%d) assigned to worker %d "
                            "(attempt %d)", j, t, wid, attempt)
                reply = {"status": "task", "job_idx": j, "task_idx": t,
                         "attempt": attempt}
                # the cross-host hop: an (instantaneous) assignment span
                # in the job's trace whose id the worker parents its
                # task span under — master → worker stays one unbroken
                # chain per attempt
                sp = _tracing.open_span(
                    self.tracer, "master.assign",
                    parent=_tracing.SpanContext(bulk.trace_id,
                                                bulk.trace_parent),
                    job=j, task=t, attempt=attempt, worker=wid) \
                    if bulk.trace_id else None
                if sp is not None:
                    _tracing.close_span(self.tracer, sp)
                    reply["traceparent"] = sp.context().traceparent()
                return reply
            if bulk.outstanding or bulk.q_has_work():
                return {"status": "wait"}
            return {"status": "done"}

    # -- gang scheduling (engine/gang.py, docs/robustness.md) ---------------

    def _gang_next_work_locked(self, bulk: _BulkJob, wid: int,
                               recs: List[dict]) -> dict:
        """One gang-mode pull: hand the caller its role in a formed
        gang, or pool it toward the next formation.  A gang forms when
        `gang_hosts` eligible workers have pooled — or, after
        `[gang] form_timeout_s`, on whatever capacity HAS pooled (the
        loss-tolerant path: a bulk that lost hosts mid-flight re-forms
        smaller instead of waiting for capacity that is gone).  Caller
        holds self._lock."""
        info_w = self._workers.get(wid)
        if info_w is None or not info_w.gang_address:
            # a worker that cannot rendezvous (SCANNER_TPU_GANG=0 /
            # [gang] enabled=false: it registered with no gang
            # address) must never become a member — handing it a gang
            # reply would make it run the task as an ordinary pull and
            # break the single-writer accounting
            return {"status": "wait"}
        for g in bulk.gangs.values():
            if wid in g.members:
                if wid not in g.roles_handed:
                    return self._gang_role_reply_locked(bulk, g, wid)
                return {"status": "wait"}  # its member run is in flight
        # prune pool entries whose workers died/preempted since joining
        for fw in list(bulk.gang_forming):
            info = self._workers.get(fw)
            if info is None or not info.active or info.preempting:
                bulk.gang_forming.pop(fw, None)
        if not bulk.q_has_work():
            if bulk.outstanding or bulk.gangs:
                return {"status": "wait"}
            return {"status": "done"}
        now = time.time()
        if wid not in bulk.gang_forming:
            if not bulk.gang_forming:
                bulk.gang_forming_since = now
            bulk.gang_forming[wid] = now
        full = len(bulk.gang_forming) >= bulk.gang_hosts
        if not full and now - bulk.gang_forming_since \
                < _gang.form_timeout_s():
            return {"status": "wait"}
        # elect members in join order; the coordinator (member 0) must
        # advertise a gang address, and the election ROTATES with the
        # epoch about to be minted — a member whose advertised port
        # went bad (reclaimed since the startup probe) costs one
        # aborted epoch, not an unbounded streak of re-forms electing
        # the same broken coordinator
        pool = sorted(bulk.gang_forming,
                      key=lambda k: bulk.gang_forming[k])
        members = pool[:bulk.gang_hosts]
        able = [m for m in members
                if self._workers.get(m) is not None
                and self._workers[m].gang_address]
        if not able:
            return {"status": "wait"}  # nobody can coordinate yet
        lead = able[(bulk.gang_epoch + 1) % len(able)]
        members.remove(lead)
        members.insert(0, lead)
        coord = self._workers[lead].gang_address
        key = self._gang_pop_task_locked(bulk)
        if key is None:
            return {"status": "wait"}
        attempt = bulk.next_attempt
        bulk.next_attempt += 1
        bulk.gang_epoch += 1
        gid = bulk.next_gang_id
        bulk.next_gang_id += 1
        g = _Gang(gang_id=gid, epoch=bulk.gang_epoch, key=key,
                  attempt=attempt, members=members, coordinator=coord,
                  formed_at=now)
        bulk.gangs[gid] = g
        bulk.gang_by_task[key] = gid
        for m in members:
            bulk.gang_forming.pop(m, None)
            bulk.held[m] = bulk.held.get(m, 0) + 1
        bulk.gang_forming_since = now if bulk.gang_forming else 0.0
        # the gang's timeout clock starts at formation (started=True:
        # a formed gang is executing, not queue-parked)
        bulk.outstanding[key] = (members[0], now, attempt, True, False)
        reform = key in bulk.gang_aborted_keys
        _gang.count_formed(reform)
        _gang.set_epoch(bulk.gang_epoch)
        # the gang root span: every member's task span parents under it
        # so per-host stragglers inside one gang stay attributable
        sp = _tracing.open_span(
            self.tracer, "gang",
            parent=_tracing.SpanContext(bulk.trace_id,
                                        bulk.trace_parent),
            gang=gid, epoch=g.epoch, job=key[0], task=key[1],
            members=len(members)) if bulk.trace_id else None
        if sp is not None:
            _tracing.close_span(self.tracer, sp)
            g.trace_parent = sp.context().traceparent()
        recs.append({"t": "gang", "g": gid, "e": g.epoch,
                     "j": key[0], "k": key[1],
                     "members": list(members)})
        _mlog.info(
            "gang %d formed at epoch %d for task (%d,%d): members %s, "
            "coordinator %s%s", gid, g.epoch, key[0], key[1], members,
            coord, " (re-form)" if reform else "")
        if wid in g.members:
            return self._gang_role_reply_locked(bulk, g, wid)
        # the pool can briefly exceed gang_hosts (a pull that found
        # only blacklisted-job work left a full pool behind): this
        # caller's join-order slot fell outside the elected set — it
        # stays pooled for the NEXT formation instead of crashing the
        # role lookup
        return {"status": "wait"}

    @staticmethod
    def _gang_pop_task_locked(bulk: _BulkJob):
        """Round-robin task pop for gang formation (no stickiness —
        gang bulks never chain state across workers)."""
        for _ in range(len(bulk.job_rr)):
            j = bulk.job_rr.popleft()
            dq = bulk.queue.get(j)
            if not dq or j in bulk.blacklisted_jobs:
                bulk.queue.pop(j, None)
                continue
            got = None
            while dq and got is None:
                t = dq.popleft()
                if (j, t) not in bulk.done:
                    got = (j, t)
            if dq:
                bulk.job_rr.append(j)
            else:
                bulk.queue.pop(j, None)
            if got is not None:
                return got
        return None

    def _gang_role_reply_locked(self, bulk: _BulkJob, g: _Gang,
                                wid: int) -> dict:
        g.roles_handed.add(wid)
        return {"status": "gang", "gang_id": g.gang_id,
                "epoch": g.epoch,
                "process_id": g.members.index(wid),
                "num_processes": len(g.members),
                "coordinator": g.coordinator,
                "job_idx": g.key[0], "task_idx": g.key[1],
                "attempt": g.attempt,
                "task_timeout": bulk.task_timeout,
                # the MASTER decides the evaluation mode per gang and
                # every member reads it off this reply — members can
                # never disagree about sharding mid-gang (a single-host
                # gang degenerates to the replicated body either way)
                "sharded": bool(bulk.gang_sharded
                                and len(g.members) > 1),
                "halo": bool(bulk.gang_halo),
                "traceparent": g.trace_parent or None}

    def _abort_gang_locked(self, bulk: _BulkJob, g: _Gang, reason: str,
                           recs: List[dict], strike: bool = False,
                           error: str = "") -> None:
        """Tear one gang down: bump the epoch (the fence — every late
        report from this gang now NACKs), release member bookkeeping,
        and requeue the task for a fresh gang on the remaining
        capacity.  Aborts are revocations, not task failures: they
        count against the transient cap, never a blacklist strike —
        unless `strike` (a member reported a DETERMINISTIC task error),
        which routes through the ordinary failure path.  Idempotent
        per gang.  Caller holds self._lock."""
        if bulk.gangs.get(g.gang_id) is not g:
            return
        bulk.gangs.pop(g.gang_id, None)
        bulk.gang_by_task.pop(g.key, None)
        bulk.gang_aborted_keys.add(g.key)
        bulk.gang_epoch += 1
        _gang.set_epoch(bulk.gang_epoch)
        _gang.count_aborted(reason)
        recs.append({"t": "gang_abort", "g": g.gang_id, "e": g.epoch})
        self._unassign(bulk, g.key)
        for m in g.members[1:]:
            if m not in g.acks:
                self._dec_held(bulk, m)
        _mlog.warning(
            "gang %d (epoch %d, task (%d,%d)) aborted: %s — epoch "
            "bumped to %d, task requeued for a fresh gang", g.gang_id,
            g.epoch, g.key[0], g.key[1], reason, bulk.gang_epoch)
        if g.key in bulk.done or g.key[0] in bulk.blacklisted_jobs:
            return
        if strike:
            if self._count_strike_locked(bulk, g.key,
                                         error or reason, recs):
                # a blacklist can complete the bulk, and this abort
                # may have arrived on a non-RPC path (heartbeat
                # preemption, stale scan) that runs no finish check of
                # its own — without this, a bulk whose LAST task
                # blacklisted here would hang unfinished forever
                self._maybe_finish_bulk(bulk)
            return
        # strike-free revocation, bounded by the transient cap so a
        # gang that can never form/agree still terminates the bulk
        if self._count_transient_locked(bulk, g.key, recs):
            _M_TRANSIENT.inc()
            _M_REVOCATIONS.inc()
            _M_TASK_RETRIES.inc()
            bulk.q_push(g.key, front=True)
            return
        if self._count_strike_locked(
                bulk, g.key,
                f"gang aborts exhausted the transient cap ({reason})",
                recs):
            self._maybe_finish_bulk(bulk)

    # shared escalation counters (one policy for RPC failures, timeout
    # revocations, and gang aborts — the journal record shapes and
    # caps must never drift between those paths)

    @staticmethod
    def _count_transient_locked(bulk: _BulkJob, key: Tuple[int, int],
                                recs: List[dict]) -> bool:
        """Count one environment-caused failure against the transient
        cap.  True = still under the cap (caller requeues strike-free);
        False = escalate to a strike.  Caller holds self._lock."""
        tn = bulk.transient_failures.get(key, 0) + 1
        bulk.transient_failures[key] = tn
        recs.append({"t": "transient", "j": key[0], "k": key[1],
                     "n": tn})
        return tn <= MAX_TRANSIENT_FAILURES

    def _count_strike_locked(self, bulk: _BulkJob,
                             key: Tuple[int, int], err: str,
                             recs: List[dict]) -> bool:
        """Count one blacklist strike; past MAX_TASK_FAILURES the job
        blacklists (returns True), otherwise the task requeues at the
        front.  Caller holds self._lock."""
        n = bulk.failures.get(key, 0) + 1
        bulk.failures[key] = n
        recs.append({"t": "strike", "j": key[0], "k": key[1], "n": n})
        _M_STRIKES.inc()
        if n >= MAX_TASK_FAILURES:
            self._blacklist_job(bulk, key[0], err, recs=recs)
            return True
        bulk.q_push(key, front=True)
        _M_TASK_RETRIES.inc()
        return False

    def _gang_for_req_locked(self, bulk: _BulkJob, req: dict,
                             rpc_name: str):
        """Resolve a gang RPC's (gang_id, epoch) fence: the live gang,
        or None (counted NACK) when the gang is gone or the epoch is
        stale.  Caller holds self._lock."""
        gid = req.get("gang_id")
        g = bulk.gangs.get(gid) if gid is not None else None
        if g is None or int(req.get("epoch", -1)) != g.epoch:
            _gang.count_stale_nack(rpc_name)
            return None
        return g

    def _fold_gang_shards_locked(self, g: _Gang, req: dict) -> None:
        """Master-side shard commit fold (sharded gangs): the writer's
        FinishedWork carries the per-member shard digests it assembled
        the output from plus the collective total; verify that the
        shards sum to the total and that every member whose ack already
        landed reported the SAME shard digest the writer assembled.
        The gang itself already refused to commit on disagreement
        (member 0's pre-save check), so a mismatch here means a
        reporting-path bug — counted and logged loudly, never a strike
        against the (already committed, already verified) task.  Caller
        holds self._lock."""
        result = "ok"
        try:
            sds = [int(x) & 0xFFFFFFFF
                   for x in (req.get("shard_digests") or ())]
        except (TypeError, ValueError):
            sds = []
        total = req.get("digest")
        if len(sds) != len(g.members) or total is None:
            result = "partial"
        elif sum(sds) & 0xFFFFFFFF != int(total) & 0xFFFFFFFF:
            result = "mismatch"
        else:
            for rank, d in g.shard_digests.items():
                if 0 <= rank < len(sds) and sds[rank] != d:
                    result = "mismatch"
                    break
        if result != "ok":
            _mlog.warning(
                "gang %d epoch %d: shard commit fold %s (writer "
                "digests %s, total %s, acked %s)", g.gang_id, g.epoch,
                result, sds, total, dict(g.shard_digests))
        _gang.count_shard_fold(result)

    def _rpc_gang_member_done(self, req: dict) -> dict:
        """A non-coordinator member finished its (non-writing) part of
        the gang program: record the ack and release its slot in the
        worker's held-count.  Member 0 completes via FinishedWork —
        the gang's single completion report."""
        with self._lock:
            self._touch_worker(req.get("worker_id"))
            bulk = self._bulk
            if bulk is None or bulk.bulk_id != req.get("bulk_id"):
                return {"ok": False}
            gid = req.get("gang_id")
            if gid in bulk.gang_retired \
                    and int(req.get("epoch", -1)) \
                    == bulk.gang_retired[gid]:
                # the writer already committed this gang's task: the
                # surviving member's ack is the healthy tail, not
                # stale fence traffic
                return {"ok": True}
            g = self._gang_for_req_locked(bulk, req, "GangMemberDone")
            if g is None:
                return {"ok": False, "gang_stale": True}
            wid = req.get("worker_id")
            if wid not in g.members or wid == g.members[0]:
                _gang.count_stale_nack("GangMemberDone")
                return {"ok": False, "gang_stale": True}
            if wid not in g.acks:
                g.acks.add(wid)
                self._dec_held(bulk, wid)
            # sharded members carry their shard digest on the ack — the
            # ack path extended to carry shard results; the commit fold
            # verifies them against the writer's assembled view
            if req.get("shard_digest") is not None:
                try:
                    g.shard_digests[g.members.index(wid)] = \
                        int(req["shard_digest"]) & 0xFFFFFFFF
                except (TypeError, ValueError):
                    pass
            return {"ok": True}

    def _rpc_gang_failed(self, req: dict) -> dict:
        """A member reported its gang run failed (rendezvous timeout,
        collective error, runner loss, evaluate error): abort the gang
        — epoch bump, strike-free requeue unless the member classified
        the failure deterministic."""
        recs: List[dict] = []
        try:
            with self._lock:
                self._touch_worker(req.get("worker_id"))
                bulk = self._bulk
                if bulk is None or bulk.bulk_id != req.get("bulk_id"):
                    return {"ok": False}
                g = self._gang_for_req_locked(bulk, req, "GangFailed")
                if g is None:
                    return {"ok": False, "gang_stale": True}
                stage = str(req.get("stage") or "member")
                _mlog.warning(
                    "gang %d epoch %d: member (worker %s) failed at "
                    "%s: %s", g.gang_id, g.epoch,
                    req.get("worker_id"), stage, req.get("error", ""))
                self._abort_gang_locked(
                    bulk, g, f"member_failed:{stage}", recs,
                    strike=not req.get("transient", True),
                    error=str(req.get("error", "")))
                self._maybe_finish_bulk(bulk)
                finished_now = bulk.finished
        finally:
            self._journal_append(recs)
        if finished_now:
            self._clear_bulk_checkpoint(bulk.bulk_id)
        return {"ok": True}

    def _rpc_started_work(self, req: dict) -> dict:
        """Worker signals that evaluation of a prefetched task begins now:
        restart its timeout clock so task_timeout measures execution, not
        time spent queued behind the previous task."""
        key = (req["job_idx"], req["task_idx"])
        with self._lock:
            self._touch_worker(req.get("worker_id"))
            bulk = self._bulk
            if bulk is None or bulk.bulk_id != req["bulk_id"]:
                return {"ok": False}
            cur = bulk.outstanding.get(key)
            if cur is not None and cur[0] == req.get("worker_id") \
                    and cur[2] == req.get("attempt"):
                bulk.outstanding[key] = (cur[0], time.time(), cur[2], True,
                                         cur[4])
                bulk.count_stage("load", key)
                return {"ok": True}
        return {"ok": False, "revoked": True}

    def _rpc_eval_done(self, req: dict) -> dict:
        """Worker signals that a task finished evaluation and is parked in
        its save stage: it stops counting against the worker's NextWork
        window so lagging savers cannot starve the evaluators (it stays
        outstanding for timeout/fault tracking until FinishedWork)."""
        key = (req["job_idx"], req["task_idx"])
        with self._lock:
            self._touch_worker(req.get("worker_id"))
            bulk = self._bulk
            if bulk is None or bulk.bulk_id != req["bulk_id"]:
                return {"ok": False}
            cur = bulk.outstanding.get(key)
            if cur is not None and cur[0] == req.get("worker_id") \
                    and cur[2] == req.get("attempt") and not cur[4]:
                bulk.outstanding[key] = (cur[0], cur[1], cur[2], cur[3],
                                         True)
                self._dec_held(bulk, cur[0])
                bulk.count_stage("evaluate", key)
                return {"ok": True}
        return {"ok": False, "revoked": True}

    def _rpc_finished_work(self, req: dict) -> dict:
        recs: List[dict] = []
        with self._lock:
            reply, need_ckpt, finished_now, bulk = \
                self._finished_work_locked(req, recs)
        # write-ahead: the completion is durable in the journal BEFORE
        # this handler acks — a kill -9 after the ack cannot lose it
        # (outside the control lock; storage must not stall heartbeats)
        self._journal_append(recs)
        if need_ckpt:
            # periodic metadata checkpoint: a master restart mid-bulk finds
            # committed-so-far tables in the megafile and resumes from the
            # persisted done-set.  Written OUTSIDE the control-plane lock —
            # the Database has its own lock, and stalling heartbeats on a
            # storage write would let the stale scan deactivate live
            # workers.
            self.db.write_megafile()
            self._persist_bulk_progress(bulk)
        if finished_now:
            self._clear_bulk_checkpoint(bulk.bulk_id)
        return reply

    def _rpc_finished_work_batch(self, req: dict) -> dict:
        """Coalesced completions (engine/shardmap.py): many FinishedWork
        payloads in one RPC with ONE journal group-commit — the batch
        is durable before any item is acked, so the write-ahead
        contract holds for every item exactly as it does for the
        singleton path.  Per-item replies ride back positionally so the
        worker can dispatch revocation/gang-stale outcomes per task."""
        items = list(req.get("items") or ())
        recs: List[dict] = []
        replies: List[dict] = []
        need_ckpt = finished_now = False
        bulk = None
        with self._lock:
            for item in items:
                it = dict(item)
                it.setdefault("bulk_id", req.get("bulk_id"))
                it.setdefault("worker_id", req.get("worker_id"))
                if "clock" not in it and req.get("clock"):
                    it["clock"] = req["clock"]
                r, ck, fin, b = self._finished_work_locked(it, recs)
                replies.append(r)
                need_ckpt = need_ckpt or ck
                finished_now = finished_now or fin
                bulk = b if b is not None else bulk
        self._journal_append(recs)
        _shardmap.count_coalesced("FinishedWork",
                                  max(0, len(items) - 1))
        if need_ckpt and bulk is not None:
            self.db.write_megafile()
            self._persist_bulk_progress(bulk)
        if finished_now and bulk is not None:
            self._clear_bulk_checkpoint(bulk.bulk_id)
        return {"ok": all(r.get("ok") for r in replies),
                "replies": replies}

    def _finished_work_locked(self, req: dict, recs: List[dict]
                              ) -> Tuple[dict, bool, bool,
                                         Optional[_BulkJob]]:
        """One completion applied under self._lock (shared by the
        singleton and batch handlers).  Returns (reply, need_ckpt,
        finished_now, bulk); the caller journals `recs` and runs the
        checkpoint/cleanup I/O outside the lock."""
        key = (req["job_idx"], req["task_idx"])
        with self._lock:  # reentrant: both callers already hold it
            self._touch_worker(req.get("worker_id"))
            bulk = self._bulk
            if bulk is None or bulk.bulk_id != req["bulk_id"]:
                return {"ok": False}, False, False, None
            # piggybacked trace spans (the worker drains its export
            # buffer into every FinishedWork, so no second RPC rides
            # the per-task hot path): absorbed before the revocation
            # check — a revoked attempt's spans are still real history.
            # The master's OWN spans drain here too: on a large bulk
            # the assign spans would otherwise pool in the tracer's
            # export buffer (cap 65536) until end-of-bulk and overflow.
            self._drain_master_spans_locked()
            self._intake_clock_locked(bulk, req)
            self._absorb_batch_locked(bulk, req.get("spans") or ())
            if bulk.gang_hosts and req.get("gang_id") is not None:
                # gang single-writer commit: only member 0 of the LIVE
                # gang at the CURRENT epoch may complete the task —
                # a completion from an aborted epoch (the gang
                # re-formed underneath a slow writer) or from a
                # non-coordinator member is NACKed, never applied, so
                # the sink commit is exactly-once per task
                g = self._gang_for_req_locked(bulk, req, "FinishedWork")
                if g is None or req.get("worker_id") != g.members[0]:
                    if g is not None:
                        _gang.count_stale_nack("FinishedWork")
                    return {"ok": False, "revoked": True,
                            "gang_stale": True}, False, False, bulk
                # accepted: retire the gang — survivors' late acks are
                # acknowledged via the retired map, and their held
                # slots release here
                if req.get("shard_digests") is not None:
                    self._fold_gang_shards_locked(g, req)
                bulk.gangs.pop(g.gang_id, None)
                bulk.gang_by_task.pop(g.key, None)
                bulk.gang_retired[g.gang_id] = g.epoch
                while len(bulk.gang_retired) > 64:
                    bulk.gang_retired.pop(
                        next(iter(bulk.gang_retired)))
                for m in g.members[1:]:
                    if m not in g.acks:
                        self._dec_held(bulk, m)
            # a completion only counts if this worker still holds the
            # assignment WITH the same attempt id — revoked
            # (timed-out/reassigned) attempts are ignored, the in-process
            # equivalent of the reference killing the slow worker
            # (stop_job_on_worker, master.cpp:2111)
            cur = bulk.outstanding.get(key)
            if cur is None or cur[0] != req.get("worker_id") \
                    or cur[2] != req.get("attempt"):
                return {"ok": False, "revoked": True}, False, False, \
                    bulk
            self._unassign(bulk, key)
            if key in bulk.done or key[0] in bulk.blacklisted_jobs:
                return {"ok": True}, False, False, bulk
            bulk.done.add(key)
            recs.append({"t": "done", "j": key[0], "k": key[1]})
            bulk.job_done[key[0]] = bulk.job_done.get(key[0], 0) + 1
            bulk.stage_rows["save"] += bulk.task_rows.get(key, 0)
            _M_TASKS_DONE.inc()
            # end-to-end latency, enqueue (bulk admission made the task
            # runnable) -> sink-committed: the serving-mode p50/p99 seed
            _M_TASK_LATENCY.observe(time.time() - bulk.admitted_at)
            _mlog.debug("task (%d,%d) finished by worker %d "
                        "(%d/%d done)", key[0], key[1],
                        req.get("worker_id", -1), len(bulk.done),
                        bulk.total_tasks)
            self._maybe_finish_job(bulk, key[0], recs=recs)
            need_ckpt = (bulk.checkpoint_frequency > 0 and not bulk.finished
                         and len(bulk.done) % bulk.checkpoint_frequency == 0)
            self._maybe_finish_bulk(bulk)
            return {"ok": True}, need_ckpt, bulk.finished, bulk

    def _rpc_failed_work(self, req: dict) -> dict:
        key = (req["job_idx"], req["task_idx"])
        err = req.get("error", "")
        recs: List[dict] = []
        with self._lock:
            self._touch_worker(req.get("worker_id"))
            bulk = self._bulk
            if bulk is None or bulk.bulk_id != req["bulk_id"]:
                return {"ok": False}
            cur = bulk.outstanding.get(key)
            if cur is None or cur[0] != req.get("worker_id") \
                    or cur[2] != req.get("attempt"):
                return {"ok": False, "revoked": True}
            self._unassign(bulk, key)
            if key in bulk.done:
                return {"ok": True}
            strike_free = False
            if req.get("transient"):
                # past the cap, a "transient" failure that never stops
                # isn't: fall through and strike like any other
                if self._count_transient_locked(bulk, key, recs):
                    _M_TRANSIENT.inc()
                    _M_TASK_RETRIES.inc()
                    _mlog.warning(
                        "task (%d,%d) transient failure on worker %d "
                        "(%d/%d before strikes begin): %s — requeued "
                        "without a blacklist strike", key[0], key[1],
                        req.get("worker_id", -1),
                        bulk.transient_failures[key],
                        MAX_TRANSIENT_FAILURES, err)
                    bulk.q_push(key, front=True)
                    strike_free = True
            blacklisted_now = finished_now = False
            if not strike_free:
                # job blacklisting past the strike cap (reference
                # master.cpp:2161-2191): one poison stream cannot sink
                # the bulk job
                blacklisted_now = self._count_strike_locked(
                    bulk, key, err, recs)
                _mlog.warning("task (%d,%d) failed on worker %d "
                              "(failure %d/%d): %s", key[0], key[1],
                              req.get("worker_id", -1),
                              bulk.failures[key],
                              MAX_TASK_FAILURES, err)
                self._maybe_finish_bulk(bulk)
                finished_now = bulk.finished
        # write-ahead: durable before the ack (outside the lock)
        self._journal_append(recs)
        if strike_free:
            return {"ok": True}
        if blacklisted_now and not finished_now:
            # a restarted master must not resurrect the poisoned job
            self._persist_bulk_progress(bulk)
        if finished_now:
            self._clear_bulk_checkpoint(bulk.bulk_id)
        return {"ok": True}

    def _job_status_locked(self, bulk: _BulkJob) -> dict:
        """One source of truth for job progress: the GetJobStatus reply,
        the client progress bar, and /statusz all read this.  Caller
        holds self._lock."""
        if bulk.compacted and bulk.status_frozen is not None:
            # compacted historical bulk: the heavy per-task state is
            # gone; serve the snapshot frozen at compaction (worker
            # liveness stays live — it is a cluster fact, not a bulk one)
            st = dict(bulk.status_frozen)
            st["num_workers"] = sum(1 for w in self._workers.values()
                                    if w.active)
            return st
        # freeze the clock at bulk completion: a historical bulk queried
        # later must report its real throughput, not a decayed one
        end = bulk.finished_at or time.time()
        elapsed = max(end - bulk.admitted_at, 1e-6)
        # fps per stage from the master-observed transitions; after a
        # master restart these count post-recovery progress only, so the
        # ETA reflects the live completion rate
        stage_fps = {s: round(r / elapsed, 2)
                     for s, r in bulk.stage_rows.items()}
        active_total = bulk.total_tasks - bulk.blacklisted_task_total
        active_done = len(bulk.done) - bulk.done_in_blacklisted
        eta = None
        done_since_start = active_done - bulk.done_at_start
        if not bulk.finished and done_since_start > 0:
            rate = done_since_start / elapsed
            eta = round((active_total - active_done) / rate, 1)
        per_job = {}
        for j, tasks in bulk.job_tasks.items():
            per_job[j] = {"tasks_done": bulk.job_done.get(j, 0),
                          "tasks_total": len(tasks),
                          "blacklisted": j in bulk.blacklisted_jobs}
        return {
            "finished": bulk.finished,
            "tasks_done": len(bulk.done),
            "total_tasks": bulk.total_tasks,
            "stage_fps": stage_fps,
            "eta_seconds": eta,
            "elapsed_seconds": round(elapsed, 1),
            "per_job": per_job,
            "failed_jobs": sorted(bulk.blacklisted_jobs),
            "error": bulk.error,
            "num_workers": sum(1 for w in self._workers.values()
                               if w.active),
            # straggler analytics from shipped spans: per-stage stats +
            # top-N slowest tasks with trace ids (also on /statusz)
            "trace_id": bulk.trace_id,
            "stragglers": self._stragglers_locked(bulk),
        }

    def _rpc_job_status(self, req: dict) -> dict:
        with self._lock:
            bulk = self._history.get(req["bulk_id"]) \
                if req.get("bulk_id") is not None else self._bulk
            if bulk is None:
                # still report cluster liveness: lets tooling (e.g.
                # tools/chaos_run.py) wait for workers to register
                # before submitting anything
                st = {"error": "no such bulk job",
                      "num_workers": sum(
                          1 for w in self._workers.values()
                          if w.active)}
            else:
                st = self._job_status_locked(bulk)
        # the master-local health roll-up rides on every status poll
        # (added OUTSIDE the control-plane lock: the engine has a lock
        # of its own) — the 4 Hz client poll and scanner_top see
        # degradation without a second RPC
        st["health"] = _health.rollup()
        return st

    def _statusz(self) -> dict:
        """JSON body of /statusz: live job progress + worker liveness."""
        now = time.time()
        with self._lock:
            workers = [{"worker_id": w.worker_id, "address": w.address,
                        "active": w.active,
                        "heartbeat_age_seconds": round(now - w.last_seen,
                                                       3)}
                       for w in self._workers.values()]
            bulk = self._bulk
            status = self._job_status_locked(bulk) \
                if bulk is not None else None
            bulk_id = bulk.bulk_id if bulk is not None else None
            mem_reports = len(self._mem_reports)
            # the Gang panel (docs/robustness.md §Gang scheduling):
            # live gangs with their epoch fence + the forming pool
            gang_panel = None
            if bulk is not None and bulk.gang_hosts:
                gang_panel = {
                    "gang_hosts": bulk.gang_hosts,
                    "epoch": bulk.gang_epoch,
                    "forming": sorted(bulk.gang_forming),
                    "live": [{"gang_id": g.gang_id, "epoch": g.epoch,
                              "job": g.key[0], "task": g.key[1],
                              "members": list(g.members),
                              "coordinator": g.coordinator,
                              "age_s": round(now - g.formed_at, 3)}
                             for g in bulk.gangs.values()],
                    # per-gang straggler attribution (newest first):
                    # slowest member, lag vs median arrival, and the
                    # barrier/collective verdict — the skew panel
                    # (docs/observability.md §Cross-host time)
                    "skew": list(reversed(bulk.gang_skew_rows))}
        return {"role": "master", "workers": workers,
                "bulk_id": bulk_id, "bulk": status,
                "gang": gang_panel,
                # the fencing epoch (docs/robustness.md §Durable
                # control plane): fenced=True means a successor owns
                # this db and every mutating RPC here is rejected
                "generation": self.generation,
                "fenced": self._fence.is_set(),
                # the Shard panel (docs/robustness.md §Sharded control
                # plane): which partition this master serves and the
                # map epoch its stale-map fence sits at
                "shard": {"shard_id": self.shard_id,
                          "num_shards": self.num_shards,
                          "map_epoch": self._map_epoch},
                # the Health panel: this process's roll-up + firing
                # alerts (util/health.py; outside the control lock)
                "health": _health.status_dict(),
                # the Memory panel: this process's HBM/ledger view plus
                # how many worker OOM reports are held for
                # GetMemoryReport
                "memory": dict(_memstats.status_dict(),
                               worker_reports=mem_reports),
                # the Frame-cache panel: per-device page pool occupancy
                # and hit rates (engine/framecache.py; a bare master
                # usually has none — workers hold the pages)
                "framecache": _framecache.status_dict(),
                # the Efficiency panel: roofline table + compile-ledger
                # summary (util/coststats.py; a bare master usually has
                # none — workers carry the kernel calls)
                "efficiency": _coststats.status_dict(),
                # the Remediation panel: playbook table + newest audit
                # entries, plus this master's gates
                "remediation": dict(
                    _controller.status_dict(),
                    admission_paused=self._admission_paused,
                    autoscale_desired=self.autoscaler.desired()
                    if self.autoscaler else None)}

    def _rpc_get_metrics(self, req: dict) -> dict:
        """Cluster-wide metrics: this process's snapshot plus every live
        worker's, merged under per-node labels.  The one place the
        master dials workers (at the address each worker advertised at
        registration) — a diagnostic pull outside the job data/control
        plane (which stays strictly worker-pull-based).  Dials run
        concurrently with a short deadline so one wedged worker cannot
        pin an RPC-server thread for the whole scrape, and an
        unreachable worker just drops out of the merged view."""
        from concurrent import futures as _fut

        with self._lock:
            targets = [(w.worker_id, w.address)
                       for w in self._workers.values()
                       if w.active and w.address]
        by_node: Dict[str, dict] = {"master": _mx.registry().snapshot()}

        def pull(wid: int, addr: str):
            c = rpc.RpcClient(addr, WORKER_SERVICE, timeout=2.0)
            try:
                return wid, c.try_call("GetMetrics", retries=0)
            finally:
                c.close()

        # req["workers"]=False: shard fan-in pulls workers through ONE
        # shard only (every shard sees the same fleet; duplicating the
        # worker dials M times would skew the merged counters M-fold)
        if targets and req.get("workers", True):
            with _fut.ThreadPoolExecutor(
                    max_workers=min(16, len(targets))) as pool:
                for wid, reply in pool.map(lambda t: pull(*t), targets):
                    if reply and "snapshot" in reply:
                        by_node[f"worker{wid}"] = reply["snapshot"]
        return {"snapshot": merge_snapshots(by_node),
                "nodes": sorted(by_node)}

    def _rpc_get_health(self, req: dict) -> dict:
        """Cluster-wide health: this process's roll-up plus every live
        worker's (GetHealth dialed at each worker's advertised address,
        the same diagnostic pull plane as GetMetrics), combined into
        one worst-of status with node-prefixed reason codes —
        Client.health() and the scanner_top ALERTS section read this."""
        from concurrent import futures as _fut

        with self._lock:
            targets = [(w.worker_id, w.address)
                       for w in self._workers.values()
                       if w.active and w.address]
        nodes: Dict[str, dict] = {"master": _health.status_dict()}

        def pull(wid: int, addr: str):
            c = rpc.RpcClient(addr, WORKER_SERVICE, timeout=2.0)
            try:
                return wid, c.try_call("GetHealth", retries=0)
            finally:
                c.close()

        if targets and req.get("workers", True):
            with _fut.ThreadPoolExecutor(
                    max_workers=min(16, len(targets))) as pool:
                for wid, reply in pool.map(lambda t: pull(*t), targets):
                    if reply and "health" in reply:
                        nodes[f"worker{wid}"] = reply["health"]
        return _health.merge_status(nodes)

    def _rpc_get_compile_ledger(self, req: dict) -> dict:
        """Cluster-wide compile ledger + roofline table: this process's
        compile report plus every live worker's (GetCompileLedger
        dialed at each worker's advertised address — the same
        diagnostic pull plane as GetMetrics/GetHealth).
        Client.compile_report() and tools/scanner_cost.py read this."""
        from concurrent import futures as _fut

        with self._lock:
            targets = [(w.worker_id, w.address)
                       for w in self._workers.values()
                       if w.active and w.address]
        nodes: Dict[str, dict] = {"master": _coststats.compile_report()}

        def pull(wid: int, addr: str):
            c = rpc.RpcClient(addr, WORKER_SERVICE, timeout=2.0)
            try:
                return wid, c.try_call("GetCompileLedger", retries=0)
            finally:
                c.close()

        if targets and req.get("workers", True):
            with _fut.ThreadPoolExecutor(
                    max_workers=min(16, len(targets))) as pool:
                for wid, reply in pool.map(lambda t: pull(*t), targets):
                    if reply and "report" in reply:
                        nodes[f"worker{wid}"] = reply["report"]
        return {"nodes": nodes}

    def _rpc_poke(self, req: dict) -> dict:
        self._last_poke = time.time()
        return {"ok": True}

    # -- remediation actions (engine/controller.py binds these) -------------

    def _pause_admission(self, transition: dict) -> str:
        """admission_pause playbook, firing side: running bulks keep
        flowing; NEW NewJob admissions answer retryable until the
        backpressure resolves and the hysteresis hold elapses."""
        reason = transition.get("rule", "backpressure")
        lbl = transition.get("labels") or {}
        if lbl:
            reason += "[" + ",".join(
                f"{k}={v}" for k, v in sorted(lbl.items())) + "]"
        with self._lock:
            self._admission_paused = reason
        _M_ADMISSION_PAUSED.set(1)
        return f"admission paused ({reason})"

    def _resume_admission(self, transition: dict) -> str:
        with self._lock:
            self._admission_paused = None
        _M_ADMISSION_PAUSED.set(0)
        return "admission resumed"

    def _autoscale_nudge(self, transition: dict) -> Optional[str]:
        """autoscale_up playbook: a device_saturation firing transition
        makes the autoscaler re-evaluate immediately instead of waiting
        for the next periodic observation."""
        target = self._autoscale_observe()
        return None if target is None else f"desired={target}"

    def _autoscale_observe(self) -> Optional[int]:
        """Feed the autoscaler one observation of the cluster: live
        worker count (preempting workers excluded — their capacity is
        already leaving), master queue depth + outstanding tasks, and
        how many workers report device_saturation firing."""
        a = self.autoscaler
        if a is None:
            return None
        with self._lock:
            workers = sum(1 for w in self._workers.values()
                          if w.active and not w.preempting)
            saturated = sum(
                1 for w in self._workers.values()
                if w.active and "device_saturation" in w.firing)
            bulk = self._bulk
            if bulk is not None and not bulk.finished:
                queued = bulk.q_count()
                outstanding = len(bulk.outstanding)
            else:
                queued = outstanding = 0
        # the master's own engine may also see saturation (in-process
        # clusters share one registry) — count it once
        if not saturated and any(
                f.get("rule") == "device_saturation"
                for f in _health.status_dict().get("firing", ())):
            saturated = 1
        return a.observe(workers=workers, queued=queued,
                         outstanding=outstanding,
                         saturated_workers=saturated)

    def _fold_worker_alerts(self) -> None:
        """Translate worker-reported firing alerts (heartbeat `firing`
        field) into cluster-level transitions for the remediation
        controller: stage_backpressure fires inside worker processes,
        but the admission gate it must actuate lives here."""
        if not _controller.enabled():
            return
        with self._lock:
            union: Set[str] = set()
            for w in self._workers.values():
                if w.active:
                    union |= w.firing
            fired = union - self._worker_firing
            resolved = self._worker_firing - union
            self._worker_firing = union
        ctrl = _controller.controller()
        for rule in sorted(fired):
            ctrl.on_transition({"state": "firing", "rule": rule,
                                "severity": "warning",
                                "labels": {"source": "workers"},
                                "value": None})
        for rule in sorted(resolved):
            ctrl.on_transition({"state": "resolved", "rule": rule,
                                "severity": "warning",
                                "labels": {"source": "workers"},
                                "value": None})

    def _rpc_post_profile(self, req: dict) -> dict:
        with self._lock:
            bulk = self._history.get(req["bulk_id"])
            if bulk is not None:
                bulk.profiles.append(req["profile"])
        return {"ok": True}

    def _rpc_get_profiles(self, req: dict) -> dict:
        with self._lock:
            bulk = self._history.get(req["bulk_id"])
            return {"profiles": list(bulk.profiles) if bulk else []}

    def _trim_history_locked(self) -> None:
        """Bound historical-bulk retention: only the newest
        SPAN_HISTORY_BULKS bulks keep full span stores and per-task
        scheduling state; older finished ones compact to straggler
        aggregates + a frozen status snapshot, which GetJobStatus /
        GetTrace / Client.stragglers keep serving — post-completion
        queries work for the whole ring and degrade (spans only) past
        it, instead of a long-lived master holding every bulk's
        10^5-task done-sets forever.  Caller holds self._lock."""
        for bid in sorted(self._history)[:-SPAN_HISTORY_BULKS]:
            old = self._history[bid]
            if old.finished and not old.compacted:
                old.compact(self._job_status_locked(old))
            else:
                old.spans = []

    # -- trace assembly (util/tracing.py) -----------------------------------

    def _absorb_span_locked(self, bulk: _BulkJob, d: dict) -> None:
        """One shipped span into the bulk's store + the incremental
        straggler aggregates (per-stage stats, slowest-task heap).
        Caller holds self._lock."""
        if bulk.compacted:
            bulk.span_drops += 1  # store dropped at compaction; count,
            # but keep feeding the (retained) aggregates below
        elif len(bulk.spans) < MAX_BULK_SPANS:
            bulk.spans.append(d)
        else:
            bulk.span_drops += 1
        name = d.get("name")
        if not isinstance(name, str):
            return
        dur = max(float(d.get("end") or 0.0)
                  - float(d.get("start") or 0.0), 0.0)
        if name in ("task", "load", "evaluate", "save", "gang") \
                or name.startswith("evaluate:") \
                or name.startswith("gang."):
            st = bulk.span_stats.setdefault(name, [0, 0.0, 0.0])
            st[0] += 1
            st[1] += dur
            st[2] = max(st[2], dur)
        # gang phase spans feed the per-(gang, epoch) barrier-skew fold
        # and the straggler attribution rows (docs/observability.md
        # §Cross-host time)
        if name in ("gang.barrier", "gang.collective"):
            self._fold_gang_phase_locked(bulk, name, d, dur)
        # roofline verdicts ride on the op spans (engine/evaluate.py
        # op.efficiency events); fold them into tiny aggregates so
        # stragglers answer "inefficient or overloaded" per op (the
        # shared fold — tracing.straggler_summary uses the same one)
        _tracing.fold_op_efficiency(d, bulk.eff_stats)
        if name == "task":
            a = d.get("attrs") or {}
            bulk.slow_seq += 1
            heapq.heappush(bulk.slowest, (
                dur, bulk.slow_seq, a.get("job"), a.get("task"),
                d.get("node"), d.get("span_id")))
            if len(bulk.slowest) > STRAGGLER_TOP_N:
                heapq.heappop(bulk.slowest)

    def _fold_gang_phase_locked(self, bulk: _BulkJob, name: str,
                                d: dict, dur: float) -> None:
        """One member's gang.barrier / gang.collective span into the
        per-(gang_id, epoch) fold.  Barrier-entry stamps are corrected
        with the shipping node's clock offset (when trustworthy) so
        the max-min skew compares arrivals on ONE clock; once every
        member reported, the skew histogram observes and an
        attribution row names the slowest member.  Caller holds
        self._lock."""
        a = d.get("attrs") or {}
        try:
            gid, ep = int(a["gang"]), int(a["epoch"])
            member, num = int(a["member"]), int(a["num"])
        except (KeyError, TypeError, ValueError):
            return
        if num <= 0:
            return
        rec = bulk.gang_arrivals.get((gid, ep))
        if rec is None:
            rec = bulk.gang_arrivals[(gid, ep)] = {
                "num": num, "job": a.get("job"), "task": a.get("task"),
                "arrive": {}, "wait": {}, "collective": {}, "node": {},
                "done": False}
            # incomplete folds from gangs that aborted mid-report are
            # garbage after the epoch bumps; bound the map
            if len(bulk.gang_arrivals) > 4 * MAX_GANG_SKEW_ROWS:
                for k in sorted(bulk.gang_arrivals)[
                        :len(bulk.gang_arrivals) - 2 * MAX_GANG_SKEW_ROWS]:
                    if not bulk.gang_arrivals[k]["done"]:
                        del bulk.gang_arrivals[k]
        if rec["done"]:
            return
        node = d.get("node")
        rec["node"][member] = node
        if name == "gang.barrier":
            start = float(d.get("start") or 0.0)
            est = bulk.clock_offsets.get(node) \
                or self._clock_offsets.get(node)
            if _clocksync.should_rebase(est):
                start += float(est["offset"])
            rec["arrive"][member] = start
            rec["wait"][member] = dur
        else:
            rec["collective"][member] = dur
        if len(rec["arrive"]) < num or len(rec["collective"]) < num:
            return
        rec["done"] = True
        arrivals = sorted(rec["arrive"].items(), key=lambda kv: kv[1])
        skew = arrivals[-1][1] - arrivals[0][1]
        _gang.observe_barrier_skew(skew)
        vals = [t for _, t in arrivals]
        median = vals[len(vals) // 2] if len(vals) % 2 \
            else (vals[len(vals) // 2 - 1] + vals[len(vals) // 2]) / 2.0
        slow_member, slow_t = arrivals[-1]
        coll_max = max(rec["collective"].values())
        row = {
            "gang": gid, "epoch": ep,
            "job": rec["job"], "task": rec["task"],
            "skew_s": round(skew, 4),
            "slowest": rec["node"].get(slow_member),
            "member": slow_member,
            "lag_s": round(slow_t - median, 4),
            # the gang step's binding cost: time donated to the last
            # arrival (the skew) vs the post-arrival reduction itself
            "bound": "barrier" if skew >= coll_max else "collective",
            "barrier_wait_max_s": round(max(rec["wait"].values()), 4),
            "collective_max_s": round(coll_max, 4),
        }
        bulk.gang_skew_rows.append(row)
        if len(bulk.gang_skew_rows) > MAX_GANG_SKEW_ROWS:
            del bulk.gang_skew_rows[:len(bulk.gang_skew_rows)
                                    - MAX_GANG_SKEW_ROWS]

    def _drain_master_spans_locked(self) -> None:
        """Move the master's own completed spans (admission, assigns,
        per-task rpc handling) into their bulks' span stores, routed by
        trace_id.  Caller holds self._lock."""
        orphans = []
        for d in self.tracer.drain_export():
            tid = d.get("trace_id")
            for bulk in self._history.values():
                if bulk.trace_id == tid:
                    self._absorb_span_locked(bulk, d)
                    break
            else:
                orphans.append(d)
        # spans for no known bulk (e.g. a pre-admission failure) are
        # dropped — the flight recorder still holds them for a dump
        del orphans

    def _stragglers_locked(self, bulk: _BulkJob) -> dict:
        """Straggler analytics from the incrementally-maintained
        aggregates: per-stage critical-path stats + the top-N slowest
        tasks with their trace ids (jump straight into the merged
        trace).  Shape matches tracing.straggler_summary."""
        per = {}
        for name, (c, tot, mx) in sorted(bulk.span_stats.items()):
            per[name] = {"count": int(c), "total_s": round(tot, 4),
                         "max_s": round(mx, 4),
                         "mean_s": round(tot / c, 4) if c else 0.0}
            # the efficiency join: a slow op at high eff is overloaded
            # (scale it), at low eff inefficient (fix it)
            per[name].update(_tracing.op_efficiency_summary(
                bulk.eff_stats.get(name)))
        slow = [{"job": j, "task": t, "seconds": round(dur, 4),
                 "node": node, "trace_id": bulk.trace_id,
                 "span_id": sid}
                for dur, _seq, j, t, node, sid
                in sorted(bulk.slowest, reverse=True)]
        out = {"per_stage": per, "slowest_tasks": slow,
               "spans": len(bulk.spans),
               "spans_dropped": bulk.span_drops}
        if bulk.gang_skew_rows:
            # per-gang straggler attribution (newest first): which host
            # made each gang slow, by how much, and whether the step
            # was barrier-bound or collective-bound
            out["gangs"] = list(reversed(bulk.gang_skew_rows))
        return out

    def _absorb_batch_locked(self, bulk: _BulkJob, spans) -> None:
        """A shipped batch into the assembly, routed by trace_id —
        stale buffer content from a previous bulk goes home instead of
        polluting this trace.  Caller holds self._lock."""
        for d in spans:
            if isinstance(d, dict) and d.get("trace_id"):
                if d["trace_id"] == bulk.trace_id:
                    self._absorb_span_locked(bulk, d)
                else:
                    for other in self._history.values():
                        if other.trace_id == d["trace_id"]:
                            self._absorb_span_locked(other, d)
                            break

    def _rpc_ship_spans(self, req: dict) -> dict:
        """Out-of-band span shipping: task-completion spans piggyback
        on FinishedWork instead, so this carries the rest — failed
        attempts, the worker's final flush, the client's root span."""
        with self._lock:
            self._touch_worker(req.get("worker_id"))
            self._drain_master_spans_locked()
            bulk = self._history.get(req["bulk_id"])
            if bulk is None:
                return {"ok": False}
            self._intake_clock_locked(bulk, req)
            self._absorb_batch_locked(bulk, req.get("spans") or [])
        return {"ok": True}

    def _intake_clock_locked(self, bulk: _BulkJob, req: dict) -> None:
        """The shipping worker's contemporaneous clock estimate rides
        every span batch ("clock"): refresh the bulk's per-node rebase
        map so GetTrace corrects these spans with the estimate that
        was live when they were stamped.  Caller holds self._lock."""
        est = req.get("clock")
        wid = req.get("worker_id")
        if est and wid is not None and _clocksync.enabled():
            node = f"worker{wid}"
            self._clock_offsets[node] = dict(est)
            if not bulk.compacted:
                bulk.clock_offsets[node] = dict(est)
            _clocksync.publish(node, est)

    def _rpc_get_trace(self, req: dict) -> dict:
        """The assembled cross-host trace of one bulk: every shipped
        worker span plus the master's own, and the straggler summary
        (Client.trace / tools/scanner_trace.py).  Spans are stored
        RAW; remote nodes' timestamps are rebased onto master time at
        read time from the per-node clock offsets — unless the caller
        asks for raw_clocks, rebase is disabled ([trace]
        rebase_clocks), or a node's offset uncertainty exceeds the
        alignment threshold (that node keeps raw stamps; a wrong
        correction smears more than it aligns)."""
        with self._lock:
            bulk = self._history.get(req["bulk_id"]) \
                if req.get("bulk_id") is not None else self._bulk
            if bulk is None:
                return {"error": "no such bulk job"}
            self._drain_master_spans_locked()
            spans = list(bulk.spans)
            offsets = dict(self._clock_offsets)
            offsets.update(bulk.clock_offsets)
            stragglers = self._stragglers_locked(bulk)
            trace_id = bulk.trace_id
            drops = bulk.span_drops
        rebased = False
        if offsets and not req.get("raw_clocks") \
                and _clocksync.rebase_enabled():
            spans = _clocksync.rebase_spans(spans, offsets)
            rebased = any(d.get("clock_rebased") for d in spans)
        return {"trace_id": trace_id,
                "spans": spans,
                "spans_dropped": drops,
                "clock_offsets": offsets,
                "clock_rebased": rebased,
                "stragglers": stragglers}

    # -- memory observability (util/memstats.py) -----------------------------

    def _rpc_ship_memory_report(self, req: dict) -> dict:
        """Workers push their one-shot OOM memory reports here (the
        ShipSpans-style out-of-band path): a worker that OOMs — or
        dies shortly after — leaves its forensics on the master."""
        report = req.get("report")
        if isinstance(report, dict):
            report = dict(report)
            # the report stamps its own origin node; the shipper's id
            # is only the fallback (any sibling worker may ship it)
            if not report.get("node"):
                report["node"] = f"worker{req.get('worker_id', '?')}"
            with self._lock:
                self._mem_reports.append(report)
            _mlog.warning(
                "memory report from worker %s: %s",
                req.get("worker_id"), report.get("reason", ""))
        return {"ok": True}

    def _rpc_get_memory_report(self, req: dict) -> dict:
        """The cluster memory view (Client.memory_report()): this
        process's live memstats snapshot plus every OOM report workers
        shipped, newest last."""
        with self._lock:
            reports = list(self._mem_reports)
        own = _memstats.last_report()
        if own is not None:
            own = dict(own)
            if not own.get("node"):
                own["node"] = "master"
            # in-process clusters share the memstats module: "our own"
            # report may be the very one a worker already shipped —
            # don't serve it twice
            if not any(r.get("seq") == own.get("seq")
                       and r.get("node") == own.get("node")
                       for r in reports):
                reports.append(own)
        return {"memory": _memstats.status_dict(), "reports": reports}

    def _rpc_shutdown(self, req: dict) -> dict:
        """Remote cluster stop (Client.shutdown_cluster / blocking
        start_master deployments).  Forwards Shutdown to every live
        registered worker first (unless workers=False) — their blocking
        wait_for_shutdown loops exit 0 — then releases this master's
        own wait_for_shutdown.  Best-effort fan-out with the ping
        deadline: an unreachable worker is already dead or draining."""
        notified = 0
        if req.get("workers", True):
            from concurrent import futures as _fut

            with self._lock:
                targets = [w.address for w in self._workers.values()
                           if w.active and w.address]

            def poke(addr: str) -> bool:
                c = rpc.RpcClient(addr, WORKER_SERVICE,
                                  timeout=PING_TIMEOUT)
                try:
                    return c.try_call("Shutdown", retries=0) is not None
                finally:
                    c.close()

            if targets:
                # concurrent like _rpc_get_metrics: a fleet of
                # unreachable workers each costs PING_TIMEOUT — serially
                # that would blow the caller's Shutdown deadline
                with _fut.ThreadPoolExecutor(
                        max_workers=min(16, len(targets))) as pool:
                    notified = sum(pool.map(poke, targets))
        self._shutdown.set()
        return {"ok": True, "workers_notified": notified}

    # -- bulk checkpoint / recovery -----------------------------------------

    def _record_admission_token_locked(self, token: str,
                                       bulk_id: int) -> None:
        """Remember a NewJob admission token for dedupe, bounded by the
        insertion ring.  Caller holds self._lock."""
        if token in self._admission_tokens:
            self._admission_tokens[token] = bulk_id
            return
        self._admission_tokens[token] = bulk_id
        self._admission_token_ring.append(token)
        while len(self._admission_token_ring) > _journal.TOKEN_RING:
            old = self._admission_token_ring.popleft()
            self._admission_tokens.pop(old, None)

    @staticmethod
    def _bulk_checkpoint_state(bulk: _BulkJob) -> dict:
        """The admission state needed to resume this bulk after a
        master restart.  Small by construction: the spec blob plus task
        geometry — per-job sink names/custom sinks are re-derived on
        recovery via prepare_readonly (the same derivation workers
        run).  Written as the checkpoint AND journaled as the `admit`
        record, so either survives the other's corruption."""
        return {
            "bulk_id": bulk.bulk_id,
            "spec_blob": bulk.spec_blob,
            "task_timeout": bulk.task_timeout,
            "checkpoint_frequency": bulk.checkpoint_frequency,
            "job_ntasks": {j: len(ts) for j, ts in bulk.job_tasks.items()},
            "job_output_rows": dict(bulk.job_output_rows),
            "sticky": bulk.sticky,
            "gang_hosts": bulk.gang_hosts,
            "gang_sharded": bulk.gang_sharded,
            "gang_halo": bulk.gang_halo,
            "token": bulk.admission_token,
        }

    def _persist_bulk_checkpoint(self, bulk: _BulkJob) -> None:
        """Persist admission state (generation-scoped, checksummed) and
        open a fresh journal for the bulk, with the same state as its
        first record — a corrupt checkpoint then falls back to journal
        replay instead of dropping the bulk."""
        if self._fence.is_set():
            return
        state = self._bulk_checkpoint_state(bulk)
        blob = seal_blob(cloudpickle.dumps(state))
        self.db.backend.write(
            md.bulk_checkpoint_path(self.generation, self.shard_id),
            blob)
        if self._journal is not None:
            self._journal.reset()
            self._journal_append([{"t": "admit", "state": state}])

    @staticmethod
    def _encode_task_set(tasks) -> Dict[int, List[int]]:
        """{job: [s0, e0, s1, e1, ...]} half-open runs — tasks complete
        mostly in order, so a million-task done-set encodes in a few
        runs per job instead of 10^6 tuples per checkpoint write."""
        by_job: Dict[int, List[int]] = {}
        for j, t in tasks:
            by_job.setdefault(j, []).append(t)
        out: Dict[int, List[int]] = {}
        for j, ts in by_job.items():
            ts.sort()
            runs: List[int] = []
            s = p = ts[0]
            for t in ts[1:]:
                if t == p + 1:
                    p = t
                    continue
                runs += [s, p + 1]
                s = p = t
            runs += [s, p + 1]
            out[j] = runs
        return out

    @staticmethod
    def _decode_task_set(enc: Dict[int, List[int]]) -> Set[Tuple[int, int]]:
        return {(j, t) for j, runs in enc.items()
                for i in range(0, len(runs), 2)
                for t in range(runs[i], runs[i + 1])}

    def _persist_bulk_progress(self, bulk: _BulkJob) -> None:
        """Snapshot completion state (under the lock) and write it (storage
        I/O must not stall heartbeats, so callers invoke this outside).
        The journal is cut at the snapshot point: every record the
        snapshot covers lives in a sealed segment below the cut, so
        compaction after the write bounds replay to one checkpoint
        window without ever deleting an uncovered record."""
        if self._fence.is_set():
            return
        with self._lock:
            # C-speed snapshot only; the Python-level run-length encode
            # happens outside so heartbeats/NextWork never wait on it
            done = set(bulk.done)
            prog = {
                "bulk_id": bulk.bulk_id,
                "failures": dict(bulk.failures),
                "transient_failures": dict(bulk.transient_failures),
                "blacklisted_jobs": sorted(bulk.blacklisted_jobs),
                "committed_jobs": sorted(bulk.committed_jobs),
                "error": bulk.error,
                "token": bulk.admission_token,
                # the gang fence's high-water mark: a successor must
                # mint strictly higher epochs than any this master
                # handed out (the journal's gang records cover the
                # checkpoint window on top of this)
                "gang_epoch": bulk.gang_epoch,
            }
            # cut INSIDE the state lock: a mutation not yet in this
            # snapshot can only be journaled after its (post-snapshot)
            # apply, which lands at or above the cut and survives
            cut = self._journal.cut() if self._journal is not None \
                else None
        prog["done_runs"] = self._encode_task_set(done)
        self.db.backend.write(
            md.bulk_progress_path(self.generation, self.shard_id),
            seal_blob(cloudpickle.dumps(prog)))
        if cut is not None and self._journal is not None:
            self._journal.compact_below(cut)
            # re-seed the admit record: compaction may have deleted the
            # segment carrying it, and the corrupt-checkpoint fallback
            # needs admission state IN the journal at all times
            self._journal_append(
                [{"t": "admit",
                  "state": self._bulk_checkpoint_state(bulk)}])

    def _clear_bulk_checkpoint(self, bulk_id: Optional[int] = None) -> None:
        """Remove the (single, fixed-path) bulk checkpoint — but never a
        NEWER active bulk's: callers run outside the control-plane lock,
        so a NewJob admission can land between a bulk finishing and its
        delayed cleanup.  The admission lock serializes us against the
        admission sequence (which writes the new checkpoint while holding
        it)."""
        if self._fence.is_set():
            return  # the successor owns (and clears) control state now
        with self._admit_lock:
            if bulk_id is not None:
                with self._lock:
                    cur = self._bulk
                    if cur is not None and not cur.finished \
                            and cur.bulk_id != bulk_id:
                        return  # a newer active bulk owns the path
            # same contract as the legacy deletes below (baselined):
            # the admission lock exists to serialize storage-mutating
            # admission + checkpoint cleanup end-to-end
            self.db.backend.delete(md.bulk_checkpoint_path(self.generation, self.shard_id))  # scanner-check: disable=SC202 admission lock serializes checkpoint cleanup by design (see baseline twin)
            self.db.backend.delete(md.bulk_progress_path(self.generation, self.shard_id))  # scanner-check: disable=SC202 admission lock serializes checkpoint cleanup by design (see baseline twin)
            if self._journal is not None:
                self._journal.reset()
            # legacy fixed-path state from pre-fencing masters
            self.db.backend.delete(
                md.bulk_checkpoint_path(shard=self.shard_id))
            self.db.backend.delete(
                md.bulk_progress_path(shard=self.shard_id))

    def _load_sealed(self, path: str, what: str) -> Optional[bytes]:
        """Read a (possibly legacy-unsealed) control-plane blob —
        payload, or None (ERROR-logged) on checksum failure so the
        caller falls back to journal replay instead of silently
        resurrecting garbage (or, as the pre-seal code did, silently
        dropping the whole bulk).  One shared policy with tooling
        (journal.read_control_blob)."""
        return _journal.read_control_blob(self.db.backend, path,
                                          what=what)

    def _find_recovery_source(self):
        """Locate the newest predecessor generation (or the legacy
        fixed path) holding bulk state.  Returns (source_gen-or-None,
        admission_state, journal_records, journal_stats) or None."""
        gens = [g for g in
                _journal.claimed_generations(self.db.backend,
                                             shard=self.shard_id)
                if g < self.generation]
        for g in sorted(gens, reverse=True) + [None]:
            records: List[dict] = []
            jstats: Dict[str, int] = {}
            if g is not None:
                records, jstats = _journal.replay(
                    self.db.backend, g, shard=self.shard_id)
            state = None
            payload = self._load_sealed(
                md.bulk_checkpoint_path(g, self.shard_id),
                "bulk checkpoint")
            if payload is not None:
                try:
                    state = cloudpickle.loads(payload)
                except Exception:  # noqa: BLE001
                    _mlog.error(
                        "bulk checkpoint at generation %s is "
                        "undecodable: falling back to journal replay",
                        g)
            if state is None:
                # the journaled `admit` record carries the same
                # admission state the checkpoint does — a corrupt
                # checkpoint costs nothing when the journal survives
                for r in records:
                    if r.get("t") == "admit" \
                            and isinstance(r.get("state"), dict):
                        state = r["state"]
            if state is not None:
                return g, state, records, jstats
        return None

    @staticmethod
    def _apply_journal_records(bulk: _BulkJob, records) -> int:
        """Replay journal records over the progress snapshot.
        Idempotent by construction — done/blacklist/commit records
        union, strike/transient records carry their cumulative count —
        so a record that raced the snapshot applies safely twice."""
        applied = 0
        for r in records:
            t = r.get("t")
            if t == "done":
                key = (int(r["j"]), int(r["k"]))
                if key in bulk.task_rows and key not in bulk.done:
                    bulk.done.add(key)
                    applied += 1
            elif t == "strike":
                key = (int(r["j"]), int(r["k"]))
                bulk.failures[key] = max(bulk.failures.get(key, 0),
                                         int(r.get("n", 1)))
            elif t == "transient":
                key = (int(r["j"]), int(r["k"]))
                bulk.transient_failures[key] = max(
                    bulk.transient_failures.get(key, 0),
                    int(r.get("n", 1)))
            elif t == "blacklist":
                j = int(r["j"])
                if j not in bulk.blacklisted_jobs:
                    bulk.blacklisted_jobs.add(j)
                    applied += 1
                if not bulk.error and r.get("error"):
                    bulk.error = str(r["error"])
            elif t == "commit":
                bulk.committed_jobs.add(int(r["j"]))
            elif t == "gang":
                bulk.next_gang_id = max(bulk.next_gang_id,
                                        int(r.get("g", 0)) + 1)
        # gang-in-flight records restore the epoch fence's high-water
        # mark (journal.gang_epoch_high_water — one fold shared with
        # tooling): a successor's first formation mints a strictly
        # higher epoch, so a pre-failover gang's late completion can
        # never be confused with a live one's (no double-commit
        # across the failover)
        bulk.gang_epoch = max(bulk.gang_epoch,
                              _journal.gang_epoch_high_water(records))
        return applied

    def _drop_recovery_source(self, g: Optional[int]) -> None:
        """Delete a predecessor generation's control state once the
        bulk has been migrated under this master's generation (a crash
        before this leaves both copies; the next recovery prefers the
        newer one)."""
        if g is None:
            self.db.backend.delete(
                md.bulk_checkpoint_path(shard=self.shard_id))
            self.db.backend.delete(
                md.bulk_progress_path(shard=self.shard_id))
        else:
            self.db.backend.delete_prefix(
                md.generation_dir(g, self.shard_id))

    def _recover_bulk(self) -> None:
        """Resume the bulk job a previous master process left behind:
        admission checkpoint (or the journaled admit record when the
        checkpoint is corrupt) + progress snapshot + write-ahead
        journal replay — zero acknowledged completions lost."""
        src = self._find_recovery_source()
        if src is None:
            return
        source_gen, state, records, jstats = src
        try:
            spec = cloudpickle.loads(state["spec_blob"])
            ex = LocalExecutor(self.db)
            _info, jobs = ex.prepare_readonly(spec["outputs"], spec["perf"])
        except Exception:  # noqa: BLE001
            # an unreadable checkpoint must not brick the master; the bulk
            # is lost (client reruns it), new jobs proceed
            _mlog.exception("bulk recovery failed; dropping checkpoint")
            try:
                self._drop_recovery_source(source_gen)
                self._clear_bulk_checkpoint()
            except Exception:  # noqa: BLE001
                pass
            return
        bulk = _BulkJob(
            bulk_id=state["bulk_id"], spec_blob=state["spec_blob"],
            task_timeout=state["task_timeout"],
            checkpoint_frequency=state["checkpoint_frequency"],
            # pre-sticky checkpoints default off (missing key)
            sticky=bool(state.get("sticky", False)),
            # pre-gang checkpoints default to independent pulls
            gang_hosts=int(state.get("gang_hosts", 0) or 0),
            # a failed-over master must keep the SAME evaluation mode
            # the bulk started with (pre-sharding checkpoints ran
            # replicated)
            gang_sharded=bool(state.get("gang_sharded", False)),
            gang_halo=bool(state.get("gang_halo", True)),
            admission_token=str(state.get("token", "") or ""),
            # pre-crash spans are gone with the old process; post-
            # recovery assignments still assemble under one fresh trace
            trace_id=_tracing.new_trace_id())
        for j, n in state["job_ntasks"].items():
            j = int(j)
            job = jobs[j]
            bulk.job_tasks[j] = {(j, t) for t in range(n)}
            for t, (s, e) in enumerate(job.tasks[:n]):
                bulk.task_rows[(j, t)] = e - s
            bulk.job_sink_names[j] = [
                d.name for d, _c, _k, _e in job.sink_tables.values()]
            bulk.job_custom_sinks[j] = list(job.custom_sinks.values())
            bulk.job_output_rows[j] = state["job_output_rows"][j]
            bulk.total_tasks += n
        try:
            prog_payload = self._load_sealed(
                md.bulk_progress_path(source_gen, self.shard_id),
                "bulk progress")
            prog = cloudpickle.loads(prog_payload) \
                if prog_payload is not None else None
            if prog is not None and prog.get("bulk_id") == bulk.bulk_id:
                if "done_runs" in prog:
                    bulk.done = self._decode_task_set(
                        prog["done_runs"])
                else:  # earlier format stored explicit tuples
                    bulk.done = {tuple(k)
                                 for k in prog.get("done", ())}
                bulk.failures = {tuple(k): v
                                 for k, v in prog["failures"].items()}
                bulk.transient_failures = {
                    tuple(k): v for k, v in
                    (prog.get("transient_failures") or {}).items()}
                bulk.blacklisted_jobs = set(prog["blacklisted_jobs"])
                bulk.committed_jobs = set(prog["committed_jobs"])
                bulk.error = prog.get("error", "")
                bulk.gang_epoch = max(
                    bulk.gang_epoch,
                    int(prog.get("gang_epoch", 0) or 0))
        except Exception:  # noqa: BLE001
            # a corrupt progress file costs the snapshot, not the bulk:
            # the journal replay below still restores every record
            # since the last compaction
            _mlog.exception("bulk progress unreadable; resuming from "
                            "admission state + journal replay")
            bulk.done = set()
            bulk.failures = {}
        # write-ahead journal replay: completions/strikes/blacklists
        # acknowledged after the last checkpoint — the records a plain
        # checkpoint-window restart would lose and re-execute
        applied = self._apply_journal_records(bulk, records)
        if bulk.gang_hosts:
            _gang.set_epoch(bulk.gang_epoch)
        if records:
            _mlog.info(
                "journal replay: %d records across %d segments "
                "(%d newly applied over the checkpoint%s)",
                jstats.get("records", 0), jstats.get("segments", 0),
                applied,
                "; torn tail tolerated" if jstats.get("torn") else "")
        # blacklist aggregates from the FINAL sets (snapshot + replay)
        for j in bulk.blacklisted_jobs:
            bulk.blacklisted_task_total += len(
                bulk.job_tasks.get(j, ()))
            bulk.done_in_blacklisted += sum(
                1 for k in bulk.job_tasks.get(j, ())
                if k in bulk.done)
        # ETA baseline: rate counts only post-recovery completions
        bulk.done_at_start = len(bulk.done) - bulk.done_in_blacklisted
        for j, _t in bulk.done:
            bulk.job_done[j] = bulk.job_done.get(j, 0) + 1
        for j, ts in sorted(bulk.job_tasks.items()):
            if j in bulk.blacklisted_jobs:
                continue
            remaining = sorted(t for (_j, t) in ts if (_j, t) not in
                               bulk.done)
            if remaining:
                bulk.queue[j] = deque(remaining)
                bulk.job_rr.append(j)
        if self.num_shards > 1:
            # shard-failover accounting: a journaled (acknowledged)
            # completion that landed back in the queue would re-execute
            # work a worker already finished.  Structurally zero —
            # replay unions into bulk.done before the queue rebuild —
            # and the master-shard-loss chaos drill asserts it stays so.
            journaled = {(int(r["j"]), int(r["k"])) for r in records
                         if r.get("t") == "done"}
            requeued = {(j, t) for j, q in bulk.queue.items()
                        for t in q}
            _shardmap.count_journal_reexec(len(journaled & requeued))
            _shardmap.count_failover()
        # published under the lock: _recover_bulk normally runs before
        # the RPC server exists, but nothing in its signature promises
        # that — and handler threads read these fields under _lock
        with self._lock:
            self._bulk = bulk
            self._history[bulk.bulk_id] = bulk
            self._next_bulk_id = max(self._next_bulk_id,
                                     bulk.bulk_id + 1)
            if bulk.admission_token:
                # client ride-through: a NewJob retried against THIS
                # master with the original token dedupes to the
                # recovered bulk instead of double-running it
                self._record_admission_token_locked(
                    bulk.admission_token, bulk.bulk_id)
        # tasks finished before the crash may complete whole jobs (or the
        # whole bulk, if the crash hit between last-task and cleanup)
        for j in list(bulk.job_tasks):
            self._maybe_finish_job(bulk, j)
        self._maybe_finish_bulk(bulk)
        if bulk.finished:
            self._clear_bulk_checkpoint()
            self._drop_recovery_source(source_gen)
            _mlog.info("recovered bulk %d was already complete", bulk.bulk_id)
        else:
            # migrate the bulk's durable state under THIS generation
            # (fresh checkpoint + progress + journal), then drop the
            # predecessor's — its fenced late writes land in a
            # directory nothing reads again
            self._persist_bulk_checkpoint(bulk)
            self._persist_bulk_progress(bulk)
            self._drop_recovery_source(source_gen)
            _mlog.info(
                "recovered bulk %d from generation %s: %d/%d tasks "
                "done, %d requeued", bulk.bulk_id,
                source_gen if source_gen is not None else "legacy",
                len(bulk.done), bulk.total_tasks, bulk.q_count())

    # -- internals ----------------------------------------------------------

    @staticmethod
    def _dec_held(bulk: _BulkJob, wid: int) -> None:
        n = bulk.held.get(wid, 0) - 1
        if n > 0:
            bulk.held[wid] = n
        else:
            bulk.held.pop(wid, None)

    @classmethod
    def _unassign(cls, bulk: _BulkJob, key) -> Optional[Tuple]:
        """Drop an outstanding assignment, keeping the per-worker held
        count in sync (save-parked tasks were already released)."""
        cur = bulk.outstanding.pop(key, None)
        if cur is not None and not cur[4]:
            cls._dec_held(bulk, cur[0])
        return cur

    def _blacklist_job(self, bulk: _BulkJob, j: int, err: str,
                       recs: Optional[List[dict]] = None) -> None:
        if j in bulk.blacklisted_jobs:
            # idempotent: two timed-out tasks of one job can both trip the
            # failure threshold in a single scan pass; double-counting the
            # finish counters would let the bulk "finish" early
            return
        _mlog.error("job %d blacklisted after repeated failures: %s", j, err)
        _M_JOBS_BLACKLISTED.inc()
        if recs is not None:
            recs.append({"t": "blacklist", "j": j, "error": err})
        bulk.blacklisted_jobs.add(j)
        bulk.blacklisted_task_total += len(bulk.job_tasks.get(j, ()))
        bulk.done_in_blacklisted += sum(
            1 for k in bulk.job_tasks.get(j, ()) if k in bulk.done)
        bulk.queue.pop(j, None)  # the rr ring drops it lazily
        for k in [k for k in bulk.outstanding if k[0] == j]:
            self._unassign(bulk, k)
        if not bulk.error:
            bulk.error = f"job {j} blacklisted after repeated failures: {err}"

    def _maybe_finish_job(self, bulk: _BulkJob, j: int,
                          recs: Optional[List[dict]] = None) -> None:
        if j in bulk.committed_jobs or j in bulk.blacklisted_jobs:
            return
        if bulk.job_tasks[j] <= bulk.done:
            # all tasks of this output stream finished: commit its tables
            # (reference: tables committed per job, master.cpp:1031-1125)
            for name in bulk.job_sink_names.get(j, []):
                if self.db.has_table(name):
                    self.db.commit_table(name)
            for stream in bulk.job_custom_sinks.get(j, []):
                stream.storage.finished(stream,
                                        bulk.job_output_rows.get(j, 0))
            bulk.committed_jobs.add(j)
            if recs is not None:
                recs.append({"t": "commit", "j": j})

    def _maybe_finish_bulk(self, bulk: _BulkJob) -> None:
        active_total = bulk.total_tasks - bulk.blacklisted_task_total
        active_done = len(bulk.done) - bulk.done_in_blacklisted
        if active_done >= active_total and not bulk.outstanding:
            bulk.mark_finished()
            _mlog.info("bulk %d finished: %d/%d tasks done",
                       bulk.bulk_id, len(bulk.done), bulk.total_tasks)
            self.db.write_megafile()

    def _scan_loop(self) -> None:
        """Liveness + timeout scanning (reference start_worker_pinger
        master.cpp:1837 and timeout scan master.cpp:1751-1776)."""
        fence_tick = 0
        while not self._shutdown.is_set():
            time.sleep(0.5)
            now = time.time()
            finished_bulk_id = None
            # generation-fence poll (~2 s): a paused-then-resumed stale
            # master discovers its successor here and stops accepting
            # mutations (path scoping already protects storage)
            fence_tick += 1
            if fence_tick % 4 == 0:
                self._check_fence()
                # same cadence: adopt newer shard-map epochs so the
                # stale-map fence reflects peers' failover re-publishes
                self._refresh_shard_map()
            recs: List[dict] = []
            with self._lock:
                # refresh the point-in-time gauges (0.5s resolution is
                # plenty for a human-watched dashboard)
                _M_WORKERS.set(sum(1 for w in self._workers.values()
                                   if w.active))
                for w in self._workers.values():
                    if w.active:
                        _M_HB_AGE.labels(worker=str(w.worker_id)).set(
                            now - w.last_seen)
                    else:
                        # drop the child: worker ids are never reused,
                        # so keeping one -1 series per dead id would
                        # grow every scrape of a week-old master
                        _M_HB_AGE.remove_labels(worker=str(w.worker_id))
                        # same churn story for the departed node's
                        # clock gauges (the rebase MAP keeps its
                        # estimate — already-shipped spans still need
                        # correcting; only the scrape surface shrinks)
                        _clocksync.unpublish(f"worker{w.worker_id}")
                cur = self._bulk
                if cur is not None and not cur.finished:
                    _M_TASKS_QUEUED.set(cur.q_count())
                    _M_TASKS_OUTSTANDING.set(len(cur.outstanding))
                else:
                    _M_TASKS_QUEUED.set(0)
                    _M_TASKS_OUTSTANDING.set(0)
                # stale workers -> deactivate + requeue their tasks
                for w in self._workers.values():
                    if w.active and now - w.last_seen > WORKER_STALE_AFTER:
                        w.active = False
                        _mlog.warning(
                            "worker %d stale (%.1fs since heartbeat): "
                            "deactivating and requeueing its tasks",
                            w.worker_id, now - w.last_seen)
                        self._requeue_worker_tasks(w.worker_id,
                                                   recs=recs)
                bulk = self._bulk
                if bulk is not None and not bulk.finished:
                    # per-task timeout
                    if bulk.task_timeout > 0:
                        for key, (wid, t0, _a, started, _ed) in \
                                list(bulk.outstanding.items()):
                            if now - t0 > bulk.task_timeout:
                                gid = bulk.gang_by_task.get(key)
                                if gid is not None:
                                    # a timed-out gang is a lost/hung
                                    # member set: abort the whole gang
                                    # (epoch bump + strike-free requeue
                                    # for a fresh gang), not a per-
                                    # worker revocation
                                    g = bulk.gangs.get(gid)
                                    if g is not None:
                                        self._abort_gang_locked(
                                            bulk, g, "timeout", recs)
                                    continue
                                self._unassign(bulk, key)
                                _M_REVOCATIONS.inc()
                                _mlog.warning(
                                    "task (%d,%d) timed out on worker %d "
                                    "after %.1fs (started=%s): revoking",
                                    key[0], key[1], wid, now - t0, started)
                                if not started:
                                    # never began executing: a queue-wait
                                    # artifact, not a task failure
                                    bulk.q_push(key, front=True)
                                    continue
                                self._count_strike_locked(
                                    bulk, key, "task timeout",
                                    recs=recs)
                        self._maybe_finish_bulk(bulk)
                    # no workers at all
                    if not any(w.active for w in self._workers.values()):
                        if now - self._no_worker_since > \
                                self.no_workers_timeout:
                            bulk.error = (
                                f"no workers available after "
                                f"{self.no_workers_timeout}s")
                            bulk.mark_finished()
                    else:
                        self._no_worker_since = now
                        # a gang bulk on a fleet whose live workers are
                        # ALL gang-incapable (registered with no gang
                        # address — SCANNER_TPU_GANG=0 / [gang]
                        # enabled=false) would otherwise wait forever:
                        # every pull answers "wait" and no formation
                        # can ever happen.  Fail it loudly on the same
                        # clock a worker-less bulk gets.
                        if bulk.gang_hosts and not bulk.finished \
                                and (bulk.q_has_work()
                                     or bulk.outstanding):
                            capable = any(
                                w.active and w.gang_address
                                for w in self._workers.values())
                            if capable:
                                bulk.gang_incapable_since = 0.0
                            elif not bulk.gang_incapable_since:
                                bulk.gang_incapable_since = now
                            elif now - bulk.gang_incapable_since \
                                    > self.no_workers_timeout:
                                bulk.error = (
                                    f"gang_hosts={bulk.gang_hosts} "
                                    "but no gang-capable worker "
                                    "joined within "
                                    f"{self.no_workers_timeout}s "
                                    "(fleet running with gang "
                                    "scheduling disabled?)")
                                bulk.mark_finished()
                if bulk is not None and bulk.finished:
                    finished_bulk_id = bulk.bulk_id
                if self.enable_watchdog and \
                        now - self._last_poke > 30.0:
                    self._shutdown.set()
            self._journal_append(recs)
            if finished_bulk_id is not None \
                    and finished_bulk_id != self._cleared_bulk_id:
                self._clear_bulk_checkpoint(finished_bulk_id)
                self._cleared_bulk_id = finished_bulk_id
            # remediation drive (outside the control lock; everything
            # below no-ops under SCANNER_TPU_REMEDIATION=0): fold
            # worker-reported alerts into cluster transitions, run
            # hysteresis-held resolve actions, observe the autoscaler
            if _controller.enabled():
                try:
                    self._fold_worker_alerts()
                    _controller.controller().tick(now)
                    self._autoscale_observe()
                except Exception:  # noqa: BLE001 — remediation must
                    # never kill the liveness scan
                    _mlog.exception("remediation tick failed")

    def _requeue_worker_tasks(self, wid: int,
                              recs: Optional[List[dict]] = None) -> None:
        bulk = self._bulk
        if bulk is None or bulk.finished:
            return
        # a dead/departing worker takes its gang memberships with it:
        # abort those gangs first (epoch bump + strike-free requeue for
        # a fresh gang on the survivors) — the dead worker may be a
        # NON-coordinator member, invisible to the outstanding map
        if recs is None:
            recs = []
        for g in list(bulk.gangs.values()):
            if wid in g.members:
                self._abort_gang_locked(bulk, g, "member_lost", recs)
        bulk.gang_forming.pop(wid, None)
        for key, (owner, _t0, _a, _s, _ed) in list(bulk.outstanding.items()):
            if owner == wid:
                self._unassign(bulk, key)
                bulk.q_push(key, front=True)
                _M_REVOCATIONS.inc()
                _M_TASK_RETRIES.inc()

    def wait_for_shutdown(self) -> None:
        while not self._shutdown.is_set():
            time.sleep(0.2)
        self.stop()

    def stop(self) -> None:
        self._shutdown.set()
        self._server.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        # drop this master's heartbeat-age gauge children: with the
        # scan loop gone nothing would ever update or remove them, and
        # a stale high-age sample would keep the health engine's
        # worker_heartbeat_stale alert firing forever in a process that
        # outlives the master (embedders, test suites)
        with self._lock:
            for w in self._workers.values():
                _M_HB_AGE.remove_labels(worker=str(w.worker_id))
            # and this master's per-node clock gauges, for the same
            # outliving-process reason
            for node in self._clock_offsets:
                _clocksync.unpublish(node)
        # unbind this master's remediation actions (owner-checked: a
        # NEWER master's re-registration in the same process must
        # survive this one's delayed stop): a later transition must not
        # actuate a dead instance — and the bound methods would
        # otherwise pin the whole Master object alive.  If admission
        # was paused, clear the gate + gauge on the way out: the
        # resume action is gone, so the pending hysteresis resolve
        # could never reset them in a process that outlives the master
        # (the same dead-master-alerts-forever class the heartbeat-age
        # gauge cleanup above handles).
        if _controller.enabled():
            for name, fn in (("pause_admission", self._pause_admission),
                             ("resume_admission",
                              self._resume_admission),
                             ("autoscale", self._autoscale_nudge)):
                _controller.unregister_action(name, owner=fn)
            with self._lock:
                was_paused = self._admission_paused is not None
                self._admission_paused = None
            if was_paused:
                _M_ADMISSION_PAUSED.set(0)


# ---------------------------------------------------------------------------
# Worker
# ---------------------------------------------------------------------------

class _ShardLink:
    """One worker's connection to one master shard: its own channel,
    the worker id THAT shard handed out (ids are per-shard), a
    generation latch scoped to that shard's namespace, and the
    freshest heartbeat reply.  The worker multiplexes pulls and
    reports across its links (docs/robustness.md §Sharded control
    plane); with one shard no links exist and the legacy single-master
    fields are the whole story."""

    def __init__(self, shard_id: int, address: str):
        self.shard_id = int(shard_id)
        self.address = str(address)
        self.client = rpc.RpcClient(address, MASTER_SERVICE,
                                    timeout=10.0)
        self.worker_id: Optional[int] = None
        self.gen = _journal.GenerationLatch()
        self.hb_reply: dict = {}
        self.hb_reply_at = 0.0
        self.hb_misses = 0

    def redial(self, address: Optional[str] = None) -> None:
        """Fresh channel (the wedged-channel pathology — see
        Worker._heartbeat_loop), optionally at a new address a
        failover respawn re-published."""
        if address:
            self.address = str(address)
        old, self.client = self.client, rpc.RpcClient(
            self.address, MASTER_SERVICE, timeout=10.0)
        old.close()

    def close(self) -> None:
        try:
            self.client.close()
        except Exception:  # noqa: BLE001 — shutdown is best-effort
            pass


# how long completions may pool in the worker-side batcher before a
# FinishedWorkBatch flush (sharded mode only): short enough that the
# master's progress view lags by at most ~one heartbeat fraction
FINISHED_BATCH_WINDOW_S = 0.05


class Worker:
    """Executes tasks pulled from the master; one process per node.

    Capability parity: reference WorkerImpl (worker.cpp) — job admission,
    local DAG re-analysis, task execution, failure reporting.
    """

    def __init__(self, master_address: str, db_path: str, port: int = 0,
                 storage_type: str = "posix",
                 num_load_workers: int = 2, num_save_workers: int = 2,
                 # None = one device-affine instance per local chip on
                 # multi-chip hosts (resolved per bulk); explicit wins
                 pipeline_instances: Optional[int] = None,
                 decoder_threads: int = 1,
                 coordinator=None,
                 metrics_port: Optional[int] = None,
                 metrics_host: str = "0.0.0.0",
                 advertise_host: Optional[str] = None,
                 compilation_cache_dir: Optional[str] = None):
        # persistent XLA executable cache: a restarted/rescheduled worker
        # re-loads its jitted kernels' executables instead of recompiling
        # (falls back to the SCANNER_TPU_COMPILATION_CACHE env var the
        # deploy manifests set; no-op when neither is configured)
        from ..util.jaxenv import enable_compilation_cache
        enable_compilation_cache(compilation_cache_dir)
        if coordinator is not None:
            # join the multi-process JAX runtime BEFORE any backend touch:
            # meshes built by kernels then span all participating hosts
            # (reference worker-per-node topology, worker.cpp:484)
            from ..parallel.distributed import initialize
            initialize(coordinator)
        # gang member runners re-derive the job from these
        # (engine/gang.py: one child process per gang epoch)
        self._db_path = db_path
        self._storage_type = storage_type
        self.db = Database(make_storage(storage_type, db_path=db_path))
        self.profiler = Profiler(node="worker")
        # this worker's span sink: stage/op spans land here and ship to
        # the master in batches (ShipSpans); the node label is refined
        # to worker<id> once registration hands out the id
        self.tracer = _tracing.Tracer(node="worker", export=True)
        # an OOM report from this process should snapshot THIS worker's
        # flight recorder, not the default client tracer (last Worker
        # constructed wins when several share a test process)
        _memstats.set_tracer(self.tracer)
        self._shutdown = threading.Event()
        # master-generation latch (engine/journal.py): replies stamped
        # with an older generation than the highest seen are a stale
        # (superseded) master's — its assignments and revocations are
        # NACKed instead of acted on
        self._gen = _journal.GenerationLatch()
        # SIGTERM drain mode (start_worker wires the signal): stop
        # pulling, finish in-flight tasks, deregister, then shut down
        self._draining = threading.Event()
        # preemption notice (spot/preemptible reclaim, or the
        # worker.preempt chaos site): drain as above, but ALSO
        # advertise the notice on every heartbeat so the master fences
        # assignment before the drain completes
        self._preempting = False
        self._server = rpc.RpcServer(WORKER_SERVICE, {
            "Ping": lambda req: {"ok": True},
            # serves the master's cluster-wide metrics aggregation
            "GetMetrics": lambda req: {
                "snapshot": _mx.registry().snapshot()},
            # serves the master's cluster-wide health aggregation
            # (GetHealth fan-in -> Client.health())
            "GetHealth": lambda req: {"health": _health.status_dict()},
            # serves the master's compile-ledger/roofline aggregation
            # (GetCompileLedger fan-in -> Client.compile_report())
            "GetCompileLedger": lambda req: {
                "report": _coststats.compile_report()},
            "Shutdown": self._rpc_shutdown,
        }, port=port, tracer=self.tracer)
        self.port = self._server.port
        self._server.start()
        self.metrics_server: Optional[MetricsServer] = None
        if metrics_port is not None:
            self.metrics_server = MetricsServer(
                port=metrics_port, statusz=self._statusz,
                healthz=lambda: {"role": "worker",
                                 "draining": self._draining.is_set()},
                # SIGTERM drain: not-ready (k8s stops routing) while
                # /healthz stays 200 (still alive, finishing in-flight)
                ready=lambda: not self._draining.is_set(),
                host=metrics_host)
        # health/SLO engine: backpressure/saturation rules read series
        # this worker's pipeline maintains; alert transition instants
        # land on THIS worker's flight recorder (node-labeled)
        _health.set_tracer(self.tracer)
        _health.ensure_started()
        # remediation controller: worker-local playbooks (frame-cache
        # shrink, ladder re-warm) actuate here; master-side ones stay
        # unbound no-ops in this process
        _controller.ensure_started()
        self.executor = LocalExecutor(
            self.db, self.profiler,
            num_load_workers=num_load_workers,
            num_save_workers=num_save_workers,
            # the per-bulk resolution (_ensure_bulk) overwrites this;
            # the executor field itself just needs a concrete int
            pipeline_instances=pipeline_instances or 1,
            decoder_threads=decoder_threads)
        rpc.wait_for_server(master_address, MASTER_SERVICE)
        # dial the master only AFTER it provably listens: a gRPC channel
        # first dialed against a not-yet-listening address can wedge in
        # connection-refused on some network stacks (see
        # rpc.wait_for_server), and this channel lives for the worker's
        # whole life — except across a master restart, where the
        # heartbeat loop recreates it (see _heartbeat_loop: the same
        # wedge can strike a channel whose peer died and came back)
        self._master_address = master_address
        self.master = rpc.RpcClient(master_address, MASTER_SERVICE,
                                    timeout=10.0)
        self._hb_misses = 0
        # the address other processes can dial THIS worker at (the
        # master's GetMetrics aggregation uses it).  localhost is right
        # for single-host clusters and tests; multi-host deployments
        # pass the pod/host DNS name (deploy.py wires the pod name)
        self.advertise_address = \
            f"{advertise_host or 'localhost'}:{self.port}"
        # the port this worker's gang runner would serve the
        # jax.distributed coordinator at if elected member 0: reserved
        # by a bind-and-release probe (the runner child binds it for
        # real), advertised at registration so the master can mint
        # rendezvous roles.  Empty when gang mode is disabled.
        self._gang_address = ""
        if _gang.enabled():
            import socket as _socket
            with _socket.socket() as _s:
                _s.bind(("0.0.0.0", 0))
                gport = _s.getsockname()[1]
            self._gang_address = \
                f"{advertise_host or 'localhost'}:{gport}"
        reg = self.master.call("RegisterWorker",
                               address=self.advertise_address,
                               gang_address=self._gang_address)
        if reg.get("worker_id") is None:
            # a FENCED (superseded) master answers an error reply:
            # fail startup loudly instead of KeyError-ing — this
            # worker is pointed at the wrong master instance
            raise ScannerException(
                "master refused worker registration: "
                f"{reg.get('error', reg)}")
        self.worker_id = reg["worker_id"]
        self.tracer.node = f"worker{self.worker_id}"
        self.executor.tracer = self.tracer
        _wlog.info("worker %d registered with master %s (port %d)",
                   self.worker_id, master_address, self.port)
        # sharded control plane: resolve the shard map from the seed
        # master and register with every OTHER shard too (each hands
        # out its own worker id).  The legacy fields (self.master /
        # worker_id / _gen / _hb_reply) become an alias for whichever
        # link currently owns this worker's active work — the whole
        # pull/report plumbing speaks through them unchanged.
        self._links: Dict[int, _ShardLink] = {}
        self._active_shard: Optional[int] = None
        self._map = _shardmap.MapHolder()
        self._map_beat = 0
        self._fin_lock = threading.Lock()
        self._fin_items: List[Tuple[int, dict]] = []
        if _shardmap.num_shards() > 1:
            smap_reply = self.master.try_call("GetShardMap",
                                              timeout=PING_TIMEOUT)
            if smap_reply and int(smap_reply.get("num_shards", 1)) > 1 \
                    and smap_reply.get("shards"):
                seed_sid = int(smap_reply.get("shard_id", 0))
                seed = _ShardLink(seed_sid, master_address)
                seed.client.close()
                seed.client = self.master
                seed.worker_id = self.worker_id
                seed.gen = self._gen
                self._links[seed_sid] = seed
                self._active_shard = seed_sid
                self._map.observe(_shardmap.ShardMap(
                    epoch=int(smap_reply.get("epoch", 0)),
                    shards={int(k): v for k, v
                            in smap_reply["shards"].items()},
                    num_shards=int(smap_reply["num_shards"])))
                self._sync_links()
                # completion batcher: pooled FinishedWork flush
                # (FinishedWorkBatch — one journal group-commit per
                # flush on the master; see _queue_finished)
                threading.Thread(target=self._fin_flush_loop,
                                 name="worker-finbatch",
                                 daemon=True).start()
        # cached per-bulk state.  The cache key is (shard, bulk_id):
        # every shard mints its own bulk ids, so bulk 1 on shard 0 and
        # bulk 1 on shard 2 are different jobs — a bare-id cache would
        # silently reuse the wrong spec after a shard switch
        self._bulk_id: Optional[int] = None
        self._bulk_key: Optional[Tuple[Optional[int], int]] = None
        self._info = None
        self._jobs = None
        self._queue_size: Optional[int] = None
        # gang mode (PerfParams.gang_hosts on the active bulk): the
        # raw spec blob travels to member runner children verbatim
        self._gang_hosts = 0
        self._spec_raw: Optional[bytes] = None
        self._task_timeout = 0.0
        self._default_pipeline_instances = pipeline_instances
        # evaluator instances reused across pipeline entries of one bulk
        self._evaluators: Dict[int, TaskEvaluator] = {}
        self._eval_lock = threading.Lock()
        self._posted_profiles: set = set()
        # heartbeat runs on its own thread so a long task never makes the
        # master think this worker died (stale-worker scan).  The
        # receive timestamp lets gang liveness judgments require a
        # beat FRESHER than the gang's formation — a stale reply must
        # read as "unknown", never as "aborted".
        self._hb_reply: dict = {}
        self._hb_reply_at = 0.0
        # clock-offset estimator vs the master (util/clocksync.py):
        # fed by the four-timestamp exchange riding every heartbeat;
        # the converged estimate is advertised on the next beat and
        # stamped onto every span batch this worker ships
        self._clock = _clocksync.OffsetEstimator()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="worker-hb", daemon=True)
        self._hb_thread.start()
        self._work_thread = threading.Thread(
            target=self._work_loop, name="worker-loop", daemon=True)
        self._work_thread.start()

    def _heartbeat_loop(self) -> None:
        while not self._shutdown.is_set():
            # spot-reclaim notice check: the worker.preempt chaos site
            # models the cloud metadata server announcing preemption —
            # a raise here IS the notice (routine drain + heartbeat
            # advertisement), distinct from worker.heartbeat below
            # which drops the beat itself
            try:
                if _faults.ACTIVE:
                    _faults.inject("worker.preempt",
                                   detail=str(self.worker_id))
            except Exception:  # noqa: BLE001 — the injected reclaim
                self.preempt("injected spot reclaim")
            try:
                if _faults.ACTIVE:
                    _faults.inject("worker.heartbeat",
                                   detail=str(self.worker_id))
            except Exception:  # noqa: BLE001 — injected fault: this
                time.sleep(PING_INTERVAL)  # beat is dropped, loop lives
                continue
            if self._links:
                # sharded control plane: one beat per (worker, shard)
                # period — the full payload goes to the shard owning
                # this worker's active work, every other shard gets a
                # slim liveness-only beat (see Master._rpc_heartbeat)
                self._beat_shards()
                time.sleep(PING_INTERVAL)
                continue
            # short per-call deadline (PING_TIMEOUT, ~2x the ping
            # period) instead of the 30s client default: a hung master
            # must cost one missed beat, not pin this thread long
            # enough for the stale scan to remove a healthy worker
            try:
                firing = _health.firing_rules()
            except Exception:  # noqa: BLE001 — liveness > health detail
                firing = []
            # the NTP exchange rides the beat: t0 just before send, the
            # master echoes it back with its t1/t2 stamps, t3 below on
            # receipt.  The current estimate is advertised too, so the
            # master publishes the offset gauges and seeds trace rebase.
            hb_kwargs = {}
            if _clocksync.enabled():
                hb_kwargs["t0"] = time.time()
                est = self._clock.estimate()
                if est is not None:
                    hb_kwargs["clock"] = est
            hb = self.master.try_call("Heartbeat", worker_id=self.worker_id,
                                      timeout=PING_TIMEOUT,
                                      preempting=self._preempting,
                                      firing=firing, **hb_kwargs)
            if hb is not None and "t1" in hb and "t0" in hb_kwargs:
                self._clock.add_sample(hb["t0"], hb["t1"], hb["t2"],
                                       time.time())
            if hb is None:
                # ride a master restart out for real: a channel whose
                # peer died mid-dial can wedge past the peer's return
                # (the wait_for_server fresh-channel note) — after 5
                # consecutive missed beats, redial on a FRESH channel
                # so failover to a successor master actually completes
                self._hb_misses += 1
                if self._hb_misses % 5 == 0 \
                        and not self._shutdown.is_set():
                    _wlog.warning(
                        "worker %d: %d consecutive heartbeat misses — "
                        "recreating the master channel (%s)",
                        self.worker_id, self._hb_misses,
                        self._master_address)
                    old, self.master = self.master, rpc.RpcClient(
                        self._master_address, MASTER_SERVICE,
                        timeout=10.0)
                    old.close()
            else:
                self._hb_misses = 0
            if hb is not None and not self._gen.observe(hb):
                # a stale master's view of the cluster: ignore it (its
                # reregister/active_bulk verdicts are not authoritative)
                time.sleep(PING_INTERVAL)
                continue
            if hb is not None:
                if hb.get("reregister"):
                    # don't rejoin a cluster we are leaving
                    if not self._draining.is_set():
                        reg = self.master.try_call(
                            "RegisterWorker",
                            address=self.advertise_address,
                            gang_address=self._gang_address,
                            timeout=PING_TIMEOUT)
                        # a FENCED master answers an error reply with
                        # no worker_id: stay on the old id and keep
                        # beating until a live master answers
                        if reg and reg.get("worker_id") is not None:
                            self.worker_id = reg["worker_id"]
                else:
                    self._hb_reply = hb
                    self._hb_reply_at = time.time()
            time.sleep(PING_INTERVAL)

    # -- sharded control plane (engine/shardmap.py) --------------------

    def _sync_links(self) -> None:
        """Reconcile the per-shard links with the newest shard map:
        dial + register with shards we hold no link to, and redial a
        link whose shard re-published at a different address (a
        failover respawn elsewhere)."""
        smap = self._map.get()
        if smap is None:
            return
        for sid in smap.shard_ids():
            addr = smap.address_of(sid)
            link = self._links.get(sid)
            if link is None:
                link = _ShardLink(sid, addr)
                self._links[sid] = link
            elif link.address != addr:
                link.redial(addr)
                link.worker_id = None  # the new process mints fresh ids
            if link.worker_id is None:
                reg = link.client.try_call(
                    "RegisterWorker", address=self.advertise_address,
                    gang_address=self._gang_address,
                    timeout=PING_TIMEOUT)
                if reg and reg.get("worker_id") is not None:
                    link.gen.observe(reg)
                    link.worker_id = reg["worker_id"]
                    if sid == self._active_shard:
                        self.worker_id = link.worker_id

    def _refresh_map(self) -> None:
        """Adopt a newer shard map from whichever shard answers — a
        respawned shard's re-publish (epoch bump) re-points its link
        here even when the shard we usually ask is the dead one."""
        reply = None
        for link in list(self._links.values()):
            reply = link.client.try_call("GetShardMap",
                                         timeout=PING_TIMEOUT)
            if reply and reply.get("shards"):
                break
        if not reply or not reply.get("shards"):
            return
        smap = _shardmap.ShardMap(
            epoch=int(reply.get("epoch", 0)),
            shards={int(k): v for k, v in reply["shards"].items()},
            num_shards=int(reply.get("num_shards", 1)))
        if self._map.observe(smap):
            self._sync_links()

    def _beat_shards(self) -> None:
        """One heartbeat pass across every shard link.  Exactly one
        full beat per period — to the shard owning our active work
        (clock exchange, firing alerts, gang liveness ride it) — and
        slim liveness-only beats to the rest; the coalescing counter
        on the master records each slim beat as a saved full payload."""
        try:
            firing = _health.firing_rules()
        except Exception:  # noqa: BLE001 — liveness > health detail
            firing = []
        active = self._active_shard
        for link in list(self._links.values()):
            if link.worker_id is None:
                continue
            kwargs: dict = {"worker_id": link.worker_id,
                            "timeout": PING_TIMEOUT,
                            "preempting": self._preempting}
            if link.shard_id != active:
                kwargs["slim"] = True
            else:
                kwargs["firing"] = firing
                if _clocksync.enabled():
                    kwargs["t0"] = time.time()
                    est = self._clock.estimate()
                    if est is not None:
                        kwargs["clock"] = est
            hb = link.client.try_call("Heartbeat", **kwargs)
            if hb is not None and "t1" in hb and "t0" in kwargs:
                self._clock.add_sample(kwargs["t0"], hb["t1"],
                                       hb["t2"], time.time())
            if hb is None:
                # same redial discipline as the single-master loop: 5
                # consecutive misses = assume a wedged channel; the
                # map refresh below re-points the address if the
                # shard's respawn re-published elsewhere
                link.hb_misses += 1
                if link.hb_misses % 5 == 0 \
                        and not self._shutdown.is_set():
                    _wlog.warning(
                        "worker: %d heartbeat misses on shard %d — "
                        "redialing %s", link.hb_misses, link.shard_id,
                        link.address)
                    link.redial()
                continue
            link.hb_misses = 0
            if not link.gen.observe(hb):
                continue  # a superseded shard master's verdicts
            if hb.get("reregister"):
                if not self._draining.is_set():
                    reg = link.client.try_call(
                        "RegisterWorker",
                        address=self.advertise_address,
                        gang_address=self._gang_address,
                        timeout=PING_TIMEOUT)
                    if reg and reg.get("worker_id") is not None:
                        link.worker_id = reg["worker_id"]
                        if link.shard_id == active:
                            self.worker_id = link.worker_id
            else:
                link.hb_reply = hb
                link.hb_reply_at = time.time()
                if link.shard_id == active:
                    self._hb_reply = hb
                    self._hb_reply_at = link.hb_reply_at
        self._map_beat += 1
        smap = self._map.get()
        if self._map_beat % 5 == 0 or (
                smap is not None
                and len(self._links) < smap.num_shards):
            self._refresh_map()

    def _bind_link(self, link: _ShardLink) -> None:
        """Point the legacy single-master fields at one shard's link;
        the pull/report plumbing (_pull_loop, _gang_loop, span/profile
        ships) all speak through self.master / self.worker_id and so
        work unchanged against whichever shard owns the active bulk."""
        self._active_shard = link.shard_id
        self._master_address = link.address
        self.master = link.client
        self.worker_id = link.worker_id
        self._gen = link.gen
        self._hb_reply = link.hb_reply
        self._hb_reply_at = link.hb_reply_at

    def _switch_active_link(self) -> None:
        """Between bulks: re-point the pull plumbing at whichever
        shard currently has work for this worker.  _work_loop only
        calls this while no pull loop runs, so the rebind never races
        an in-flight bulk."""
        cur = self._links.get(self._active_shard) \
            if self._active_shard is not None else None
        if cur is not None \
                and cur.hb_reply.get("active_bulk") is not None:
            return
        for link in self._links.values():
            if link.worker_id is None:
                continue
            if link.hb_reply.get("active_bulk") is not None:
                _wlog.info(
                    "worker: switching to shard %d (bulk %s, worker "
                    "id %d there)", link.shard_id,
                    link.hb_reply.get("active_bulk"), link.worker_id)
                self._bind_link(link)
                return

    def _queue_finished(self, bulk_id: int, item: dict) -> None:
        """Pool a completion for the next FinishedWorkBatch flush
        (sharded mode): the master journals the whole batch in ONE
        group-commit before acking, so pooling trades ≤
        FINISHED_BATCH_WINDOW_S of progress-view lag for an RPC (and
        fsync) per task.  An unflushed completion lost with the
        process re-queues via the ordinary assignment timeout — the
        same contract as a lost FinishedWork RPC."""
        with self._fin_lock:
            self._fin_items.append((bulk_id, item))

    def _fin_flush_loop(self) -> None:
        while not self._shutdown.is_set():
            time.sleep(FINISHED_BATCH_WINDOW_S)
            try:
                self._flush_finished()
            except Exception:  # noqa: BLE001 — keep the flusher alive
                _wlog.exception("finished-work batch flush failed")
        self._flush_finished()  # final drain on shutdown

    def _flush_finished(self) -> None:
        with self._fin_lock:
            items, self._fin_items = self._fin_items, []
        if not items:
            return
        by_bulk: Dict[int, List[dict]] = {}
        for b, item in items:
            by_bulk.setdefault(b, []).append(item)
        for b, its in by_bulk.items():
            if len(its) == 1:
                self.master.try_call(
                    "FinishedWork", bulk_id=b,
                    worker_id=self.worker_id, **its[0])
            else:
                self.master.try_call(
                    "FinishedWorkBatch", bulk_id=b,
                    worker_id=self.worker_id,
                    clock=self._clock.estimate(), items=its)

    def _rpc_shutdown(self, req: dict) -> dict:
        self._shutdown.set()
        return {"ok": True}

    def drain(self) -> None:
        """Begin SIGTERM drain: the pull loop stops taking new tasks,
        in-flight tasks run to completion (and report FinishedWork),
        then the worker deregisters and shuts down.  Size the pod's
        terminationGracePeriod (deploy.py) to cover the longest task."""
        if self._draining.is_set():
            return
        _wlog.info("worker %d: drain requested (SIGTERM) — finishing "
                   "in-flight tasks, no new pulls", self.worker_id)
        self._draining.set()

    def draining(self) -> bool:
        return self._draining.is_set()

    def preempt(self, reason: str = "spot reclaim") -> None:
        """Preemption-as-routine: a reclaim notice starts an ordinary
        drain (finish in-flight, stop pulling, deregister) AND
        advertises itself on every remaining heartbeat so the master
        fences assignment immediately — anything this worker cannot
        finish inside the reclaim window requeues strike-free via the
        normal drain/stale paths.  Idempotent."""
        if self._preempting:
            return
        self._preempting = True
        _M_PREEMPTIONS.inc()
        _wlog.warning("worker %d: preemption notice (%s) — fencing via "
                      "heartbeat, draining in-flight tasks",
                      self.worker_id, reason)
        self.drain()

    def preempting(self) -> bool:
        return self._preempting

    def _finish_drain(self) -> None:
        """In-flight work is done: leave the cluster cleanly.  The
        explicit UnregisterWorker makes the master requeue-check and
        deactivate immediately instead of burning WORKER_STALE_AFTER
        on the stale scan."""
        if self._links:
            self._flush_finished()  # pooled completions leave first
            for link in self._links.values():
                if link.worker_id is not None:
                    link.client.try_call("UnregisterWorker",
                                         worker_id=link.worker_id,
                                         timeout=PING_TIMEOUT)
        else:
            self.master.try_call("UnregisterWorker",
                                 worker_id=self.worker_id,
                                 timeout=PING_TIMEOUT)
        _wlog.info("worker %d: drain complete, deregistered",
                   self.worker_id)
        self._shutdown.set()

    def _statusz(self) -> dict:
        # getattr guards: the endpoint is live before __init__ finishes
        ex = getattr(self, "executor", None)
        master = getattr(self, "master", None)
        return {
            "role": "worker",
            "worker_id": getattr(self, "worker_id", None),
            "master": master.address if master else None,
            "master_generation": self._gen.highest(),
            "draining": self._draining.is_set(),
            "preempting": self._preempting,
            "bulk_id": getattr(self, "_bulk_id", None),
            # gang mode (engine/gang.py): the active bulk's requested
            # gang size and the coordinator address this worker
            # advertises for member-0 election
            "gang_hosts": getattr(self, "_gang_hosts", 0),
            "gang_address": getattr(self, "_gang_address", ""),
            "pipeline_instances": ex.pipeline_instances if ex else None,
            "num_load_workers": ex.num_load_workers if ex else None,
            "num_save_workers": ex.num_save_workers if ex else None,
            # the Health panel: roll-up + firing alerts (util/health.py)
            "health": _health.status_dict(),
            # the Memory panel: per-device HBM + allocation-ledger view
            "memory": _memstats.status_dict(),
            # the Frame-cache panel: page pool occupancy + hit rates
            "framecache": _framecache.status_dict(),
            # the Efficiency panel: per-op roofline + compile ledger
            "efficiency": _coststats.status_dict(),
            # the Remediation panel: playbooks bound in THIS process
            # (frame-cache shrink, ladder re-warm) + audit tail
            "remediation": _controller.status_dict(),
        }

    # ------------------------------------------------------------------

    def _work_loop(self) -> None:
        while not self._shutdown.is_set():
            if self._draining.is_set():
                # _pull_loop (if any was running) returned after its
                # in-flight tasks finished: deregister and stop
                self._finish_drain()
                break
            if self._links:
                self._switch_active_link()
            bulk_id = self._hb_reply.get("active_bulk")
            if bulk_id is None:
                time.sleep(PING_INTERVAL / 4)
                continue
            try:
                self._ensure_bulk(bulk_id)
                if self._gang_hosts > 0 and _gang.enabled():
                    # gang mode: the bulk's tasks are co-scheduled
                    # member runs, not independent pipeline pulls
                    self._gang_loop(bulk_id)
                else:
                    self._pull_loop(bulk_id)
            except Exception:  # noqa: BLE001
                # a pipeline-level failure (e.g. evaluator construction)
                # must not kill this thread while the heartbeat keeps the
                # worker looking alive — back off and retry
                _wlog.exception("worker %d: pipeline failure in bulk %d",
                                self.worker_id, bulk_id)
                time.sleep(PING_INTERVAL)
                continue
            self._post_profile(bulk_id)
            # the master may report the bulk active for up to one ping
            # after its last task: don't respin the whole pipeline
            # (threads + NextWork RPCs) in a tight loop meanwhile
            time.sleep(PING_INTERVAL / 4)

    def _ship_spans(self, bulk_id: int) -> None:
        """Drain this worker's completed trace spans and ship them to
        the master in one ShipSpans batch — the out-of-band path
        (failed attempts, the final flush); completion spans piggyback
        on FinishedWork instead.  Best-effort: a failed ship loses
        those spans from the assembled trace (the flight recorder
        still holds them locally), never the task."""
        spans = self.tracer.drain_export()
        if spans:
            self.master.try_call("ShipSpans", bulk_id=bulk_id,
                                 worker_id=self.worker_id, spans=spans,
                                 clock=self._clock.estimate())

    def _ship_memory_report(self) -> None:
        """Push the newest unshipped OOM memory report (if any) to the
        master — best-effort, like span shipping: the local log and
        flight recorder still hold the forensics if the RPC fails."""
        report = _memstats.take_unshipped_report()
        if report is None:
            return
        self.master.try_call("ShipMemoryReport",
                             worker_id=self.worker_id, report=report)

    def _post_profile(self, bulk_id: int) -> None:
        """Ship this worker's profile to the master once per bulk job
        (reference: worker profile files, worker.cpp:2067-2138)."""
        if (self._active_shard, bulk_id) in self._posted_profiles:
            return
        self._posted_profiles.add((self._active_shard, bulk_id))
        # final span flush: whatever the per-task ships didn't cover
        # (e.g. spans of tasks that failed mid-pipeline)
        self._ship_spans(bulk_id)
        self._ship_memory_report()
        # serialize the XLA device timeline INTO the profile before it
        # crosses hosts: the trace *directory* path is meaningless on
        # the master's filesystem (util/jaxprof.py)
        from ..util.jaxprof import embed_device_events
        for rec in self.profiler.device_traces:
            try:
                embed_device_events(rec)
            except Exception:  # noqa: BLE001 — profile > device detail
                _wlog.exception("embedding device trace events failed")
        self.master.try_call("PostProfile", bulk_id=bulk_id,
                             profile=self.profiler.to_dict())

    def _ensure_bulk(self, bulk_id: int) -> None:
        if self._bulk_key == (self._active_shard, bulk_id):
            return
        raw = self.master.call("GetJob", bulk_id=bulk_id)["spec"]
        spec = cloudpickle.loads(raw)
        # master created tables after our metadata cache was filled
        self.db.refresh_meta()
        outputs = spec["outputs"]
        perf = spec["perf"]
        # gang mode latch + the verbatim spec blob member runner
        # children re-derive the job from (engine/gang.py)
        self._spec_raw = raw
        self._gang_hosts = int(getattr(perf, "gang_hosts", 0) or 0)
        self._task_timeout = float(getattr(perf, "task_timeout", 0.0)
                                   or 0.0)
        # fresh profiler per bulk so PostProfile ships only this job's spans
        self.profiler = Profiler(
            node=f"worker{self.worker_id}",
            level=int(getattr(perf, "profiler_level", 1)))
        self.executor.profiler = self.profiler
        # the job's PerfParams drive this node's pipeline shape (reference
        # worker.cpp:1467 pipeline instance spin-up from job params); an
        # unset knob restores the worker's constructor default — which on
        # a multi-chip host resolves to one device-affine pipeline
        # instance per local chip (engine/evaluate.py
        # default_pipeline_instances; SCANNER_TPU_DEVICE_AFFINITY=0
        # keeps the literal default)
        from .evaluate import default_pipeline_instances
        self.executor.pipeline_instances = int(
            getattr(perf, "pipeline_instances_per_node", None)
            or default_pipeline_instances(
                self._default_pipeline_instances))
        self._queue_size = int(getattr(perf, "queue_size_per_pipeline", 4))
        info, jobs = self.executor.prepare_readonly(outputs, perf)
        # stateful task affinity: incremental plans when the master's
        # sticky assignment hands us a job's tasks in order (any break
        # degrades to self-contained plans / StateCarryMiss re-runs)
        self.executor.setup_chains(info, jobs, perf)
        self.executor._stream_opt = bool(
            getattr(perf, "stream_work_packets", True))
        with self._eval_lock:
            for te in self._evaluators.values():
                te.close()
            self._evaluators = {}
        self._info, self._jobs = info, jobs
        self._bulk_id = bulk_id
        self._bulk_key = (self._active_shard, bulk_id)
        _wlog.info("worker %d joined bulk %d: %d jobs, pipeline=%d",
                   self.worker_id, bulk_id, len(jobs),
                   self.executor.pipeline_instances)

    def _pull_next(self, bulk_id: int):
        """Ask the master for one task; returns TaskItem, 'wait', None
        (bulk over), or ('task_error', j, t, exc)."""
        if self._draining.is_set():
            return None  # drain: stop pulling, let the pipeline empty
        if self._hb_reply.get("active_bulk") != bulk_id:
            return None
        # the window covers the load+evaluate stages only: save-parked
        # tasks are released from the master's held-count by the EvalDone
        # RPC, so lagging savers can't throttle the evaluators while a
        # small window still spreads small jobs across workers
        window = (self.executor.pipeline_instances
                  + self.executor.num_load_workers)
        reply = self.master.try_call("NextWork", worker_id=self.worker_id,
                                     bulk_id=bulk_id, window=window)
        if reply is not None and not self._gen.observe(reply):
            # stale-generation assignment: NACK — never run work a
            # superseded master handed out (the live master owns the
            # task queue; a double-assignment would race its attempt)
            return "wait"
        if reply is None or reply.get("status") is None \
                or reply["status"] in ("none", "done"):
            return None
        if reply["status"] == "wait":
            return "wait"
        j, t = reply["job_idx"], reply["task_idx"]
        attempt = reply.get("attempt", 0)
        try:
            job = self._jobs[j]
            ti = TaskItem(job, t, job.tasks[t], attempt=attempt)
            # the master's assign-span context: this task's span (and
            # everything under it) chains into the job's trace
            ti.trace_ctx = _tracing.parse_traceparent(
                reply.get("traceparent"))
            return ti
        except Exception as e:  # noqa: BLE001  (job-list skew etc.)
            return ("task_error", j, t, attempt, e)

    def _pull_loop(self, bulk_id: int) -> None:
        """Drive the full multi-stage pipeline from the master's queue:
        N loaders pull+decode concurrently (decode releases the GIL), P
        evaluator instances execute, S savers persist — the reference
        worker's per-node stage threads (worker.cpp:1467-1724, 1876-1890).
        The worker keeps up to (loaders + queue depths + P) tasks in
        flight; the master's timeout clock restarts per task at
        StartedWork."""

        def source():
            if self._shutdown.is_set():
                return None
            nxt = self._pull_next(bulk_id)
            if isinstance(nxt, tuple) and nxt[0] == "task_error":
                _tag, j, t, attempt, exc = nxt
                _wlog.error("worker %d: task (%d,%d) unresolvable",
                            self.worker_id, j, t, exc_info=exc)
                self.master.try_call(
                    "FailedWork", bulk_id=bulk_id,
                    worker_id=self.worker_id, job_idx=j, task_idx=t,
                    attempt=attempt,
                    transient=_is_transient_failure(exc),
                    error=f"{type(exc).__name__}: {exc}")
                return "wait"
            return nxt

        def on_start(w) -> bool:
            # restart the master's timeout clock: evaluation of this
            # prefetched task starts now.  A revoked reply means this
            # attempt timed out in our queue and was re-assigned — drop it
            # rather than evaluate/save a stale attempt concurrently with
            # its replacement (reference stop_job_on_worker,
            # master.cpp:2111)
            reply = self.master.try_call(
                "StartedWork", bulk_id=bulk_id, worker_id=self.worker_id,
                job_idx=w.job.job_idx, task_idx=w.task_idx,
                attempt=w.attempt)
            if reply is not None and not self._gen.observe(reply):
                # a stale master's revocation verdict is not
                # authoritative: NACK it and keep the attempt running
                # (the live master still holds the assignment)
                return True
            return reply is None or bool(reply.get("ok"))

        def on_eval_done(w) -> None:
            # hand-off to the save stage: release this task from the
            # NextWork window so parked saves don't starve the evaluators
            self.master.try_call(
                "EvalDone", bulk_id=bulk_id, worker_id=self.worker_id,
                job_idx=w.job.job_idx, task_idx=w.task_idx,
                attempt=w.attempt)

        def on_done(w) -> None:
            # this task's span chain piggybacks ON FinishedWork (the
            # task span closed before on_done fired): the master holds
            # the full chain the moment the completion — which can
            # finish the bulk — lands, with no second per-task RPC
            item = dict(job_idx=w.job.job_idx, task_idx=w.task_idx,
                        attempt=w.attempt,
                        spans=self.tracer.drain_export(),
                        clock=self._clock.estimate())
            if self._links:
                # sharded mode: pool for the FinishedWorkBatch flush
                self._queue_finished(bulk_id, item)
            else:
                self.master.try_call(
                    "FinishedWork", bulk_id=bulk_id,
                    worker_id=self.worker_id, **item)

        def on_task_error(w, exc) -> bool:
            _wlog.exception("worker %d: task (%d,%d) failed",
                            self.worker_id, w.job.job_idx, w.task_idx,
                            exc_info=exc)
            self._ship_spans(bulk_id)  # the error span chain ships too
            # an OOM-failed task generated a memory report: ship it now
            # so the master holds the forensics before the requeue
            self._ship_memory_report()
            self.master.try_call(
                "FailedWork", bulk_id=bulk_id, worker_id=self.worker_id,
                job_idx=w.job.job_idx, task_idx=w.task_idx,
                attempt=w.attempt,
                # storage/RPC failures requeue strike-free on the master
                transient=_is_transient_failure(exc),
                error=f"{type(exc).__name__}: {exc}")
            return True  # keep the pipeline running

        def evaluator_factory(idx: int, skip_fetch: bool) -> TaskEvaluator:
            with self._eval_lock:
                te = self._evaluators.get(idx)
                if te is None:
                    te = TaskEvaluator(
                        self._info, self.profiler,
                        skip_fetch_resources=skip_fetch,
                        precompile=LocalExecutor.precompile_hint(
                            self._jobs or []),
                        # device affinity: reused instance idx keeps
                        # owning chip idx mod n across pipeline entries
                        instance=idx,
                        instances=self.executor.pipeline_instances)
                    self._evaluators[idx] = te
                return te

        # level >= 2: capture this node's XLA device timeline for the
        # bulk; the trace dir ships in the profile (PostProfile) and
        # Profile.write_trace merges it when readable from that host
        from ..util.jaxprof import device_trace
        with device_trace(self.profiler):
            self.executor.run_pipeline(
                self._info, source, on_start=on_start, on_done=on_done,
                on_eval_done=on_eval_done, on_task_error=on_task_error,
                evaluator_factory=evaluator_factory, close_evaluators=False,
                queue_size=self._queue_size)

    # -- gang member path (engine/gang.py) ---------------------------------

    def _next_gang(self, bulk_id: int):
        """One gang-mode NextWork pull: a role reply dict, "wait", or
        None (bulk over / draining).  A reply stamped by a stale master
        generation is NACKed exactly like an ordinary assignment — a
        superseded master must not be able to convene a gang."""
        if self._draining.is_set():
            return None
        if self._hb_reply.get("active_bulk") != bulk_id:
            return None
        reply = self.master.try_call("NextWork",
                                     worker_id=self.worker_id,
                                     bulk_id=bulk_id, window=0)
        if reply is not None and not self._gen.observe(reply):
            return "wait"
        if reply is None or reply.get("status") in (None, "none",
                                                    "done"):
            return None
        if reply["status"] == "wait":
            return "wait"
        return reply

    def _gang_loop(self, bulk_id: int) -> None:
        """Drive gang member runs from the master's formation pool:
        pull a role, run the member to completion in its own child
        process, report, repeat.  One member at a time per worker —
        a gang IS this node's unit of work."""
        while not self._shutdown.is_set():
            nxt = self._next_gang(bulk_id)
            if nxt is None:
                return
            if nxt == "wait":
                time.sleep(PING_INTERVAL / 4)
                continue
            try:
                self._run_gang_member(bulk_id, nxt)
            except Exception:  # noqa: BLE001 — a reporting failure
                # must not kill the loop while the heartbeat keeps
                # this worker looking alive
                _wlog.exception("worker %d: gang member run failed",
                                self.worker_id)
                time.sleep(PING_INTERVAL)

    def _run_gang_member(self, bulk_id: int, role: dict) -> None:
        gid, epoch = role["gang_id"], role["epoch"]
        pid = int(role["process_id"])
        task_timeout = float(role.get("task_timeout")
                             or self._task_timeout or 0.0)
        request = {
            "db_path": self._db_path,
            "storage_type": self._storage_type,
            "spec": self._spec_raw, "bulk_id": bulk_id,
            "job_idx": role["job_idx"], "task_idx": role["task_idx"],
            "attempt": role.get("attempt", 0),
            "gang_id": gid, "epoch": epoch,
            "process_id": pid,
            "num_processes": int(role["num_processes"]),
            "coordinator": role["coordinator"],
            "init_timeout": _gang.init_timeout_s(),
            "task_timeout": task_timeout,
            # evaluation mode is the MASTER's call, read off the role
            # reply verbatim — never this worker's local config
            "sharded": bool(role.get("sharded")),
            "halo": bool(role.get("halo", True)),
            "traceparent": role.get("traceparent"),
            "node": f"worker{self.worker_id}",
        }
        _wlog.info(
            "worker %d: gang %d epoch %d — member %d/%d for task "
            "(%d,%d), coordinator %s", self.worker_id, gid, epoch, pid,
            request["num_processes"], role["job_idx"],
            role["task_idx"], role["coordinator"])
        t_form = time.time()

        def gang_alive() -> bool:
            # heartbeat-fed gang liveness: only a beat provably SENT
            # after the formation may testify — its receive time must
            # clear t_form by the beat's own deadline (PING_TIMEOUT),
            # so a reply that was in flight when the gang formed (or a
            # stale reply held across a master hiccup) reads as
            # "unknown" and never reaps a healthy runner.  A fresh
            # beat whose per-worker gang list lacks this gang means it
            # was aborted underneath us — reap now instead of blocking
            # in a dead collective until the member timeout.
            if self._hb_reply_at <= t_form + PING_TIMEOUT:
                return True
            hb = self._hb_reply
            if "gangs" not in hb:
                return True  # legacy master: no liveness feed
            return gid in (hb.get("gangs") or ())

        res = _gang.spawn_member(
            request, timeout=_gang.member_timeout_s(task_timeout),
            alive=gang_alive)
        # the member child's phase seconds fold into THIS process's
        # metrics registry (the child's registry is never scraped);
        # sharded data-plane stats (shard rows, decode rows, halo
        # bytes) fold the same way
        _gang.count_phases(res.get("phases"), res.get("role"))
        _gang.count_shard_stats(res.get("shard"), res.get("role"))
        # the member's spans (task under the gang root, stages, ops)
        # came back in the result file — ship them so the gang's whole
        # story assembles under one trace on the master.  The batch
        # carries this worker's clock estimate: the child shares this
        # host's clock, so its spans rebase with the same offset.
        spans = list(res.get("spans") or ()) + self.tracer.drain_export()
        if spans:
            self.master.try_call("ShipSpans", bulk_id=bulk_id,
                                 worker_id=self.worker_id, spans=spans,
                                 clock=self._clock.estimate())
        base = dict(bulk_id=bulk_id, worker_id=self.worker_id,
                    job_idx=role["job_idx"],
                    task_idx=role["task_idx"],
                    attempt=role.get("attempt", 0),
                    gang_id=gid, epoch=epoch)
        if res.get("ok"):
            # single-writer completion: member 0 carries the gang's
            # FinishedWork — with the collective digest total and the
            # per-member shard digests it assembled from (sharded runs)
            # for the master's shard commit fold; everyone else acks,
            # the ack extended to carry its own shard digest
            if pid == 0:
                reply = self.master.try_call(
                    "FinishedWork", **base,
                    digest=res.get("digest"),
                    shard_digests=res.get("shard_digests"))
            else:
                reply = self.master.try_call(
                    "GangMemberDone", **base,
                    shard_digest=res.get("shard_digest"))
            if reply is not None and self._gen.observe(reply) \
                    and reply.get("gang_stale"):
                _wlog.warning(
                    "worker %d: gang %d epoch %d completion NACKed as "
                    "stale — the gang re-formed underneath this "
                    "member", self.worker_id, gid, epoch)
        else:
            _wlog.warning(
                "worker %d: gang %d epoch %d member %d failed at %s: "
                "%s", self.worker_id, gid, epoch, pid,
                res.get("stage"), res.get("error"))
            self.master.try_call(
                "GangFailed", **base,
                stage=res.get("stage", "member"),
                transient=bool(res.get("transient", True)),
                error=str(res.get("error", "")))

    def wait_for_shutdown(self) -> None:
        while not self._shutdown.is_set():
            time.sleep(0.2)
        self.stop()

    def stop(self) -> None:
        self._shutdown.set()
        self._server.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()
            self.metrics_server = None
        with self._eval_lock:
            for te in self._evaluators.values():
                te.close()
            self._evaluators = {}
        if self._links:
            for link in self._links.values():
                link.close()  # the active link IS self.master
            self._links = {}
        else:
            self.master.close()


# ---------------------------------------------------------------------------
# Client side
# ---------------------------------------------------------------------------

class ClusterClient:
    """Submits bulk jobs to a master and polls progress
    (reference Client.run gRPC path + _start_heartbeat, client.py:324).

    Against a sharded control plane the given address is just the SEED:
    the client resolves the versioned shard map from it (GetShardMap,
    lazily, cached), routes each admission to the shard the token
    hashes to — stamping the map's epoch so a stale map is NACKed
    instead of silently routing past a failover — and fans
    metrics/health/status reads in across every shard."""

    def __init__(self, master_address: str, db: Database,
                 enable_watchdog: bool = False, poll_interval: float = 0.25,
                 master_down_timeout: float = 120.0, **_kw):
        self.db = db
        self._master_address = master_address
        self.master = rpc.RpcClient(master_address, MASTER_SERVICE)
        self.poll_interval = poll_interval
        self._last_refresh = time.time()
        # sharded control plane: the resolved map (None = unsharded,
        # the overwhelmingly common case), per-shard channels keyed by
        # shard id, and the shard the last run() admitted to (its
        # GetJobStatus poll goes there, as does Client.trace's pull)
        self._smap: Optional[_shardmap.ShardMap] = None
        self._smap_resolved = False
        self._shard_clients: Dict[int, rpc.RpcClient] = {}
        self._last_shard: Optional[int] = None
        # how long GetJobStatus may fail continuously before the client
        # gives up — long enough to ride out a master restart (it recovers
        # the bulk from its checkpoint), short enough that a dead master
        # raises instead of hanging the caller forever
        self.master_down_timeout = master_down_timeout
        # bulk id of the most recent run() (Client.trace maps its job id
        # to the master-side bulk through this), and the admission
        # token it was admitted under (NewJob dedupe across retries
        # and master restarts)
        self.last_bulk_id: Optional[int] = None
        self.last_admission_token: Optional[str] = None
        self._watchdog_stop = threading.Event()
        if enable_watchdog:
            t = threading.Thread(target=self._poke_loop, daemon=True)
            t.start()

    def _poke_loop(self) -> None:
        while not self._watchdog_stop.is_set():
            self.master.try_call("PokeWatchdog")
            time.sleep(5.0)

    def _refresh_channel(self) -> None:
        """Replace the master channel with a freshly dialed one (other
        threads pick the new client up on their next call; in-flight
        calls on the closed channel surface as transport failures
        try_call already tolerates)."""
        self._last_refresh = time.time()
        old, self.master = self.master, rpc.RpcClient(
            self._master_address, MASTER_SERVICE)
        old.close()

    # -- sharded control plane (engine/shardmap.py) --------------------

    def _resolve_shard_map(self, force: bool = False) \
            -> Optional[_shardmap.ShardMap]:
        """The cluster's shard map, or None (unsharded).  Resolved
        lazily via GetShardMap — every shard serves it; an unsharded
        master answers num_shards=1, which caches as None — and
        re-resolved on force (a stale-map NACK, a wedged shard)."""
        if self._smap_resolved and not force:
            return self._smap
        reply = self.master.try_call("GetShardMap", timeout=5.0)
        if reply is None and self._smap is not None:
            # the seed shard may be the dead one: any shard serves
            # the map, so ask the rest before giving up
            for sid in self._smap.shard_ids():
                c = self._shard_clients.get(sid)
                if c is None:
                    continue
                reply = c.try_call("GetShardMap", timeout=5.0)
                if reply:
                    break
        if reply is None:
            return self._smap  # unreachable: keep what we have
        self._smap_resolved = True
        if int(reply.get("num_shards", 1) or 1) <= 1 \
                or not reply.get("shards"):
            self._smap = None
        else:
            smap = _shardmap.ShardMap(
                epoch=int(reply.get("epoch", 0)),
                shards={int(k): v
                        for k, v in reply["shards"].items()},
                num_shards=int(reply["num_shards"]))
            if self._smap is None or smap.epoch >= self._smap.epoch:
                self._smap = smap
        return self._smap

    def _shard_client(self, sid: Optional[int]) -> rpc.RpcClient:
        """The channel for one shard (the seed channel doubles as its
        own shard's); dials on first use, re-dials when the map moved
        the shard's address (failover respawn)."""
        smap = self._smap
        addr = smap.address_of(sid) if (smap and sid is not None) \
            else None
        if addr is None or addr == self._master_address:
            return self.master
        c = self._shard_clients.get(sid)
        if c is None or c.address != addr:
            if c is not None:
                c.close()
            c = rpc.RpcClient(addr, MASTER_SERVICE)
            self._shard_clients[sid] = c
        return c

    def _redial_shard(self, sid: Optional[int]) -> None:
        """Fresh channel to one shard (the wedged-channel pathology —
        see _refresh_channel), re-resolving the map first so a
        failover respawn's re-published address is what gets dialed."""
        self._resolve_shard_map(force=True)
        self._last_refresh = time.time()
        if sid is None or self._smap is None:
            self._refresh_channel()
            return
        addr = self._smap.address_of(sid)
        if addr is None or addr == self._master_address:
            self._refresh_channel()
            return
        old = self._shard_clients.pop(sid, None)
        if old is not None:
            old.close()
        self._shard_clients[sid] = rpc.RpcClient(addr, MASTER_SERVICE)

    def run(self, outputs, perf: PerfParams, cache_mode: CacheMode,
            show_progress: bool) -> List[Profiler]:
        import uuid

        from ..util.retry import retry_until_deadline
        spec = cloudpickle.dumps({
            "outputs": list(outputs), "perf": perf,
            "cache_mode": cache_mode.value})
        # client-minted admission token: the master dedupes on it, so
        # NewJob becomes safe to repeat end-to-end — a retry after an
        # ambiguous timeout, or against the SUCCESSOR of a restarted
        # master (tokens ride the checkpoint/journal), returns the
        # already-admitted bulk id instead of double-running the bulk
        token = uuid.uuid4().hex
        self.last_admission_token = token
        # load shedding (admission_pause remediation playbook): a
        # paused master answers retryable instead of admitting onto a
        # backpressured cluster — back off and retry until it resumes,
        # bounded by the same deadline a dead master gets.  Transport
        # failures (a master mid-restart) ride the same deadline: the
        # token makes the repeat safe.
        admit_deadline = time.time() + self.master_down_timeout
        admit_fails = [0]
        # sharded routing: the token hashes to its owning shard, and
        # the admission carries the map epoch it routed with — a
        # master holding a newer map NACKs it (stale_map) and we
        # refresh + re-route instead of mutating past a failover
        smap = self._resolve_shard_map()
        route = {"shard": smap.shard_for(token) if smap else None}

        def _admit() -> dict:
            cli = self._shard_client(route["shard"])
            kwargs = {}
            if self._smap is not None:
                kwargs["map_epoch"] = self._smap.epoch
            try:
                return cli.call("NewJob", spec=spec, token=token,
                                timeout=120.0, **kwargs)
            except rpc.RpcError:
                # the wedged-channel pathology (see _refresh_channel):
                # a channel whose peer died mid-dial can stay stuck
                # past the successor's return — redial fresh every few
                # failed admission attempts, like the status poll does
                admit_fails[0] += 1
                if admit_fails[0] % 8 == 0:
                    if route["shard"] is not None:
                        self._redial_shard(route["shard"])
                        nm = self._smap
                        if nm is not None:
                            route["shard"] = nm.shard_for(token)
                    else:
                        self._refresh_channel()
                raise

        while True:
            reply = retry_until_deadline(
                _admit,
                is_transient=lambda e: isinstance(e, rpc.RpcError),
                deadline=admit_deadline, label="rpc:NewJob:admission")
            if reply.get("admission_paused") \
                    and time.time() < admit_deadline:
                time.sleep(float(reply.get("retry_after") or 1.0))
                continue
            if reply.get("stale_map") \
                    and time.time() < admit_deadline:
                # the map moved underneath this admission (a shard
                # failed over): refresh, re-route, re-present — the
                # token dedupes if the first attempt actually landed
                self._resolve_shard_map(force=True)
                if self._smap is not None:
                    route["shard"] = self._smap.shard_for(token)
                continue
            break
        if "error" in reply:
            raise JobException(reply["error"])
        self._last_shard = route["shard"]
        poll = self._shard_client(route["shard"])
        bulk_id = reply["bulk_id"]
        self.last_bulk_id = bulk_id
        last_ok = time.time()
        retoken_tried = False
        while True:
            # try_call: a master restarting mid-bulk (it recovers the job
            # from its checkpoint) must look like slow progress, not a
            # client-visible failure — but a master that stays dead past
            # master_down_timeout raises instead of hanging forever.
            # Sharded: the poll goes to the ADMITTING shard — re-looked
            # up each pass, so a redial's fresh channel is picked up
            poll = self._shard_client(route["shard"])
            st = poll.try_call("GetJobStatus", bulk_id=bulk_id)
            if st is None:
                now = time.time()
                if now - last_ok > self.master_down_timeout:
                    raise JobException(
                        f"master unreachable for "
                        f"{self.master_down_timeout:.0f}s while waiting "
                        f"on bulk {bulk_id}")
                if now - last_ok > 10.0 \
                        and now - self._last_refresh > 10.0:
                    # a channel whose peer died mid-dial can wedge past
                    # the restart (see rpc.wait_for_server): redial the
                    # restarted/successor master on a FRESH channel
                    if route["shard"] is not None:
                        self._redial_shard(route["shard"])
                    else:
                        self._refresh_channel()
                time.sleep(self.poll_interval)
                continue
            last_ok = time.time()
            if "tasks_done" not in st:
                # the master came back without this bulk under the id
                # we knew: re-present the admission token ONCE — a
                # successor that recovered the bulk (or renumbered it)
                # hands its id back via the dedupe path, and polling
                # resumes; only a truly lost bulk surfaces as an error
                if not retoken_tried:
                    retoken_tried = True
                    # resolve=True: a lookup-only probe — an unknown
                    # token answers unknown_token instead of admitting
                    # a fresh bulk this client would then abandon
                    reply = poll.try_call(
                        "NewJob", spec=spec, token=token, resolve=True,
                        timeout=120.0)
                    if reply and reply.get("dedup") \
                            and reply.get("bulk_id") is not None:
                        bulk_id = reply["bulk_id"]
                        self.last_bulk_id = bulk_id
                        continue
                raise JobException(st.get("error", "bulk job lost"))
            if show_progress:
                # same numbers as /statusz (GetJobStatus is the single
                # source of truth for job progress)
                fps = (st.get("stage_fps") or {}).get("save")
                eta = st.get("eta_seconds")
                extra = ""
                if fps:
                    extra += f" {fps:.0f} rows/s"
                if eta is not None:
                    extra += f" eta {eta:.0f}s"
                print(f"\rtasks {st['tasks_done']}/{st['total_tasks']} "
                      f"workers={st['num_workers']}{extra}",
                      end="", flush=True)
            if st.get("finished"):
                if show_progress:
                    print()
                self.db.refresh_meta()
                if st.get("error"):
                    raise JobException(st["error"])
                if st.get("failed_jobs"):
                    raise JobException(
                        f"jobs failed: {st['failed_jobs']}")
                # workers post profiles right after their last task; give
                # them a beat, then collect what arrived
                time.sleep(2 * self.poll_interval)
                reply = poll.try_call("GetProfiles",
                                      bulk_id=bulk_id) or {}
                return [Profiler.from_dict(d)
                        for d in reply.get("profiles", [])]
            time.sleep(self.poll_interval)

    def metrics(self) -> dict:
        """Cluster-wide merged metrics snapshot (master + every live
        worker, node-labeled) via the master's GetMetrics RPC.
        Sharded: fanned in across every shard — each shard's master
        samples relabel to shard<k>, and the worker fan-out rides ONE
        shard only (every shard sees the same fleet; pulling workers M
        times would skew the merged counters M-fold)."""
        smap = self._resolve_shard_map()
        if smap is None:
            reply = self.master.call("GetMetrics", timeout=30.0)
            return reply["snapshot"]
        sids = smap.shard_ids()
        primary = sids[0] if sids else 0
        by_node: Dict[str, dict] = {}
        for sid in sids:
            reply = self._shard_client(sid).try_call(
                "GetMetrics", timeout=30.0, workers=(sid == primary))
            if not reply or "snapshot" not in reply:
                continue  # a dead shard drops out of the merged view
            snap = reply["snapshot"]
            for entry in snap.values():
                for s in entry.get("samples", []):
                    lb = s.get("labels") or {}
                    if lb.get("node") == "master":
                        s["labels"] = dict(lb, node=f"shard{sid}")
            by_node[f"shard{sid}"] = snap
        # inner node labels (shard<k>/worker<i>) win over the outer
        # key in merge_snapshots, which is exactly what we want here
        return merge_snapshots(by_node)

    def job_status(self, bulk_id: Optional[int] = None) -> dict:
        """Progress of one bulk.  Sharded: asks the admitting shard
        first, then the rest — the bulk lives on exactly one shard."""
        smap = self._resolve_shard_map()
        if smap is None:
            return self.master.call("GetJobStatus", bulk_id=bulk_id)
        order = smap.shard_ids()
        if self._last_shard in order:
            order = [self._last_shard] + \
                [s for s in order if s != self._last_shard]
        best: Optional[dict] = None
        for sid in order:
            st = self._shard_client(sid).try_call("GetJobStatus",
                                                  bulk_id=bulk_id)
            if st and "tasks_done" in st:
                return st
            if st and best is None:
                best = st
        if best is not None:
            return best
        return self.master.call("GetJobStatus", bulk_id=bulk_id)

    def health(self) -> dict:
        """Cluster-wide health roll-up (GetHealth RPC): worst-of status
        across master + every live worker, node-prefixed reason codes,
        and each node's firing alerts.  Sharded: every shard's roll-up
        folds in (worst-of again, shard<k>-prefixed) — an unreachable
        shard reports unhealthy rather than silently vanishing."""
        smap = self._resolve_shard_map()
        if smap is None:
            return self.master.call("GetHealth", timeout=30.0)
        sids = smap.shard_ids()
        primary = sids[0] if sids else 0
        nodes: Dict[str, dict] = {}
        for sid in sids:
            reply = self._shard_client(sid).try_call(
                "GetHealth", timeout=30.0, workers=(sid == primary))
            nodes[f"shard{sid}"] = reply if reply else {
                "status": "unhealthy",
                "reasons": ["shard_unreachable"], "firing": []}
        return _health.merge_status(nodes)

    def get_trace(self, bulk_id: Optional[int] = None,
                  raw_clocks: bool = False) -> dict:
        """The master-assembled cross-host trace of a bulk: span dicts
        from every node plus the straggler summary (GetTrace RPC).
        Remote spans arrive rebased onto master time per node clock
        offset unless raw_clocks=True."""
        # sharded: the trace lives with the bulk, on the admitting shard
        return self._shard_client(self._last_shard).call(
            "GetTrace", bulk_id=bulk_id, raw_clocks=raw_clocks)

    def memory_report(self) -> dict:
        """Cluster memory forensics (GetMemoryReport RPC): the master's
        live HBM/ledger view plus every OOM report workers shipped."""
        return self.master.call("GetMemoryReport")

    def compile_report(self) -> dict:
        """Cluster compile ledger + roofline table (GetCompileLedger
        RPC): per-node XLA compile entries with cache hit/miss labels
        and the per-(op, device, bucket) efficiency table."""
        return self.master.call("GetCompileLedger", timeout=30.0)

    def ship_spans(self, bulk_id: int, spans: List[dict]) -> None:
        """Contribute client-side spans (the job's root) to the
        master's assembled trace, so GetTrace dumps are self-contained
        — a scanner_trace --verify of the bulk walks every task chain
        to the root without needing this process.  Best-effort."""
        if spans:
            self._shard_client(self._last_shard).try_call(
                "ShipSpans", bulk_id=bulk_id, spans=spans)

    def shutdown_cluster(self, workers: bool = True) -> int:
        """Stop the master — and, by default, every registered worker —
        via the Shutdown RPC (the counterpart of blocking
        start_master/start_worker deployments, whose wait_for_shutdown
        loops exit on it).  Returns how many workers acknowledged.
        Sharded: every shard gets the Shutdown (workers notified once,
        through the first shard — re-notifying is harmless but slow)."""
        smap = self._resolve_shard_map()
        if smap is None:
            reply = self.master.call("Shutdown", workers=workers,
                                     timeout=30.0)
            return int(reply.get("workers_notified", 0))
        notified = 0
        notify = workers
        for sid in smap.shard_ids():
            reply = self._shard_client(sid).try_call(
                "Shutdown", workers=notify, timeout=30.0)
            if reply:
                notified += int(reply.get("workers_notified", 0))
                notify = False
        return notified

    def close(self) -> None:
        self._watchdog_stop.set()
        for c in self._shard_clients.values():
            c.close()
        self._shard_clients = {}
        self.master.close()


# ---------------------------------------------------------------------------
# Process entry points (reference scannerpy start_master/start_worker,
# client.py:1593/1651, tests/spawn_worker.py)
# ---------------------------------------------------------------------------

def start_master(db_path: str, port: int = 5000, block: bool = False,
                 **kw) -> Master:
    m = Master(db_path=db_path, port=port, **kw)
    if block:
        m.wait_for_shutdown()
    return m


def start_worker(master_address: str, db_path: str, port: int = 0,
                 block: bool = False, **kw) -> Worker:
    w = Worker(master_address, db_path=db_path, port=port, **kw)
    if block:
        # SIGTERM = drain (kubernetes pod termination, deploy.py sizes
        # terminationGracePeriod for it): finish in-flight tasks, stop
        # pulling, deregister — then wait_for_shutdown returns and the
        # process exits 0 instead of dying mid-task
        import signal

        def _sigterm(_signum, _frame):
            w.drain()

        try:
            signal.signal(signal.SIGTERM, _sigterm)
        except ValueError:
            pass  # not the main thread: the embedder owns signals
        w.wait_for_shutdown()
    return w
