"""Durable control plane: write-ahead bulk journal + master generation
fencing (docs/robustness.md §Durable control plane).

Two halves, both built on the storage backend the cluster already
shares (no new dependency, works on posix/GCS/memory alike):

**Write-ahead bulk journal** (`BulkJournal`) — between periodic
checkpoints the master appends every task-completion / strike /
blacklist / commit / admission / gang-lifecycle event as a checksummed
record into
rotated segment objects under the master's generation directory
(`jobs/g<gen>/journal/seg_*.bin`).  A completion is acknowledged to
the worker only after its record is durable, so a `kill -9` mid-bulk
loses **zero** acknowledged completions — recovery is checkpoint +
journal replay instead of a lossy checkpoint window.  Replay is
idempotent (done-sets union, failure counts carry their cumulative
value) so a record that raced a snapshot can be applied twice safely,
and a torn tail record — a crash mid-append on a non-atomic backend —
is tolerated: the complete prefix replays, the tail is dropped with a
warning.  Each checkpoint `cut()`s the journal and deletes the
segments the snapshot covers (compaction), bounding replay work to one
checkpoint window.

**Generation fencing** — a master claims a monotonic generation at
startup via `write_exclusive` CAS on a per-generation marker object
(`claim_generation`; exactly one concurrent claimant wins any given
generation).  Checkpoint/journal paths are generation-scoped, so a
paused-then-resumed stale master's late writes land in a directory its
successor never reads; every mutating control RPC reply is stamped
with the serving master's generation, workers latch the highest
generation they have seen (`GenerationLatch`) and NACK
assignments/revocations stamped with an older one, and a master that
observes a newer claim fences itself (mutating RPCs answer
`{"fenced": True}`, persistence stops).

Kill switch: ``SCANNER_TPU_JOURNAL=0`` / ``[robustness]
journal_enabled`` restores the pre-journal (checkpoint-window)
recovery; fencing is always on (one storage CAS at master startup).
``SCANNER_TPU_MASTER_GENERATION`` attaches a master at a forced
generation WITHOUT claiming — the stale-master lever chaos drills use;
never set it in production.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..common import StorageException
from ..storage import metadata as md
from ..storage.backend import StorageBackend
from ..storage.items import (ItemCorruptionError, checksum_blob,
                             open_blob, verify_blob_checksum)
from ..util import metrics as _mx
from ..util.log import get_logger

_log = get_logger("journal")

# the [robustness] config keys this module accepts (scanner-check SC312
# keeps config.default_config() and this tuple in sync, both ways)
CONFIG_KEYS = ("journal_enabled", "journal_rotate_records")

# admission tokens the master remembers for NewJob dedupe (bounded: a
# token outlives its bulk only until 64 newer admissions displaced it)
TOKEN_RING = 64

# record types the master's recovery replay understands (engine/
# service.py _apply_journal_records).  The gang pair journals gang-in-
# flight state — `gang` at formation, `gang_abort` at teardown — whose
# replay restores the (gang_id, epoch) fence's high-water mark across
# a master failover: the successor's first formation mints a strictly
# higher epoch, so a pre-failover gang's late completion NACKs instead
# of double-committing (docs/robustness.md §Gang scheduling).
RECORD_TYPES = ("admit", "done", "strike", "transient", "blacklist",
                "commit", "gang", "gang_abort")


def gang_epoch_high_water(records) -> int:
    """Highest gang epoch any journaled gang record carries (0 when
    none) — the floor a recovering master's next formation must mint
    above.  Tooling/test twin of the in-recovery fold."""
    high = 0
    for r in records:
        if isinstance(r, dict) and r.get("t") in ("gang", "gang_abort"):
            high = max(high, int(r.get("e", 0) or 0))
    return high

_M_GENERATION = _mx.registry().gauge(
    "scanner_tpu_master_generation",
    "The monotonic master generation this process claimed (or attached "
    "to) at startup — the fencing epoch every mutating control RPC is "
    "stamped with (docs/robustness.md §Durable control plane).")
_M_APPENDS = _mx.registry().counter(
    "scanner_tpu_journal_appends_total",
    "Records appended to the write-ahead bulk journal (task "
    "completions, strikes, blacklists, commits, admissions).")
_M_BYTES = _mx.registry().counter(
    "scanner_tpu_journal_bytes_total",
    "Encoded bytes appended to the write-ahead bulk journal.")
_M_REPLAYED = _mx.registry().counter(
    "scanner_tpu_journal_replayed_records_total",
    "Journal records replayed over the checkpoint during bulk "
    "recovery — completions a plain checkpoint-window restart would "
    "have lost and re-executed.")
_M_STALE = _mx.registry().counter(
    "scanner_tpu_stale_master_rejections_total",
    "Mutations rejected on generation-fence grounds: side=worker "
    "counts stale-generation master replies a worker NACKed, "
    "side=master counts mutating RPCs a fenced (superseded) master "
    "refused.", labels=["side"])


def _flag(v: Optional[str], default: bool) -> bool:
    if v is None or v == "":
        return default
    return v.strip().lower() not in ("0", "false", "no", "off")


_enabled = _flag(os.environ.get("SCANNER_TPU_JOURNAL"), True)
_rotate_records = int(
    os.environ.get("SCANNER_TPU_JOURNAL_ROTATE", "") or 256)


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Deployment default ([robustness] journal_enabled); the
    SCANNER_TPU_JOURNAL env var is read at import and wins."""
    global _enabled
    _enabled = bool(on)


def rotate_records() -> int:
    return _rotate_records


def set_rotate_records(n: int) -> None:
    global _rotate_records
    _rotate_records = max(1, int(n))


# ---------------------------------------------------------------------------
# master generation claims (CAS on the storage backend)
# ---------------------------------------------------------------------------

def try_claim(backend: StorageBackend, gen: int,
              note: str = "", shard: int = 0) -> bool:
    """Atomically claim one specific generation: True for exactly one
    concurrent claimant (write_exclusive CAS), False for the rest.
    Claims are scoped per control-plane shard (storage/metadata.py
    shard_prefix) — shards fence independently."""
    payload = md.pack({"generation": gen, "pid": os.getpid(),
                       "time": time.time(), "note": note,
                       "shard": int(shard)})
    return backend.write_exclusive(md.generation_path(gen, shard),
                                   payload)


def claimed_generations(backend: StorageBackend,
                        shard: int = 0) -> List[int]:
    out = []
    for p in backend.list_prefix(md.generation_prefix(shard)):
        base = p.rsplit("/", 1)[-1]
        try:
            out.append(int(base.split(".")[0]))
        except ValueError:
            continue
    return sorted(out)


def highest_claimed(backend: StorageBackend, shard: int = 0) -> int:
    gens = claimed_generations(backend, shard)
    return gens[-1] if gens else 0


def claim_generation(backend: StorageBackend, note: str = "",
                     shard: int = 0) -> int:
    """Claim the next free generation (monotonic; a successor always
    outranks every predecessor on the same db + shard).  The
    SCANNER_TPU_MASTER_GENERATION env var attaches at a forced
    generation WITHOUT claiming — the stale-master chaos lever."""
    forced = os.environ.get("SCANNER_TPU_MASTER_GENERATION")
    if forced:
        gen = int(forced)
        _log.warning("attached at forced master generation %d (no "
                     "claim; SCANNER_TPU_MASTER_GENERATION)", gen)
        _M_GENERATION.set(gen)
        return gen
    gen = highest_claimed(backend, shard)
    while True:
        gen += 1
        if try_claim(backend, gen, note=note, shard=shard):
            _M_GENERATION.set(gen)
            _log.info("claimed master generation %d (shard %d)",
                      gen, shard)
            return gen
        # lost the CAS race for this generation: someone else is also
        # starting up; take the next slot (latest claim outranks)


class GenerationLatch:
    """Worker-side fence: latch the highest master generation seen on
    any reply; a reply stamped with an older generation is a stale
    master's — its assignments/revocations are NACKed."""

    def __init__(self) -> None:
        self._highest = 0
        self._lock = threading.Lock()

    def highest(self) -> int:
        with self._lock:
            return self._highest

    def observe(self, reply: Optional[dict]) -> bool:
        """True when the reply may be acted on; False (counted) when it
        came from a stale (superseded) master generation.  Replies
        with no generation stamp (legacy masters) always pass."""
        if not isinstance(reply, dict):
            return True
        gen = reply.get("generation")
        if gen is None:
            return True
        gen = int(gen)
        with self._lock:
            if gen >= self._highest:
                self._highest = gen
                return True
        _M_STALE.labels(side="worker").inc()
        _log.warning("NACKing reply from stale master generation %d "
                     "(highest seen: %d)", gen, self.highest())
        return False


# ---------------------------------------------------------------------------
# record framing
# ---------------------------------------------------------------------------

# per-record frame: payload length, checksum-algorithm version (the
# items.py crc32c/zlib marker), crc over the payload.  Records carry
# their own checksum so a torn tail is detected per record, not per
# segment.
_REC_HDR = struct.Struct("<III")


def encode_record(rec: dict) -> bytes:
    payload = md.pack(rec)
    version, crc = checksum_blob(payload)
    return _REC_HDR.pack(len(payload), version, crc) + payload


def decode_segment(data: bytes, path: str = "",
                   tolerate_tail: bool = True
                   ) -> Tuple[List[dict], Optional[str]]:
    """Parse one segment's records.  Returns (records, problem): a torn
    tail record (truncated frame, or a checksum failure on the FINAL
    record while tolerate_tail) yields the complete prefix with
    problem="torn"; a mid-stream corruption stops parsing with
    problem="corrupt" (records after it are unknowable)."""
    out: List[dict] = []
    off = 0
    n = len(data)
    while off < n:
        if n - off < _REC_HDR.size:
            return out, "torn"
        length, version, crc = _REC_HDR.unpack_from(data, off)
        start = off + _REC_HDR.size
        if n - start < length:
            return out, "torn"
        payload = data[start:start + length]
        try:
            verify_blob_checksum(version, crc, payload, path)
            rec = md.unpack(payload)
        except ItemCorruptionError:
            # checksum failure on the very last record = a torn tail
            # in disguise (partial overwrite); anywhere else = real
            # corruption — stop, later records' framing is untrusted
            if tolerate_tail and start + length >= n:
                return out, "torn"
            return out, "corrupt"
        except Exception:  # noqa: BLE001 — undecodable payload
            return out, "corrupt"
        if isinstance(rec, dict):
            out.append(rec)
        off = start + length
    return out, None


# ---------------------------------------------------------------------------
# the write-ahead bulk journal
# ---------------------------------------------------------------------------

class BulkJournal:
    """Rotated, checksummed event segments for the active bulk.

    The storage backends are whole-blob stores (no append primitive),
    so the open segment is rewritten atomically on every append —
    bounded by `rotate_records`, after which the segment seals and a
    new one opens.  `append()` is durable on return: callers ack their
    RPC only after it."""

    def __init__(self, backend: StorageBackend, generation: int,
                 rotate: Optional[int] = None, shard: int = 0):
        self.backend = backend
        self.generation = generation
        self.shard = int(shard)
        self.rotate = int(rotate or rotate_records())
        self._lock = threading.Lock()
        self._seg = 0
        self._buf: List[bytes] = []
        # third-party backends may predate the sync= kwarg: probe once
        import inspect
        try:
            self._sync_kw = "sync" in inspect.signature(
                backend.write).parameters
        except (TypeError, ValueError):
            self._sync_kw = False

    def append(self, *records: dict) -> None:
        """Durably append records (group-committed under one write)."""
        if not records:
            return
        encoded = [encode_record(r) for r in records]
        with self._lock:
            self._buf.extend(encoded)
            path = md.journal_segment_path(self.generation, self._seg,
                                           self.shard)
            # group-commit serialization by design: concurrent
            # appenders queue on this lock and each write carries every
            # record buffered so far; the open segment must be
            # rewritten whole for the frame sequence to stay parseable.
            # sync=False: process-kill durability only needs the page
            # cache, and the frame format tolerates the torn tail a
            # machine crash could leave — one fsync per acknowledged
            # completion would dominate master throughput otherwise.
            blob = b"".join(self._buf)
            if self._sync_kw:
                self.backend.write(path, blob, sync=False)  # scanner-check: disable=SC202 group-commit WAL write; appenders must serialize on the open segment
            else:
                self.backend.write(path, blob)  # scanner-check: disable=SC202 group-commit WAL write (legacy backend, no sync=)
            _M_APPENDS.inc(len(encoded))
            _M_BYTES.inc(sum(len(e) for e in encoded))
            if len(self._buf) >= self.rotate:
                self._seg += 1
                self._buf = []

    def cut(self) -> int:
        """Seal the open segment; every record appended before this
        call lives in a segment below the returned index, every record
        appended after it lands at or above.  Call while holding the
        state lock the journaled mutations happen under — then a
        snapshot taken at the same point covers exactly the sealed
        segments, and `compact_below(cut)` is safe."""
        with self._lock:
            if self._buf:
                self._seg += 1
                self._buf = []
            return self._seg

    def compact_below(self, seg: int) -> None:
        """Delete sealed segments a checkpoint now covers."""
        for path in self.backend.list_prefix(
                md.journal_dir(self.generation, self.shard)):
            base = path.rsplit("/", 1)[-1]
            try:
                idx = int(base.split("_")[-1].split(".")[0])
            except ValueError:
                continue
            if idx < seg:
                self.backend.delete(path)

    def reset(self) -> None:
        """Start over for a new bulk: drop every segment of this
        generation and rewind to segment 0."""
        with self._lock:
            self.backend.delete_prefix(  # scanner-check: disable=SC202 bulk boundary only (admission/clear), not a hot path
                md.journal_dir(self.generation, self.shard))
            self._seg = 0
            self._buf = []


def replay(backend: StorageBackend, generation: int, shard: int = 0
           ) -> Tuple[List[dict], Dict[str, int]]:
    """Read every surviving record of one generation's journal, in
    order.  A torn tail on the final segment is tolerated (warned); a
    mid-journal corruption stops replay there at ERROR — the prefix is
    still applied, everything after it is unknowable."""
    paths = sorted(backend.list_prefix(md.journal_dir(generation,
                                                      shard)))
    records: List[dict] = []
    stats = {"segments": len(paths), "records": 0, "torn": 0,
             "corrupt": 0}
    for i, path in enumerate(paths):
        last = i == len(paths) - 1
        data = backend.read(path)
        recs, problem = decode_segment(data, path=path,
                                       tolerate_tail=last)
        records.extend(recs)
        if problem == "torn":
            stats["torn"] += 1
            if last:
                _log.warning(
                    "journal %s has a torn tail record: replaying the "
                    "%d complete records before it", path, len(recs))
            else:
                # a truncated NON-final segment means later segments'
                # records may depend on lost ones — same verdict as
                # corruption
                stats["corrupt"] += 1
                _log.error(
                    "journal %s is truncated mid-stream: stopping "
                    "replay at %d records", path, len(records))
                break
        elif problem == "corrupt":
            stats["corrupt"] += 1
            _log.error(
                "journal %s has a corrupt record: stopping replay at "
                "%d records (later records are untrusted)", path,
                len(records))
            break
    stats["records"] = len(records)
    if records:
        _M_REPLAYED.inc(len(records))
    return records, stats


def read_control_blob(backend: StorageBackend, path: str,
                      what: str = "control blob") -> Optional[bytes]:
    """Read a (possibly legacy-unsealed) control-plane blob.  Returns
    its payload, or None — logged at ERROR — when the blob fails its
    checksum: callers fall back to journal replay instead of silently
    resurrecting garbage.  The one shared seal/legacy/corruption
    policy for the master's recovery AND tooling/tests."""
    if not backend.exists(path):
        return None
    raw = backend.read(path)
    try:
        return open_blob(raw, path)
    except ItemCorruptionError:
        _log.error("%s at %s failed its checksum: falling back to "
                   "journal replay", what, path)
        return None
    except StorageException:
        # no sealed-blob magic: a legacy (pre-checksum) write
        return raw


def load_bulk_progress(backend: StorageBackend,
                       shard: int = 0) -> Optional[dict]:
    """The newest generation's persisted bulk-progress snapshot
    (crc-verified; legacy unsealed files still load), or None.  A
    tooling/test helper — the master's own recovery path lives in
    engine/service.py."""
    import cloudpickle

    gens = sorted(claimed_generations(backend, shard), reverse=True)
    for g in gens + [None]:
        payload = read_control_blob(backend,
                                    md.bulk_progress_path(g, shard),
                                    what="bulk progress")
        if payload is None:
            continue
        try:
            return cloudpickle.loads(payload)
        except Exception:  # noqa: BLE001 — undecodable snapshot
            continue
    return None


def count_stale_rejection(side: str) -> None:
    """Shared counter hook for fence rejections (side=master|worker)."""
    _M_STALE.labels(side=side).inc()


def set_generation_gauge(gen: int) -> None:
    _M_GENERATION.set(gen)
