"""Ulysses-style sequence parallelism: all-to-all head-sharded attention.

The complement of ring attention (`ring_attention.py`) for sequences
sharded across devices (the reference has neither — SURVEY §5: its
long-context machinery is stencil/warmup/slice scheduling; attention
enters with this framework's model kernels).  Where the ring rotates K/V
blocks around the `sp` axis (n steps of neighbor ICI traffic, memory
O(T/n)), Ulysses re-shards ONCE: an all-to-all converts the layout from
time-sharded/full-heads to head-sharded/full-time, each device runs
plain full attention for its head group, and a reverse all-to-all
restores the time sharding (DeepSpeed Ulysses, Jacobs et al. 2023).

Trade-offs, mapped to TPU:
* two all-to-alls per call (ICI-friendly single collective each) vs the
  ring's n ppermute steps — fewer, larger transfers;
* requires heads % axis_size == 0 and materializes the full (T, T)
  attention for H/n heads — the right regime is moderate T with spare
  head parallelism; ring wins at extreme T.

Both share the (B, T, H, D) contract and in/out shardings, so model code
(`TemporalBlock(attn_fn=...)`) can swap them freely.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P
from ..util.jaxenv import axis_size as _axis_size
from ..util.jaxenv import shard_map

from .ring_attention import reference_attention


def _ulysses_block(q, k, v, axis_name: str, causal: bool,
                   scale: Optional[float]):
    """Local computation: q,k,v are (B, Tl, H, D) time-blocks of a
    sequence sharded over axis_name."""
    n = _axis_size(axis_name)
    H = q.shape[2]
    if H % n:
        raise ValueError(
            f"ulysses attention needs heads ({H}) divisible by the "
            f"'{axis_name}' axis size ({n}); use ring attention otherwise")

    def to_heads(x):
        # (B, Tl, H, D) -> (B, T, H/n, D): give away head groups, gather
        # every device's time block — one tiled all-to-all over ICI
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    # full-T plain attention on the local head group — shared math with
    # the single-device path so masking/scaling can never diverge
    out = reference_attention(to_heads(q), to_heads(k), to_heads(v),
                              causal=causal, scale=scale)
    # reverse all-to-all: hand back time blocks, regather all heads
    return jax.lax.all_to_all(out, axis_name, split_axis=1,
                              concat_axis=2, tiled=True)


def make_ulysses_attention(mesh: Mesh, axis: str = "sp",
                           causal: bool = False,
                           scale: Optional[float] = None):
    """Returns attn(q, k, v) over (B, T, H, D) arrays with T sharded on
    `axis` — the same contract as make_ring_attention, interchangeable in
    TemporalBlock(attn_fn=...)."""
    fn = functools.partial(_ulysses_block, axis_name=axis, causal=causal,
                           scale=scale)
    return shard_map(fn, mesh=mesh,
                     in_specs=(P(None, axis), P(None, axis), P(None, axis)),
                     out_specs=P(None, axis))
