"""In-program pipeline parallelism (the 'pp' mesh axis).

The engine's task pipeline already gives *inter*-node pipelining
(SURVEY §2.6 strategy 2); this module adds the in-program counterpart for
models whose repeated trunk is too large for one chip's HBM: a GPipe-style
microbatch schedule laid out TPU-natively —

* stage parameters live stacked on a leading axis sharded over 'pp'
  (each pp rank holds exactly its stage — the HBM win),
* a `lax.scan` runs the M + S - 1 schedule steps; every step each rank
  applies its stage and hands its activation to the next rank with a
  single `ppermute` hop over ICI (neighbor traffic only, no all-to-all),
* bubble steps compute on clamped inputs and are masked out of the
  output, so their cotangents are zero and `jax.grad` through the scan +
  ppermute yields exact pipeline-parallel gradients with no custom VJP.

Composes with 'dp' (batch stays sharded across the pipeline).  The stage
function must be collective-free (tp/sp belong inside a stage only via
nested meshes); shapes are static and the schedule is a fixed-length scan
— nothing here blocks XLA from overlapping the ppermute with the next
step's compute.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence

import jax
import jax.numpy as jnp
from ..util.jaxenv import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(params_list: Sequence[Any]):
    """Stack S per-stage parameter pytrees into one tree whose leaves have
    a leading stage axis (the axis `make_pipeline` shards over 'pp').
    All stages must share a structure (same module repeated)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *params_list)


def make_pipeline(mesh: Mesh, stage_fn: Callable[[Any, Any], Any],
                  num_microbatches: int, axis: str = "pp"):
    """Build `pipe(stacked_params, x) -> y` running `stage_fn`
    sequentially across the mesh's `axis` ranks with a microbatched
    GPipe schedule.

    stage_fn(stage_params, x) must map (mb, ...) -> (mb, ...) with an
    unchanged shape/dtype (a repeated trunk block).  x is (B, ...) with B
    sharded over 'dp' and divisible by num_microbatches on every dp
    shard; the result equals stage_{S-1}(... stage_0(x)) and is
    replicated over `axis`.
    """
    S = int(mesh.shape[axis])
    M = int(num_microbatches)
    if M < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {M}")

    def local_fn(stacked_local, x_loc):
        # each rank's shard of the stacked params is its own stage
        p_loc = jax.tree_util.tree_map(lambda a: a[0], stacked_local)
        rank = jax.lax.axis_index(axis)
        b = x_loc.shape[0]
        if b % M:
            raise ValueError(
                f"per-shard batch {b} not divisible by "
                f"num_microbatches {M}")
        mb = b // M
        xm = x_loc.reshape((M, mb) + x_loc.shape[1:])
        out0 = jnp.zeros_like(xm)
        buf0 = jnp.zeros_like(xm[0])

        def step(carry, t):
            buf, out = carry
            # stage 0 injects microbatch t (clamped in bubble steps);
            # later stages consume the shuttle buffer
            x_in = jnp.where(
                rank == 0,
                jax.lax.dynamic_index_in_dim(
                    xm, jnp.clip(t, 0, M - 1), 0, keepdims=False),
                buf)
            y = stage_fn(p_loc, x_in)
            # neighbor hop stage i -> i+1; rank 0's recv slot gets zeros
            # (never read: rank 0 always takes xm)
            buf_next = jax.lax.ppermute(
                y, axis, [(i, i + 1) for i in range(S - 1)])
            # the last stage retires microbatch t-(S-1) when it's real;
            # clamped writes are masked so bubbles never clobber output
            oidx = t - (S - 1)
            valid = (rank == S - 1) & (oidx >= 0)
            oclamped = jnp.clip(oidx, 0, M - 1)
            cur = jax.lax.dynamic_index_in_dim(out, oclamped, 0,
                                               keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(valid, y, cur), oclamped, 0)
            return (buf_next, out), None

        (_, out), _ = jax.lax.scan(step, (buf0, out0),
                                   jnp.arange(M + S - 1))
        # results live on the last rank; psum of the masked value
        # replicates them over the pipeline axis
        out = jax.lax.psum(
            jnp.where(rank == S - 1, out, jnp.zeros_like(out)), axis)
        return out.reshape(x_loc.shape)

    def full_spec(leaf, lead_axis):
        return P(*((lead_axis,) + (None,) * (leaf.ndim - 1)))

    def pipe(stacked_params, x):
        for leaf in jax.tree_util.tree_leaves(stacked_params):
            if leaf.shape[0] != S:
                raise ValueError(
                    f"stacked stage axis has {leaf.shape[0]} stages but "
                    f"mesh axis '{axis}' has {S} ranks; they must match "
                    f"(each rank runs exactly one stage)")
        in_specs = (
            jax.tree_util.tree_map(lambda a: full_spec(a, axis),
                                   stacked_params),
            full_spec(x, "dp"),
        )
        fn = shard_map(local_fn, mesh=mesh, in_specs=in_specs,
                       out_specs=full_spec(x, "dp"), check_vma=False)
        return fn(stacked_params, x)

    return pipe
