from .mesh import AXIS_ORDER, auto_axes, make_mesh, shard_batch, sharding
from .halo import sharded_stencil_map, temporal_diff
from .pp import make_pipeline, stack_stage_params
from .ring_attention import make_ring_attention, reference_attention
from .ulysses import make_ulysses_attention
from .distributed import (CoordinatorConfig, host_local_array,
                          initialize, is_initialized, replicate_to_global)

__all__ = [
    "AXIS_ORDER", "auto_axes", "make_mesh", "shard_batch", "sharding",
    "sharded_stencil_map", "temporal_diff", "make_pipeline",
    "stack_stage_params", "make_ring_attention",
    "make_ulysses_attention", "reference_attention",
    "CoordinatorConfig", "host_local_array", "initialize",
    "is_initialized", "replicate_to_global",
]
