"""Ring attention: exact attention over sequences sharded across devices.

The reference has no in-engine attention (SURVEY §5: "no ring attention /
Ulysses — no tensor compute exists in-engine"); its long-context machinery is
stencil/warmup/slice scheduling.  The TPU build adds model kernels, so
long-sequence attention becomes first-class: K/V blocks rotate around the
`sp` mesh axis via jax.lax.ppermute (ICI neighbor exchange) while each
device keeps flash-style online-softmax accumulators for its local queries —
memory O(T/n) per device, exact results (Liu et al., Ring Attention with
Blockwise Transformers).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ..util.jaxenv import axis_size as _axis_size
from ..util.jaxenv import pvary as _pvary
from ..util.jaxenv import shard_map

# single source of truth: the pallas kernel's masked-row guards compare
# the m carry this module initializes against the same sentinel
from ..kernels.pallas_attention import HAVE_PALLAS, NEG_INF


def _flash_block_k(tl: int, block_k: Optional[int]) -> int:
    """Largest divisor of the local block length ≤ the requested tile."""
    if block_k is not None and block_k < 1:
        raise ValueError(f"block_k must be >= 1, got {block_k}")
    want = min(tl, block_k or 512)
    while tl % want:
        want -= 1
    return want


def _ring_attention_block(q, k, v, axis_name: str, causal: bool,
                          scale: Optional[float],
                          block_k: Optional[int] = None):
    """Local computation: q,k,v are (B, Tl, H, D) blocks of a sequence
    sharded over axis_name.

    Flash-style tiling inside the ring rotation: each arriving K/V block
    is consumed in `block_k`-wide tiles, so the logits intermediate is
    (B, H, Tl, block_k) instead of (B, Tl, Tl) per step — the long-T
    memory bound that makes ring attention worthwhile in the first
    place."""
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    s = scale if scale is not None else (D ** -0.5)
    qf = q.astype(jnp.float32) * s
    bk = _flash_block_k(Tl, block_k)
    n_tiles = Tl // bk

    # accumulators: running max m, normalizer l, weighted value sum acc.
    # pcast marks them device-varying over the ring axis so the fori_loop
    # carry types match (shard_map vma tracking).
    vary = lambda x: _pvary(x, (axis_name,))
    m0 = vary(jnp.full((B, H, Tl), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((B, H, Tl), jnp.float32))
    acc0 = vary(jnp.zeros((B, H, Tl, D), jnp.float32))

    q_pos = idx * Tl + jnp.arange(Tl)

    def tile_update(m, l, acc, ks, vs, k_pos):
        """Online-softmax update for one (B, bk, H, D) K/V tile."""
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, ks.astype(jnp.float32))
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        # guard fully-masked rows (m_new == NEG_INF) against NaNs
        m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(logits - m_safe[..., None])
        p = jnp.where(jnp.isfinite(logits), p, 0.0)
        correction = jnp.where(m <= NEG_INF / 2, 0.0,
                               jnp.exp(m - m_safe))
        l_new = l * correction + p.sum(axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, vs.astype(jnp.float32))
        return m_new, l_new, acc_new

    def step(i, carry):
        m, l, acc, kb, vb = carry
        # the block arriving at step i originated on device (idx + i) % n
        src = (idx + i) % n
        # double-buffer: issue the rotation FIRST — the tile loop only
        # reads the current buffers, so XLA can run the ICI transfer
        # concurrently with this step's compute
        perm = [(j, (j - 1) % n) for j in range(n)]
        kb_next = jax.lax.ppermute(kb, axis_name, perm)
        vb_next = jax.lax.ppermute(vb, axis_name, perm)

        def tile(j, inner):
            m, l, acc = inner
            ks = jax.lax.dynamic_slice_in_dim(kb, j * bk, bk, axis=1)
            vs = jax.lax.dynamic_slice_in_dim(vb, j * bk, bk, axis=1)
            k_pos = src * Tl + j * bk + jnp.arange(bk)
            return tile_update(m, l, acc, ks, vs, k_pos)

        m, l, acc = jax.lax.fori_loop(0, n_tiles, tile, (m, l, acc))
        return m, l, acc, kb_next, vb_next

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, step, (m0, l0, acc0, k, v))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # (B,Tl,H,D)


def _ring_attention_block_pallas(q, k, v, axis_name: str, causal: bool,
                                 scale: Optional[float],
                                 block_q: Optional[int] = None,
                                 block_k: Optional[int] = None,
                                 interpret: bool = False):
    """Pallas variant of the local ring step: each arriving K/V block is
    consumed by ONE fused flash kernel (kernels/pallas_attention.py) —
    logits stay in VMEM, the online-softmax update fuses with both MXU
    matmuls.  Exactness is identical to the XLA path."""
    from ..kernels.pallas_attention import flash_block_update
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Tl, H, D = q.shape
    s = scale if scale is not None else (D ** -0.5)
    # (B, Tl, H, D) -> (B*H, Tl, D): per-head rows for the kernel grid
    qf = jnp.transpose(q.astype(jnp.float32) * s, (0, 2, 1, 3)) \
        .reshape(B * H, Tl, D)

    vary = lambda x: _pvary(x, (axis_name,))
    m0 = vary(jnp.full((B * H, Tl), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((B * H, Tl), jnp.float32))
    acc0 = vary(jnp.zeros((B * H, Tl, D), jnp.float32))
    q_off = idx * Tl
    bq = block_q or 256
    bk = block_k or 256

    # the ring is unrolled (n is a static mesh size): each iteration is
    # one pallas call + one ppermute, and unrolling sidesteps a jax
    # lowering-cache bug with interpret-mode pallas inside fori_loop
    m, l, acc, kb, vb = m0, l0, acc0, k, v
    perm = [(j, (j - 1) % n) for j in range(n)]
    for i in range(n):
        src = (idx + i) % n
        kb_next = jax.lax.ppermute(kb, axis_name, perm) if i < n - 1 \
            else kb
        vb_next = jax.lax.ppermute(vb, axis_name, perm) if i < n - 1 \
            else vb
        kf = jnp.transpose(kb, (0, 2, 1, 3)).reshape(B * H, Tl, D)
        vf = jnp.transpose(vb, (0, 2, 1, 3)).reshape(B * H, Tl, D)
        m, l, acc = flash_block_update(
            qf, kf, vf, m, l, acc, q_off, src * Tl, causal=causal,
            block_q=bq, block_k=bk, interpret=interpret,
            vma=(axis_name,))
        kb, vb = kb_next, vb_next
    out = acc / jnp.maximum(l[..., None], 1e-30)
    out = out.reshape(B, H, Tl, D)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # (B,Tl,H,D)


def make_ring_attention(mesh: Mesh, axis: str = "sp", causal: bool = False,
                        scale: Optional[float] = None,
                        block_k: Optional[int] = None,
                        impl: str = "xla",
                        block_q: Optional[int] = None,
                        interpret: Optional[bool] = None):
    """Returns attn(q, k, v) over arrays (B, T, H, D) with T sharded on
    `axis` (batch replicated or dp-sharded orthogonally).  `block_k`
    bounds the flash tile width (default 512, clipped to the local
    block).

    impl="pallas" runs each ring step through the fused pallas flash
    kernel (forward only — the backward pass recomputes through the XLA
    path via custom_vjp, so gradients work identically); its tiles
    default to 256x256 (`block_q`/`block_k`), clipped to divisors of the
    local block.  `interpret` defaults to auto: native on TPU,
    interpreter elsewhere (tests)."""
    fn = functools.partial(_ring_attention_block, axis_name=axis,
                           causal=causal, scale=scale, block_k=block_k)
    specs = dict(in_specs=(P(None, axis), P(None, axis), P(None, axis)),
                 out_specs=P(None, axis))
    xla_sm = shard_map(fn, mesh=mesh, **specs)
    if impl == "xla":
        return xla_sm
    if impl != "pallas":
        raise ValueError(f"impl must be 'xla' or 'pallas', got {impl!r}")
    if not HAVE_PALLAS:
        raise RuntimeError(
            "impl='pallas' requires jax.experimental.pallas, which this "
            "jax build lacks; use impl='xla'")
    for name, b in (("block_q", block_q), ("block_k", block_k)):
        if b is not None and b < 1:
            raise ValueError(f"{name} must be >= 1, got {b}")
    if interpret is None:
        try:
            interpret = jax.devices()[0].platform != "tpu"
        except Exception:  # pragma: no cover
            interpret = True
    pfn = functools.partial(_ring_attention_block_pallas, axis_name=axis,
                            causal=causal, scale=scale, block_q=block_q,
                            block_k=block_k, interpret=interpret)
    # check_vma=False: the pallas interpreter's internal dynamic_slices
    # don't propagate varying-axis types (jax asks for exactly this
    # workaround in its error); the XLA path keeps full vma checking
    pal_sm = shard_map(pfn, mesh=mesh, check_vma=False, **specs)

    @jax.custom_vjp
    def attn(q, k, v):
        return pal_sm(q, k, v)

    def fwd(q, k, v):
        return pal_sm(q, k, v), (q, k, v)

    def bwd(res, g):
        _, vjp = jax.vjp(xla_sm, *res)
        return vjp(g)

    attn.defvjp(fwd, bwd)
    return attn


def reference_attention(q, k, v, causal: bool = False,
                        scale: Optional[float] = None):
    """Single-device exact attention for testing ring equivalence."""
    B, T, H, D = q.shape
    s = scale if scale is not None else (D ** -0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * s,
                        k.astype(jnp.float32))
    if causal:
        mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)
