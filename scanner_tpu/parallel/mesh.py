"""Device mesh and sharding helpers.

The reference scales by sharding (job, task) lists over worker processes
(SURVEY §2.6); the TPU build adds in-program parallelism: a job's kernel can
itself be a multi-chip XLA program laid out over a jax Mesh, with XLA
inserting ICI collectives.  These helpers standardize mesh construction and
axis conventions across the framework:

    dp — data/batch parallel        sp — sequence/context parallel
    tp — tensor/model parallel      pp — in-program pipeline parallel
                                         (parallel/pp.py; the engine's
                                         task pipeline covers the
                                         inter-node case)
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("dp", "sp", "tp")


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh over `devices` (default: all) with the given axis
    sizes; missing axes get size 1, and a single unconstrained axis absorbs
    the remaining device count.  Optional axes — 'pp' (pipeline stages,
    parallel/pp.py) and 'ep' (MoE expert parallelism; expert tensors and
    their per-expert compute shard over it, models/pose.py
    param_shardings) — are appended only when requested so existing
    dp/sp/tp meshes keep their rank."""
    if devices is None:
        devices = jax.devices()
    axes = dict(axes or {})
    order = AXIS_ORDER + tuple(a for a in ("pp", "ep") if a in axes)
    unknown = set(axes) - set(order)
    if unknown:
        raise ValueError(
            f"unknown mesh axes {sorted(unknown)}; valid: {order}")
    sizes = [axes.get(a, 0) for a in order]
    known = [s for s in sizes if s > 0]
    prod = math.prod(known) if known else 1
    if 0 not in sizes and prod <= len(devices):
        # fully specified: use a prefix of the device list
        devices = list(devices)[:prod]
    n = len(devices)
    if 0 in sizes:
        rem = n // prod
        if prod * rem != n:
            raise ValueError(
                f"cannot factor {n} devices into axes {axes}")
        # the first unspecified axis absorbs the remainder; others get 1
        seen_unknown = False
        fixed = []
        for s in sizes:
            if s > 0:
                fixed.append(s)
            elif not seen_unknown:
                fixed.append(rem)
                seen_unknown = True
            else:
                fixed.append(1)
        sizes = fixed
    if math.prod(sizes) != n:
        raise ValueError(
            f"mesh axes {dict(zip(order, sizes))} need "
            f"{math.prod(sizes)} devices, have {n}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, order)


def host_mesh(num_processes: int,
              devices: Optional[Sequence] = None) -> Mesh:
    """The gang mesh: a ("hosts", "local") Mesh whose row p is process
    p's local device slice.  Built by every gang member over the GLOBAL
    device set after jax.distributed rendezvous; host_local_array
    staging and the gang's collectives (digest reduction, output-shard
    all-gather, halo exchange — engine/gang.py) all key off the "hosts"
    axis.  Requires the device count to divide evenly across processes
    (jax guarantees this for homogeneous hosts)."""
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    num = int(num_processes)
    if num <= 0 or devices.size % num:
        raise ValueError(
            f"cannot split {devices.size} devices over {num} hosts")
    return Mesh(devices.reshape(num, devices.size // num),
                ("hosts", "local"))


def auto_axes(n: int) -> Dict[str, int]:
    """Factor n devices into a balanced (dp, sp, tp) assignment."""
    def split(x):
        f = int(math.sqrt(x))
        while x % f:
            f -= 1
        return f, x // f
    a, rest = split(n)
    b, c = split(rest)
    return {"dp": a, "sp": b, "tp": c}


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def shard_batch(mesh: Mesh, arr, axis: str = "dp"):
    """Place a host array with its leading dim sharded over one mesh axis."""
    return jax.device_put(arr, sharding(mesh, axis))
