"""Multi-host device meshes (jax.distributed).

The reference scales across nodes with one worker process per node and
NCCL/MPI underneath (scanner/engine/worker.cpp:484 topology,
master.cpp:1558-1607 task sharding).  The TPU equivalent is JAX's
multi-process runtime: every host runs the same program, calls
`jax.distributed.initialize`, and sees the GLOBAL device set; meshes built
over `jax.devices()` then span hosts, and XLA routes collectives over
ICI/DCN automatically.  Engine workers opt in via the `coordinator=`
config (engine/service.py Worker), making a pod slice's hosts one logical
accelerator for in-program dp/sp/tp sharding while the task engine keeps
distributing (job, task) work units between programs.

Order matters: `initialize()` must run before the first JAX backend touch
in the process.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

from ..common import ScannerException


@dataclass
class CoordinatorConfig:
    """Multi-process JAX runtime wiring for one engine worker/host.

    address: "host:port" of process 0's coordinator service.
    num_processes: total participating processes (hosts).
    process_id: this process's rank in [0, num_processes).
    local_device_ids: optional explicit local device ids (rarely needed;
        TPU runtimes discover their local chips).
    """

    address: str
    num_processes: int
    process_id: int
    local_device_ids: Optional[Sequence[int]] = None


_init_config: Optional[CoordinatorConfig] = None


def initialize(config: CoordinatorConfig,
               init_timeout: Optional[float] = None) -> None:
    """Join the multi-process JAX runtime (idempotent per process for the
    SAME config; a different config after initialization is an error, not
    a silent no-op).

    Must be called before any jax.devices()/computation in this process;
    afterwards `jax.devices()` is the global device list and
    `jax.local_devices()` this host's slice.  Meshes built by
    `make_mesh()` then span all hosts.
    """
    global _init_config
    if _init_config is not None:
        if _init_config != config:
            raise ScannerException(
                f"jax.distributed already initialized with {_init_config}; "
                f"cannot re-initialize with {config}")
        return
    import jax

    kwargs = {}
    if config.local_device_ids is not None:
        kwargs["local_device_ids"] = list(config.local_device_ids)
    if init_timeout is not None:
        kwargs["initialization_timeout"] = int(init_timeout)
    try:
        jax.distributed.initialize(
            coordinator_address=config.address,
            num_processes=config.num_processes,
            process_id=config.process_id,
            **kwargs)
    except RuntimeError as e:
        raise ScannerException(
            f"jax.distributed.initialize failed for "
            f"process {config.process_id}/{config.num_processes} at "
            f"{config.address}: {e}") from e
    _init_config = config


def is_initialized() -> bool:
    return _init_config is not None


def host_local_array(mesh, spec, local_data):
    """Assemble a global jax.Array from THIS process's shard of the data.

    `local_data` is the numpy block this host contributes (its slice along
    the sharded axes); the result is a global array laid out per `spec`
    over `mesh`.  The per-host data-feeding primitive for input pipelines
    (each engine worker decodes only its own rows).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if not isinstance(spec, PartitionSpec):
        spec = PartitionSpec(*spec)
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), local_data)


def replicate_to_global(mesh, spec, full_data):
    """Place an identical host array (present on every process) as a global
    sharded array — convenient for params/targets in tests and small
    inputs.  Every process must pass the same `full_data`."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if not isinstance(spec, PartitionSpec):
        spec = PartitionSpec(*spec)
    return jax.device_put(full_data, NamedSharding(mesh, spec))
