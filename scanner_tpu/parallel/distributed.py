"""Multi-host device meshes (jax.distributed).

The reference scales across nodes with one worker process per node and
NCCL/MPI underneath (scanner/engine/worker.cpp:484 topology,
master.cpp:1558-1607 task sharding).  The TPU equivalent is JAX's
multi-process runtime: every host runs the same program, calls
`jax.distributed.initialize`, and sees the GLOBAL device set; meshes built
over `jax.devices()` then span hosts, and XLA routes collectives over
ICI/DCN automatically.  Engine workers opt in via the `coordinator=`
config (engine/service.py Worker), making a pod slice's hosts one logical
accelerator for in-program dp/sp/tp sharding while the task engine keeps
distributing (job, task) work units between programs.  Gang-scheduled
tasks (engine/gang.py) rendezvous here too — one short-lived runtime per
gang epoch, with `shutdown()` tearing the latch down between epochs so a
surviving member can re-form at a NEW coordinator.

Order matters: `initialize()` must run before the first JAX backend touch
in the process.

Failure classification: a rendezvous that does not complete raises
`RendezvousError` — the engine treats it as TRANSIENT (the peer set
changed under us: a member died, a coordinator moved), so the task
requeues strike-free instead of striking a healthy job
(engine/service.py `_is_transient_failure`).
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

from ..common import ScannerException

# default bound on how long initialize() may block in the rendezvous
# when the caller passes no explicit timeout: an unbounded default
# would let one lost peer pin every survivor in
# jax.distributed.initialize forever.  300 s matches jax's own default
# — long-lived pod-slice workers (Worker(coordinator=...), whose hosts
# can legitimately come up minutes apart during a node-pool scale-up)
# keep their full budget; gang members pass the much tighter
# [gang] init_timeout_s per gang instead (engine/gang.py).
DEFAULT_INIT_TIMEOUT_S = 300.0


class RendezvousError(ScannerException):
    """Joining (or re-joining) the multi-process runtime failed: the
    coordinator is unreachable, a peer never arrived, or the bounded
    initialization timeout elapsed.  Classified transient by the engine
    — the gang re-forms on the remaining capacity, no blacklist
    strike."""


@dataclass
class CoordinatorConfig:
    """Multi-process JAX runtime wiring for one engine worker/host.

    address: "host:port" of process 0's coordinator service.
    num_processes: total participating processes (hosts).
    process_id: this process's rank in [0, num_processes).
    local_device_ids: optional explicit local device ids (rarely needed;
        TPU runtimes discover their local chips).
    """

    address: str
    num_processes: int
    process_id: int
    local_device_ids: Optional[Sequence[int]] = None


_init_config: Optional[CoordinatorConfig] = None


def initialize(config: CoordinatorConfig,
               init_timeout: Optional[float] = None) -> None:
    """Join the multi-process JAX runtime (idempotent per process for the
    SAME config; a different config while initialized is an error, not a
    silent no-op — call `shutdown()` first to re-form at a new
    coordinator).

    Must be called before any jax.devices()/computation in this process;
    afterwards `jax.devices()` is the global device list and
    `jax.local_devices()` this host's slice.  Meshes built by
    `make_mesh()` then span all hosts.

    `init_timeout` bounds the rendezvous; None applies
    DEFAULT_INIT_TIMEOUT_S — never unbounded, so one lost peer cannot
    pin the survivors in initialize forever.  A failed or timed-out
    rendezvous raises `RendezvousError` (transient to the engine).
    """
    global _init_config
    if _init_config is not None:
        if _init_config != config:
            raise ScannerException(
                f"jax.distributed already initialized with {_init_config}; "
                f"cannot re-initialize with {config} — call shutdown() "
                f"first to rendezvous at a new coordinator")
        return
    import jax

    # CPU-backend runs (tests, dryruns, chaos drills) need the gloo
    # collectives client or every cross-process computation fails with
    # "Multiprocess computations aren't implemented on the CPU
    # backend".  Selected only when the process is pinned to CPU
    # (JAX_PLATFORMS, as force_cpu_platform/cpu_only_env set) — TPU
    # runtimes keep their native ICI/DCN collectives.
    plats = (os.environ.get("JAX_PLATFORMS") or "").lower()
    if "cpu" in [p.strip() for p in plats.split(",")]:
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:  # noqa: BLE001 — older/newer jax without
            pass           # the flag: keep the default behavior

    kwargs = {}
    if config.local_device_ids is not None:
        kwargs["local_device_ids"] = list(config.local_device_ids)
    if init_timeout is None:
        init_timeout = DEFAULT_INIT_TIMEOUT_S
    kwargs["initialization_timeout"] = int(init_timeout)
    # timestamped rendezvous events onto the caller's current span
    # (engine/gang.py runs this under its gang.rendezvous span):
    # connect -> initialized brackets the actual coordinator wait, so
    # a slow member's join cost is readable off the merged timeline
    from ..util import tracing as _tracing
    _tracing.add_event("rendezvous.connect", address=config.address,
                       process_id=config.process_id,
                       num_processes=config.num_processes)
    try:
        jax.distributed.initialize(
            coordinator_address=config.address,
            num_processes=config.num_processes,
            process_id=config.process_id,
            **kwargs)
    except Exception as e:  # noqa: BLE001 — jax surfaces rendezvous
        # failure as RuntimeError and timeouts as XlaRuntimeError
        # (DEADLINE_EXCEEDED) depending on version; both are the same
        # transient peer-set failure to the engine
        _tracing.add_event("rendezvous.failed",
                           error=f"{type(e).__name__}")
        raise RendezvousError(
            f"jax.distributed.initialize failed for "
            f"process {config.process_id}/{config.num_processes} at "
            f"{config.address}: {e}") from e
    _tracing.add_event("rendezvous.initialized",
                       process_id=config.process_id)
    _init_config = config


def shutdown() -> None:
    """Leave the multi-process runtime and RESET the re-init latch.

    Before this existed, `_init_config` was set once per process and any
    different config raised forever — a surviving gang member could
    never rendezvous at a new coordinator after its gang aborted.  Now
    the distributed client shuts down cleanly, the latch resets, and a
    follow-up `initialize()` with a NEW config (new coordinator, new
    num_processes) is legal.  Backend handles built over the old global
    device set are cleared best-effort; gang members avoid the issue
    entirely by running one process per epoch (engine/gang.py).
    Idempotent; never raises."""
    global _init_config
    if _init_config is None:
        return
    try:
        import jax
        jax.distributed.shutdown()
    except Exception:  # noqa: BLE001 — a dead coordinator must not
        pass           # wedge the teardown path
    try:
        import jax
        # drop cached backends so a later initialize() rebuilds the
        # global device view for the NEW process set (deprecated alias
        # on some versions; best-effort either way)
        jax.clear_backends()
    except Exception:  # noqa: BLE001
        pass
    _init_config = None


def is_initialized() -> bool:
    return _init_config is not None


def current_config() -> Optional[CoordinatorConfig]:
    """The config this process is initialized with, or None."""
    return _init_config


def ceil_chunk(n_rows: int, num_shards: int) -> int:
    """Rows per shard under the ceil-chunk layout (the uneven-staging
    unit: every shard holds `chunk` rows except a short or empty tail)."""
    if num_shards <= 0:
        raise ScannerException(f"num_shards must be > 0, got {num_shards}")
    return -(-max(int(n_rows), 0) // num_shards) if n_rows > 0 else 0


def shard_rows(n_rows: int, rank: int, num_shards: int) -> tuple:
    """Contiguous row shard [lo, hi) of rank `rank` under the ceil-chunk
    layout: equal `ceil(n/num)` chunks with the remainder on the LAST
    non-empty shard (tail shards may be empty).  This is the one row
    layout shared by `shard_range` on the data plane (engine/gang.py)
    and the uneven `host_local_array` staging below — data decoded per
    this split stages with zero re-indexing."""
    chunk = ceil_chunk(n_rows, num_shards)
    lo = min(rank * chunk, n_rows)
    hi = min((rank + 1) * chunk, n_rows)
    return lo, hi


def host_local_array(mesh, spec, local_data, global_rows: Optional[int]
                     = None):
    """Assemble a global jax.Array from THIS process's shard of the data.

    `local_data` is the numpy block this host contributes (its slice along
    the sharded axes); the result is a global array laid out per `spec`
    over `mesh`.  The per-host data-feeding primitive for input pipelines
    (each engine worker decodes only its own rows).

    `global_rows` engages the UNEVEN staging path for row counts not
    divisible by the host axis (the last-shard-remainder case
    `shard_rows` produces): each host passes only its own rows —
    possibly fewer than a full chunk, possibly zero — and the function
    zero-pads every host block to `ceil_chunk` rows so XLA sees an
    evenly divisible global array of `num_hosts * chunk` rows.  Callers
    slice logical rows back out after any gather (`all_gather_rows`
    does this for you); zero padding is also identity-safe under the
    digest-sum collectives.  Requires the LEADING dim sharded over a
    single mesh axis (the gang "hosts" layout).
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    if not isinstance(spec, PartitionSpec):
        spec = PartitionSpec(*spec)
    if global_rows is None:
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), local_data)
    axis = spec[0] if len(spec) else None
    if not isinstance(axis, str):
        raise ScannerException(
            "uneven host_local_array staging requires the leading dim "
            f"sharded over one named mesh axis, got spec {spec}")
    num = int(mesh.shape[axis])
    chunk = ceil_chunk(int(global_rows), num)
    local_data = np.asarray(local_data)
    if len(local_data) > chunk:
        raise ScannerException(
            f"host block of {len(local_data)} rows exceeds the "
            f"ceil-chunk of {chunk} ({global_rows} rows over {num} "
            f"'{axis}' shards)")
    padded = np.zeros((chunk,) + local_data.shape[1:], local_data.dtype)
    if len(local_data):
        padded[:len(local_data)] = local_data
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), padded)


@functools.lru_cache(maxsize=32)
def _replicated_identity(mesh):
    """The jitted replicate-everything identity for one mesh.  Cached on
    the mesh: rebuilding the jit per call keys jax's compile cache on a
    fresh lambda every time, so each gather re-traces — a ~100ms-1s tax
    per collective instead of a one-time compile."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.jit(lambda a: a,
                   out_shardings=NamedSharding(mesh, PartitionSpec()))


def all_gather_rows(mesh, axis: str, local_block,
                    global_rows: Optional[int] = None):
    """All-gather per-host row blocks into one full host ndarray on
    EVERY process: stage this host's block via `host_local_array`
    (uneven-aware when `global_rows` is passed) and run one jitted
    identity whose output sharding is fully replicated — XLA lowers the
    resharding to an all-gather over ICI/DCN (gloo on CPU runs).  The
    transport primitive sharded gang members assemble their output
    shards through (engine/gang.py)."""
    import jax
    import numpy as np

    arr = host_local_array(mesh, (axis,), local_block,
                           global_rows=global_rows)
    rep = _replicated_identity(mesh)(arr)
    out = np.asarray(jax.device_get(rep))
    return out[:global_rows] if global_rows is not None else out


def replicate_to_global(mesh, spec, full_data):
    """Place an identical host array (present on every process) as a global
    sharded array — convenient for params/targets in tests and small
    inputs.  Every process must pass the same `full_data`."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if not isinstance(spec, PartitionSpec):
        spec = PartitionSpec(*spec)
    return jax.device_put(full_data, NamedSharding(mesh, spec))
