"""Stencil halo exchange over a device mesh.

Capability parity: the reference's stencil scheduling gives each task the
extra boundary rows its temporal window needs (derive_stencil_requirements,
dag_analysis.cpp:1328; REPEAT_EDGE boundary).  When a sliced stream is
instead mapped across TPU devices (sequence sharding), the same boundary
rows move as a **halo exchange between neighbor shards over ICI** — a pair
of jax.lax.ppermute shifts, exactly the blockwise/ring neighbor pattern
(SURVEY §5 long-context plan).
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..util.jaxenv import axis_size as _axis_size
from ..util.jaxenv import shard_map


def _halo_exchange_block(x: jnp.ndarray, lo: int, hi: int,
                         axis_name: str) -> jnp.ndarray:
    """Inside shard_map: extend the local block of a sequence-sharded array
    with `lo` trailing rows of the left neighbor and `hi` leading rows of
    the right neighbor.  Edge shards repeat their own edge (REPEAT_EDGE,
    matching the engine's stencil boundary)."""
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    parts = []
    if lo > 0:
        left = jax.lax.ppermute(x[-lo:], axis_name,
                                [(i, (i + 1) % n) for i in range(n)])
        # shard 0 has no left neighbor: repeat its own first rows
        edge = jnp.repeat(x[:1], lo, axis=0)
        parts.append(jnp.where(idx == 0, edge, left))
    parts.append(x)
    if hi > 0:
        right = jax.lax.ppermute(x[:hi], axis_name,
                                 [(i, (i - 1) % n) for i in range(n)])
        edge = jnp.repeat(x[-1:], hi, axis=0)
        parts.append(jnp.where(idx == n - 1, edge, right))
    return jnp.concatenate(parts, axis=0)


def sharded_stencil_map(fn: Callable, stencil: Sequence[int],
                        mesh: Mesh, axis: str = "sp"):
    """Lift a per-window function to a sequence-sharded array.

    fn(window_block) maps a block of shape (m + lo + hi, ...) to outputs
    (m, ...) where lo = -min(stencil), hi = max(stencil); the returned
    callable takes the full sequence sharded over `axis` and computes every
    output row with neighbor halos exchanged over ICI.
    """
    lo = max(0, -min(stencil))
    hi = max(0, max(stencil))
    n = mesh.shape[axis]

    def local(x):
        padded = _halo_exchange_block(x, lo, hi, axis)
        return fn(padded)

    mapped = shard_map(local, mesh=mesh, in_specs=P(axis),
                       out_specs=P(axis))

    def wrapper(x):
        block = x.shape[0] // n
        if max(lo, hi) > block:
            raise ValueError(
                f"stencil halo ({lo},{hi}) exceeds the per-shard block of "
                f"{block} rows ({x.shape[0]} rows over {n} '{axis}' shards);"
                f" multi-hop halos are not supported — use fewer shards or "
                f"a narrower stencil")
        return mapped(x)

    return wrapper


@functools.lru_cache(maxsize=32)
def _mapped_halo(mesh: Mesh, lo: int, hi: int, axis: str):
    """The compiled ppermute pair for one (mesh, halo extent) geometry.
    Cached on the MESH, not per call: rebuilding the shard_map closure
    every exchange defeats jax's compile cache (it keys on function
    identity) and re-traces a fresh XLA program per task — ~1s of
    compile inside the gang's stage phase instead of a ~ms collective."""
    return jax.jit(shard_map(
        functools.partial(_halo_exchange_block, lo=lo, hi=hi,
                          axis_name=axis),
        mesh=mesh, in_specs=P(axis), out_specs=P(axis)))


def warm_halo_exchange(mesh: Mesh, shape, dtype, lo: int, hi: int,
                       axis: str = "hosts") -> None:
    """Run one throwaway exchange on zeros of the real block geometry so
    the trace/compile (and the mesh's first-collective channel setup)
    happens OUTSIDE any timed region.  SPMD: every process in the mesh
    must call this together, with identical arguments."""
    import numpy as np

    exchange_row_halo(mesh, np.zeros(shape, dtype), lo, hi, axis)


def exchange_row_halo(mesh: Mesh, local_block, lo: int, hi: int,
                      axis: str = "hosts"):
    """Exchange boundary rows of a host-sharded row block between
    neighbor processes and return (left_halo, right_halo) as host
    ndarrays — THIS process's view of its neighbors' edges.

    `local_block` is this host's (chunk, ...) rows of a sequence laid
    out contiguously over the mesh's `axis` (every host passes the SAME
    chunk count; the gang pads uneven tails before calling).  The
    exchange is the `_halo_exchange_block` ppermute pair run under
    shard_map over the global mesh, so boundary rows move over ICI/DCN
    instead of each host widening its decode (engine/gang.py sharded
    members).  Edge shards see REPEAT_EDGE copies of their own rows in
    the returned halos — callers that own real data beyond the global
    boundary must source those rows themselves.
    """
    import numpy as np

    from .distributed import host_local_array

    local_block = np.ascontiguousarray(local_block)
    chunk = int(local_block.shape[0])
    if max(lo, hi) > chunk:
        raise ValueError(
            f"halo ({lo},{hi}) exceeds the per-shard block of {chunk} "
            f"rows; multi-hop halos are not supported")
    g = host_local_array(mesh, (axis,), local_block)
    out = _mapped_halo(mesh, lo, hi, axis)(g)
    # P(axis) shards only the row dim; every local device holds an
    # identical replica of this host's padded block
    mine = np.asarray(out.addressable_shards[0].data)
    left = mine[:lo]
    right = mine[lo + chunk:lo + chunk + hi]
    return left, right


def temporal_diff(mesh: Mesh, axis: str = "sp"):
    """Example/standard op: frame-to-previous-frame difference over a
    sequence sharded across devices (the shot-detection primitive)."""
    def block(padded):
        # padded has 1 halo row on the left
        return padded[1:] - padded[:-1]

    return sharded_stencil_map(block, stencil=[-1, 0], mesh=mesh, axis=axis)
