"""Pluggable storage backends for streams: the Source/Sink extension API.

Capability parity: reference scanner/api/source.h (Source::read :69,
REGISTER_SOURCE :131), sink.h (Sink::write/finished :75-86,
REGISTER_SINK :181), enumerator.h, and the scannertools FilesStream used by
tutorial 05 (SURVEY §2.4).

A CustomStorage implements row-granular reads (source side) and item
writes (sink side); a CustomStream binds one stored stream of that storage
into a graph.  The engine treats these exactly like named-table streams —
the DAG analysis only needs `num_rows`, the loader calls `read_rows`, the
saver calls `write_item`.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Iterator, List, Optional, Sequence

from ..common import NullElement, ScannerException, StorageException
from .streams import StoredStream


class CustomStorage:
    """Extension point: subclass and implement the four methods."""

    def num_rows(self, stream: "CustomStream") -> int:
        raise NotImplementedError

    def read_rows(self, stream: "CustomStream",
                  rows: Sequence[int]) -> List[Any]:
        """Return deserialized elements for the given rows (source side)."""
        raise NotImplementedError

    def write_item(self, stream: "CustomStream", start_row: int,
                   elements: Sequence[Any]) -> None:
        """Persist rows [start_row, start_row+len) (sink side); must be
        atomic per item and idempotent (tasks may be re-executed)."""
        raise NotImplementedError

    def finished(self, stream: "CustomStream",
                 total_rows: int) -> None:
        """Durability barrier after all items of a job completed
        (reference Sink::finished, sink.h:86)."""

    def exists(self, stream: "CustomStream") -> bool:
        """Does this stream already hold data? (CacheMode enforcement.)"""
        try:
            return self.num_rows(stream) > 0
        except Exception:
            return False

    def delete_stream(self, stream: "CustomStream") -> None:
        """Remove all stored rows (CacheMode.Overwrite)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support overwrite; "
            f"delete the output manually")


class CustomStream(StoredStream):
    """A stream stored by a CustomStorage (not in the database)."""

    is_video = False
    is_custom = True

    def __init__(self, storage: CustomStorage, name: str):
        self._storage = storage
        self.name = name
        self._sc = self  # custom streams need no Database binding

    def __getstate__(self):
        d = self.__dict__.copy()
        d["_sc"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._sc = self

    def bind(self, db) -> None:  # engine rebinding is a no-op
        self._sc = self

    @property
    def storage(self) -> CustomStorage:
        return self._storage

    def len(self) -> int:
        return self._storage.num_rows(self)

    def exists(self) -> bool:
        try:
            return self.len() >= 0
        except Exception:
            return False

    def committed(self) -> bool:
        return self.exists()

    def load(self, rows: Optional[Sequence[int]] = None) -> Iterator[Any]:
        n = self.len()
        rows = list(rows) if rows is not None else list(range(n))
        for e in self._storage.read_rows(self, rows):
            yield e


class FilesStorage(CustomStorage):
    """One file per row in a directory (scannertools
    `storage.files.FilesStream` equivalent, tutorial 05).

    Rows are raw bytes by default; pass codec="pickle" for objects.
    """

    def __init__(self, root: str, ext: str = "bin", codec: str = "raw"):
        self.root = root
        self.ext = ext
        self.codec = codec

    def _dir(self, stream: CustomStream) -> str:
        return os.path.join(self.root, stream.name)

    def _path(self, stream: CustomStream, row: int) -> str:
        return os.path.join(self._dir(stream), f"{row:08d}.{self.ext}")

    def num_rows(self, stream: CustomStream) -> int:
        d = self._dir(stream)
        if not os.path.isdir(d):
            raise StorageException(f"no such file stream: {d}")
        return sum(1 for f in os.listdir(d) if f.endswith("." + self.ext))

    def read_rows(self, stream: CustomStream, rows: Sequence[int]):
        out = []
        for r in rows:
            with open(self._path(stream, r), "rb") as f:
                b = f.read()
            out.append(pickle.loads(b) if self.codec == "pickle" else b)
        return out

    def write_item(self, stream: CustomStream, start_row: int,
                   elements: Sequence[Any]) -> None:
        d = self._dir(stream)
        os.makedirs(d, exist_ok=True)
        for i, e in enumerate(elements):
            if isinstance(e, NullElement):
                raise ScannerException(
                    "FilesStorage cannot store null rows")
            b = pickle.dumps(e) if self.codec == "pickle" else bytes(e)
            p = self._path(stream, start_row + i)
            tmp = p + ".tmp"
            with open(tmp, "wb") as f:
                f.write(b)
            os.replace(tmp, p)

    def finished(self, stream: CustomStream, total_rows: int) -> None:
        d = self._dir(stream)
        if not os.path.isdir(d):
            return  # zero-row job or non-shared filesystem: nothing local
        dir_fd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)

    def exists(self, stream: CustomStream) -> bool:
        return os.path.isdir(self._dir(stream))

    def delete_stream(self, stream: CustomStream) -> None:
        import shutil
        shutil.rmtree(self._dir(stream), ignore_errors=True)


class FilesStream(CustomStream):
    def __init__(self, name: str, root: str, ext: str = "bin",
                 codec: str = "raw"):
        super().__init__(FilesStorage(root, ext=ext, codec=codec), name)
