"""Database: table CRUD over a storage backend.

Capability parity: reference scanner/engine/metadata.{h,cpp} (metadata
accessors, megafile) + table_meta_cache.{h,cpp} (TableMetaCache) + the
client-side new_table/table paths (client.py:418-546).

The master process is the single writer of db_metadata; workers only write
item files.  All metadata writes are atomic whole-file replaces.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Union

from ..common import StorageException
from . import items, metadata as md
from .backend import StorageBackend


class Database:
    def __init__(self, backend: StorageBackend):
        self.backend = backend
        self._meta: Optional[md.DatabaseMetadata] = None
        self._table_cache: Dict[int, md.TableDescriptor] = {}
        self._lock = threading.RLock()

    # -- db metadata --------------------------------------------------------

    @property
    def meta(self) -> md.DatabaseMetadata:
        with self._lock:
            if self._meta is None:
                if self.backend.exists(md.db_meta_path()):
                    self._meta = md.DatabaseMetadata.deserialize(
                        self.backend.read(md.db_meta_path()))
                else:
                    self._meta = md.DatabaseMetadata()
            return self._meta

    def refresh_meta(self) -> md.DatabaseMetadata:
        """Drop caches and re-read metadata from storage (worker side)."""
        with self._lock:
            self._meta = None
            self._table_cache.clear()
            return self.meta

    def save_meta(self) -> None:
        with self._lock:
            self.backend.write(md.db_meta_path(), self.meta.serialize())

    # -- table descriptors --------------------------------------------------

    def table_descriptor(self, table: Union[str, int]) -> md.TableDescriptor:
        with self._lock:
            tid = self.meta.table_id(table) if isinstance(table, str) else table
            if tid not in self._table_cache:
                desc = md.TableDescriptor.deserialize(
                    self.backend.read(md.table_descriptor_path(tid)))
                self._table_cache[tid] = desc
            return self._table_cache[tid]

    def write_table_descriptor(self, desc: md.TableDescriptor) -> None:
        with self._lock:
            self.backend.write(md.table_descriptor_path(desc.id),
                               desc.serialize())
            self._table_cache[desc.id] = desc

    # -- table lifecycle ----------------------------------------------------

    def create_table(self, name: str, columns: Sequence[md.ColumnDescriptor],
                     end_rows: Sequence[int], job_id: int = -1,
                     commit: bool = False) -> md.TableDescriptor:
        """Register a table (uncommitted unless commit=True) and persist its
        descriptor.  Item data is written separately."""
        with self._lock:
            meta = self.meta
            if meta.has_table(name):
                raise StorageException(f"table already exists: {name}")
            tid = meta.add_table(name)
            desc = md.TableDescriptor(
                id=tid, name=name, columns=list(columns),
                end_rows=list(end_rows), job_id=job_id, timestamp=time.time())
            self.write_table_descriptor(desc)
            if commit:
                meta.commit_table(tid)
            self.save_meta()
            return desc

    def delete_table(self, name: str) -> None:
        with self._lock:
            meta = self.meta
            if not meta.has_table(name):
                return
            tid = meta.remove_table(name)
            self._table_cache.pop(tid, None)
            self.save_meta()
            self.backend.delete_prefix(md.table_dir(tid))

    def commit_table(self, table: Union[str, int]) -> None:
        with self._lock:
            tid = self.meta.table_id(table) if isinstance(table, str) else table
            self.meta.commit_table(tid)
            self.save_meta()

    def table_is_committed(self, name: str) -> bool:
        return self.meta.table_is_committed(name)

    def has_table(self, name: str) -> bool:
        return self.meta.has_table(name)

    def list_tables(self) -> List[str]:
        return sorted(self.meta.tables.keys())

    # -- direct data write (client new_table / ingest) ----------------------

    def new_table(self, name: str, columns: Sequence[str],
                  rows: Sequence[Sequence[bytes]],
                  overwrite: bool = False) -> md.TableDescriptor:
        """Create and commit a small table from in-memory rows.

        `rows` is row-major: rows[i][j] is row i of column j — matching the
        reference Client.new_table (client.py:418).
        """
        with self._lock:
            if self.has_table(name):
                if not overwrite:
                    raise StorageException(f"table already exists: {name}")
                self.delete_table(name)
            cols = [md.ColumnDescriptor(c, md.ColumnType.BYTES) for c in columns]
            n = len(rows)
            desc = self.create_table(name, cols, end_rows=[n] if n else [],
                                     commit=True)
            for j, cname in enumerate(columns):
                col_rows = [rows[i][j] for i in range(n)]
                if n:
                    items.write_item(self.backend,
                                     md.column_item_path(desc.id, cname, 0),
                                     col_rows)
            return desc

    # -- row reads ----------------------------------------------------------

    def load_column(self, table: Union[str, int], column: str,
                    rows: Optional[Sequence[int]] = None,
                    sparsity_threshold: int = 8
                    ) -> Iterator[Optional[bytes]]:
        """Yield serialized rows of a column (None for stored nulls).

        Video columns yield *encoded* data here; frame decode lives in
        storage/streams.py which wraps this with the video layer.
        """
        desc = self.table_descriptor(table)
        if column not in desc.column_names():
            raise StorageException(
                f"table {desc.name} has no column {column} "
                f"(has {desc.column_names()})")
        return self._load_column_iter(desc, column, rows, sparsity_threshold)

    def _load_column_iter(self, desc, column, rows, sparsity_threshold
                          ) -> Iterator[Optional[bytes]]:
        if rows is None:
            for item_idx in range(len(desc.end_rows)):
                path = md.column_item_path(desc.id, column, item_idx)
                yield from items.read_item(self.backend, path)
        else:
            # group requested global rows by item, preserve request order
            rows_arr = list(rows)
            by_item: Dict[int, List[int]] = {}
            order: List[tuple] = []
            for r in rows_arr:
                it = desc.item_of_row(r)
                start, _ = desc.item_bounds(it)
                by_item.setdefault(it, []).append(r - start)
                order.append((it, len(by_item[it]) - 1))
            fetched: Dict[int, List[Optional[bytes]]] = {}
            for it, local in by_item.items():
                path = md.column_item_path(desc.id, column, it)
                fetched[it] = items.read_item_rows(
                    self.backend, path, local, sparsity_threshold)
            for it, idx in order:
                yield fetched[it][idx]

    # -- megafile (all table descriptors in one blob) -----------------------

    def write_megafile(self) -> None:
        """Pack every committed table descriptor into one file so cluster
        start-up does one large read instead of N small ones (reference
        write_table_megafile, metadata.cpp)."""
        with self._lock:
            blobs = {}
            for name, tid in self.meta.tables.items():
                if not self.meta.committed.get(tid, False):
                    continue
                try:
                    blobs[str(tid)] = self.table_descriptor(tid).to_dict()
                except StorageException:
                    continue
            self.backend.write(md.megafile_path(), md.pack(blobs))

    def load_megafile(self) -> None:
        with self._lock:
            if not self.backend.exists(md.megafile_path()):
                return
            blobs = md.unpack(self.backend.read(md.megafile_path()))
            for tid_s, d in blobs.items():
                desc = md.TableDescriptor.from_dict(d)
                self._table_cache[desc.id] = desc
