"""User-facing stored stream handles.

Capability parity: reference scannerpy/storage.py — StorageBackend/
StoredStream (:19,81), NamedStorage/NamedStream (:187,250),
NamedVideoStorage/NamedVideoStream (:221,304), NullElement handling.

A stored stream is one column of one table.  NamedStream is the blob flavor,
NamedVideoStream the keyframe-indexed video flavor (decodes on load).
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Iterator, List, Optional, Sequence

import numpy as np

from ..common import NullElement, ScannerException
from . import metadata as md
from .database import Database


def decode_element(blob: Optional[bytes], codec: str):
    """Single source of truth for row decoding by column codec."""
    if blob is None:
        return NullElement()
    if codec == "pickle":
        return pickle.loads(blob)
    if codec == "image":
        from ..video.ingest import decode_image
        return decode_image(blob)
    return blob


class StoredStream:
    """Base: a named, typed, committed-or-not stream of rows."""

    is_video = False

    def __init__(self, sc, name: str):
        # sc is a Client or anything exposing ._db (a Database)
        self._sc = sc
        self.name = name

    @property
    def db(self) -> Database:
        if self._sc is None:
            raise ScannerException(
                f"stream {self.name} is unbound; it traveled over RPC and "
                f"must be re-bound to a Database first")
        return self._sc._db if hasattr(self._sc, "_db") else self._sc

    def bind(self, db: Database) -> None:
        self._sc = db

    def __getstate__(self) -> dict:
        # streams travel to the master/workers inside cloudpickled graphs;
        # the Client (grpc channels etc.) must not come along
        d = self.__dict__.copy()
        d["_sc"] = None
        return d

    # -- engine-facing ------------------------------------------------------

    @property
    def column(self) -> str:
        return "output"

    def exists(self) -> bool:
        return self.db.has_table(self.name)

    def committed(self) -> bool:
        return self.db.table_is_committed(self.name)

    def len(self) -> int:
        return self.db.table_descriptor(self.name).num_rows

    def __len__(self) -> int:
        return self.len()

    def delete(self) -> None:
        self.db.delete_table(self.name)

    # -- reading ------------------------------------------------------------

    def load_bytes(self, rows: Optional[Sequence[int]] = None
                   ) -> Iterator[Optional[bytes]]:
        desc = self.db.table_descriptor(self.name)
        col = self.column if self.column in desc.column_names() \
            else next(c for c in desc.column_names() if c != "index")
        yield from self.db.load_column(self.name, col, rows=rows)

    def load(self, rows: Optional[Sequence[int]] = None,
             column: Optional[str] = None) -> Iterator[Any]:
        """Deserialize rows (reference StoredStream.load, storage.py:135).

        Dispatches on the stored column type, so a NamedStream bound to a
        frame column an engine job wrote in video mode decodes correctly
        (the items under it are H.264 packet runs, not blob rows)."""
        desc = self.db.table_descriptor(self.name)
        col = column or (
            self.column if self.column in desc.column_names()
            else next(c for c in desc.column_names() if c != "index"))
        if desc.column_type(col) == md.ColumnType.VIDEO:
            from ..video.ingest import iter_frames
            if rows is None:
                rows = range(desc.num_rows)
            yield from iter_frames(self.db, self.name, list(rows), col)
            return
        codec = None
        for c in desc.columns:
            if c.name == col:
                codec = getattr(c, "codec", "pickle")
        for blob in self.db.load_column(self.name, col, rows=rows):
            yield decode_element(blob, codec or "raw")


class NamedStream(StoredStream):
    """Blob stream stored in a named table (reference NamedStream)."""


class NamedVideoStream(StoredStream):
    """Keyframe-indexed video stream (reference NamedVideoStream:304).

    With `path=`, the video is ingested lazily at first use
    (reference storage.py:235 auto-ingest).
    """

    is_video = True

    def __init__(self, sc, name: str, path: Optional[str] = None,
                 inplace: bool = False):
        super().__init__(sc, name)
        self._path = path
        self._inplace = inplace

    @property
    def column(self) -> str:
        return "frame"

    def ensure_ingested(self) -> None:
        if self._path is not None and not self.exists():
            from ..common import ScannerException
            from ..video import ingest_videos
            _, failed = ingest_videos(self.db, [(self.name, self._path)],
                                      inplace=self._inplace)
            if failed:
                # single-stream auto-ingest: a failure here IS fatal
                raise ScannerException(
                    f"ingest of {failed[0][0]} failed: {failed[0][1]}")

    def len(self) -> int:
        self.ensure_ingested()
        return super().len()

    def estimate_size(self) -> int:
        return self.estimate_geometry()[0]

    def estimate_keyint(self) -> int:
        """Typical keyframe spacing in DISPLAY frames (0 = unknown).
        PerfParams.estimate aligns io packets to this so task boundaries
        land on keyframes and consecutive tasks never re-decode a GOP
        prefix."""
        return self.estimate_geometry()[1]

    def estimate_geometry(self) -> tuple:
        """(frame_bytes, keyint) from ONE descriptor read — the estimate
        loop runs over every stream of every job at launch, so metadata
        I/O here is per-corpus, not per-call."""
        self.ensure_ingested()
        vd = self._video_meta()
        frame_bytes = int(vd.width * vd.height * 3)
        kfs = np.asarray(vd.keyframe_indices)
        if len(kfs) < 2:
            return frame_bytes, 0
        # decode->display: keyframe display positions are the pts ranks
        pts = np.asarray(vd.sample_pts, np.int64)
        disp_of_dec = np.empty(len(pts), np.int64)
        disp_of_dec[np.argsort(pts, kind="stable")] = np.arange(len(pts))
        gaps = np.diff(np.sort(disp_of_dec[kfs]))
        keyint = int(np.median(gaps)) if len(gaps) else 0
        return frame_bytes, keyint

    def _video_meta(self) -> md.VideoDescriptor:
        from ..video import load_video_meta
        return load_video_meta(self.db, self.name, self.column)

    def load(self, rows: Optional[Sequence[int]] = None) -> Iterator[Any]:
        """Decode frames (reference NamedVideoStream.load via hwang);
        the column-type dispatch lives in StoredStream.load."""
        self.ensure_ingested()
        yield from super().load(rows=rows)

    def save_mp4(self, path: str) -> None:
        from ..video import export_mp4
        export_mp4(self.db, self.name, path, self.column)

    def as_hwang(self):  # pragma: no cover - reference-compat shim
        raise ScannerException(
            "as_hwang is CUDA-reference-specific; use load() instead")
