"""Column item-file format.

One item file holds the serialized rows of one column for one row range.
Layout (little-endian):

    magic   u32  = 0x53434954 ("SCIT")
    version u32
    nrows   u64
    sizes   u64[nrows]   (NULL_SIZE marks a null row)
    payloads, concatenated

The sizes header is fixed-position so a reader can fetch it with one ranged
read and then fetch only the rows it needs — the sparse-read path the
reference implements in ColumnSource (column_source.cpp, sparse vs dense via
load_sparsity_threshold).
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Union

import numpy as np

from ..common import NullElement, StorageException
from .backend import StorageBackend

MAGIC = 0x53434954
VERSION = 1
NULL_SIZE = 0xFFFFFFFFFFFFFFFF
_HEADER = struct.Struct("<IIQ")

RowData = Union[bytes, NullElement]


def build_item(rows: Sequence[RowData]) -> bytes:
    sizes = np.empty(len(rows), dtype=np.uint64)
    payloads: List[bytes] = []
    for i, r in enumerate(rows):
        if isinstance(r, NullElement):
            sizes[i] = NULL_SIZE
        else:
            b = bytes(r)
            sizes[i] = len(b)
            payloads.append(b)
    return b"".join([_HEADER.pack(MAGIC, VERSION, len(rows)),
                     sizes.tobytes()] + payloads)


def write_item(backend: StorageBackend, path: str, rows: Sequence[RowData]) -> None:
    backend.write(path, build_item(rows))


def _parse_header(buf: bytes, path: str):
    if len(buf) < _HEADER.size:
        raise StorageException(f"item file too short: {path}")
    magic, version, nrows = _HEADER.unpack_from(buf, 0)
    if magic != MAGIC:
        raise StorageException(f"bad item magic in {path}")
    if version != VERSION:
        raise StorageException(f"unsupported item version {version} in {path}")
    return nrows


def read_item(backend: StorageBackend, path: str) -> List[Optional[bytes]]:
    """Read every row of an item. Null rows come back as None."""
    buf = backend.read(path)
    nrows = _parse_header(buf, path)
    sizes = np.frombuffer(buf, dtype=np.uint64, count=nrows, offset=_HEADER.size)
    out: List[Optional[bytes]] = []
    off = _HEADER.size + 8 * nrows
    for s in sizes:
        if s == NULL_SIZE:
            out.append(None)
        else:
            s = int(s)
            out.append(buf[off:off + s])
            off += s
    return out


def read_item_rows(backend: StorageBackend, path: str,
                   local_rows: Sequence[int],
                   sparsity_threshold: int = 8) -> List[Optional[bytes]]:
    """Read selected rows (local indices) from an item.

    If the requested rows are dense relative to the item, the whole file is
    fetched with one read; otherwise the sizes header is read first and each
    row fetched with a ranged read.
    """
    if len(local_rows) == 0:
        return []
    header = backend.read_range(path, 0, _HEADER.size)
    nrows = _parse_header(header, path)
    if nrows == 0:
        raise StorageException(f"empty item: {path}")
    dense = len(local_rows) * sparsity_threshold >= nrows
    if dense:
        all_rows = read_item(backend, path)
        return [all_rows[r] for r in local_rows]
    sizes_buf = backend.read_range(path, _HEADER.size, 8 * nrows)
    sizes = np.frombuffer(sizes_buf, dtype=np.uint64, count=nrows)
    payload_sizes = np.where(sizes == NULL_SIZE, 0, sizes).astype(np.uint64)
    offsets = np.zeros(nrows, dtype=np.uint64)
    np.cumsum(payload_sizes[:-1], out=offsets[1:])
    base = _HEADER.size + 8 * nrows
    out: List[Optional[bytes]] = []
    for r in local_rows:
        if r < 0 or r >= nrows:
            raise StorageException(f"row {r} out of item bounds ({nrows}): {path}")
        if sizes[r] == NULL_SIZE:
            out.append(None)
        else:
            out.append(backend.read_range(path, base + int(offsets[r]),
                                          int(sizes[r])))
    return out


def item_num_rows(backend: StorageBackend, path: str) -> int:
    header = backend.read_range(path, 0, _HEADER.size)
    return _parse_header(header, path)
