"""Column item-file format.

One item file holds the serialized rows of one column for one row range.
Layout (little-endian), versions 2/3:

    magic   u32  = 0x53434954 ("SCIT")
    version u32  (2 = crc is crc32c/Castagnoli, 3 = crc is zlib crc32)
    nrows   u64
    crc     u32  checksum of the whole item with this field zeroed —
                 header INCLUDED, so rot in nrows (which shifts every
                 payload offset) is caught, not just payload rot
    sizes   u64[nrows]   (NULL_SIZE marks a null row)
    payloads, concatenated

The sizes header is fixed-position so a reader can fetch it with one ranged
read and then fetch only the rows it needs — the sparse-read path the
reference implements in ColumnSource (column_source.cpp, sparse vs dense via
load_sparsity_threshold).

The checksum is verified on every whole-item read (the dense path —
sparse ranged reads skip it, matching the reference where per-range
integrity rides on the transport).  A mismatch raises
``ItemCorruptionError`` — a StorageException subclass the cluster
treats as a *transient* task failure (engine/service.py FailedWork
classification): the task requeues and re-reads instead of striking
its job toward the blacklist, because bit rot on one replica/read is
retryable while a poisoned kernel is not.  Version-1 items (no crc)
remain readable so pre-existing databases survive the upgrade.

crc32c comes from google_crc32c (C-accelerated; declared in
setup.py).  The checksum ALGORITHM is recorded in the version field,
so nodes with differing installs can never misread a valid item as
corrupt: a writer without google_crc32c falls back to zlib.crc32 and
stamps version 3; a reader without google_crc32c skips verification
of version-2 items (logged once) instead of guessing.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from ..common import NullElement, StorageException
from ..util import metrics as _mx
from .backend import StorageBackend

MAGIC = 0x53434954
VERSION_CRC32C = 2   # crc field is crc32c (Castagnoli)
VERSION_CRC32 = 3    # crc field is zlib crc32 (no-google_crc32c fallback)
NULL_SIZE = 0xFFFFFFFFFFFFFFFF
_HEADER_V1 = struct.Struct("<IIQ")
_HEADER_V2 = struct.Struct("<IIQI")  # shared by versions 2 and 3
# the largest header any version uses; ranged header reads fetch this
# many bytes and let the version field decide how much is meaningful
HEADER_MAX = _HEADER_V2.size

RowData = Union[bytes, NullElement]

_M_CORRUPTIONS = _mx.registry().counter(
    "scanner_tpu_item_corruptions_total",
    "Stored-item reads whose crc32c checksum did not match — corrupted "
    "bytes detected and surfaced as a retryable StorageException.")


class ItemCorruptionError(StorageException):
    """Item bytes failed their crc32c check.  Retryable: re-reading (or
    re-assigning the task to another worker) may succeed."""


import zlib

try:
    import google_crc32c

    def _crc32c_extend(crc: int, chunk: bytes) -> int:
        # google_crc32c's C layer accepts only `bytes` chunks
        return int(google_crc32c.extend(crc, chunk))
except ImportError:  # pragma: no cover - env ships the C lib
    _crc32c_extend = None

_HAVE_CRC32C = _crc32c_extend is not None

# write with the strongest available algorithm, stamped in the version
_WRITE_VERSION = VERSION_CRC32C if _HAVE_CRC32C else VERSION_CRC32
_warned_unverifiable = False

# bound on the per-chunk bytes copy the crc32c C API forces when
# hashing a read buffer (the zlib path hashes a zero-copy memoryview)
_CRC_CHUNK = 4 << 20


def _checksum_parts(version: int, parts) -> int:
    """Incremental checksum over byte chunks — the write path hashes
    the sizes array + payloads in place instead of materializing the
    joined body twice."""
    crc = 0
    if version == VERSION_CRC32C:
        for p in parts:
            crc = _crc32c_extend(crc, p)
        return crc
    for p in parts:
        crc = zlib.crc32(p, crc)
    return crc & 0xFFFFFFFF


def _checksum_stream(version: int, hdr0: bytes, buf, start: int) -> int:
    """Checksum hdr0 + buf[start:] without materializing the tail as
    one big copy: zlib hashes a zero-copy memoryview; crc32c (whose C
    layer only accepts bytes) hashes bounded-size chunks."""
    if version == VERSION_CRC32C:
        crc = _crc32c_extend(0, hdr0)
        mv = memoryview(buf)
        for off in range(start, len(buf), _CRC_CHUNK):
            crc = _crc32c_extend(crc, bytes(mv[off:off + _CRC_CHUNK]))
        return crc
    return zlib.crc32(memoryview(buf)[start:], zlib.crc32(hdr0)) \
        & 0xFFFFFFFF


def build_item(rows: Sequence[RowData]) -> bytes:
    sizes = np.empty(len(rows), dtype=np.uint64)
    payloads: List[bytes] = []
    for i, r in enumerate(rows):
        if isinstance(r, NullElement):
            sizes[i] = NULL_SIZE
        else:
            b = bytes(r)
            sizes[i] = len(b)
            payloads.append(b)
    parts = [sizes.tobytes()] + payloads
    # checksum spans the header too (crc field zeroed): a flipped bit
    # in nrows would silently re-base every payload offset otherwise
    hdr0 = _HEADER_V2.pack(MAGIC, _WRITE_VERSION, len(rows), 0)
    crc = _checksum_parts(_WRITE_VERSION, [hdr0] + parts)
    return b"".join(
        [_HEADER_V2.pack(MAGIC, _WRITE_VERSION, len(rows), crc)] + parts)


def write_item(backend: StorageBackend, path: str, rows: Sequence[RowData]) -> None:
    backend.write(path, build_item(rows))


def _parse_header(buf: bytes, path: str) -> Tuple[int, int, int,
                                                  Optional[int]]:
    """-> (nrows, header_size, version, crc-or-None for v1)."""
    if len(buf) < _HEADER_V1.size:
        raise StorageException(f"item file too short: {path}")
    magic, version, nrows = _HEADER_V1.unpack_from(buf, 0)
    if magic != MAGIC:
        raise StorageException(f"bad item magic in {path}")
    if version == 1:
        return nrows, _HEADER_V1.size, version, None
    if version in (VERSION_CRC32C, VERSION_CRC32):
        if len(buf) < _HEADER_V2.size:
            raise StorageException(f"item file too short: {path}")
        _m, _v, nrows, crc = _HEADER_V2.unpack_from(buf, 0)
        return nrows, _HEADER_V2.size, version, crc
    raise StorageException(f"unsupported item version {version} in {path}")


def _verify(buf: bytes, hdr: int, version: int, nrows: int, crc: int,
            path: str) -> None:
    global _warned_unverifiable
    if version == VERSION_CRC32C and not _HAVE_CRC32C:
        # written by a node WITH google_crc32c, read by one without:
        # skipping verification beats the alternative — guessing with a
        # different polynomial would flag every valid item as corrupt
        # and burn the whole transient-retry budget on phantom rot
        if not _warned_unverifiable:
            _warned_unverifiable = True
            from ..util.log import get_logger
            get_logger("storage").warning(
                "google_crc32c unavailable: crc32c item checksums "
                "(version 2) cannot be verified on this node")
        return
    hdr0 = _HEADER_V2.pack(MAGIC, version, nrows, 0)
    if _checksum_stream(version, hdr0, buf, hdr) != crc:
        _M_CORRUPTIONS.inc()
        raise ItemCorruptionError(
            f"item checksum mismatch ({len(buf)} bytes): {path}")


# ---------------------------------------------------------------------------
# sealed control-plane blobs (bulk checkpoint / journal payloads)
# ---------------------------------------------------------------------------

# distinct magic so a sealed blob can never be confused with an item
# file or a legacy (unsealed) checkpoint
BLOB_MAGIC = 0x53434B50  # "SCKP"
_BLOB_HDR = struct.Struct("<III")  # magic, checksum-algo version, crc


def checksum_blob(payload: bytes) -> Tuple[int, int]:
    """(algorithm version, crc) of one payload with the strongest
    available algorithm — the same crc32c/zlib selection item files use
    (the algorithm travels with the data, so mixed installs never
    misread valid bytes as corrupt)."""
    return _WRITE_VERSION, _checksum_parts(_WRITE_VERSION, [payload])


def verify_blob_checksum(version: int, crc: int, payload: bytes,
                         path: str = "") -> None:
    """Raise ItemCorruptionError when `payload` fails its recorded
    checksum.  A crc32c-stamped blob on a node without google_crc32c is
    skipped (same contract as item verification: never guess with the
    wrong polynomial)."""
    global _warned_unverifiable
    if version == VERSION_CRC32C and not _HAVE_CRC32C:
        if not _warned_unverifiable:
            _warned_unverifiable = True
            from ..util.log import get_logger
            get_logger("storage").warning(
                "google_crc32c unavailable: crc32c item checksums "
                "(version 2) cannot be verified on this node")
        return
    if version not in (VERSION_CRC32C, VERSION_CRC32):
        raise StorageException(
            f"unsupported blob checksum version {version} in {path}")
    if _checksum_parts(version, [payload]) != crc:
        raise ItemCorruptionError(
            f"sealed blob checksum mismatch ({len(payload)} bytes): "
            f"{path}")


def seal_blob(payload: bytes) -> bytes:
    """Wrap a control-plane payload (bulk checkpoint, progress
    snapshot) with a checksummed header, so rot in the master's
    recovery state is *detected* at restart instead of silently
    resurrecting a half-garbage bulk (engine/service.py
    `_recover_bulk` falls back to journal replay on a corrupt
    checkpoint)."""
    version, crc = checksum_blob(payload)
    return _BLOB_HDR.pack(BLOB_MAGIC, version, crc) + payload


def open_blob(data: bytes, path: str = "") -> bytes:
    """Verify + unwrap a sealed blob.  Raises StorageException when the
    data is not a sealed blob at all (callers may fall back to treating
    it as a legacy unsealed payload) and ItemCorruptionError when the
    checksum fails."""
    if len(data) < _BLOB_HDR.size:
        raise StorageException(f"not a sealed blob (too short): {path}")
    magic, version, crc = _BLOB_HDR.unpack_from(data, 0)
    if magic != BLOB_MAGIC:
        raise StorageException(f"not a sealed blob: {path}")
    payload = data[_BLOB_HDR.size:]
    verify_blob_checksum(version, crc, payload, path)
    return payload


def read_item(backend: StorageBackend, path: str) -> List[Optional[bytes]]:
    """Read every row of an item. Null rows come back as None."""
    buf = backend.read(path)
    nrows, hdr, version, crc = _parse_header(buf, path)
    if crc is not None:
        _verify(buf, hdr, version, nrows, crc, path)
    sizes = np.frombuffer(buf, dtype=np.uint64, count=nrows, offset=hdr)
    out: List[Optional[bytes]] = []
    off = hdr + 8 * nrows
    for s in sizes:
        if s == NULL_SIZE:
            out.append(None)
        else:
            s = int(s)
            out.append(buf[off:off + s])
            off += s
    return out


def read_item_rows(backend: StorageBackend, path: str,
                   local_rows: Sequence[int],
                   sparsity_threshold: int = 8) -> List[Optional[bytes]]:
    """Read selected rows (local indices) from an item.

    If the requested rows are dense relative to the item, the whole file is
    fetched with one read (checksum-verified); otherwise the sizes header is
    read first and each row fetched with a ranged read.
    """
    if len(local_rows) == 0:
        return []
    header = backend.read_range(path, 0, HEADER_MAX)
    nrows, hdr, _ver, _crc = _parse_header(header, path)
    if nrows == 0:
        raise StorageException(f"empty item: {path}")
    dense = len(local_rows) * sparsity_threshold >= nrows
    if dense:
        all_rows = read_item(backend, path)
        return [all_rows[r] for r in local_rows]
    sizes_buf = backend.read_range(path, hdr, 8 * nrows)
    sizes = np.frombuffer(sizes_buf, dtype=np.uint64, count=nrows)
    payload_sizes = np.where(sizes == NULL_SIZE, 0, sizes).astype(np.uint64)
    offsets = np.zeros(nrows, dtype=np.uint64)
    np.cumsum(payload_sizes[:-1], out=offsets[1:])
    base = hdr + 8 * nrows
    out: List[Optional[bytes]] = []
    for r in local_rows:
        if r < 0 or r >= nrows:
            raise StorageException(f"row {r} out of item bounds ({nrows}): {path}")
        if sizes[r] == NULL_SIZE:
            out.append(None)
        else:
            out.append(backend.read_range(path, base + int(offsets[r]),
                                          int(sizes[r])))
    return out


def item_num_rows(backend: StorageBackend, path: str) -> int:
    header = backend.read_range(path, 0, HEADER_MAX)
    return _parse_header(header, path)[0]


# kept for external readers of the "current" write format
VERSION = _WRITE_VERSION
