"""Storage backends.

Capability parity: the reference delegates persistence to the external
`storehouse` library (POSIX/GCS/S3 — reference scanner/util/storehouse.h,
python config.py:56).  Here the same narrow interface is defined natively;
POSIX is the production backend (works against local disk, NFS and
GCS-via-gcsfuse), Memory backs unit tests.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterator, List, Optional

from ..common import StorageException
from ..util import faults as _faults


class StorageBackend:
    """A flat blob store keyed by slash-separated paths.

    Writes are atomic (visible entirely or not at all) so that concurrent
    readers — other workers, the master — never observe torn metadata.
    """

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def read_range(self, path: str, offset: int, size: int) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes, sync: bool = True) -> None:
        """Atomically replace `path` with `data`.  sync=False skips the
        durability barrier (fsync) where the backend has one: the blob
        still survives a PROCESS kill (the page cache outlives it) but
        not a machine crash — the right trade for the master's
        write-ahead journal segments, whose format tolerates a torn
        tail and which would otherwise pay one fsync per acknowledged
        task completion."""
        raise NotImplementedError

    def write_exclusive(self, path: str, data: bytes) -> bool:
        """Create `path` with `data` only if it does not exist.

        Returns True when this call created the blob, False when it already
        existed (data untouched).  Used for cross-worker arbitration
        markers (first writer wins); backends should override with a
        truly atomic variant.  This default is a best-effort
        exists/write sequence — racy across processes, but it keeps
        pre-existing third-party backends working at save time.
        """
        if self.exists(path):
            return False
        self.write(path, data)
        return True

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def size(self, path: str) -> int:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> None:
        raise NotImplementedError

    def list_prefix(self, prefix: str) -> List[str]:
        raise NotImplementedError


class PosixStorage(StorageBackend):
    """Blobs are files under a root directory."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _abs(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path))
        if p != self.root and not p.startswith(self.root + os.sep):
            raise StorageException(f"path escapes storage root: {path}")
        return p

    def read(self, path: str) -> bytes:
        try:
            with open(self._abs(path), "rb") as f:
                data = f.read()
        except FileNotFoundError as e:
            raise StorageException(f"not found: {path}") from e
        if _faults.ACTIVE:
            data = _faults.inject("storage.read", data, detail=path)
        return data

    def read_range(self, path: str, offset: int, size: int) -> bytes:
        try:
            with open(self._abs(path), "rb") as f:
                f.seek(offset)
                data = f.read(size)
        except FileNotFoundError as e:
            raise StorageException(f"not found: {path}") from e
        if _faults.ACTIVE:
            data = _faults.inject("storage.read", data, detail=path)
        return data

    def write(self, path: str, data: bytes, sync: bool = True) -> None:
        if _faults.ACTIVE:
            _faults.inject("storage.write", detail=path)
        p = self._abs(path)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + f".tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            if sync:
                os.fsync(f.fileno())
        os.replace(tmp, p)

    def write_exclusive(self, path: str, data: bytes) -> bool:
        if _faults.ACTIVE:
            _faults.inject("storage.write", detail=path)
        p = self._abs(path)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        # write a private tmp first, then link() it into place: the blob
        # becomes visible fully written (a losing racer must never read a
        # partially-written marker), and link() fails with EEXIST for all
        # but exactly one concurrent creator
        tmp = p + f".xtmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, p)
            return True
        except FileExistsError:
            return False
        except OSError:
            # hard links are unsupported on gcsfuse and some NFS mounts
            # (EPERM/ENOTSUP/EOPNOTSUPP); fall back to O_CREAT|O_EXCL,
            # still atomic on POSIX though the loser may observe a
            # partially-written marker on non-POSIX overlays
            try:
                fd = os.open(p, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                return False
            try:
                os.write(fd, data)
                os.fsync(fd)
            finally:
                os.close(fd)
            return True
        finally:
            os.unlink(tmp)

    def exists(self, path: str) -> bool:
        return os.path.exists(self._abs(path))

    def size(self, path: str) -> int:
        try:
            return os.path.getsize(self._abs(path))
        except FileNotFoundError as e:
            raise StorageException(f"not found: {path}") from e

    def delete(self, path: str) -> None:
        try:
            os.remove(self._abs(path))
        except FileNotFoundError:
            pass

    def delete_prefix(self, prefix: str) -> None:
        import shutil
        p = self._abs(prefix)
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        elif os.path.exists(p):
            os.remove(p)

    def list_prefix(self, prefix: str) -> List[str]:
        p = self._abs(prefix)
        out: List[str] = []
        if not os.path.isdir(p):
            return out
        for dirpath, _dirs, files in os.walk(p):
            for fn in files:
                out.append(os.path.relpath(os.path.join(dirpath, fn), self.root))
        return sorted(out)

    def local_path(self, path: str) -> str:
        """Direct filesystem path — used to hand files to the C++ layer."""
        return self._abs(path)


class MemoryStorage(StorageBackend):
    """In-process blob store for unit tests."""

    def __init__(self):
        self._blobs: Dict[str, bytes] = {}
        self._lock = threading.Lock()

    def read(self, path: str) -> bytes:
        with self._lock:
            if path not in self._blobs:
                raise StorageException(f"not found: {path}")
            data = self._blobs[path]
        if _faults.ACTIVE:
            data = _faults.inject("storage.read", data, detail=path)
        return data

    def read_range(self, path: str, offset: int, size: int) -> bytes:
        return self.read(path)[offset:offset + size]

    def write(self, path: str, data: bytes, sync: bool = True) -> None:
        if _faults.ACTIVE:
            _faults.inject("storage.write", detail=path)
        with self._lock:
            self._blobs[path] = bytes(data)

    def write_exclusive(self, path: str, data: bytes) -> bool:
        if _faults.ACTIVE:
            _faults.inject("storage.write", detail=path)
        with self._lock:
            if path in self._blobs:
                return False
            self._blobs[path] = bytes(data)
            return True

    def exists(self, path: str) -> bool:
        with self._lock:
            return path in self._blobs

    def size(self, path: str) -> int:
        return len(self.read(path))

    def delete(self, path: str) -> None:
        with self._lock:
            self._blobs.pop(path, None)

    @staticmethod
    def _under(name: str, prefix: str) -> bool:
        # path-component boundary: "tables/5" must not cover "tables/52"
        if not prefix:
            return True
        return name == prefix or name.startswith(prefix + "/")

    def delete_prefix(self, prefix: str) -> None:
        with self._lock:
            for k in [k for k in self._blobs if self._under(k, prefix)]:
                del self._blobs[k]

    def list_prefix(self, prefix: str) -> List[str]:
        with self._lock:
            return sorted(k for k in self._blobs if self._under(k, prefix))


def make_storage(storage_type: str, **kw) -> StorageBackend:
    db_path = kw.get("db_path")
    # a gs:// db_path selects GCS regardless of the declared type, so
    # `Client(db_path="gs://bucket/db")` just works
    if storage_type == "gcs" or (
            isinstance(db_path, str) and db_path.startswith("gs://")):
        from .gcs import GcsStorage
        if isinstance(db_path, str) and db_path.startswith("gs://"):
            return GcsStorage.from_url(db_path, client=kw.get("client"))
        if "bucket" not in kw:
            raise StorageException(
                "gcs storage requires a gs://bucket/prefix db_path or an "
                "explicit bucket= option")
        return GcsStorage(kw["bucket"], kw.get("prefix", ""),
                          client=kw.get("client"))
    if storage_type == "posix":
        return PosixStorage(kw["db_path"])
    if storage_type == "memory":
        return MemoryStorage()
    raise StorageException(f"unknown storage type: {storage_type}")
