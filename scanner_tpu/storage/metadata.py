"""Database metadata descriptors and the on-disk path scheme.

Capability parity: reference scanner/metadata.proto (DatabaseDescriptor:6,
VideoDescriptor:63, TableDescriptor:120) and scanner/engine/metadata.{h,cpp}
(path scheme metadata.h:38-100, megafile write/read metadata.cpp).

Descriptors are plain dataclasses serialized with msgpack; numpy index arrays
are stored as raw little-endian buffers so the hot video index loads with a
single frombuffer (no per-element decode).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import msgpack
import numpy as np

from ..common import StorageException

FORMAT_VERSION = 1


# ---------------------------------------------------------------------------
# Path scheme (all relative to the database root)
# ---------------------------------------------------------------------------

def db_meta_path() -> str:
    return "db_metadata.bin"


def megafile_path() -> str:
    return "table_megafile.bin"


def table_dir(table_id: int) -> str:
    return f"tables/{table_id}"


def table_descriptor_path(table_id: int) -> str:
    return f"tables/{table_id}/descriptor.bin"


def column_item_path(table_id: int, column: str, item: int) -> str:
    return f"tables/{table_id}/{column}_{item}.bin"


def video_meta_path(table_id: int, column: str, item: int) -> str:
    return f"tables/{table_id}/{column}_{item}.vmeta"


def job_dir(job_id: int) -> str:
    return f"jobs/{job_id}"


def job_profile_path(job_id: int, node: str) -> str:
    return f"jobs/{job_id}/profile_{node}.trace"


def shard_prefix(shard: int = 0) -> str:
    """Control-plane namespace root of one master shard.  Shard 0 is
    the legacy unprefixed layout byte-for-byte (a pre-sharding db IS a
    one-shard db); shard k > 0 nests under `jobs/s<k>/` so each
    shard's generation claims, checkpoints and journals are disjoint —
    shard failover is single-master recovery scoped to one prefix
    (engine/shardmap.py)."""
    return "jobs" if not shard else f"jobs/s{int(shard):02d}"


def generation_prefix(shard: int = 0) -> str:
    """Directory of master-generation claim markers (one small blob per
    claimed generation; `write_exclusive` CAS makes each claim atomic —
    engine/journal.py claim_generation).  Scoped per control-plane
    shard (see shard_prefix)."""
    return f"{shard_prefix(shard)}/generations"


def generation_path(gen: int, shard: int = 0) -> str:
    return f"{generation_prefix(shard)}/{gen:08d}.bin"


def generation_dir(gen: int, shard: int = 0) -> str:
    """Per-generation control-plane state root: checkpoint, progress and
    journal of the master that claimed `gen` live under it, so a fenced
    (superseded) master's late writes can never clobber its successor's
    state — they land in a directory the successor never reads from
    again once recovery migrated the bulk."""
    return f"{shard_prefix(shard)}/g{gen:08d}"


def bulk_checkpoint_path(gen: Optional[int] = None,
                         shard: int = 0) -> str:
    """Active bulk job's admission state (spec blob + task geometry) —
    lets a restarted master resume the job (reference
    recover_and_init_database, master.cpp:1311).  Generation-scoped
    when `gen` is given; the legacy fixed path (pre-fencing masters)
    remains readable for recovery."""
    if gen is None:
        return f"{shard_prefix(shard)}/active_bulk.bin"
    return f"{generation_dir(gen, shard)}/active_bulk.bin"


def bulk_progress_path(gen: Optional[int] = None,
                       shard: int = 0) -> str:
    """Active bulk job's progress (done-set, blacklist, commits), written
    with each periodic checkpoint.  Generation-scoped when `gen` is
    given (see bulk_checkpoint_path)."""
    if gen is None:
        return f"{shard_prefix(shard)}/active_bulk_progress.bin"
    return f"{generation_dir(gen, shard)}/active_bulk_progress.bin"


def journal_dir(gen: int, shard: int = 0) -> str:
    """Write-ahead bulk-journal segments of one master generation
    (engine/journal.py)."""
    return f"{generation_dir(gen, shard)}/journal"


def journal_segment_path(gen: int, seg: int, shard: int = 0) -> str:
    return f"{journal_dir(gen, shard)}/seg_{seg:08d}.bin"


def shardmap_prefix() -> str:
    """Versioned shard-map epochs (engine/shardmap.py; one small blob
    per epoch, CAS-published, highest epoch wins)."""
    return "jobs/shardmap"


def shardmap_path(epoch: int) -> str:
    return f"{shardmap_prefix()}/e{epoch:08d}.bin"


# ---------------------------------------------------------------------------
# msgpack helpers with numpy support
# ---------------------------------------------------------------------------

def _default(obj):
    if isinstance(obj, np.ndarray):
        return {b"__nd__": True, b"d": obj.tobytes(), b"t": str(obj.dtype),
                b"s": list(obj.shape)}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    raise TypeError(f"cannot serialize {type(obj)}")


def _ext_hook_obj(obj):
    if isinstance(obj, dict) and obj.get(b"__nd__"):
        return np.frombuffer(obj[b"d"], dtype=obj[b"t"]).reshape(obj[b"s"])
    return obj


def pack(obj) -> bytes:
    return msgpack.packb(obj, default=_default, use_bin_type=True)


def unpack(data: bytes):
    return msgpack.unpackb(data, object_hook=_ext_hook_obj, raw=False,
                           strict_map_key=False)


# ---------------------------------------------------------------------------
# Descriptors
# ---------------------------------------------------------------------------

class ColumnType(enum.IntEnum):
    BYTES = 0
    VIDEO = 1


@dataclass
class VideoDescriptor:
    """Index for one stored encoded-video item.

    Unlike the reference's H.264-specific NAL index
    (h264_byte_stream_index_creator.h:31-57), this index is codec-agnostic:
    the demuxer records per-sample offsets/sizes/keyframe flags in *decode
    order* straight from the container, so any libavcodec codec works; H.264
    remains the fast path for encode output.
    """

    width: int = 0
    height: int = 0
    fps: float = 0.0
    num_frames: int = 0
    codec: str = "h264"
    # decoder configuration record (e.g. avcC / SPS+PPS)
    extradata: bytes = b""
    # per-sample byte offset into the packet stream file, decode order
    sample_offsets: np.ndarray = field(default_factory=lambda: np.zeros(0, np.uint64))
    sample_sizes: np.ndarray = field(default_factory=lambda: np.zeros(0, np.uint64))
    # indices (into decode order) of keyframe samples, ascending
    keyframe_indices: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int64))
    # pts/dts per sample (source time base), decode order; pts maps decode
    # order -> display order, dts is needed to remux B-frame streams
    sample_pts: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int64))
    sample_dts: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int64))
    # time base of pts/dts as a rational
    tb_num: int = 1
    tb_den: int = 30
    # path of the packet-stream blob this index describes; "" = column item
    # file itself (normal ingest), otherwise an absolute path (in-place ingest
    # of an external mp4 keeps data where it is - reference ingest.cpp:382)
    data_path: str = ""
    # if data_path points at an external container, samples are (offset,size)
    # into that file

    def to_dict(self) -> dict:
        return {
            "width": self.width, "height": self.height, "fps": self.fps,
            "num_frames": self.num_frames, "codec": self.codec,
            "extradata": self.extradata,
            "sample_offsets": np.asarray(self.sample_offsets, np.uint64),
            "sample_sizes": np.asarray(self.sample_sizes, np.uint64),
            "keyframe_indices": np.asarray(self.keyframe_indices, np.int64),
            "sample_pts": np.asarray(self.sample_pts, np.int64),
            "sample_dts": np.asarray(self.sample_dts, np.int64),
            "tb_num": self.tb_num, "tb_den": self.tb_den,
            "data_path": self.data_path,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "VideoDescriptor":
        return cls(**d)

    def serialize(self) -> bytes:
        return pack(self.to_dict())

    @classmethod
    def deserialize(cls, data: bytes) -> "VideoDescriptor":
        return cls.from_dict(unpack(data))


@dataclass
class ColumnDescriptor:
    name: str
    type: ColumnType = ColumnType.BYTES
    # row codec: "raw" (bytes as written), "pickle" (python objects),
    # "video" (encoded frames)
    codec: str = "raw"

    def to_dict(self) -> dict:
        return {"name": self.name, "type": int(self.type),
                "codec": self.codec}

    @classmethod
    def from_dict(cls, d: dict) -> "ColumnDescriptor":
        return cls(name=d["name"], type=ColumnType(d["type"]),
                   codec=d.get("codec", "raw"))


@dataclass
class TableDescriptor:
    """One stored table (a set of aligned named streams).

    `end_rows[i]` is the exclusive end row of item i; item files hold rows
    [end_rows[i-1], end_rows[i]).  Item boundaries are fixed at job-admission
    time (io-packet boundaries), so workers write items independently and the
    master commits the table once all are present — same recovery model as
    the reference (metadata.proto:120, master.cpp:1619-1663).
    """

    id: int
    name: str
    columns: List[ColumnDescriptor] = field(default_factory=list)
    end_rows: List[int] = field(default_factory=list)
    job_id: int = -1
    timestamp: float = 0.0

    @property
    def num_rows(self) -> int:
        return self.end_rows[-1] if self.end_rows else 0

    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]

    def column_type(self, name: str) -> ColumnType:
        for c in self.columns:
            if c.name == name:
                return c.type
        raise StorageException(f"table {self.name}: no column {name}")

    def item_of_row(self, row: int) -> int:
        """Index of the item containing global row `row`."""
        lo = int(np.searchsorted(np.asarray(self.end_rows), row, side="right"))
        if lo >= len(self.end_rows):
            raise StorageException(
                f"table {self.name}: row {row} out of range ({self.num_rows})")
        return lo

    def item_bounds(self, item: int) -> Tuple[int, int]:
        start = self.end_rows[item - 1] if item > 0 else 0
        return start, self.end_rows[item]

    def to_dict(self) -> dict:
        return {
            "id": self.id, "name": self.name,
            "columns": [c.to_dict() for c in self.columns],
            "end_rows": list(self.end_rows),
            "job_id": self.job_id, "timestamp": self.timestamp,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TableDescriptor":
        return cls(id=d["id"], name=d["name"],
                   columns=[ColumnDescriptor.from_dict(c) for c in d["columns"]],
                   end_rows=list(d["end_rows"]), job_id=d["job_id"],
                   timestamp=d.get("timestamp", 0.0))

    def serialize(self) -> bytes:
        return pack(self.to_dict())

    @classmethod
    def deserialize(cls, data: bytes) -> "TableDescriptor":
        return cls.from_dict(unpack(data))


@dataclass
class DatabaseMetadata:
    """Authoritative name->id map plus commit flags.

    Mirrors reference DatabaseDescriptor (metadata.proto:6-30): a table is
    visible to readers only once committed; failed jobs leave uncommitted
    tables which are ignored and reclaimed.
    """

    next_table_id: int = 0
    next_job_id: int = 0
    # name -> table id
    tables: Dict[str, int] = field(default_factory=dict)
    committed: Dict[int, bool] = field(default_factory=dict)

    def has_table(self, name: str) -> bool:
        return name in self.tables

    def table_id(self, name: str) -> int:
        if name not in self.tables:
            raise StorageException(f"no such table: {name}")
        return self.tables[name]

    def table_is_committed(self, name: str) -> bool:
        return self.has_table(name) and self.committed.get(self.tables[name], False)

    def add_table(self, name: str) -> int:
        if name in self.tables:
            raise StorageException(f"table already exists: {name}")
        tid = self.next_table_id
        self.next_table_id += 1
        self.tables[name] = tid
        self.committed[tid] = False
        return tid

    def remove_table(self, name: str) -> int:
        tid = self.tables.pop(name)
        self.committed.pop(tid, None)
        return tid

    def commit_table(self, tid: int) -> None:
        self.committed[tid] = True

    def new_job_id(self) -> int:
        jid = self.next_job_id
        self.next_job_id += 1
        return jid

    def serialize(self) -> bytes:
        return pack({
            "version": FORMAT_VERSION,
            "next_table_id": self.next_table_id,
            "next_job_id": self.next_job_id,
            "tables": self.tables,
            "committed": {str(k): v for k, v in self.committed.items()},
        })

    @classmethod
    def deserialize(cls, data: bytes) -> "DatabaseMetadata":
        d = unpack(data)
        version = d.get("version", 0)
        if version != FORMAT_VERSION:
            raise StorageException(
                f"unsupported db metadata version {version} "
                f"(expected {FORMAT_VERSION})")
        return cls(next_table_id=d["next_table_id"],
                   next_job_id=d["next_job_id"],
                   tables=dict(d["tables"]),
                   committed={int(k): v for k, v in d["committed"].items()})
