"""Native Google Cloud Storage backend.

Capability parity: reference storehouse GCSStorage
(scanner/util/storehouse.h; python config.py:56 selects "gcs") — the
production store for 1000-video corpora.  Unlike gcsfuse-over-POSIX this
speaks the GCS API directly: ranged reads for sparse row fetches
(items.read_item_rows), resumable chunked uploads for large items, and
generation preconditions for the atomic first-writer-wins marker
(`write_exclusive`, if_generation_match=0) that POSIX gets from
O_CREAT|O_EXCL.

GCS object visibility is atomic (an object never appears partially
written), which satisfies the StorageBackend atomicity contract without a
rename step.  The client is injectable so unit tests run against an
in-memory fake; nothing imports google.cloud at module import time.
"""

from __future__ import annotations

from typing import List, Optional

from ..common import StorageException
from ..util import faults as _faults
from ..util.retry import call_with_backoff
from .backend import StorageBackend

# resumable-upload chunk size; also the threshold above which the client
# library switches from one-shot to resumable uploads
_CHUNK_SIZE = 16 * 1024 * 1024

# transient service errors worth retrying (rate limit + server-side);
# matched structurally so fakes don't need the google exception classes
_TRANSIENT_CODES = {429, 500, 502, 503, 504}
_TRANSIENT_NAMES = {"TooManyRequests", "InternalServerError", "BadGateway",
                    "ServiceUnavailable", "GatewayTimeout",
                    "DeadlineExceeded", "RetryError"}


def _transient(e: Exception) -> bool:
    return getattr(e, "code", None) in _TRANSIENT_CODES \
        or type(e).__name__ in _TRANSIENT_NAMES \
        or isinstance(e, ConnectionError)


def parse_gs_url(url: str):
    """'gs://bucket/some/prefix' -> (bucket, 'some/prefix')."""
    if not url.startswith("gs://"):
        raise StorageException(f"not a gs:// url: {url}")
    rest = url[len("gs://"):]
    bucket, _, prefix = rest.partition("/")
    if not bucket:
        raise StorageException(f"gs:// url missing bucket: {url}")
    return bucket, prefix.strip("/")


class GcsStorage(StorageBackend):
    """Blobs are GCS objects under gs://bucket/prefix/."""

    def __init__(self, bucket: str, prefix: str = "",
                 client=None, retries: int = 5,
                 backoff_base: float = 0.1, backoff_cap: float = 5.0):
        self._retries = retries
        self._backoff_base = backoff_base
        self._backoff_cap = backoff_cap
        if client is None:
            try:
                from google.cloud import storage as gcs
            except ImportError as e:  # pragma: no cover - env without lib
                raise StorageException(
                    "google-cloud-storage is required for the gcs "
                    "backend") from e
            client = gcs.Client()
        self._client = client
        self._bucket = client.bucket(bucket)
        self.prefix = prefix.strip("/")

    @staticmethod
    def from_url(url: str, client=None) -> "GcsStorage":
        bucket, prefix = parse_gs_url(url)
        return GcsStorage(bucket, prefix, client=client)

    def _key(self, path: str) -> str:
        path = path.lstrip("/")
        if not self.prefix:
            return path
        return f"{self.prefix}/{path}" if path else self.prefix

    def _blob(self, path: str, chunked: bool = False):
        blob = self._bucket.blob(self._key(path))
        if chunked:
            blob.chunk_size = _CHUNK_SIZE
        return blob

    @staticmethod
    def _not_found(e: Exception) -> bool:
        # google.api_core.exceptions.NotFound has code 404; tested
        # structurally so fakes don't need the real exception class
        return getattr(e, "code", None) == 404 \
            or type(e).__name__ == "NotFound"

    @staticmethod
    def _precondition_failed(e: Exception) -> bool:
        return getattr(e, "code", None) == 412 \
            or type(e).__name__ == "PreconditionFailed"

    def _with_retry(self, fn):
        """Run fn() retrying transient 429/5xx/connection errors with
        full-jitter exponential backoff (storehouse retry parity).
        Retries count into scanner_tpu_retry_attempts_total{site="gcs"}
        and the final give-up logs at WARNING with the accumulated wait
        (util/retry.py) — a throttled bucket is visible live, not only
        as mysteriously slow tasks."""

        def attempt():
            # chaos hook fires per ATTEMPT (inside the backoff loop), so
            # an injected transient error exercises this retry path
            if _faults.ACTIVE:
                _faults.inject("gcs.request")
            return fn()

        return call_with_backoff(
            attempt, is_transient=_transient, retries=self._retries,
            base=self._backoff_base, cap=self._backoff_cap, label="gcs")

    # -- reads ----------------------------------------------------------

    def read(self, path: str) -> bytes:
        try:
            return self._with_retry(
                lambda: self._blob(path).download_as_bytes())
        except Exception as e:  # noqa: BLE001
            if self._not_found(e):
                raise StorageException(f"not found: {path}") from e
            raise

    def read_range(self, path: str, offset: int, size: int) -> bytes:
        def fetch(start: int, want: int) -> bytes:
            try:
                # GCS range end is INCLUSIVE
                return self._with_retry(
                    lambda: self._blob(path).download_as_bytes(
                        start=start, end=start + want - 1))
            except Exception as e:  # noqa: BLE001
                if self._not_found(e):
                    raise StorageException(f"not found: {path}") from e
                # requesting past EOF returns 416; mirror POSIX short read
                if getattr(e, "code", None) == 416:
                    return b""
                raise

        if size <= 0:
            return b""
        # a truncated transfer surfaces as a short byte string; re-issue
        # the remaining range until EOF (empty/unchanged) or complete
        out = fetch(offset, size)
        while 0 < len(out) < size:
            more = fetch(offset + len(out), size - len(out))
            if not more:
                break  # genuine EOF — short read mirrors POSIX
            out += more
        return out

    # -- writes ---------------------------------------------------------

    def write(self, path: str, data: bytes, sync: bool = True) -> None:
        # resumable chunked upload above _CHUNK_SIZE; object visibility
        # is atomic either way.  Retry-safe: re-uploading the same bytes
        # is idempotent.  `sync` is meaningless here (GCS objects are
        # durable at acknowledgment); accepted for interface parity.
        self._with_retry(
            lambda: self._blob(path, chunked=len(data) > _CHUNK_SIZE)
            .upload_from_string(bytes(data),
                                content_type="application/octet-stream"))

    def write_exclusive(self, path: str, data: bytes) -> bool:
        try:
            # NOT retried wholesale: a retry after an ambiguous transient
            # failure could observe its OWN first attempt's object and
            # misreport "lost the race".  if_generation_match=0 makes the
            # server reject duplicates, so only connection-refused (never
            # sent) errors are safe to retry — covered by _transient on
            # the underlying channel inside one upload call.
            self._blob(path).upload_from_string(
                bytes(data), content_type="application/octet-stream",
                if_generation_match=0)
            return True
        except Exception as e:  # noqa: BLE001
            if self._precondition_failed(e):
                return False
            raise

    # -- metadata/management --------------------------------------------

    def exists(self, path: str) -> bool:
        return bool(self._with_retry(lambda: self._blob(path).exists()))

    def size(self, path: str) -> int:
        blob = self._with_retry(
            lambda: self._bucket.get_blob(self._key(path)))
        if blob is None:
            raise StorageException(f"not found: {path}")
        return int(blob.size)

    def delete(self, path: str) -> None:
        try:
            self._with_retry(lambda: self._blob(path).delete())
        except Exception as e:  # noqa: BLE001
            if not self._not_found(e):
                raise

    @staticmethod
    def _under(name: str, key: str) -> bool:
        """Path-component-boundary prefix match: 'tables/5' covers
        'tables/5' and 'tables/5/...' but NOT 'tables/52/...' (object
        stores have no directories; a raw string prefix would silently
        hit sibling tables)."""
        if not key:
            return True
        return name == key or name.startswith(key + "/")

    def delete_prefix(self, prefix: str) -> None:
        key = self._key(prefix)
        blobs = self._with_retry(
            lambda: list(self._client.list_blobs(self._bucket, prefix=key)))
        for blob in blobs:
            if not self._under(blob.name, key):
                continue
            try:
                self._with_retry(blob.delete)
            except Exception as e:  # noqa: BLE001
                if not self._not_found(e):
                    raise

    def list_prefix(self, prefix: str) -> List[str]:
        root = self._key(prefix)
        strip = len(self.prefix) + 1 if self.prefix else 0
        blobs = self._with_retry(
            lambda: list(self._client.list_blobs(self._bucket,
                                                 prefix=root)))
        return sorted(blob.name[strip:] for blob in blobs
                      if self._under(blob.name, root))
