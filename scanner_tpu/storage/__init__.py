from .backend import StorageBackend, PosixStorage, MemoryStorage, make_storage
from .gcs import GcsStorage, parse_gs_url
from .custom import CustomStorage, CustomStream, FilesStorage, FilesStream
from .database import Database
from .metadata import (ColumnDescriptor, ColumnType, DatabaseMetadata,
                       TableDescriptor, VideoDescriptor)

__all__ = [
    "StorageBackend", "PosixStorage", "MemoryStorage", "make_storage",
    "GcsStorage", "parse_gs_url",
    "Database", "CustomStorage", "CustomStream", "FilesStorage",
    "FilesStream", "ColumnDescriptor", "ColumnType", "DatabaseMetadata",
    "TableDescriptor", "VideoDescriptor",
]
