from .backend import StorageBackend, PosixStorage, MemoryStorage, make_storage
from .database import Database
from .metadata import (ColumnDescriptor, ColumnType, DatabaseMetadata,
                       TableDescriptor, VideoDescriptor)

__all__ = [
    "StorageBackend", "PosixStorage", "MemoryStorage", "make_storage",
    "Database", "ColumnDescriptor", "ColumnType", "DatabaseMetadata",
    "TableDescriptor", "VideoDescriptor",
]
