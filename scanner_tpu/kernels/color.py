"""YUV420 -> RGB conversion, device (jnp) and host (numpy) flavors.

The decode pipeline can ship planar I420 (1.5 B/px) to the accelerator
instead of packed RGB24 (3 B/px) and convert there — halving host->device
bytes, the first-order term of every device pipeline (PERF.md §1).  The
reference did the same on GPU: NV12 frames converted by a CUDA kernel
(reference scanner/util/image.cu:22 nv12_to_rgb); here the conversion is
a jit-compiled jnp op XLA fuses ahead of the first consumer kernel.

Both flavors implement the SAME arithmetic — BT.601 limited range with
nearest-neighbor chroma upsampling in 8-bit integer fixed point — so
device and host pipelines are bit-identical on every backend
(test_video.py pins this).  Note
swscale's own yuv420p->RGB24 path (the decoder's "rgb24" output) uses
fixed-point coefficients and bilinear chroma; the two conversions agree
closely but not bit-for-bit, which is why a pipeline picks ONE decode
format end-to-end rather than mixing per stage.
"""

from __future__ import annotations

import functools

import numpy as np

# ITU-R BT.601 studio swing (the default signaled range of the h264/hevc
# streams the engine ingests), in the classic 8-bit fixed-point form:
#   R = (298(Y-16)           + 409(V-128) + 128) >> 8
#   G = (298(Y-16) - 100(U-128) - 208(V-128) + 128) >> 8
#   B = (298(Y-16) + 516(U-128)            + 128) >> 8
# Integer arithmetic is EXACT on every backend — float fma/reassociation
# under XLA fusion would cost odd one-count rounding differences between
# host and device at some geometries.


def _split_planes(flat, h: int, w: int):
    """Slice flat I420 rows into Y/U/V planes; works identically on
    numpy and jax arrays (shared so the two flavors cannot drift)."""
    ch, cw = (h + 1) // 2, (w + 1) // 2
    y = flat[..., : h * w].reshape(*flat.shape[:-1], h, w)
    u = flat[..., h * w: h * w + ch * cw].reshape(*flat.shape[:-1], ch, cw)
    v = flat[..., h * w + ch * cw:].reshape(*flat.shape[:-1], ch, cw)
    return y, u, v


def _combine(y, u, v, xp):
    """Shared fixed-point arithmetic on int32 planes already at full
    resolution; returns int32 0..255."""
    yy = 298 * (y - 16)
    uu = u - 128
    vv = v - 128
    r = (yy + 409 * vv + 128) >> 8
    g = (yy - 100 * uu - 208 * vv + 128) >> 8
    b = (yy + 516 * uu + 128) >> 8
    rgb = xp.stack([r, g, b], axis=-1)
    return xp.clip(rgb, 0, 255)


def yuv420_to_rgb_host(flat: np.ndarray, h: int, w: int) -> np.ndarray:
    """(..., yuv420_frame_bytes) uint8 -> (..., h, w, 3) uint8 on host."""
    y, u, v = _split_planes(np.asarray(flat), h, w)
    up = np.repeat(np.repeat(u, 2, axis=-2), 2, axis=-1)[..., :h, :w]
    vp = np.repeat(np.repeat(v, 2, axis=-2), 2, axis=-1)[..., :h, :w]
    out = _combine(y.astype(np.int32), up.astype(np.int32),
                   vp.astype(np.int32), np)
    return out.astype(np.uint8)


@functools.lru_cache(maxsize=16)
def _device_converter(h: int, w: int):
    import jax
    import jax.numpy as jnp

    def convert(flat):
        y, u, v = _split_planes(flat, h, w)
        up = jnp.repeat(jnp.repeat(u, 2, axis=-2), 2, axis=-1)[..., :h, :w]
        vp = jnp.repeat(jnp.repeat(v, 2, axis=-2), 2, axis=-1)[..., :h, :w]
        out = _combine(y.astype(jnp.int32), up.astype(jnp.int32),
                       vp.astype(jnp.int32), jnp)
        return out.astype(jnp.uint8)

    return jax.jit(convert)


def yuv420_to_rgb_device(flat, h: int, w: int):
    """(..., yuv420_frame_bytes) uint8 -> (..., h, w, 3) uint8 as a
    jit-compiled device op (cached per geometry)."""
    return _device_converter(int(h), int(w))(flat)
