"""Shot-boundary detection kernels.

Capability parity: the reference's shot_detection example app
(examples/README.md walkthrough): color-histogram + temporal difference +
threshold.  Here the temporal difference is a stencil op, so the engine's
exact-row scheduling decodes only the frames each boundary test needs.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..common import DeviceType, FrameType
from ..graph.ops import Kernel, register_op
from ..util.coststats import CostDescriptor
from .imgproc import HISTOGRAM_BINS, _frame_shape, _histogram_impl


@register_op(device=DeviceType.TPU, stencil=[-1, 0], batch=16)
class HistDiff(Kernel):
    """L1 distance between the color histograms of consecutive frames.

    Convenient single-op form; each frame's histogram is computed twice
    (as `cur` and again as the next row's `prev`).  The cheaper composition
    is Histogram -> HistogramDelta: the engine's stencil element cache
    reuses each histogram, and the stencil data shrinks from full frames to
    3x16 ints."""

    def cost(self, shapes):
        """Two histograms over the (b, 2, ...) stencil window (bins+2
        flops per input element, the Histogram model) plus the per-row
        L1 over 2 * C * bins histogram cells, where C is the trailing
        channel axis.  Reads the window once, emits one float per row.
        Works for the classic (b, 2, H, W, C) frame window and for any
        array window a fused chain hands this op (e.g. Histogram
        output windows)."""
        s = _frame_shape(shapes)
        if s is None or len(s) < 3:
            return None
        b, c = s[0], s[-1]
        px = 1
        for d in s:
            px *= d
        flops = px * (HISTOGRAM_BINS + 2) + b * 2 * c * HISTOGRAM_BINS
        return CostDescriptor(flops=float(flops), bytes_in=float(px),
                              bytes_out=float(b * 8))

    def execute_traced(self, frame):
        """Traced core: (batch, 2, ...) window in, (batch,) float32 L1
        distances out — pure jax, so fused chains
        (engine/evaluate.py FusedKernelInstance) can inline it.  The
        histograms are exact small-int counts, so the float32 L1 sums
        are exact and the host conversion in finish() is bit-stable."""
        arr = jnp.asarray(frame)
        prev, cur = arr[:, 0], arr[:, 1]
        hp = _histogram_impl(prev).astype(jnp.float32)
        hc = _histogram_impl(cur).astype(jnp.float32)
        return jnp.abs(hp - hc).sum(axis=(1, 2))

    def finish(self, result):
        """Host tail: the per-row float list execute() always returned."""
        return [float(x) for x in np.asarray(result)]

    def execute(self, frame: Sequence[Sequence[FrameType]]
                ) -> Sequence[Any]:
        from ..engine.batch import is_array_data
        if not is_array_data(frame):
            # per-row window lists (host path): stack to (batch, 2, ...)
            frame = np.stack([np.stack([w[0], w[1]]) for w in frame])
        return self.finish(self.execute_traced(frame))


@register_op(stencil=[-1, 0])
class HistogramDelta(Kernel):
    """L1 distance between consecutive rows of a Histogram stream — the
    efficient shot-detection primitive (each histogram computed once)."""

    def execute(self, hist: Sequence[Any]) -> Any:
        prev = np.concatenate([np.asarray(c) for c in hist[0]]).astype(
            np.float64)
        cur = np.concatenate([np.asarray(c) for c in hist[1]]).astype(
            np.float64)
        return float(np.abs(prev - cur).sum())


@register_op()
class ShotBoundary(Kernel):
    """Thresholds a HistDiff stream into 0/1 boundary flags."""

    def __init__(self, config, threshold: float = 0.0):
        super().__init__(config)
        self.threshold = float(threshold)

    def new_stream(self, threshold: float = None):
        if threshold is not None:
            self.threshold = float(threshold)

    def execute(self, diff: Any) -> Any:
        return bool(diff > self.threshold)


def detect_shots(diffs: np.ndarray, z: float = 2.5,
                 min_gap: int = 8) -> np.ndarray:
    """Offline boundary pick: z-score threshold + minimum shot length
    (the app-level logic of the reference shot_detect example)."""
    diffs = np.asarray(diffs, np.float64)
    mu, sigma = diffs.mean(), diffs.std() + 1e-9
    cand = np.nonzero((diffs - mu) / sigma > z)[0]
    out = []
    for c in cand:
        if not out or c - out[-1] >= min_gap:
            out.append(int(c))
    return np.asarray(out, np.int64)
