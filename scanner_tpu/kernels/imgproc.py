"""Image-processing kernel stdlib (JAX).

Capability parity: the scannertools kernel stdlib the reference tutorials
import (examples/tutorials/00_basic.py `import scannertools.imgproc`:
Histogram, Resize, Blur, OpticalFlow) and tests/test_ops.cpp (Histogram:13,
Resize:114, Blur:239, OpticalFlow:63).

All kernels are batched: XLA sees (batch, H, W, C) uint8 arrays, the natural
TPU layout.  jit caches compile per (shape, dtype), and the engine's
bucketed dispatch (engine/evaluate.py) rounds every call up a small
power-of-two ladder capped at the declared batch= — so each op compiles a
bounded executable set however ragged the task geometry is.  The batch
declaration is a memory cap, not a promise of exact call sizes.
"""

from __future__ import annotations

import functools
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..common import DeviceType, FrameType
from ..graph.ops import Kernel, register_op
from ..util.coststats import CostDescriptor

HISTOGRAM_BINS = 16


def _frame_shape(shapes, idx: int = 0):
    """The idx-th input's array shape, or None when the engine handed a
    per-row list (host path) — cost hooks then fall back to the derived
    default rather than guess."""
    if idx < len(shapes) and isinstance(shapes[idx], tuple):
        return shapes[idx]
    return None


@functools.partial(jax.jit, static_argnames=("bins",))
def _histogram_impl(frames: jnp.ndarray, bins: int = HISTOGRAM_BINS):
    """(batch, H, W, C) uint8 -> (batch, C, bins) int32 counts.

    vmapped bincount: lowers to a segment reduction — good on CPU/GPU
    XLA, but on TPU the scatter machinery serializes (measured 116 fps
    for a 480x640 batch on v5e vs 932 fps for compare+sum)."""
    b, c = frames.shape[0], frames.shape[-1]
    vals = (frames.astype(jnp.int32) * bins) // 256
    vals = vals.reshape(b, -1, c).transpose(0, 2, 1).reshape(b * c, -1)
    counts = jax.vmap(lambda v: jnp.bincount(v, length=bins))(vals)
    return counts.reshape(b, c, bins)


def _histogram_seq_impl(frames: jnp.ndarray, bins: int = HISTOGRAM_BINS):
    """(batch, H, W, C) uint8 -> (batch, C, bins) int32 via one
    compare+sum pass per bin inside a lax.scan: no scatter and no
    materialized (B, P, C, bins) one-hot (that bool tensor costs ~5x on
    XLA CPU — 80 ms vs 16 ms at 8x240x320, measured 2026-08).  The scan
    over bin ids — rather than an unrolled python loop — is load-bearing
    for FUSION chains: `vals` becomes a loop invariant XLA must
    materialize ONCE, where an unrolled loop leaves 16 sibling
    compare+reduce consumers and XLA CPU re-fuses the whole upstream
    producer (e.g. a composed Blur) into every one of them — it also
    deletes optimization_barrier, so this loop structure is the only
    reliable fence.  This is the lowering fused chains trace on
    host-only backends, where Histogram's numpy bincount fast path is
    unreachable inside a jit.

    The per-bin reduce is hierarchical: uint8 partial sums over 128-wide
    chunks (128 matches fit uint8), then an int32 reduce over the tiny
    partials.  A direct int32 reduce converts every compare result to 4
    bytes first, quadrupling accumulate traffic — 14.4 ms vs 4.4 ms at
    8x240x320 on XLA CPU (measured 2026-08).  Assumes bins < 255 (the
    chunk padding uses 255 as a never-matches bin id)."""
    b, c = frames.shape[0], frames.shape[-1]
    vals = ((frames.astype(jnp.int32) * bins) // 256).astype(jnp.uint8)
    vals = vals.reshape(b, -1, c).transpose(0, 2, 1)    # (B, C, P)
    chunk = 128
    pad = (-vals.shape[-1]) % chunk
    if pad:
        vals = jnp.pad(vals, ((0, 0), (0, 0), (0, pad)),
                       constant_values=255)
    vals = vals.reshape(b, c, -1, chunk)
    ids = jnp.arange(bins, dtype=jnp.uint8)

    def _bin(carry, i):
        part = (vals == i).sum(3, dtype=jnp.uint8)
        return carry, part.astype(jnp.int32).sum(2)

    _, cols = jax.lax.scan(_bin, 0, ids)
    return jnp.moveaxis(cols, 0, -1)


@functools.partial(jax.jit, static_argnames=("bins",))
def _histogram_cmp_impl(frames: jnp.ndarray, bins: int = HISTOGRAM_BINS):
    """(batch, H, W, C) uint8 -> (batch, C, bins) int32 via one-hot
    compare + reduce: pure VPU work, no scatter — the TPU-fast lowering
    (8x over bincount on v5e, measured on hardware 2026-07)."""
    b, c = frames.shape[0], frames.shape[-1]
    vals = (frames.astype(jnp.int32) * bins) // 256
    vals = vals.reshape(b, -1, c)                       # (B, P, C)
    ids = jnp.arange(bins, dtype=jnp.int32)
    onehot = (vals[..., None] == ids)                   # (B, P, C, bins)
    return onehot.sum(1, dtype=jnp.int32)               # (B, C, bins)


@register_op(device=DeviceType.TPU, batch=16)
class Histogram(Kernel):
    """Per-channel 16-bin color histogram; returns [r, g, b] int32 arrays
    per frame (matching scannertools' UniformList(Histogram, parts=3)).

    Backend selection (hardware-measured, see PERF.md §2): TPU runs the
    hand-written pallas compare+reduce kernel (kernels/pallas_ops.py,
    5240 fps on v5e at the 128x480x640 batch vs 4365 fps for the XLA
    compare+sum and 161 fps for bincount), falling back to compare+sum
    if the pallas compile fails; a host-only backend uses numpy's C
    bincount; other accelerators the vmapped-bincount XLA path.  Set
    SCANNER_TPU_PALLAS=0 to force the XLA path on TPU."""

    def __init__(self, config):
        super().__init__(config)
        import os

        from . import pallas_ops
        self._on_tpu = pallas_ops.on_tpu()
        self._use_pallas = (pallas_ops.HAVE_PALLAS and self._on_tpu
                            and os.environ.get("SCANNER_TPU_PALLAS") != "0")
        # on a host-only backend numpy's C bincount beats the XLA-CPU
        # scatter lowering; accelerators take the XLA/pallas path
        self._use_numpy = (not self._use_pallas and not self._on_tpu
                           and jax.default_backend() == "cpu")

    @staticmethod
    def _histogram_np(frames: np.ndarray) -> np.ndarray:
        b, c = frames.shape[0], frames.shape[-1]
        bins = HISTOGRAM_BINS
        assert bins == 16, "np fast path assumes 16 bins (uint8 >> 4)"
        v = (frames >> 4).astype(np.int32)
        v += np.arange(c, dtype=np.int32) * bins
        flat = v.reshape(b, -1)
        # int32, matching the XLA/pallas paths so stored output dtype does
        # not depend on which backend ran the job
        out = np.empty((b, c, bins), np.int32)
        for i in range(b):
            out[i] = np.bincount(flat[i], minlength=c * bins).reshape(c,
                                                                      bins)
        return out

    def cost(self, shapes):
        """Compare+reduce histogram: per pixel-channel, one fixed-point
        binning (2 ops) plus `bins` compares and `bins` accumulates.
        Reads the uint8 frames once, writes (b, C, bins) int32."""
        s = _frame_shape(shapes)
        if s is None or len(s) != 4:
            return None
        b, h, w, c = s
        px = b * h * w * c
        return CostDescriptor(
            flops=float(px * (HISTOGRAM_BINS + 2)),
            bytes_in=float(px),
            bytes_out=float(b * c * HISTOGRAM_BINS * 4))

    def execute(self, frame: Sequence[FrameType]) -> Sequence[Any]:
        """Returns the (batch, C, bins) int32 counts as ONE batch array.

        Device paths return it WITHOUT materializing on host: jax arrays
        chain asynchronously through the column store and the sink
        fetches once per task — a blocking np.asarray per work packet
        would serialize the pipeline on d2h latency (~180 ms/fetch over
        the tunnel, PERF.md §1).  Each stored row is a (C, bins) array;
        row[c] indexes channel c's histogram (scannertools parity:
        UniformList(Histogram, parts=3))."""
        if self._use_numpy and isinstance(frame, np.ndarray):
            return self._histogram_np(frame)
        if self._use_pallas:
            from .pallas_ops import histogram_frames
            try:
                return histogram_frames(jnp.asarray(frame))
            except Exception:  # exotic build: fall back to XLA for good
                self._use_pallas = False
        if self._on_tpu:
            return _histogram_cmp_impl(jnp.asarray(frame))
        return _histogram_impl(jnp.asarray(frame))

    def execute_traced(self, frame):
        """Fusion-chain core: inside a composed trace the numpy fast
        path is unreachable (the input is a tracer), and the bincount
        lowering serializes on scatter on every backend.  TPU traces
        the measured-fast compare+sum; hosts and other accelerators the
        per-bin compare+sum (see _histogram_seq_impl)."""
        frame = jnp.asarray(frame)
        if self._on_tpu:
            return _histogram_cmp_impl(frame)
        return _histogram_seq_impl(frame)


def _resize_band(in_size: int, out_size: int):
    """Contiguous tap indices + normalized triangle weights for one
    axis of a separable bilinear resize (half-pixel centers, antialias
    width max(scale, 1) — the jax.image.resize bilinear kernel).  Every
    output row reads the same small tap count k, so the resize lowers
    to k weighted gathers per axis instead of a dense contraction."""
    scale = in_size / out_size
    centers = (np.arange(out_size) + 0.5) * scale - 0.5
    idx = np.arange(in_size)
    wts = 1.0 - np.abs(centers[:, None] - idx[None, :]) / max(scale, 1.0)
    wts = np.clip(wts, 0.0, None)
    nz = wts > 0
    k = int(nz.sum(1).max())
    start = np.where(nz.any(1), nz.argmax(1), 0)
    start = np.minimum(start, in_size - k)
    taps = start[:, None] + np.arange(k)[None, :]
    tw = np.take_along_axis(wts, taps, 1)
    tw = (tw / tw.sum(1, keepdims=True)).astype(np.float32)
    return jnp.asarray(taps), jnp.asarray(tw), k


@functools.partial(jax.jit, static_argnames=("h", "w"))
def _resize_impl(frames: jnp.ndarray, h: int, w: int):
    """Separable gather-based bilinear resize.  The triangle kernel is
    sparse — k taps per output row (k=4 for a 2x downscale) — but
    jax.image.resize materializes it as a dense [in, out] contraction,
    which XLA CPU executes in full: 91.7 ms vs 12.9 ms for the tap form
    at 8x480x640 -> 240x320 (measured 2026-08).  h/w are static, so the
    tap tables are concrete numpy at trace time.

    Structure matters as much as the tap count.  The h-pass gathers
    uint8 rows and converts AFTER the gather (converting the whole
    input first makes XLA materialize a 4x-bigger f32 copy), and the
    w-pass runs in a lax.scan over output row blocks with the h-pass
    result as a loop invariant: left to itself, XLA CPU merges the two
    passes into one 2-D gather of hk*wk taps per output element,
    discarding separability — the loop invariant pins the h-pass to
    one materialization (8.4 ms -> 6.4 ms alone, and it is what keeps
    fused chains from re-fusing the resize into downstream taps)."""
    b, c = frames.shape[0], frames.shape[-1]
    hi, hw_, hk = _resize_band(frames.shape[1], h)
    wi, ww_, wk = _resize_band(frames.shape[2], w)
    y = sum(hw_[:, j, None, None] * frames[:, hi[:, j], :, :]
            .astype(jnp.float32) for j in range(hk))
    rb = min(48, h)
    nb = -(-h // rb)
    y = jnp.pad(y, ((0, 0), (0, nb * rb - h), (0, 0), (0, 0)))

    def _block(carry, s):
        ys = jax.lax.dynamic_slice_in_dim(y, s, rb, 1)
        o = sum(ww_[:, j, None] * ys[:, :, wi[:, j], :] for j in range(wk))
        return carry, jnp.clip(jnp.round(o), 0, 255).astype(jnp.uint8)

    _, blocks = jax.lax.scan(_block, 0, jnp.arange(nb) * rb)
    return jnp.moveaxis(blocks, 0, 1).reshape(b, nb * rb, w, c)[:, :h]


@register_op(device=DeviceType.TPU, batch=16)
class Resize(Kernel):
    """Bilinear resize to (width, height) — per-stream args like the
    reference Resize op (test_ops.cpp:114, stream-protobuf args)."""

    def __init__(self, config, width: int = 0, height: int = 0):
        super().__init__(config)
        self.width, self.height = int(width), int(height)

    def new_stream(self, width: int = None, height: int = None):
        if width is not None:
            self.width = int(width)
        if height is not None:
            self.height = int(height)

    def cost(self, shapes):
        """Separable bilinear resample: 4 taps (mul+add) per output
        pixel-channel = 8 flops.  Reads the source frames, writes the
        (b, H, W, c) uint8 result."""
        s = _frame_shape(shapes)
        if s is None or len(s) != 4 or not (self.height and self.width):
            return None
        b, h, w, c = s
        out_px = b * self.height * self.width * c
        return CostDescriptor(flops=float(out_px * 8),
                              bytes_in=float(b * h * w * c),
                              bytes_out=float(out_px))

    def execute(self, frame: Sequence[FrameType]) -> Sequence[FrameType]:
        # device in -> device out: chained TPU ops never bounce to host
        return _resize_impl(jnp.asarray(frame), self.height, self.width)


@functools.partial(jax.jit, static_argnames=("oh", "ow"))
def _crop_resize_impl(frames: jnp.ndarray, boxes: jnp.ndarray, oh: int,
                      ow: int):
    """Crop unit-coordinate boxes [y1,x1,y2,x2] out of (b,H,W,C) frames
    and resample each to (oh, ow).  scale_and_translate keeps the output
    shape static whatever the box is — no dynamic shapes on device."""
    H, W = frames.shape[1], frames.shape[2]

    def one(frame, box):
        y1, x1, y2, x2 = box[0] * H, box[1] * W, box[2] * H, box[3] * W
        h = jnp.maximum(y2 - y1, 1.0)
        w = jnp.maximum(x2 - x1, 1.0)
        scale = jnp.asarray([oh / h, ow / w], jnp.float32)
        # output pixel o maps to input o/scale + translate/..; translate
        # is in OUTPUT units: shift so input y1 lands on output 0
        translate = jnp.asarray([-y1 * oh / h, -x1 * ow / w], jnp.float32)
        out = jax.image.scale_and_translate(
            frame.astype(jnp.float32), (oh, ow, frame.shape[-1]),
            (0, 1), scale, translate, method="linear")
        return jnp.clip(jnp.round(out), 0, 255).astype(jnp.uint8)

    return jax.vmap(one)(frames, boxes)


@register_op(device=DeviceType.TPU, batch=16)
class CropResize(Kernel):
    """Crop a per-row box (unit coords [y1, x1, y2, x2]) out of each frame
    and resize to (height, width) — the region-extraction step of the
    reference's re-id/feature apps (open-reid extract_features.py resamples
    person crops to 256x128), with static output shapes so the whole op
    stays on device.  `size` sets a square output; height/width override
    per axis."""

    def __init__(self, config, size: int = 64, height: int = 0,
                 width: int = 0):
        super().__init__(config)
        self.height = int(height) or int(size)
        self.width = int(width) or int(size)

    def precompile_input(self, name: str):
        # boxes are unit coords, so a full-frame box warms the exact
        # executable the real calls hit (engine bucket-ladder warm-up)
        if name == "box":
            return np.asarray([0.0, 0.0, 1.0, 1.0], np.float32)
        return None

    def cost(self, shapes):
        """Crop + bilinear resample to (height, width): like Resize, 4
        taps (mul+add) per output pixel-channel = 8 flops; the per-box
        scale/translate arithmetic is O(b) and ignored.  Reads the
        frames and the (b, 4) float32 boxes, writes the crops."""
        s = _frame_shape(shapes)
        if s is None or len(s) != 4:
            return None
        b, h, w, c = s
        out_px = b * self.height * self.width * c
        return CostDescriptor(flops=float(out_px * 8),
                              bytes_in=float(b * h * w * c + b * 4 * 4),
                              bytes_out=float(out_px))

    def execute(self, frame: Sequence[FrameType],
                box: Sequence[Any]) -> Sequence[FrameType]:
        boxes = jnp.asarray(np.stack([np.asarray(b, np.float32)
                                      for b in box]))
        return _crop_resize_impl(jnp.asarray(frame), boxes, self.height,
                                 self.width)


def _gaussian_kernel1d(ksize: int, sigma: float) -> np.ndarray:
    r = (ksize - 1) / 2.0
    x = np.arange(ksize, dtype=np.float32) - r
    k = np.exp(-(x ** 2) / (2.0 * max(sigma, 1e-6) ** 2))
    return (k / k.sum()).astype(np.float32)


@functools.partial(jax.jit, static_argnames=("ksize",))
def _blur_impl(frames: jnp.ndarray, kern: jnp.ndarray, ksize: int):
    """Separable gaussian as shift-add: per tap, one scaled slice of the
    edge-padded image, summed — pure elementwise VPU work.  The previous
    depthwise conv_general_dilated lowering (batch*channel images of ONE
    feature each) hits XLA CPU's scalar conv path and ran 28x slower at
    the 8x240x320 bench geometry (195 ms vs 7 ms, measured 2026-08);
    one-feature convs are equally hostile to the TPU MXU.

    The shift-add runs inside a lax.scan over output ROW BLOCKS with the
    padded input as a loop invariant.  That structure is load-bearing
    for fusion chains: XLA CPU's loop fusion duplicates a producer into
    every sibling consumer (it also deletes optimization_barrier), so a
    composed upstream member would be recomputed once per tap slice —
    the loop invariant pins it to ONE materialization while the taps
    stay fully fused inside the block body.  Per-element arithmetic is
    identical to the unfenced form (bit-exact; block rows past `h` are
    computed on zero padding and cropped)."""
    b, h, w, c = frames.shape
    pad = ksize // 2
    x = jnp.pad(frames.astype(jnp.float32),
                ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="edge")
    rb = min(48, h)
    nb = -(-h // rb)
    # out-of-bounds zero pad so the last block's slice never clamps
    # (dynamic_slice clamps starts, which would silently shift rows)
    x = jnp.pad(x, ((0, 0), (0, nb * rb - h), (0, 0), (0, 0)))

    def _block(carry, s):
        xs = jax.lax.dynamic_slice_in_dim(x, s, rb + 2 * pad, 1)
        v = sum(kern[i] * xs[:, i:i + rb, :, :] for i in range(ksize))
        o = sum(kern[j] * v[:, :, j:j + w, :] for j in range(ksize))
        return carry, jnp.clip(jnp.round(o), 0, 255).astype(jnp.uint8)

    _, blocks = jax.lax.scan(_block, 0, jnp.arange(nb) * rb)
    return jnp.moveaxis(blocks, 0, 1).reshape(b, nb * rb, w, c)[:, :h]


@register_op(device=DeviceType.TPU, batch=16)
class Blur(Kernel):
    """Gaussian blur (reference tests/test_ops.cpp:239 Blur)."""

    def __init__(self, config, kernel_size: int = 3, sigma: float = 0.5):
        super().__init__(config)
        self.ksize = int(kernel_size) | 1  # odd
        self.kern = jnp.asarray(_gaussian_kernel1d(self.ksize, float(sigma)))

    def cost(self, shapes):
        """Separable gaussian: two 1-D passes of `ksize` taps each —
        2 * ksize * 2 flops per pixel-channel.  uint8 in, uint8 out,
        same geometry."""
        s = _frame_shape(shapes)
        if s is None or len(s) != 4:
            return None
        b, h, w, c = s
        px = b * h * w * c
        return CostDescriptor(flops=float(px * 4 * self.ksize),
                              bytes_in=float(px), bytes_out=float(px))

    def execute(self, frame: Sequence[FrameType]) -> Sequence[FrameType]:
        # device in -> device out: chained TPU ops never bounce to host
        return _blur_impl(jnp.asarray(frame), self.kern, self.ksize)


@jax.jit
def _grayscale(frames: jnp.ndarray) -> jnp.ndarray:
    w = jnp.asarray([0.299, 0.587, 0.114], jnp.float32)
    return (frames.astype(jnp.float32) * w).sum(-1)


HS_ITERS = 16  # fixed Horn-Schunck iteration count (cost model reads it)


@functools.partial(jax.jit, static_argnames=("iters",))
def _horn_schunck(prev: jnp.ndarray, nxt: jnp.ndarray, iters: int = HS_ITERS,
                  alpha: float = 15.0):
    """Classic Horn-Schunck optical flow, batched; (b,h,w) grayscale in,
    (b,h,w,2) float32 flow out.  Fixed-iteration lax.scan keeps the whole
    solve inside one XLA program (no data-dependent control flow)."""
    Ix = (jnp.roll(prev, -1, 2) - jnp.roll(prev, 1, 2)) * 0.5
    Iy = (jnp.roll(prev, -1, 1) - jnp.roll(prev, 1, 1)) * 0.5
    It = nxt - prev

    avg_k = jnp.asarray([[1 / 12, 1 / 6, 1 / 12],
                         [1 / 6, 0.0, 1 / 6],
                         [1 / 12, 1 / 6, 1 / 12]], jnp.float32)

    def avg(x):
        b, h, w = x.shape
        xp = jnp.pad(x[:, None], ((0, 0), (0, 0), (1, 1), (1, 1)),
                     mode="edge")
        return jax.lax.conv_general_dilated(
            xp, avg_k[None, None], (1, 1), "VALID")[:, 0]

    denom = alpha ** 2 + Ix ** 2 + Iy ** 2

    def step(carry, _):
        u, v = carry
        ub, vb = avg(u), avg(v)
        t = (Ix * ub + Iy * vb + It) / denom
        return (ub - Ix * t, vb - Iy * t), None

    (u, v), _ = jax.lax.scan(step, (jnp.zeros_like(Ix), jnp.zeros_like(Iy)),
                             None, length=iters)
    return jnp.stack([u, v], axis=-1)


@register_op(device=DeviceType.TPU, stencil=[-1, 0], batch=4)
class OpticalFlow(Kernel):
    """Dense optical flow between consecutive frames (reference scannertools
    OpticalFlow / test_ops.cpp:63, StenciledKernel).  Output per row:
    float32 (H, W, 2) flow from the previous frame to the current."""

    def cost(self, shapes):
        """Horn-Schunck: grayscale both frames (~5 flops/px each),
        gradients (~6/px), then HS_ITERS solver iterations of two 3x3
        averaging convs (36 flops/px) plus ~12 arithmetic ops/px.
        Reads the (b, 2, H, W, C) uint8 stencil window, writes
        (b, H, W, 2) float32 flow."""
        s = _frame_shape(shapes)
        if s is None or len(s) != 5:
            return None
        b, win, h, w, c = s
        px = b * h * w
        flops = px * (win * 5 + 6 + HS_ITERS * (36 + 12))
        return CostDescriptor(flops=float(flops),
                              bytes_in=float(b * win * h * w * c),
                              bytes_out=float(px * 2 * 4))

    def execute(self, frame: Sequence[Sequence[FrameType]]
                ) -> Sequence[FrameType]:
        from ..engine.batch import is_array_data
        if is_array_data(frame):
            # engine-gathered (batch, window, H, W, C) array: slice, don't
            # restack
            arr = jnp.asarray(frame)
            prev, nxt = arr[:, 0], arr[:, 1]
        else:
            prev = jnp.asarray(np.stack([w[0] for w in frame]))
            nxt = jnp.asarray(np.stack([w[1] for w in frame]))
        return _horn_schunck(_grayscale(prev), _grayscale(nxt))
