"""Pallas TPU kernels for hot ops.

The stdlib ops default to plain XLA (which fuses well); these hand-written
kernels exist where XLA's lowering leaves throughput on the table.  The
histogram is the flagship case: bincount lowers to sort/segment machinery,
while the VPU can do compare+reduce entirely in VMEM.

Kernels run under `interpret=True` on CPU (tests) and compile natively on
TPU.  Layout follows the pallas guide: last dim 128 lanes, f32/i32 tiles
(8, 128), grid accumulation over the pixel axis with @pl.when init.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas is part of jax, but guard for exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

LANES = 128
SUBLANES = 8
PIX_BLOCK = 16384  # int32 pixels per grid step: 8*16384*4 = 512 KB VMEM


def _hist_kernel(vals_ref, out_ref, *, bins: int):
    """One grid step: vals_ref (SUBLANES, PIX_BLOCK) int32 bin indices,
    out_ref (SUBLANES, LANES) int32 counts (bins <= LANES, rest padding).

    Grid dim 1 walks the pixel axis revisiting the same out block;
    accumulate with an explicit zero-init on the first visit."""

    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    vals = vals_ref[:, :]
    # compare+reduce per bin on the VPU; static Python loop unrolls into
    # `bins` vectorized passes, no scatter
    cols = []
    for b in range(bins):
        cols.append(jnp.sum((vals == b).astype(jnp.int32), axis=1))
    counts = jnp.stack(cols, axis=1)  # (SUBLANES, bins)
    pad = jnp.zeros((counts.shape[0], LANES - bins), jnp.int32)
    out_ref[:, :] += jnp.concatenate([counts, pad], axis=1)


@functools.partial(jax.jit, static_argnames=("bins", "interpret"))
def pallas_histogram(vals: jnp.ndarray, bins: int = 16,
                     interpret: bool = False) -> jnp.ndarray:
    """(R, P) int32 bin indices -> (R, bins) int32 counts.

    Rows are padded to a SUBLANES multiple and pixels to PIX_BLOCK; padding
    pixels carry bin id `bins` (out of range) so they count nowhere.
    """
    if bins > LANES:
        raise ValueError(f"bins must be <= {LANES}")
    R, P = vals.shape
    Rp = -(-R // SUBLANES) * SUBLANES
    Pp = -(-P // PIX_BLOCK) * PIX_BLOCK
    padded = jnp.full((Rp, Pp), bins, jnp.int32)
    padded = padded.at[:R, :P].set(vals)
    grid = (Rp // SUBLANES, Pp // PIX_BLOCK)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, bins=bins),
        out_shape=jax.ShapeDtypeStruct((Rp, LANES), jnp.int32),
        grid=grid,
        in_specs=[pl.BlockSpec((SUBLANES, PIX_BLOCK),
                               lambda r, p: (r, p))],
        out_specs=pl.BlockSpec((SUBLANES, LANES), lambda r, p: (r, 0)),
        interpret=interpret,
    )(padded)
    return out[:R, :bins]


def histogram_frames(frames: jnp.ndarray, bins: int = 16,
                     interpret: bool = False) -> jnp.ndarray:
    """(B, H, W, C) uint8 -> (B, C, bins) int32, pallas path."""
    b, c = frames.shape[0], frames.shape[-1]
    vals = (frames.astype(jnp.int32) * bins) // 256
    vals = vals.reshape(b, -1, c).transpose(0, 2, 1).reshape(b * c, -1)
    return pallas_histogram(vals, bins=bins,
                            interpret=interpret).reshape(b, c, bins)


def on_tpu() -> bool:
    try:
        # default_backend, not devices()[0]: a platform probe must not
        # look like a chip pin (scanner-check SC106 device-affinity lint)
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False
