"""Pallas TPU flash-attention block kernel for ring attention.

The ring rotation (parallel/ring_attention.py) consumes one arriving K/V
block per step, updating online-softmax accumulators (m, l, acc) for the
local queries.  The XLA path tiles that update with fori_loop +
dynamic_slice; this kernel fuses one whole (q-block x kv-block) update
into a single pallas_call so logits never leave VMEM and the
exp/correction arithmetic fuses with the two MXU matmuls.

Layout (pallas guide): grid (BH, q_tiles, kv_tiles) with kv innermost;
q/k/v tiles (block, D) f32 in VMEM; m/l carries (1, block_q) — lane-major
vectors; acc (1, block_q, D).  The kv axis revisits the same output
block, initializing from the carry refs at kv==0 (flash accumulation).
Global q/k positions for causal masking arrive via scalar prefetch, so
the same compiled kernel serves every ring step (the k offset is a
traced value — the block's origin device changes per step).

No reference counterpart: the reference has no in-engine attention
(SURVEY §5); this is TPU-native long-context machinery.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

try:  # pallas ships with jax; guard for exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

NEG_INF = -1e30

# vma (varying-mesh-axes) tracking is a newer-jax feature: there,
# ShapeDtypeStruct takes a `vma=` kwarg the ring path must set when
# calling inside shard_map.  Old releases have no vma tracking at all —
# the kwarg must simply be dropped (probed once, version-static).
try:
    jax.ShapeDtypeStruct((), jnp.float32, vma=frozenset())
    _HAVE_VMA = True
except TypeError:
    _HAVE_VMA = False


def _flash_kernel(offs_ref,                      # SMEM (2,): q_off, k_off
                  q_ref, k_ref, v_ref,           # VMEM tiles
                  m_in_ref, l_in_ref, acc_in_ref,  # carries (previous step)
                  m_out_ref, l_out_ref, acc_out_ref,
                  *, causal: bool, block_q: int, block_k: int):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_out_ref[...] = m_in_ref[...]
        l_out_ref[...] = l_in_ref[...]
        acc_out_ref[...] = acc_in_ref[...]

    q = q_ref[0]                                  # (block_q, D) pre-scaled
    k = k_ref[0]                                  # (block_k, D)
    v = v_ref[0]                                  # (block_k, D)
    logits = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)       # (block_q, block_k)

    if causal:
        q_pos = offs_ref[0] + pl.program_id(1) * block_q \
            + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = offs_ref[1] + ki * block_k \
            + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)

    m_prev = m_out_ref[0, 0, :]                   # (block_q,)
    l_prev = l_out_ref[0, 0, :]
    m_blk = jnp.max(logits, axis=1)               # (block_q,)
    m_new = jnp.maximum(m_prev, m_blk)
    # fully-masked rows keep m == NEG_INF; exp against a zero pivot and
    # zero correction so they contribute nothing and produce no NaN/inf
    m_safe = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(logits - m_safe[:, None])         # (block_q, block_k)
    p = jnp.where(logits <= NEG_INF / 2, 0.0, p)
    corr = jnp.where(m_prev <= NEG_INF / 2, 0.0,
                     jnp.exp(m_prev - m_safe))    # (block_q,)
    l_new = l_prev * corr + jnp.sum(p, axis=1)
    pv = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)       # (block_q, D)
    m_out_ref[0, 0, :] = m_new
    l_out_ref[0, 0, :] = l_new
    acc_out_ref[0, 0] = acc_out_ref[0, 0] * corr[:, None] + pv


def _block_size(tl: int, want: int) -> int:
    """Largest divisor of tl that is <= want."""
    if want < 1:
        raise ValueError(f"block size must be >= 1, got {want}")
    b = min(tl, want)
    while tl % b:
        b -= 1
    return b


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "vma"))
def flash_block_update(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                       m: jnp.ndarray, l: jnp.ndarray, acc: jnp.ndarray,
                       q_off, k_off, *, causal: bool = False,
                       block_q: int = 256, block_k: int = 256,
                       interpret: bool = False,
                       vma: Optional[Tuple[str, ...]] = None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One flash update of online-softmax state with a K/V block.

    q: (BH, Tq, D) queries, ALREADY scaled by 1/sqrt(D).
    k, v: (BH, Tk, D) the arriving block.
    m, l: (BH, Tq) running max / normalizer;  acc: (BH, Tq, D).
    q_off, k_off: global positions of q[.,0] / k[.,0] (for causal masks);
    may be traced values (ring step index).
    vma: mesh axes the outputs vary over — required when called inside
    shard_map with vma checking (the ring path passes its sequence axis).
    Returns updated (m, l, acc) in float32.
    """
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    bq = _block_size(Tq, block_q)
    bk = _block_size(Tk, block_k)
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    vkw = {} if vma is None or not _HAVE_VMA else {"vma": frozenset(vma)}
    grid = (BH, Tq // bq, Tk // bk)
    kern = functools.partial(_flash_kernel, causal=causal,
                             block_q=bq, block_k=bk)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    m3 = m[:, None, :]        # (BH, 1, Tq): lane-major carry blocks
    l3 = l[:, None, :]
    acc4 = acc[:, None, :, :]  # (BH, 1, Tq, D)
    # index maps receive the scalar-prefetch ref as a trailing arg
    m_o, l_o, acc_o = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, D),
                             lambda b, qi, ki, s: (b, qi, 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, qi, ki, s: (b, ki, 0)),
                pl.BlockSpec((1, bk, D),
                             lambda b, qi, ki, s: (b, ki, 0)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, qi, ki, s: (b, 0, qi)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, qi, ki, s: (b, 0, qi)),
                pl.BlockSpec((1, 1, bq, D),
                             lambda b, qi, ki, s: (b, 0, qi, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, bq),
                             lambda b, qi, ki, s: (b, 0, qi)),
                pl.BlockSpec((1, 1, bq),
                             lambda b, qi, ki, s: (b, 0, qi)),
                pl.BlockSpec((1, 1, bq, D),
                             lambda b, qi, ki, s: (b, 0, qi, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((BH, 1, Tq), jnp.float32, **vkw),
            jax.ShapeDtypeStruct((BH, 1, Tq), jnp.float32, **vkw),
            jax.ShapeDtypeStruct((BH, 1, Tq, D), jnp.float32, **vkw),
        ],
        interpret=interpret,
    )(offs, qf, kf, vf, m3, l3, acc4)
    return m_o[:, 0], l_o[:, 0], acc_o[:, 0]
