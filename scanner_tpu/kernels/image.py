"""Image encode/decode kernels.

Capability parity: reference util/image_encoder.cpp (lodepng/jpeg encode)
and the scannertools image ops.  PIL handles the codecs; these are host
(CPU) ops by nature.
"""

from __future__ import annotations

import io
from typing import Any, Sequence

import numpy as np

from ..common import DeviceType, FrameType
from ..graph.ops import Kernel, register_op


@register_op()
class ImageEncode(Kernel):
    """frame -> encoded image bytes (png/jpeg/webp)."""

    def __init__(self, config, format: str = "png", quality: int = 90):
        super().__init__(config)
        self.format = format.upper()
        self.quality = int(quality)

    def execute(self, frame: FrameType) -> bytes:
        from PIL import Image
        img = Image.fromarray(np.asarray(frame))
        buf = io.BytesIO()
        kw = {"quality": self.quality} if self.format in ("JPEG",) else {}
        img.save(buf, format=self.format, **kw)
        return buf.getvalue()


@register_op()
class ImageDecode(Kernel):
    """encoded image bytes -> RGB frame."""

    def execute(self, data: bytes) -> FrameType:
        from ..video.ingest import decode_image
        return decode_image(data)


@register_op()
class Grayscale(Kernel):
    """RGB frame -> single-channel-replicated grayscale frame (host op)."""

    def execute(self, frame: FrameType) -> FrameType:
        f = np.asarray(frame).astype(np.float32)
        g = (0.299 * f[..., 0] + 0.587 * f[..., 1] + 0.114 * f[..., 2])
        return np.repeat(g[..., None], 3, axis=-1).astype(np.uint8)
