# Importing registers the stdlib ops (like `import scannertools.imgproc`
# in the reference tutorials).
from . import image, imgproc, shot  # noqa: F401
