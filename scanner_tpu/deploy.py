"""Cluster deployment tooling for GKE TPU pods.

Capability parity: reference scannerpy/kube.py (CloudConfig, MachineType,
ClusterConfig with price estimation, Cluster create/scale/delete managing
master + worker deployments, kube.py:38-779) — retargeted from GPU node
pools to TPU node pools.  Manifest generation is pure (testable offline);
the Cluster methods shell out to gcloud/kubectl when present.
"""

from __future__ import annotations

import json
import shutil
import subprocess
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .common import ScannerException

# us-central1 on-demand ballpark $/hr (documented estimates, like the
# reference's price table)
TPU_PRICES = {
    "v5litepod-1": 1.2,
    "v5litepod-4": 4.8,
    "v5litepod-8": 9.6,
    "v5p-8": 16.6,
}
CPU_PRICE_PER_CORE = 0.033

# GKE node-pool accelerator labels per slice family
TPU_ACCELERATOR_LABELS = {
    "v5litepod": "tpu-v5-lite-podslice",
    "v5p": "tpu-v5p-slice",
}


def tpu_chips(tpu_type: str) -> int:
    """Chip count from the slice name suffix ('v5litepod-4' -> 4)."""
    try:
        return int(tpu_type.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        raise ScannerException(f"cannot parse TPU type: {tpu_type}")


def tpu_accelerator_label(tpu_type: str) -> str:
    family = tpu_type.rsplit("-", 1)[0]
    if family not in TPU_ACCELERATOR_LABELS:
        raise ScannerException(f"unknown TPU family: {family}")
    return TPU_ACCELERATOR_LABELS[family]


@dataclass
class CloudConfig:
    project: str
    zone: str = "us-central1-a"
    storage_bucket: Optional[str] = None


@dataclass
class MachineType:
    """One worker node shape: a TPU slice + host CPU."""

    tpu_type: str = "v5litepod-4"
    cpus: int = 24
    memory_gb: int = 96

    def price_per_hour(self) -> float:
        return TPU_PRICES.get(self.tpu_type, 0.0) \
            + self.cpus * CPU_PRICE_PER_CORE


@dataclass
class ClusterConfig:
    id: str
    num_workers: int
    master_cpus: int = 8
    worker: MachineType = field(default_factory=MachineType)
    image: str = "scanner-tpu:latest"
    db_path: str = "/data/db"
    master_port: int = 5000

    def price_per_hour(self) -> float:
        return (self.master_cpus * CPU_PRICE_PER_CORE
                + self.num_workers * self.worker.price_per_hour())


def master_manifest(cfg: ClusterConfig) -> Dict:
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": f"{cfg.id}-master"},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": f"{cfg.id}-master"}},
            "template": {
                "metadata": {"labels": {"app": f"{cfg.id}-master"}},
                "spec": {"containers": [{
                    "name": "master", "image": cfg.image,
                    "command": ["python", "-c",
                                ("from scanner_tpu.engine.service import "
                                 "start_master; start_master("
                                 f"'{cfg.db_path}', port={cfg.master_port},"
                                 " block=True)")],
                    "ports": [{"containerPort": cfg.master_port}],
                    "resources": {"requests": {"cpu": str(cfg.master_cpus)}},
                }]},
            },
        },
    }


def worker_manifest(cfg: ClusterConfig) -> Dict:
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": f"{cfg.id}-worker"},
        "spec": {
            "replicas": cfg.num_workers,
            "selector": {"matchLabels": {"app": f"{cfg.id}-worker"}},
            "template": {
                "metadata": {"labels": {"app": f"{cfg.id}-worker"}},
                "spec": {
                    "nodeSelector": {
                        "cloud.google.com/gke-tpu-accelerator":
                            tpu_accelerator_label(cfg.worker.tpu_type),
                    },
                    "containers": [{
                        "name": "worker", "image": cfg.image,
                        "command": ["python", "-c",
                                    ("from scanner_tpu.engine.service import"
                                     " start_worker; start_worker("
                                     f"'{cfg.id}-master:{cfg.master_port}',"
                                     f" '{cfg.db_path}', block=True)")],
                        "resources": {
                            "requests": {"cpu": str(cfg.worker.cpus)},
                            "limits": {"google.com/tpu":
                                       str(tpu_chips(cfg.worker.tpu_type))},
                        },
                    }],
                },
            },
        },
    }


def service_manifest(cfg: ClusterConfig) -> Dict:
    return {
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": f"{cfg.id}-master"},
        "spec": {
            "selector": {"app": f"{cfg.id}-master"},
            "ports": [{"port": cfg.master_port,
                       "targetPort": cfg.master_port}],
        },
    }


class Cluster:
    """Lifecycle wrapper (reference kube.py Cluster): create/scale/delete
    via gcloud/kubectl; manifests() works without either installed."""

    def __init__(self, cloud: CloudConfig, cfg: ClusterConfig):
        self.cloud = cloud
        self.cfg = cfg

    def manifests(self) -> List[Dict]:
        return [master_manifest(self.cfg), service_manifest(self.cfg),
                worker_manifest(self.cfg)]

    def manifests_json(self) -> str:
        return "\n---\n".join(json.dumps(m, indent=2)
                              for m in self.manifests())

    def _kubectl(self, *args, input_data: Optional[str] = None):
        if shutil.which("kubectl") is None:
            raise ScannerException(
                "kubectl not available; use manifests_json() and apply "
                "manually")
        return subprocess.run(["kubectl", *args], input=input_data,
                              text=True, check=True, capture_output=True)

    def create(self) -> None:
        self._kubectl("apply", "-f", "-", input_data=self.manifests_json())

    def scale(self, num_workers: int) -> None:
        self.cfg.num_workers = num_workers
        self._kubectl("scale", f"deployment/{self.cfg.id}-worker",
                      f"--replicas={num_workers}")

    def delete(self) -> None:
        self._kubectl("delete", "-f", "-", input_data=self.manifests_json())

    def master_address(self) -> str:
        return f"{self.cfg.id}-master:{self.cfg.master_port}"
