"""Cluster deployment tooling for GKE TPU pods.

Capability parity: reference scannerpy/kube.py (CloudConfig, MachineType,
ClusterConfig with price estimation, Cluster create/scale/delete managing
master + worker deployments, kube.py:38-779) — retargeted from GPU node
pools to TPU node pools, with the pieces a TPU deployment actually needs:

  * gcloud lifecycle COMMANDS are generated as pure argv lists
    (`cluster_create_commands` etc.) and only executed when gcloud is
    present — the reference shells out inline; generating first keeps
    every path unit-testable offline and lets operators audit/copy the
    exact commands.
  * workers are a StatefulSet behind a headless Service: multi-host TPU
    slices need stable pod identities so every host derives its
    jax.distributed rank from its pod ordinal and dials pod 0 as the
    coordinator (scanner_tpu/parallel/distributed.py).
  * the worker env wires SCANNER_TPU_LOG, the db path (gs:// selects the
    native GCS backend), and the coordinator address; a ConfigMap carries
    ~/.scanner_tpu.toml.
"""

from __future__ import annotations

import json
import shutil
import subprocess
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .common import ScannerException
from .config import dump_toml

# us-central1 on-demand ballpark $/hr (documented estimates, like the
# reference's price table); spot ~= 60% off
TPU_PRICES = {
    "v5litepod-1": 1.2,
    "v5litepod-4": 4.8,
    "v5litepod-8": 9.6,
    "v5p-8": 16.6,
}
SPOT_DISCOUNT = 0.4
CPU_PRICE_PER_CORE = 0.033

# GKE node-pool accelerator labels + machine types per slice family
TPU_ACCELERATOR_LABELS = {
    "v5litepod": "tpu-v5-lite-podslice",
    "v5p": "tpu-v5p-slice",
}
TPU_MACHINE_TYPES = {
    "v5litepod": "ct5lp-hightpu-{chips}t",
    "v5p": "ct5p-hightpu-{chips}t",
}
# chips per host for multi-host topology math (v5e: 4 chips/host)
TPU_CHIPS_PER_HOST = {"v5litepod": 4, "v5p": 4}
# physical slice topologies GKE requires for TPU node pools
TPU_TOPOLOGIES = {
    "v5litepod": {1: "1x1", 4: "2x2", 8: "2x4", 16: "4x4", 32: "4x8"},
    "v5p": {8: "2x2x1", 16: "2x2x2", 32: "2x4x2"},
}


def tpu_topology(tpu_type: str) -> str:
    family, chips = tpu_family(tpu_type), tpu_chips(tpu_type)
    try:
        return TPU_TOPOLOGIES[family][chips]
    except KeyError:
        raise ScannerException(
            f"no known GKE topology for {tpu_type}; add it to "
            f"TPU_TOPOLOGIES")


def tpu_chips(tpu_type: str) -> int:
    """Chip count from the slice name suffix ('v5litepod-4' -> 4)."""
    try:
        return int(tpu_type.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        raise ScannerException(f"cannot parse TPU type: {tpu_type}")


def tpu_family(tpu_type: str) -> str:
    family = tpu_type.rsplit("-", 1)[0]
    if family not in TPU_ACCELERATOR_LABELS:
        raise ScannerException(f"unknown TPU family: {family}")
    return family


def tpu_accelerator_label(tpu_type: str) -> str:
    return TPU_ACCELERATOR_LABELS[tpu_family(tpu_type)]


def tpu_chips_per_host(tpu_type: str) -> int:
    """Chips on one host of this slice type (the pod's google.com/tpu
    limit and the gcloud machine type must agree on this)."""
    return min(tpu_chips(tpu_type), TPU_CHIPS_PER_HOST[tpu_family(tpu_type)])


def tpu_hosts(tpu_type: str) -> int:
    """Hosts in one slice (multi-host slices get one engine worker per
    host, all joined into one jax.distributed runtime)."""
    family = tpu_family(tpu_type)
    per = TPU_CHIPS_PER_HOST[family]
    chips = tpu_chips(tpu_type)
    return max(1, chips // per)


@dataclass
class CloudConfig:
    project: str
    zone: str = "us-central1-a"
    storage_bucket: Optional[str] = None


@dataclass
class MachineType:
    """One worker node shape: a TPU slice + host CPU."""

    tpu_type: str = "v5litepod-4"
    cpus: int = 24
    memory_gb: int = 96
    spot: bool = False

    def price_per_hour(self) -> float:
        price = TPU_PRICES.get(self.tpu_type, 0.0) \
            + self.cpus * CPU_PRICE_PER_CORE
        return price * SPOT_DISCOUNT if self.spot else price

    def machine_type(self) -> str:
        return TPU_MACHINE_TYPES[tpu_family(self.tpu_type)].format(
            chips=tpu_chips_per_host(self.tpu_type))


@dataclass
class ClusterConfig:
    id: str
    num_workers: int
    master_cpus: int = 8
    worker: MachineType = field(default_factory=MachineType)
    image: str = "scanner-tpu:latest"
    db_path: str = "/data/db"      # or gs://bucket/db for the GCS backend
    master_port: int = 5000
    # None = workers resolve one device-affine pipeline instance per
    # local chip (engine/evaluate.py default_pipeline_instances); an
    # explicit int — including 1 — is used as given
    pipeline_instances: Optional[int] = None
    log_level: str = "info"
    autoscale: bool = False
    max_workers: Optional[int] = None
    # 0 = no /metrics|/healthz|/statusz endpoint (the default); non-zero
    # serves it on that port on master AND workers and exposes the
    # container port for Prometheus scraping (docs/observability.md)
    metrics_port: int = 0
    # persistent XLA compilation-cache directory for workers ("" =
    # disabled).  Point it at pod-local scratch or a gs:// prefix shared
    # by the fleet: a restarted/rescheduled worker then re-loads its
    # jitted kernel executables instead of re-paying seconds of TPU
    # compile per bucket shape (PERF.md §5).  Wired into the ConfigMap
    # toml ([perf] section) and each worker's
    # SCANNER_TPU_COMPILATION_CACHE env var.
    compilation_cache_dir: str = ""
    # seconds kubernetes waits between SIGTERM and SIGKILL on worker
    # pods.  start_worker maps SIGTERM to drain mode (finish in-flight
    # tasks, stop pulling, deregister — engine/service.py
    # Worker.drain), so size this to cover the longest task plus its
    # save; a too-small value turns every rolling update into a crash
    # the stale scan must clean up.
    termination_grace_period: int = 120
    # user alert rules appended to the built-in health/SLO ruleset
    # (docs/observability.md §Health & SLOs clause grammar); wired into
    # the ConfigMap's [alerts] section so every pod's engine evaluates
    # them.  "" = defaults only.
    alert_rules: str = ""
    # alert->action remediation (engine/controller.py), wired into the
    # ConfigMap's [remediation] section for every pod.  False =
    # signal-only (alerts fire, nothing actuates); dry_run keeps the
    # decision pipeline + audit live without invoking actions.  The
    # autoscaler bounds feed Master(autoscale=True); the production
    # actuator is Cluster.scale (scale-down drains pods via SIGTERM ->
    # Worker.drain, so in-flight tasks are never killed).
    remediation: bool = True
    remediation_dry_run: bool = False
    autoscale_min: int = 1
    autoscale_max: int = 8
    # gang-scheduled multi-host execution (engine/gang.py, docs/
    # robustness.md §Gang scheduling).  Workers advertise a gang
    # coordinator port from their pod DNS name automatically (any pod
    # port is reachable inside the cluster network — no containerPort
    # row needed); these knobs wire the [gang] ConfigMap section +
    # each worker's rendezvous bound.  Disable for fleets that never
    # run gang bulks to skip the per-worker port reservation.
    gang: bool = True
    gang_init_timeout_s: int = 60
    gang_form_timeout_s: int = 5
    # mesh-partitioned gang evaluation (members compute only their row
    # shard; ~N× per-gang throughput) and the stencil halo exchange
    # that rides on it — the fleet-wide [gang] sharded/halo_exchange
    # defaults; gang_sharded=False pins a fleet to the replicated
    # N×-redundant evaluation (the A/B + fallback mode)
    gang_sharded: bool = True
    gang_halo_exchange: bool = True

    def price_per_hour(self) -> float:
        return (self.master_cpus * CPU_PRICE_PER_CORE
                + self.num_workers * self.worker.price_per_hour())


# ---------------------------------------------------------------------------
# gcloud lifecycle commands (pure; execution is optional)
# ---------------------------------------------------------------------------

def cluster_create_commands(cloud: CloudConfig,
                            cfg: ClusterConfig) -> List[List[str]]:
    """argv lists that bring up the GKE cluster + TPU node pool
    (reference kube.py get_or_create_cluster; gcloud only runs when the
    operator executes these)."""
    base = ["gcloud", "container", "--project", cloud.project]
    hosts = tpu_hosts(cfg.worker.tpu_type)
    cmds = [
        base + ["clusters", "create", cfg.id,
                "--zone", cloud.zone,
                "--num-nodes", "1",
                "--machine-type", f"n2-standard-{cfg.master_cpus}"],
    ]

    def pool_cmd(name: str, nodes: int) -> List[str]:
        c = base + ["node-pools", "create", name,
                    "--cluster", cfg.id,
                    "--zone", cloud.zone,
                    "--machine-type", cfg.worker.machine_type(),
                    "--tpu-topology", tpu_topology(cfg.worker.tpu_type),
                    "--num-nodes", str(nodes)]
        if cfg.worker.spot:
            c.append("--spot")
        return c

    if hosts <= 1:
        pool = pool_cmd(f"{cfg.id}-tpu", cfg.num_workers)
        if cfg.autoscale:
            max_slices = cfg.max_workers or cfg.num_workers * 2
            pool += ["--enable-autoscaling", "--min-nodes", "0",
                     "--max-nodes", str(max_slices)]
        cmds.append(pool)
    else:
        # one node pool PER SLICE: a multi-host coordinator group must be
        # slice-coherent, and only a dedicated pool (selected via
        # cloud.google.com/gke-nodepool) guarantees its pods land on one
        # physical slice.  With autoscale, idle slices park at 0 nodes.
        n_pools = (cfg.max_workers or cfg.num_workers * 2) \
            if cfg.autoscale else cfg.num_workers
        for i in range(n_pools):
            # surplus autoscale pools (no StatefulSet yet) start empty:
            # the autoscaler fills a slice pool only when its pods arrive
            nodes = hosts if i < cfg.num_workers else 0
            pool = pool_cmd(f"{cfg.id}-tpu-{i}", nodes)
            if cfg.autoscale:
                pool += ["--enable-autoscaling", "--min-nodes", "0",
                         "--max-nodes", str(hosts)]
            cmds.append(pool)
    return cmds


def cluster_delete_commands(cloud: CloudConfig,
                            cfg: ClusterConfig) -> List[List[str]]:
    return [["gcloud", "container", "--project", cloud.project,
             "clusters", "delete", cfg.id, "--zone", cloud.zone,
             "--quiet"]]


def cluster_resize_commands(cloud: CloudConfig, cfg: ClusterConfig,
                            num_workers: int) -> List[List[str]]:
    """Scale worker capacity from cfg.num_workers to num_workers.
    Single-host: resize the shared pool.  Multi-host: slices scale by
    creating/deleting whole per-slice pools."""
    hosts = tpu_hosts(cfg.worker.tpu_type)
    base = ["gcloud", "container", "--project", cloud.project]
    if cfg.autoscale:
        # autoscaling pools follow their pods: scaling is kubectl-only
        # (per-slice pools were pre-created 0..hosts at cluster create,
        # and re-creating them here would fail with already-exists)
        return []
    if hosts <= 1:
        return [base + ["clusters", "resize", cfg.id,
                        "--node-pool", f"{cfg.id}-tpu",
                        "--num-nodes", str(num_workers),
                        "--zone", cloud.zone, "--quiet"]]
    cur = cfg.num_workers
    cmds = []
    for i in range(cur, num_workers):       # grow: add slice pools
        c = base + ["node-pools", "create", f"{cfg.id}-tpu-{i}",
                    "--cluster", cfg.id,
                    "--zone", cloud.zone,
                    "--machine-type", cfg.worker.machine_type(),
                    "--tpu-topology", tpu_topology(cfg.worker.tpu_type),
                    "--num-nodes", str(hosts)]
        if cfg.worker.spot:
            c.append("--spot")
        cmds.append(c)
    for i in range(num_workers, cur):       # shrink: drop slice pools
        cmds.append(base + ["node-pools", "delete", f"{cfg.id}-tpu-{i}",
                            "--cluster", cfg.id,
                            "--zone", cloud.zone, "--quiet"])
    return cmds


# ---------------------------------------------------------------------------
# kubernetes manifests (pure)
# ---------------------------------------------------------------------------

def config_manifest(cfg: ClusterConfig) -> Dict:
    """ConfigMap carrying ~/.scanner_tpu.toml for every pod."""
    sections = {
        "storage": {"type": "gcs" if cfg.db_path.startswith("gs://")
                    else "posix",
                    "db_path": cfg.db_path},
        "network": {"master": f"{cfg.id}-master",
                    "master_port": cfg.master_port,
                    "worker_port": 5001,
                    "metrics_port": cfg.metrics_port},
    }
    if cfg.compilation_cache_dir:
        sections["perf"] = {
            "compilation_cache_dir": cfg.compilation_cache_dir}
    if cfg.alert_rules:
        sections["alerts"] = {"rules": cfg.alert_rules}
    sections["remediation"] = {
        "enabled": cfg.remediation,
        "dry_run": cfg.remediation_dry_run,
        "autoscale_min": cfg.autoscale_min,
        "autoscale_max": cfg.autoscale_max,
    }
    sections["gang"] = {
        "enabled": cfg.gang,
        "init_timeout_s": cfg.gang_init_timeout_s,
        "form_timeout_s": cfg.gang_form_timeout_s,
        "sharded": cfg.gang_sharded,
        "halo_exchange": cfg.gang_halo_exchange,
    }
    toml = dump_toml(sections)
    return {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": f"{cfg.id}-config"},
        "data": {"scanner_tpu.toml": toml},
    }


def _metrics_arg(cfg: ClusterConfig) -> str:
    return f", metrics_port={cfg.metrics_port}" if cfg.metrics_port else ""


def _probes(cfg: ClusterConfig) -> Dict:
    """Container liveness/readiness probes against the metrics
    endpoint's health routes (util/metrics.py MetricsServer).
    Liveness -> /healthz, which answers 200 whenever the process can
    answer at all: alert states (HBM pressure, latency burn) are
    workload facts a restart cannot fix, so the probe only fails when
    the process is dead or wedged.  Readiness -> /readyz, which goes
    503 while the health roll-up is `unhealthy` OR a SIGTERM drain is
    in progress — k8s stops routing to the pod while its in-flight
    tasks finish instead of killing it.  Only emitted when the
    endpoint exists (metrics_port set)."""
    if not cfg.metrics_port:
        return {}
    return {
        "livenessProbe": {
            "httpGet": {"path": "/healthz", "port": cfg.metrics_port},
            "periodSeconds": 10, "failureThreshold": 6},
        "readinessProbe": {
            "httpGet": {"path": "/readyz", "port": cfg.metrics_port},
            "periodSeconds": 5, "failureThreshold": 2},
    }


def master_manifest(cfg: ClusterConfig) -> Dict:
    ports = [{"containerPort": cfg.master_port}]
    if cfg.metrics_port:
        ports.append({"containerPort": cfg.metrics_port,
                      "name": "metrics"})
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": f"{cfg.id}-master"},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": f"{cfg.id}-master"}},
            "template": {
                "metadata": {"labels": {"app": f"{cfg.id}-master"}},
                "spec": {"containers": [{
                    "name": "master", "image": cfg.image,
                    "command": ["python", "-c",
                                ("from scanner_tpu.engine.service import "
                                 "start_master; start_master("
                                 f"'{cfg.db_path}', port={cfg.master_port}"
                                 f"{_metrics_arg(cfg)},"
                                 " block=True)")],
                    "env": [{"name": "SCANNER_TPU_LOG",
                             "value": cfg.log_level}],
                    "ports": ports,
                    **_probes(cfg),
                    "resources": {"requests": {"cpu": str(cfg.master_cpus)}},
                }]},
            },
        },
    }


def _worker_command(cfg: ClusterConfig, hosts: int,
                    slice_idx: int = 0) -> List[str]:
    """Worker entry: single-host slices start a plain worker; multi-host
    slices derive the in-slice rank directly from the pod ordinal (each
    slice is its own StatefulSet) and join pod 0's jax.distributed
    coordinator before serving."""
    # each pod advertises its stable headless-service DNS name so the
    # master's GetMetrics aggregation can dial it cross-host (a bare
    # localhost registration would silently drop every worker from the
    # cluster metrics view)
    adv = (f"advertise_host=os.environ['POD_NAME'] + "
           f"'.{cfg.id}-workers', ")
    if hosts <= 1:
        return ["python", "-c",
                ("import os; "
                 "from scanner_tpu.engine.service import start_worker; "
                 f"start_worker('{cfg.id}-master:{cfg.master_port}', "
                 f"'{cfg.db_path}', "
                 f"pipeline_instances={cfg.pipeline_instances}"
                 f"{_metrics_arg(cfg)}, {adv}"
                 "block=True)")]
    sts = f"{cfg.id}-worker-s{slice_idx}"
    return ["python", "-c", (
        "import os; "
        "from scanner_tpu.engine.service import start_worker; "
        "from scanner_tpu.parallel.distributed import CoordinatorConfig; "
        "pid = int(os.environ['POD_NAME'].rsplit('-', 1)[1]); "
        f"coord = CoordinatorConfig("
        f"address=\"{sts}-0.{cfg.id}-workers:8476\", "
        f"num_processes={hosts}, process_id=pid); "
        f"start_worker('{cfg.id}-master:{cfg.master_port}', "
        f"'{cfg.db_path}', "
        f"pipeline_instances={cfg.pipeline_instances}"
        f"{_metrics_arg(cfg)}, {adv}"
        "coordinator=coord, block=True)")]


def _worker_statefulset(cfg: ClusterConfig, name: str, replicas: int,
                        command: List[str],
                        extra_selector: Optional[Dict] = None) -> Dict:
    per_host_chips = tpu_chips_per_host(cfg.worker.tpu_type)
    node_selector = {
        "cloud.google.com/gke-tpu-accelerator":
            tpu_accelerator_label(cfg.worker.tpu_type),
        # GKE TPU pods must state the physical slice topology they expect
        "cloud.google.com/gke-tpu-topology":
            tpu_topology(cfg.worker.tpu_type),
    }
    node_selector.update(extra_selector or {})
    return {
        "apiVersion": "apps/v1", "kind": "StatefulSet",
        "metadata": {"name": name},
        "spec": {
            "serviceName": f"{cfg.id}-workers",
            "replicas": replicas,
            "podManagementPolicy": "Parallel",
            "selector": {"matchLabels": {"app": f"{cfg.id}-worker",
                                         "sts": name}},
            "template": {
                "metadata": {"labels": {"app": f"{cfg.id}-worker",
                                        "sts": name}},
                "spec": {
                    "nodeSelector": node_selector,
                    # SIGTERM -> Worker.drain; give in-flight tasks this
                    # long to finish before the SIGKILL follow-up
                    "terminationGracePeriodSeconds":
                        cfg.termination_grace_period,
                    "containers": [{
                        "name": "worker", "image": cfg.image,
                        "command": command,
                        **({"ports": [{"containerPort": cfg.metrics_port,
                                       "name": "metrics"}]}
                           if cfg.metrics_port else {}),
                        **_probes(cfg),
                        "env": [
                            {"name": "SCANNER_TPU_LOG",
                             "value": cfg.log_level},
                            {"name": "POD_NAME",
                             "valueFrom": {"fieldRef": {
                                 "fieldPath": "metadata.name"}}},
                            # worker-side persistent XLA executable cache
                            # (Worker.__init__ picks the env var up)
                            *([{"name": "SCANNER_TPU_COMPILATION_CACHE",
                                "value": cfg.compilation_cache_dir}]
                              if cfg.compilation_cache_dir else []),
                            # gang member runners rendezvous with this
                            # bound (engine/gang.py); 0 also strips the
                            # gang port reservation from the worker
                            *([{"name": "SCANNER_TPU_GANG_INIT_TIMEOUT",
                                "value": str(cfg.gang_init_timeout_s)}]
                              if cfg.gang else
                              [{"name": "SCANNER_TPU_GANG",
                                "value": "0"}]),
                        ],
                        "resources": {
                            "requests": {"cpu": str(cfg.worker.cpus)},
                            "limits": {"google.com/tpu":
                                       str(per_host_chips)},
                        },
                        "volumeMounts": [{
                            "name": "config",
                            "mountPath": "/root/.scanner_tpu.toml",
                            "subPath": "scanner_tpu.toml"}],
                    }],
                    "volumes": [{"name": "config",
                                 "configMap": {
                                     "name": f"{cfg.id}-config"}}],
                },
            },
        },
    }


def worker_manifests(cfg: ClusterConfig) -> List[Dict]:
    """Worker StatefulSets behind one headless Service.

    Single-host slices: one StatefulSet, one pod per slice.  Multi-host
    slices: one StatefulSet PER SLICE, pinned to that slice's dedicated
    node pool (cloud.google.com/gke-nodepool) — nothing else guarantees a
    jax.distributed coordinator group lands on one physical slice, and a
    group split across slices hangs at initialize()."""
    hosts = tpu_hosts(cfg.worker.tpu_type)
    if hosts <= 1:
        return [_worker_statefulset(cfg, f"{cfg.id}-worker",
                                    cfg.num_workers,
                                    _worker_command(cfg, hosts))]
    return [
        _worker_statefulset(
            cfg, f"{cfg.id}-worker-s{i}", hosts,
            _worker_command(cfg, hosts, slice_idx=i),
            extra_selector={
                "cloud.google.com/gke-nodepool": f"{cfg.id}-tpu-{i}"})
        for i in range(cfg.num_workers)
    ]


def worker_manifest(cfg: ClusterConfig) -> Dict:
    """Back-compat single-manifest accessor (single-host configs)."""
    ms = worker_manifests(cfg)
    if len(ms) != 1:
        raise ScannerException(
            "multi-host configs produce one StatefulSet per slice; use "
            "worker_manifests()")
    return ms[0]


def service_manifest(cfg: ClusterConfig) -> Dict:
    return {
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": f"{cfg.id}-master"},
        "spec": {
            "selector": {"app": f"{cfg.id}-master"},
            "ports": [{"port": cfg.master_port,
                       "targetPort": cfg.master_port}],
        },
    }


def workers_service_manifest(cfg: ClusterConfig) -> Dict:
    """Headless service giving StatefulSet pods stable DNS names
    (<pod>.<cfg.id>-workers) — the coordinator address for multi-host."""
    return {
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": f"{cfg.id}-workers"},
        "spec": {
            "clusterIP": "None",
            "selector": {"app": f"{cfg.id}-worker"},
            "ports": [{"port": 8476, "name": "coordinator"}],
        },
    }


class Cluster:
    """Lifecycle wrapper (reference kube.py Cluster): create/scale/delete
    via gcloud/kubectl; manifests() and *_commands() work without
    either installed."""

    def __init__(self, cloud: CloudConfig, cfg: ClusterConfig):
        self.cloud = cloud
        self.cfg = cfg

    # -- pure outputs ---------------------------------------------------

    def manifests(self) -> List[Dict]:
        return [config_manifest(self.cfg), master_manifest(self.cfg),
                service_manifest(self.cfg),
                workers_service_manifest(self.cfg),
                *worker_manifests(self.cfg)]

    def manifests_json(self) -> str:
        return "\n---\n".join(json.dumps(m, indent=2)
                              for m in self.manifests())

    def create_commands(self) -> List[List[str]]:
        return cluster_create_commands(self.cloud, self.cfg)

    def delete_commands(self) -> List[List[str]]:
        return cluster_delete_commands(self.cloud, self.cfg)

    # -- execution (requires gcloud/kubectl on PATH) --------------------

    def _run(self, argv: List[str],
             input_data: Optional[str] = None):
        if shutil.which(argv[0]) is None:
            raise ScannerException(
                f"{argv[0]} not available; use manifests_json() / "
                f"*_commands() and run manually")
        return subprocess.run(argv, input=input_data, text=True,
                              check=True, capture_output=True)

    def create_cluster(self) -> None:
        for cmd in self.create_commands():
            self._run(cmd)

    def delete_cluster(self) -> None:
        for cmd in self.delete_commands():
            self._run(cmd)

    def create(self) -> None:
        self._run(["kubectl", "apply", "-f", "-"],
                  input_data=self.manifests_json())

    def scale(self, num_workers: int) -> None:
        if shutil.which("kubectl") is None:
            raise ScannerException(
                "kubectl not available; use manifests_json() / "
                "*_commands() and run manually")
        hosts = tpu_hosts(self.cfg.worker.tpu_type)
        # pool changes are derived from old-vs-new worker counts, so
        # compute them BEFORE mutating cfg
        resize = cluster_resize_commands(self.cloud, self.cfg, num_workers)
        old = self.cfg.num_workers
        if hosts <= 1:
            self._run(["kubectl", "scale",
                       f"statefulset/{self.cfg.id}-worker",
                       f"--replicas={num_workers}"])
        else:
            # slice-granular: apply manifests for the new slice set, drop
            # StatefulSets of removed slices
            self.cfg.num_workers = num_workers
            self._run(["kubectl", "apply", "-f", "-"],
                      input_data=self.manifests_json())
            for i in range(num_workers, old):
                self._run(["kubectl", "delete", "statefulset",
                           f"{self.cfg.id}-worker-s{i}", "--ignore-not-found"])
        self.cfg.num_workers = num_workers
        if not resize:
            return  # autoscaling pools follow their pods
        if shutil.which("gcloud") is None:
            # the operator applies the pool changes with the printed
            # commands
            print("deploy: gcloud not available; run manually:")
            for cmd in resize:
                print(" ", " ".join(cmd))
            return
        for cmd in resize:
            self._run(cmd)

    def delete(self) -> None:
        self._run(["kubectl", "delete", "-f", "-"],
                  input_data=self.manifests_json())

    def master_address(self) -> str:
        return f"{self.cfg.id}-master:{self.cfg.master_port}"

    def scale_actuator(self):
        """The autoscaler-facing replica setter
        (``Master(autoscale=True, scale_actuator=cluster.scale_actuator())``):
        just ``Cluster.scale`` — kubernetes removes surplus pods via
        SIGTERM, which ``start_worker`` maps to ``Worker.drain``, so an
        autoscale-down never kills in-flight tasks."""
        return self.scale
