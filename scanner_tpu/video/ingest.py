"""Video ingest into the database, mp4 export, and synthetic test clips.

Capability parity: reference ingest path (ingest.cpp:867 ingest_videos,
parse_and_write_video:175, parse_video_inplace:382) and storage.py save_mp4.

An ingested video becomes a committed table with columns
['index', 'frame']: 'index' stores the row number (8-byte LE) and 'frame'
is a VIDEO column whose single item is the demuxed packet stream, described
by a VideoDescriptor side file.
"""

from __future__ import annotations

import os
import struct
import tempfile
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..common import ScannerException
from ..storage import items
from ..storage import metadata as md
from ..storage.backend import PosixStorage
from ..storage.database import Database
from . import lib
from .automata import DecoderAutomata


def ingest_videos(
        db: Database, named_paths: Sequence[Tuple[str, str]],
        inplace: bool = False, force: bool = False,
) -> Tuple[List[md.TableDescriptor], List[Tuple[str, str]]]:
    """Ingest videos as named tables; returns (descriptors, failures).

    One corrupt file must not abort a corpus ingest: per-video failures
    are collected as (path, reason) and returned alongside the tables
    that did ingest (reference ingest.cpp:872-978 failed_videos and
    client.py:965 ingest_videos -> (tables, failures)).  A failed video
    leaves no table behind.  inplace=True indexes the original file
    without copying packet data (reference ingest.cpp:382); force=True
    deletes an existing table of the same name first.
    """
    if not named_paths:
        raise ScannerException("must ingest at least one video")
    # a name collision (with an existing table, or within the list) is a
    # caller error, not a per-video decode failure: raise up front like
    # the reference (client.py:1005), before any work or deletion
    names = [name for name, _ in named_paths]
    if len(set(names)) != len(names):
        dup = sorted({n for n in names if names.count(n) > 1})
        raise ScannerException(f"duplicate table names in ingest: {dup}")
    if not force:
        for name in names:
            if db.has_table(name):
                raise ScannerException(f"table already exists: {name}")
    out: List[md.TableDescriptor] = []
    failures: List[Tuple[str, str]] = []
    for name, path in named_paths:
        # with force=, delete a colliding table only immediately before
        # its own ingest attempt — never up front for the whole list, so
        # an abort partway cannot leave later tables deleted-but-never-
        # re-ingested.  (A failed forced re-ingest still loses the old
        # table: create-then-rename would be needed to avoid that.)
        if force and db.has_table(name):
            db.delete_table(name)
        try:
            out.append(_ingest_one(db, name, path, inplace))
        except ScannerException as e:
            failures.append((path, str(e)))
    return out, failures


def _ingest_one(db: Database, name: str, path: str,
                inplace: bool) -> md.TableDescriptor:
    if db.has_table(name):
        raise ScannerException(f"table already exists: {name}")
    cols = [md.ColumnDescriptor("index", md.ColumnType.BYTES),
            md.ColumnDescriptor("frame", md.ColumnType.VIDEO)]
    if inplace:
        vd = lib.ingest_file(path, None)
        desc = db.create_table(name, cols, end_rows=[vd.num_frames])
    else:
        desc = None
        tmp_path = None
        try:
            if isinstance(db.backend, PosixStorage):
                # write the packet stream straight into storage
                desc = db.create_table(name, cols, end_rows=[0])
                item_rel = md.column_item_path(desc.id, "frame", 0)
                target = db.backend.local_path(item_rel)
                os.makedirs(os.path.dirname(target), exist_ok=True)
                vd = lib.ingest_file(path, target)
            else:
                fd, tmp_path = tempfile.mkstemp(suffix=".pkts")
                os.close(fd)
                vd = lib.ingest_file(path, tmp_path)
                desc = db.create_table(name, cols, end_rows=[0])
                with open(tmp_path, "rb") as f:
                    db.backend.write(md.column_item_path(desc.id, "frame", 0),
                                     f.read())
        except Exception:
            # don't leave an orphaned uncommitted table squatting the name
            if desc is not None:
                db.delete_table(name)
            raise
        finally:
            if tmp_path:
                os.unlink(tmp_path)
        desc.end_rows = [vd.num_frames]
        db.write_table_descriptor(desc)
    db.backend.write(md.video_meta_path(desc.id, "frame", 0), vd.serialize())
    # index column: row number, one item
    idx_rows = [struct.pack("<q", i) for i in range(vd.num_frames)]
    items.write_item(db.backend, md.column_item_path(desc.id, "index", 0),
                     idx_rows)
    db.commit_table(desc.id)
    return db.table_descriptor(desc.id)


def ingest_images(db: Database, name: str, paths: Sequence[str]
                  ) -> md.TableDescriptor:
    """Ingest still images as a frame table (reference ingest.cpp image
    ingest).  Images stay in their encoded form (codec 'image'); readers
    and the engine decode to RGB numpy on demand via PIL."""
    if db.has_table(name):
        raise ScannerException(f"table already exists: {name}")
    cols = [md.ColumnDescriptor("index", md.ColumnType.BYTES),
            md.ColumnDescriptor("frame", md.ColumnType.BYTES,
                                codec="image")]
    blobs = []
    for p in paths:
        with open(p, "rb") as f:
            blobs.append(f.read())
    desc = db.create_table(name, cols, end_rows=[len(paths)])
    try:
        items.write_item(db.backend,
                         md.column_item_path(desc.id, "frame", 0), blobs)
        items.write_item(db.backend,
                         md.column_item_path(desc.id, "index", 0),
                         [struct.pack("<q", i) for i in range(len(paths))])
    except Exception:
        # don't leave an orphaned uncommitted table squatting the name
        db.delete_table(name)
        raise
    db.commit_table(desc.id)
    return desc


def decode_image(blob: bytes) -> np.ndarray:
    import io

    from PIL import Image
    return np.asarray(Image.open(io.BytesIO(blob)).convert("RGB"))


def load_video_meta(db: Database, table, column: str = "frame",
                    item: int = 0) -> md.VideoDescriptor:
    desc = db.table_descriptor(table)
    return md.VideoDescriptor.deserialize(
        db.backend.read(md.video_meta_path(desc.id, column, item)))


def open_automata(db: Database, table, column: str = "frame",
                  n_threads: int = 1) -> DecoderAutomata:
    desc = db.table_descriptor(table)
    vd = load_video_meta(db, table, column)
    return DecoderAutomata(db.backend, vd,
                           md.column_item_path(desc.id, column, 0),
                           n_threads=n_threads)


def load_frames(db: Database, table, rows: Sequence[int],
                column: str = "frame") -> np.ndarray:
    """Client-side exact frame read across item boundaries (reference
    storage.py NamedVideoStream.load / as_hwang).  Rows are global display
    indices; job-output tables store one independently-decodable video item
    per task."""
    desc = db.table_descriptor(table)
    rows_l = [int(r) for r in rows]
    if not rows_l:
        vd0 = load_video_meta(db, table, column, 0)
        return np.zeros((0, vd0.height, vd0.width, 3), np.uint8)
    by_item: dict = {}
    for r in rows_l:
        item = desc.item_of_row(r)
        start, _ = desc.item_bounds(item)
        by_item.setdefault(item, []).append(r - start)
    frames: dict = {}
    for item, local in by_item.items():
        start, _ = desc.item_bounds(item)
        vd = md.VideoDescriptor.deserialize(
            db.backend.read(md.video_meta_path(desc.id, column, item)))
        auto = DecoderAutomata(db.backend, vd,
                               md.column_item_path(desc.id, column, item))
        try:
            got = auto.get_frames(local)
        finally:
            auto.close()
        for lr, f in zip(local, got):
            frames[start + lr] = f
    return np.stack([frames[r] for r in rows_l])


def iter_frames(db: Database, table, rows: Sequence[int],
                column: str = "frame", chunk: int = 64):
    """Yield decoded frames in request order, keeping one DecoderAutomata
    per item alive across chunks (streaming flavor of load_frames)."""
    desc = db.table_descriptor(table)
    rows_l = [int(r) for r in rows]
    autos: dict = {}
    try:
        for i in range(0, len(rows_l), chunk):
            part = rows_l[i:i + chunk]
            by_item: dict = {}
            for r in part:
                it = desc.item_of_row(r)
                start, _ = desc.item_bounds(it)
                by_item.setdefault(it, []).append(r - start)
            frames: dict = {}
            for it, local in by_item.items():
                start, _ = desc.item_bounds(it)
                if it not in autos:
                    vd = md.VideoDescriptor.deserialize(db.backend.read(
                        md.video_meta_path(desc.id, column, it)))
                    autos[it] = DecoderAutomata(
                        db.backend, vd,
                        md.column_item_path(desc.id, column, it))
                got = autos[it].get_frames(local)
                for lr, f in zip(local, got):
                    frames[start + lr] = f
            for r in part:
                yield frames[r]
    finally:
        for a in autos.values():
            a.close()


def export_mp4(db: Database, table, out_path: str,
               column: str = "frame") -> None:
    """Remux a stored video column to an .mp4 without re-encoding
    (reference storage.py:365 save_mp4)."""
    desc = db.table_descriptor(table)
    data_parts = []
    sizes_l, keys_l, pts_l, dts_l = [], [], [], []
    vd0: Optional[md.VideoDescriptor] = None
    pts_base = 0
    for item in range(len(desc.end_rows)):
        vd = md.VideoDescriptor.deserialize(
            db.backend.read(md.video_meta_path(desc.id, column, item)))
        if vd0 is None:
            vd0 = vd
        elif (vd.tb_num, vd.tb_den) != (vd0.tb_num, vd0.tb_den):
            raise ScannerException(
                "export_mp4: items have differing time bases")
        if vd.data_path:
            with open(vd.data_path, "rb") as f:
                raw = f.read()
            for o, s in zip(vd.sample_offsets, vd.sample_sizes):
                data_parts.append(raw[int(o):int(o) + int(s)])
        else:
            data_parts.append(db.backend.read(
                md.column_item_path(desc.id, column, item)))
        sizes_l.append(np.asarray(vd.sample_sizes, np.uint64))
        kf = np.zeros(vd.num_frames, np.uint8)
        kf[np.asarray(vd.keyframe_indices, np.int64)] = 1
        keys_l.append(kf)
        # shift each item's timestamps so concatenated items play back to
        # back (multi-item tables are always this library's own encodes,
        # which stamp frame-number pts starting at 0)
        pts = np.asarray(vd.sample_pts, np.int64)
        dts = np.asarray(vd.sample_dts, np.int64)
        shift = pts_base - int(pts.min())
        pts_l.append(pts + shift)
        dts_l.append(dts + shift)
        pts_base = int(pts_l[-1].max()) + _pts_step(vd)
    assert vd0 is not None
    lib.write_mp4(out_path, vd0.width, vd0.height, vd0.fps or 30.0,
                  vd0.codec, vd0.extradata, b"".join(data_parts),
                  np.concatenate(sizes_l), np.concatenate(keys_l),
                  np.concatenate(pts_l), np.concatenate(dts_l),
                  tb=(vd0.tb_num, vd0.tb_den))


def _pts_step(vd: md.VideoDescriptor) -> int:
    """Typical pts increment between consecutive display frames."""
    pts = np.sort(np.asarray(vd.sample_pts, np.int64))
    if len(pts) < 2:
        return 1
    diffs = np.diff(pts)
    diffs = diffs[diffs > 0]
    return int(np.median(diffs)) if len(diffs) else 1


# ---------------------------------------------------------------------------
# Synthetic clips for tests/benchmarks (replaces the reference's downloaded
# GCS fixtures, py_test.py:81 — this environment has no network egress)
# ---------------------------------------------------------------------------

def frame_pattern(i: int, height: int, width: int) -> np.ndarray:
    """Deterministic per-frame pattern: R channel encodes i%14 with 16-unit
    spacing, wide enough to survive lossy H.264 quantization."""
    f = np.zeros((height, width, 3), np.uint8)
    f[:, :, 0] = (i * 16) % 224
    f[:, :, 1] = np.linspace(0, 239, width, dtype=np.uint8)[None, :]
    sq = max(4, height // 8)
    x = (i * 5) % max(1, width - sq)
    f[:sq, x:x + sq, 2] = 230
    return f


def frame_pattern_id(frame: np.ndarray) -> int:
    """Recover i%14 from a decoded pattern frame (R is ~(i*16)%224)."""
    r = float(frame[..., 0].mean())
    return int(round(r / 16.0)) % 14


def encode_frames_mp4(path: str, frames, width: int, height: int,
                      fps: float = 24.0, keyint: int = 12,
                      crf: int = 18, bframes: int = 0,
                      open_gop: bool = False,
                      frame_pts=None, codec: str = "libx264") -> None:
    """Encode an iterable of (H, W, 3) uint8 frames to an .mp4.

    bframes>0 produces a reordered (pts!=dts) stream like real-world
    encodes; open_gop=True additionally uses non-IDR recovery-point
    keyframes (leading B frames reference across GOP boundaries);
    frame_pts (iterable of int, 1/fps ticks, strictly increasing)
    produces a variable-frame-rate stream — the three fixture knobs for
    real-world-stream decode tests.  `codec` is any libavcodec encoder
    name (libx264 default; libx265/mpeg4/... produce fixtures for the
    codec-agnostic ingest/decode path — the container records the
    encoder's own descriptor, so unmapped names cannot mislabel the
    stream).  crf and open_gop are honored for libx264 and libx265;
    other encoders use their libavcodec defaults."""
    enc = lib.Encoder(width, height, fps=fps, keyint=keyint, crf=crf,
                      bframes=bframes, open_gop=open_gop, codec=codec)
    if frame_pts is None:
        for frame in frames:
            enc.feed(frame)
    else:
        for frame, p in zip(frames, frame_pts, strict=True):
            enc.feed(frame, pts=np.asarray([p], np.int64))
    enc.flush()
    data, sizes, keys, pts, dts = enc.take_packets()
    lib.write_mp4(path, width, height, fps, enc.descriptor, enc.extradata,
                  data, sizes, keys, pts, dts)
    enc.close()


def synthesize_video(path: str, num_frames: int = 90, width: int = 128,
                     height: int = 96, fps: float = 24.0,
                     keyint: int = 12, bframes: int = 0,
                     open_gop: bool = False, frame_pts=None) -> None:
    """Encode a deterministic test clip to an .mp4 with libx264."""
    encode_frames_mp4(
        path, (frame_pattern(i, height, width) for i in range(num_frames)),
        width, height, fps=fps, keyint=keyint, bframes=bframes,
        open_gop=open_gop, frame_pts=frame_pts)
