"""Exact-frame decode planning and execution.

Capability parity: reference DecoderAutomata (decoder_automata.h:28-88,
decoder_automata.cpp:72-238) — turn "give me display frames {i...}" into
minimal keyframe-aligned packet feeds, decode them, and deliver exactly the
requested frames.

Instead of the reference's two-thread feeder/retriever state machine, the
whole run executes inside one C call (scvid_decode_run) with a wanted-frame
mask; parallelism comes from running many automata on separate Python threads
(the C side releases the GIL).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..common import ScannerException
from ..storage.backend import StorageBackend
from ..storage.metadata import VideoDescriptor
from .lib import Decoder


@dataclass
class DecodeRun:
    """One keyframe-aligned packet feed."""
    start_dec: int       # first packet (decode order), always a keyframe
    end_dec: int         # last packet fed, inclusive
    out_disp: np.ndarray  # display indices delivered, ascending


class VideoIndex:
    """Derived lookup structures over a VideoDescriptor's sample index."""

    def __init__(self, vd: VideoDescriptor):
        self.vd = vd
        n = vd.num_frames
        pts = np.asarray(vd.sample_pts)
        # decode indices sorted by presentation time = display order
        self.dec_of_disp = np.argsort(pts, kind="stable").astype(np.int64)
        self.disp_of_dec = np.empty(n, np.int64)
        self.disp_of_dec[self.dec_of_disp] = np.arange(n)
        # feeding packets [0..M[d]] guarantees display frames [0..d] emitted
        self.max_dec_through_disp = np.maximum.accumulate(self.dec_of_disp)
        self.kf_decs = np.asarray(vd.keyframe_indices)
        self.kf_disps = self.disp_of_dec[self.kf_decs]
        if not np.all(np.diff(self.kf_disps) > 0):
            # sort keyframes by display position (defensive; decode order
            # keyframes are display-ordered for closed-GOP streams)
            order = np.argsort(self.kf_disps)
            self.kf_decs = self.kf_decs[order]
            self.kf_disps = self.kf_disps[order]

    def governing_keyframe(self, disp: int) -> Tuple[int, int]:
        """(keyframe decode idx, keyframe display idx) for a display frame."""
        i = int(np.searchsorted(self.kf_disps, disp, side="right")) - 1
        if i < 0:
            raise ScannerException(f"no keyframe before display frame {disp}")
        return int(self.kf_decs[i]), int(self.kf_disps[i])

    def plan(self, wanted_disp: Sequence[int],
             decode_through: int = 16) -> List[DecodeRun]:
        """Build minimal decode runs covering `wanted_disp` (sorted unique).

        decode_through: if the next wanted frame's keyframe starts within
        this many packets of the current run's end, keep decoding through
        rather than reseeking — a reseek costs a codec flush and re-reads.
        """
        wanted = np.unique(np.asarray(list(wanted_disp), dtype=np.int64))
        if len(wanted) == 0:
            return []
        if wanted[0] < 0 or wanted[-1] >= self.vd.num_frames:
            raise ScannerException(
                f"frame request {wanted[0]}..{wanted[-1]} out of range "
                f"(video has {self.vd.num_frames} frames)")
        runs: List[DecodeRun] = []
        cur_start = cur_end = -1
        cur_disps: List[int] = []

        def close_run():
            if cur_start < 0:
                return
            runs.append(DecodeRun(cur_start, cur_end,
                                  np.asarray(cur_disps, np.int64)))

        for w in wanted:
            kf_dec, kf_disp = self.governing_keyframe(int(w))
            need_end = int(self.max_dec_through_disp[w])
            if cur_start >= 0 and kf_dec <= cur_end + decode_through:
                cur_end = max(cur_end, need_end)
                cur_disps.append(int(w))
            else:
                close_run()
                cur_start, cur_end = kf_dec, need_end
                cur_disps = [int(w)]
        close_run()
        return runs


class DecoderAutomata:
    """Owns one Decoder handle and executes decode plans against stored
    packet data."""

    def __init__(self, backend: StorageBackend, vd: VideoDescriptor,
                 data_path: str, n_threads: int = 1,
                 output_format: str = "rgb24"):
        self.backend = backend
        self.vd = vd
        self.index = VideoIndex(vd)
        # in-place ingested streams read from the original container file
        self.data_path = vd.data_path or data_path
        self._external = bool(vd.data_path)
        # "rgb24": (n, h, w, 3) frames; "yuv420": (n, frame_bytes) planar
        # I420 rows at 1.5 B/px for device-side conversion
        # (kernels/color.py) — half the host->device bytes
        self.output_format = output_format
        self.decoder = Decoder(vd.codec, vd.extradata, vd.width, vd.height,
                               n_threads, output_format=output_format)
        # reused decode scratch (grown geometrically) — avoids a fresh
        # multi-MB allocation per decode run (reference keeps pooled
        # buffers for the same reason, util/memory.cpp BlockAllocator)
        self._scratch = np.empty(0, np.uint8)

    @property
    def frame_bytes(self) -> int:
        from .lib import yuv420_frame_bytes
        if self.output_format == "yuv420":
            return yuv420_frame_bytes(self.vd.height, self.vd.width)
        return self.vd.height * self.vd.width * 3

    def _scratch_buf(self, nbytes: int) -> np.ndarray:
        if self._scratch.nbytes < nbytes:
            self._scratch = np.empty(int(nbytes * 1.5) + 1, np.uint8)
        return self._scratch

    def close(self):
        self.decoder.close()
        self._scratch = np.empty(0, np.uint8)

    def _read_packets(self, start_dec: int, end_dec: int
                      ) -> Tuple[bytes, np.ndarray]:
        offs = self.vd.sample_offsets[start_dec:end_dec + 1].astype(np.int64)
        sizes = self.vd.sample_sizes[start_dec:end_dec + 1].astype(np.int64)
        if self._external:
            # external container: samples may be non-contiguous; one spanning
            # read then slice (containers interleave audio but video spans
            # are still compact enough)
            lo = int(offs.min())
            hi = int((offs + sizes).max())
            with open(self.data_path, "rb") as f:
                f.seek(lo)
                span = f.read(hi - lo)
            parts = [span[o - lo:o - lo + s] for o, s in zip(offs, sizes)]
            return b"".join(parts), sizes.astype(np.uint64)
        # packed stream: contiguous by construction
        lo = int(offs[0])
        hi = int(offs[-1] + sizes[-1])
        data = self.backend.read_range(self.data_path, lo, hi - lo)
        if len(data) != hi - lo:
            raise ScannerException(
                f"short packet read from {self.data_path}")
        return data, sizes.astype(np.uint64)

    def _decode_run_pts(self, run: DecodeRun, out: np.ndarray) -> None:
        """Decode one run into `out` ((n_out, h*w*3) rows in display
        order), selecting frames by TIMESTAMP rather than emission
        position.  Pts matching keeps delivery exact on streams where
        positional masks break: open-GOP seeks (the decoder emits or
        drops leading frames whose references precede the keyframe) and
        VFR containers (display order is defined by pts alone).  If a
        wanted frame is not delivered — an open-GOP leading frame whose
        references live in the previous GOP — the whole run retries from
        one keyframe earlier until it decodes or the stream start is hit
        (reference decoder_automata feeder restarts at decoder_automata
        .cpp:238; the reference never handled open GOPs at all)."""
        h, w = self.vd.height, self.vd.width
        pts_all = np.asarray(self.vd.sample_pts, np.int64)
        wanted_pts = pts_all[self.index.dec_of_disp[
            np.asarray(run.out_disp, np.int64)]]
        start = run.start_dec
        while True:
            data, sizes = self._read_packets(start, run.end_dec)
            pkt_pts = pts_all[start:run.end_dec + 1]
            self.decoder.reset()
            n, oh, ow, deliv = self.decoder.decode_run_pts(
                data, sizes, pkt_pts, wanted_pts, out, flush=True)
            if n and (oh, ow) != (h, w):
                raise ScannerException(
                    f"decoded geometry {oh}x{ow} != descriptor {h}x{w}")
            if deliv.all():
                return
            # open-GOP leading frames: restart from one keyframe earlier
            ki = int(np.searchsorted(self.index.kf_decs, start,
                                     side="right")) - 1
            if ki <= 0 or start <= 0:
                missing = wanted_pts[~deliv].tolist()
                raise ScannerException(
                    f"frames with pts {missing[:5]} not delivered "
                    f"(run {start}..{run.end_dec}; stream damaged or "
                    f"index stale)")
            start = int(self.index.kf_decs[ki - 1])

    def stream_frames(self, rows: Sequence[int], packets_per_call: int = 16,
                      max_frames_per_yield: int = 16):
        """Incrementally decode ascending unique display rows, yielding
        ``(row_array, frames_array)`` slices as the codec emits them.

        One decode session per keyframe run: packets are fed in slices of
        ``packets_per_call`` through repeated bounded
        ``decode_run_pts_stream`` calls WITHOUT resetting the codec (the
        C layer stops — does not error — at ``max_frames_per_yield``
        matched frames and reports the packets it consumed, so the
        output buffer is a work packet, not a packet run plus a
        reorder-delay margin).  Peak memory is one yield slice.  This is
        the work-packet streaming loader's decode primitive (reference
        element cache + feeder threads, evaluate_worker.h:207-218 /
        decoder_automata.cpp).  Frames arrive in display order; yields
        are disjoint and cover exactly `rows`.  Open-GOP / false-keyframe
        retries restart the run from an earlier keyframe for the
        still-undelivered tail only.
        """
        rows_arr = np.unique(np.asarray(list(rows), np.int64))
        if len(rows_arr) == 0:
            return
        frame_bytes = self.frame_bytes
        shape_tail = ((self.vd.height, self.vd.width, 3)
                      if self.output_format == "rgb24" else (frame_bytes,))
        pts_all = np.asarray(self.vd.sample_pts, np.int64)
        empty_sizes = np.zeros(0, np.uint64)
        empty_pts = np.zeros(0, np.int64)
        for run in self.index.plan(rows_arr):
            out_disp = np.asarray(run.out_disp, np.int64)
            start = run.start_dec
            while True:  # open-GOP / false-keyframe retry loop
                rem_rows = out_disp
                rem_pts = pts_all[self.index.dec_of_disp[rem_rows]]
                self.decoder.reset()
                pos = start
                while len(rem_rows):
                    if pos <= run.end_dec:
                        end = min(pos + packets_per_call - 1, run.end_dec)
                        data, sizes = self._read_packets(pos, end)
                        pkt_pts = pts_all[pos:end + 1]
                    else:
                        # flush-only continuation: harvest codec backlog
                        data, sizes, pkt_pts = b"", empty_sizes, empty_pts
                        end = pos - 1
                    buf = self._scratch_buf(
                        max_frames_per_yield * frame_bytes)
                    n, oh, ow, deliv, consumed = \
                        self.decoder.decode_run_pts_stream(
                            data, sizes, pkt_pts, rem_pts,
                            buf[:max_frames_per_yield * frame_bytes],
                            max_frames=max_frames_per_yield,
                            flush=(end >= run.end_dec))
                    if n and (oh, ow) != (self.vd.height, self.vd.width):
                        raise ScannerException(
                            f"decoded geometry {oh}x{ow} != descriptor "
                            f"{self.vd.height}x{self.vd.width}")
                    if n:
                        got = buf[:n * frame_bytes].reshape(
                            (n,) + shape_tail).copy()
                        yield rem_rows[deliv], got
                    rem_rows = rem_rows[~deliv]
                    rem_pts = rem_pts[~deliv]
                    pos += consumed
                    if pos > run.end_dec and n == 0 and consumed == 0:
                        break  # flushed dry; tail undeliverable here
                if not len(rem_rows):
                    break
                # leading open-GOP frames (or a false keyframe): retry the
                # undelivered tail from one keyframe earlier
                out_disp = rem_rows
                ki = int(np.searchsorted(self.index.kf_decs, start,
                                         side="right")) - 1
                if ki <= 0 or start <= 0:
                    raise ScannerException(
                        f"frames with pts {rem_pts[:5].tolist()} not "
                        f"delivered (run {start}..{run.end_dec}; stream "
                        f"damaged or index stale)")
                start = int(self.index.kf_decs[ki - 1])

    def get_frames(self, rows: Sequence[int]) -> np.ndarray:
        """Decode exactly the given display-order frame indices.

        Returns uint8 array in *request order* — duplicates and arbitrary
        order allowed (Gather semantics).  Shape is
        (len(rows), h, w, 3) for "rgb24" output, or
        (len(rows), frame_bytes) planar I420 rows for "yuv420".
        """
        rows_arr = np.asarray(list(rows), np.int64)
        h, w = self.vd.height, self.vd.width
        frame_bytes = self.frame_bytes
        shape = ((len(rows_arr), h, w, 3)
                 if self.output_format == "rgb24"
                 else (len(rows_arr), frame_bytes))
        if len(rows_arr) == 0:
            return np.zeros(shape, np.uint8)
        runs = self.index.plan(rows_arr)
        result = np.empty(shape, np.uint8)
        if len(runs) == 1 and np.array_equal(
                np.asarray(runs[0].out_disp, np.int64), rows_arr):
            # fast path: the run emits exactly the requested rows in
            # request order — decode straight into the result batch (the
            # zero-copy head of the engine's batched column path)
            self._decode_run_pts(runs[0], result.reshape(-1))
            return result
        # request-order positions of each decoded display index
        positions: dict = {}
        for i, r in enumerate(rows_arr.tolist()):
            positions.setdefault(int(r), []).append(i)
        for run in runs:
            n_out = len(run.out_disp)
            scratch = self._scratch_buf(n_out * frame_bytes)
            out = scratch[:n_out * frame_bytes]
            self._decode_run_pts(run, out)
            out = out.reshape((n_out,) + shape[1:])
            for i, d in enumerate(run.out_disp):
                for pos in positions.get(int(d), ()):
                    result[pos] = out[i]
        return result
