"""ctypes bindings for libscvid (cpp/scvid.cpp).

Every call into the library releases the GIL, so one Python process can run
many decoder handles truly in parallel — the replacement for the reference's
decoder thread pool (decoder_automata.cpp feeder threads, worker.cpp:1631
decoder_cpus).
"""

from __future__ import annotations

import ctypes as C
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..common import ScannerException
from ..storage.metadata import VideoDescriptor

_LIB_PATH = os.path.join(os.path.dirname(__file__), "libscvid.so")

# Must match scvid_api_version() in cpp/scvid.cpp.  Bumped together with
# any exported-symbol or struct-layout change so a stale prebuilt .so is
# refused with a clear "rebuild" error instead of a late AttributeError
# on a missing symbol (advisor round-4 finding).
_API_VERSION = 3


class _Index(C.Structure):
    _fields_ = [
        ("width", C.c_int32),
        ("height", C.c_int32),
        ("fps", C.c_double),
        ("num_samples", C.c_int64),
        ("codec", C.c_char * 32),
        ("tb_num", C.c_int32),
        ("tb_den", C.c_int32),
        ("sample_offsets", C.POINTER(C.c_uint64)),
        ("sample_sizes", C.POINTER(C.c_uint64)),
        ("sample_pts", C.POINTER(C.c_int64)),
        ("sample_dts", C.POINTER(C.c_int64)),
        ("keyflags", C.POINTER(C.c_uint8)),
        ("extradata", C.POINTER(C.c_uint8)),
        ("extradata_size", C.c_int64),
    ]


_lib = None


def _needs_rebuild(cpp_dir: str) -> bool:
    """True when the checked-out C sources are newer than the built .so
    (a stale prebuilt library would be missing newly added symbols)."""
    if not os.path.exists(_LIB_PATH):
        return True
    so_mtime = os.path.getmtime(_LIB_PATH)
    for src in ("scvid.cpp", "scvid_api.h", "Makefile"):
        p = os.path.join(cpp_dir, src)
        if os.path.exists(p) and os.path.getmtime(p) > so_mtime:
            return True
    return False


def _lib_version(handle) -> int:
    try:
        handle.scvid_api_version.restype = C.c_int32
        return int(handle.scvid_api_version())
    except AttributeError:
        return -1


def _load_checked():
    """Build/refresh libscvid as needed and CDLL it, verifying the API
    version.  Raises with a clear message when no good library can be
    produced.

    When the source tree is present, the WHOLE sequence — staleness
    check, make, dlopen, version check, version-triggered rebuild — runs
    under one flock, so a concurrent process can never dlopen a
    partially-linked .so (and the unlink before the version-triggered
    rebuild forces a fresh inode: dlopen of the same inode would hand
    back the already-mapped stale library)."""
    cpp_dir = os.path.join(os.path.dirname(__file__), "..", "..", "cpp")
    has_make = os.path.exists(os.path.join(cpp_dir, "Makefile"))
    build_err = ""

    def _make() -> str:
        import subprocess
        r = subprocess.run(["make", "-C", cpp_dir],
                           capture_output=True, text=True)
        return "" if r.returncode == 0 else f"\nbuild failed:\n{r.stderr}"

    def _open():
        nonlocal build_err
        if has_make and _needs_rebuild(cpp_dir):
            build_err = _make()
        if not os.path.exists(_LIB_PATH):
            raise ScannerException(
                f"libscvid.so not built; run `make -C cpp` (expected at "
                f"{_LIB_PATH}){build_err}")
        lib = C.CDLL(_LIB_PATH)
        if _lib_version(lib) != _API_VERSION and has_make:
            # version-stale .so with a fresh mtime (e.g. copied in from
            # another checkout): force the rebuild the mtime check missed
            os.unlink(_LIB_PATH)
            build_err = _make()
            if os.path.exists(_LIB_PATH):
                lib = C.CDLL(_LIB_PATH)
        got = _lib_version(lib)
        if got != _API_VERSION:
            raise ScannerException(
                f"stale libscvid.so (API version {got}, need "
                f"{_API_VERSION}); rebuild with `make -C cpp`{build_err}")
        return lib

    if not has_make:
        return _open()
    import fcntl
    with open(os.path.join(cpp_dir, ".build.lock"), "w") as lk:
        fcntl.flock(lk, fcntl.LOCK_EX)
        return _open()


def get_lib():
    global _lib
    if _lib is None:
        lib = _load_checked()
        lib.scvid_last_error.restype = C.c_char_p
        lib.scvid_set_log_level.argtypes = [C.c_int]
        lib.scvid_ingest.restype = C.POINTER(_Index)
        lib.scvid_ingest.argtypes = [C.c_char_p, C.c_char_p]
        lib.scvid_index_free.argtypes = [C.POINTER(_Index)]
        lib.scvid_decoder_create.restype = C.c_void_p
        lib.scvid_decoder_create.argtypes = [
            C.c_char_p, C.c_char_p, C.c_int64, C.c_int32, C.c_int32, C.c_int32]
        lib.scvid_decoder_destroy.argtypes = [C.c_void_p]
        lib.scvid_decoder_reset.argtypes = [C.c_void_p]
        lib.scvid_decoder_set_output_format.argtypes = [C.c_void_p,
                                                        C.c_int32]
        lib.scvid_decode_run.restype = C.c_int64
        lib.scvid_decode_run.argtypes = [
            C.c_void_p, C.c_char_p, C.POINTER(C.c_uint64), C.c_int64,
            C.c_char_p, C.c_int64, C.c_int32, C.c_void_p, C.c_int64,
            C.POINTER(C.c_int64)]
        lib.scvid_decode_run_pts.restype = C.c_int64
        lib.scvid_decode_run_pts.argtypes = [
            C.c_void_p, C.c_char_p, C.POINTER(C.c_uint64),
            C.POINTER(C.c_int64), C.c_int64, C.POINTER(C.c_int64),
            C.c_int64, C.c_char_p, C.c_int32, C.c_void_p, C.c_int64,
            C.POINTER(C.c_int64)]
        lib.scvid_decoder_emitted.restype = C.c_int64
        lib.scvid_decoder_emitted.argtypes = [C.c_void_p]
        lib.scvid_decode_run_pts_stream.restype = C.c_int64
        lib.scvid_decode_run_pts_stream.argtypes = [
            C.c_void_p, C.c_char_p, C.POINTER(C.c_uint64),
            C.POINTER(C.c_int64), C.c_int64, C.POINTER(C.c_int64),
            C.c_int64, C.c_char_p, C.c_int32, C.c_int64, C.c_void_p,
            C.c_int64, C.POINTER(C.c_int64), C.POINTER(C.c_int64)]
        lib.scvid_encoder_create.restype = C.c_void_p
        lib.scvid_encoder_create.argtypes = [
            C.c_int32, C.c_int32, C.c_int32, C.c_int32, C.c_char_p,
            C.c_int64, C.c_int32, C.c_int32, C.c_int32, C.c_int32]
        lib.scvid_encoder_destroy.argtypes = [C.c_void_p]
        lib.scvid_encoder_extradata.restype = C.c_int64
        lib.scvid_encoder_extradata.argtypes = [C.c_void_p, C.c_void_p,
                                                C.c_int64]
        lib.scvid_encoder_descriptor.restype = C.c_char_p
        lib.scvid_encoder_descriptor.argtypes = [C.c_void_p]
        lib.scvid_encoder_feed.restype = C.c_int32
        lib.scvid_encoder_feed.argtypes = [C.c_void_p, C.c_void_p, C.c_int64]
        lib.scvid_encoder_feed_pts.restype = C.c_int32
        lib.scvid_encoder_feed_pts.argtypes = [
            C.c_void_p, C.c_void_p, C.c_int64, C.POINTER(C.c_int64)]
        lib.scvid_encoder_flush.restype = C.c_int32
        lib.scvid_encoder_flush.argtypes = [C.c_void_p]
        lib.scvid_encoder_pending.restype = C.c_int64
        lib.scvid_encoder_pending.argtypes = [C.c_void_p]
        lib.scvid_encoder_pending_bytes.restype = C.c_int64
        lib.scvid_encoder_pending_bytes.argtypes = [C.c_void_p]
        lib.scvid_encoder_take.argtypes = [
            C.c_void_p, C.c_void_p, C.POINTER(C.c_uint64), C.c_void_p,
            C.POINTER(C.c_int64), C.POINTER(C.c_int64)]
        lib.scvid_mp4_write.restype = C.c_int32
        lib.scvid_mp4_write.argtypes = [
            C.c_char_p, C.c_int32, C.c_int32, C.c_int32, C.c_int32,
            C.c_int32, C.c_int32,
            C.c_char_p, C.c_char_p, C.c_int64, C.c_char_p,
            C.POINTER(C.c_uint64), C.c_char_p, C.POINTER(C.c_int64),
            C.POINTER(C.c_int64), C.c_int64]
        lib.scvid_set_log_level(16)  # AV_LOG_ERROR
        _lib = lib
    return _lib


def _err() -> str:
    return get_lib().scvid_last_error().decode("utf-8", "replace")


def ingest_file(path: str, out_packets_path: Optional[str]
                ) -> VideoDescriptor:
    """Demux a video file into (packet stream, index).

    out_packets_path=None performs in-place ingest: the index references the
    original container (reference ingest.cpp:382 parse_video_inplace).
    """
    lib = get_lib()
    idx_p = lib.scvid_ingest(
        path.encode(), out_packets_path.encode() if out_packets_path else None)
    if not idx_p:
        raise ScannerException(f"ingest failed for {path}: {_err()}")
    idx = idx_p.contents
    n = idx.num_samples
    try:
        vd = VideoDescriptor(
            width=idx.width, height=idx.height, fps=idx.fps, num_frames=n,
            codec=idx.codec.decode(),
            extradata=bytes(
                C.cast(idx.extradata,
                       C.POINTER(C.c_uint8 * idx.extradata_size)).contents)
            if idx.extradata_size > 0 else b"",
            sample_offsets=np.ctypeslib.as_array(idx.sample_offsets,
                                                 (n,)).copy(),
            sample_sizes=np.ctypeslib.as_array(idx.sample_sizes, (n,)).copy(),
            keyframe_indices=np.nonzero(
                np.ctypeslib.as_array(idx.keyflags, (n,)))[0].astype(np.int64),
            sample_pts=np.ctypeslib.as_array(idx.sample_pts, (n,)).copy(),
            sample_dts=np.ctypeslib.as_array(idx.sample_dts, (n,)).copy(),
            tb_num=idx.tb_num, tb_den=idx.tb_den,
            data_path=os.path.abspath(path) if out_packets_path is None else "")
    finally:
        lib.scvid_index_free(idx_p)
    if len(vd.keyframe_indices) == 0 or vd.keyframe_indices[0] != 0:
        raise ScannerException(
            f"{path}: stream does not start with a keyframe")
    return vd


def yuv420_frame_bytes(height: int, width: int) -> int:
    """Bytes per planar I420 frame (Y + quarter-res U and V planes)."""
    ch, cw = (height + 1) // 2, (width + 1) // 2
    return height * width + 2 * ch * cw


class Decoder:
    """One hardware-thread decode pipeline. Not thread-safe per-instance;
    use one per worker thread.

    output_format selects the decoded pixel layout:
      - "rgb24"  (default): packed (h, w, 3) — host conversion via swscale
      - "yuv420": planar I420, yuv420_frame_bytes(h, w) per frame — for
        pipelines that ship 1.5 B/px to an accelerator and convert there
        (kernels/color.py; the reference shipped NV12 and converted
        on-GPU for the same halving, util/image.cu:22)
    """

    def __init__(self, codec: str, extradata: bytes, width: int, height: int,
                 n_threads: int = 1, output_format: str = "rgb24"):
        self._lib = get_lib()
        self._h = self._lib.scvid_decoder_create(
            codec.encode(), extradata, len(extradata), width, height,
            n_threads)
        if not self._h:
            raise ScannerException(f"decoder create failed: {_err()}")
        if output_format not in ("rgb24", "yuv420"):
            self._lib.scvid_decoder_destroy(self._h)
            self._h = None
            raise ScannerException(
                f"unknown decoder output_format {output_format!r}")
        self.output_format = output_format
        if output_format == "yuv420":
            self._lib.scvid_decoder_set_output_format(self._h, 1)

    def close(self):
        if self._h:
            self._lib.scvid_decoder_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def reset(self):
        self._lib.scvid_decoder_reset(self._h)

    def decode_run(self, packets: bytes, sizes: np.ndarray,
                   wanted: np.ndarray, out: np.ndarray,
                   flush: bool = True) -> Tuple[int, int, int]:
        """Decode a packet run; write frames selected by `wanted` (uint8 mask
        over emitted frames since last reset) into `out` (flat uint8).
        Returns (n_written, height, width)."""
        sizes = np.ascontiguousarray(sizes, dtype=np.uint64)
        wanted = np.ascontiguousarray(wanted, dtype=np.uint8)
        assert out.dtype == np.uint8 and out.flags["C_CONTIGUOUS"]
        dims = (C.c_int64 * 2)()
        n = self._lib.scvid_decode_run(
            self._h, packets,
            sizes.ctypes.data_as(C.POINTER(C.c_uint64)), len(sizes),
            wanted.ctypes.data_as(C.c_char_p), len(wanted),
            1 if flush else 0,
            out.ctypes.data_as(C.c_void_p), out.nbytes, dims)
        if n < 0:
            raise ScannerException(f"decode failed: {_err()}")
        return int(n), int(dims[0]), int(dims[1])

    def decode_run_pts_stream(self, packets: bytes, sizes: np.ndarray,
                              pkt_pts: np.ndarray, wanted_pts: np.ndarray,
                              out: np.ndarray, max_frames: int,
                              flush: bool = False
                              ) -> Tuple[int, int, int, np.ndarray, int]:
        """Resumable bounded decode (scvid_decode_run_pts_stream): write
        at most `max_frames` matched frames, report packets consumed so
        the caller re-feeds the rest.  Codec state is NOT reset between
        calls — the work-packet streaming primitive."""
        sizes = np.ascontiguousarray(sizes, dtype=np.uint64)
        pkt_pts = np.ascontiguousarray(pkt_pts, dtype=np.int64)
        wanted_pts = np.ascontiguousarray(wanted_pts, dtype=np.int64)
        assert out.dtype == np.uint8 and out.flags["C_CONTIGUOUS"]
        deliv = np.zeros(len(wanted_pts), np.uint8)
        dims = (C.c_int64 * 2)()
        consumed = C.c_int64(0)
        n = self._lib.scvid_decode_run_pts_stream(
            self._h, packets,
            sizes.ctypes.data_as(C.POINTER(C.c_uint64)),
            pkt_pts.ctypes.data_as(C.POINTER(C.c_int64)), len(sizes),
            wanted_pts.ctypes.data_as(C.POINTER(C.c_int64)),
            len(wanted_pts),
            deliv.ctypes.data_as(C.c_char_p),
            1 if flush else 0, int(max_frames),
            out.ctypes.data_as(C.c_void_p), out.nbytes, dims,
            C.byref(consumed))
        if n < 0:
            raise ScannerException(f"decode failed: {_err()}")
        return (int(n), int(dims[0]), int(dims[1]), deliv.astype(bool),
                int(consumed.value))

    def decode_run_pts(self, packets: bytes, sizes: np.ndarray,
                       pkt_pts: np.ndarray, wanted_pts: np.ndarray,
                       out: np.ndarray, flush: bool = True
                       ) -> Tuple[int, int, int, np.ndarray]:
        """Decode a packet run selecting frames by TIMESTAMP membership
        (robust to open-GOP leading frames and VFR streams; see
        scvid_decode_run_pts).  wanted_pts must be sorted ascending,
        unique.  Returns (n_written, height, width, delivered_mask);
        missing timestamps are reported in the mask, not raised — the
        caller replans (e.g. from an earlier keyframe)."""
        sizes = np.ascontiguousarray(sizes, dtype=np.uint64)
        pkt_pts = np.ascontiguousarray(pkt_pts, dtype=np.int64)
        wanted_pts = np.ascontiguousarray(wanted_pts, dtype=np.int64)
        assert out.dtype == np.uint8 and out.flags["C_CONTIGUOUS"]
        deliv = np.zeros(len(wanted_pts), np.uint8)
        dims = (C.c_int64 * 2)()
        n = self._lib.scvid_decode_run_pts(
            self._h, packets,
            sizes.ctypes.data_as(C.POINTER(C.c_uint64)),
            pkt_pts.ctypes.data_as(C.POINTER(C.c_int64)), len(sizes),
            wanted_pts.ctypes.data_as(C.POINTER(C.c_int64)),
            len(wanted_pts),
            deliv.ctypes.data_as(C.c_char_p),
            1 if flush else 0,
            out.ctypes.data_as(C.c_void_p), out.nbytes, dims)
        if n < 0:
            raise ScannerException(f"decode failed: {_err()}")
        return int(n), int(dims[0]), int(dims[1]), deliv.astype(bool)


class Encoder:
    def __init__(self, width: int, height: int, fps: float = 30.0,
                 codec: str = "libx264", bitrate: int = 0, crf: int = 20,
                 keyint: int = 16, bframes: int = 0,
                 open_gop: bool = False):
        self._lib = get_lib()
        fps_num, fps_den = _fps_to_rational(fps)
        self.width, self.height = width, height
        self.fps_num, self.fps_den = fps_num, fps_den
        self._h = self._lib.scvid_encoder_create(
            width, height, fps_num, fps_den, codec.encode(), bitrate, crf,
            keyint, bframes, 1 if open_gop else 0)
        if not self._h:
            raise ScannerException(f"encoder create failed: {_err()}")

    def close(self):
        if self._h:
            self._lib.scvid_encoder_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    @property
    def extradata(self) -> bytes:
        n = self._lib.scvid_encoder_extradata(self._h, None, 0)
        if n == 0:
            return b""
        buf = C.create_string_buffer(n)
        self._lib.scvid_encoder_extradata(self._h, buf, n)
        return buf.raw

    @property
    def descriptor(self) -> str:
        """Container-level codec descriptor of this encoder's output
        ("h264", "hevc", ...) — the name write_mp4 and the ingest index
        agree on, straight from libavcodec (no name mapping)."""
        return self._lib.scvid_encoder_descriptor(self._h).decode()

    def feed(self, frames: np.ndarray,
             pts: Optional[np.ndarray] = None) -> None:
        """frames: uint8 array (n, h, w, 3) or (h, w, 3).

        pts (optional): per-frame presentation timestamps in the encoder
        time base (1/fps ticks), strictly increasing across all feeds —
        gaps produce variable-frame-rate streams."""
        frames = np.ascontiguousarray(frames, dtype=np.uint8)
        if frames.ndim == 3:
            frames = frames[None]
        if frames.shape[1:] != (self.height, self.width, 3):
            raise ScannerException(
                f"encoder expects {self.height}x{self.width}x3 frames, got "
                f"{frames.shape[1:]}")
        n = frames.shape[0]
        if pts is None:
            ok = self._lib.scvid_encoder_feed(
                self._h, frames.ctypes.data_as(C.c_void_p), n)
        else:
            pts = np.ascontiguousarray(pts, dtype=np.int64)
            if len(pts) != n:
                raise ScannerException(
                    f"{len(pts)} timestamps for {n} frames")
            ok = self._lib.scvid_encoder_feed_pts(
                self._h, frames.ctypes.data_as(C.c_void_p), n,
                pts.ctypes.data_as(C.POINTER(C.c_int64)))
        if ok < 0:
            raise ScannerException(f"encode failed: {_err()}")

    def flush(self) -> None:
        if self._lib.scvid_encoder_flush(self._h) < 0:
            raise ScannerException(f"encode flush failed: {_err()}")

    def take_packets(self):
        """Returns (data: bytes, sizes, keys, pts, dts) and clears the
        internal queue."""
        n = self._lib.scvid_encoder_pending(self._h)
        if n == 0:
            return b"", np.zeros(0, np.uint64), np.zeros(0, np.uint8), \
                np.zeros(0, np.int64), np.zeros(0, np.int64)
        total = self._lib.scvid_encoder_pending_bytes(self._h)
        data = np.empty(total, np.uint8)
        sizes = np.empty(n, np.uint64)
        keys = np.empty(n, np.uint8)
        pts = np.empty(n, np.int64)
        dts = np.empty(n, np.int64)
        self._lib.scvid_encoder_take(
            self._h, data.ctypes.data_as(C.c_void_p),
            sizes.ctypes.data_as(C.POINTER(C.c_uint64)),
            keys.ctypes.data_as(C.c_void_p),
            pts.ctypes.data_as(C.POINTER(C.c_int64)),
            dts.ctypes.data_as(C.POINTER(C.c_int64)))
        return data.tobytes(), sizes, keys, pts, dts


def _fps_to_rational(fps: float) -> Tuple[int, int]:
    if abs(fps - round(fps)) < 1e-6:
        return int(round(fps)), 1
    # exact small rationals (12.5 -> 25/2) fall out naturally; NTSC rates
    # (29.97...) resolve to their x1001 form (30000/1001) within the bound
    from fractions import Fraction
    frac = Fraction(fps).limit_denominator(100000)
    return frac.numerator, frac.denominator


def write_mp4(path: str, width: int, height: int, fps: float, codec: str,
              extradata: bytes, packets: bytes, sizes: np.ndarray,
              keys: np.ndarray, pts: np.ndarray, dts: np.ndarray,
              tb: Optional[Tuple[int, int]] = None) -> None:
    """tb: (num, den) time base of pts/dts; default = frame numbering at
    `fps` (matches this library's Encoder output)."""
    lib = get_lib()
    fps_num, fps_den = _fps_to_rational(fps)
    tb_num, tb_den = tb if tb is not None else (fps_den, fps_num)
    sizes = np.ascontiguousarray(sizes, np.uint64)
    keys = np.ascontiguousarray(keys, np.uint8)
    pts = np.ascontiguousarray(pts, np.int64)
    dts = np.ascontiguousarray(dts, np.int64)
    r = lib.scvid_mp4_write(
        path.encode(), width, height, fps_num, fps_den, tb_num, tb_den,
        codec.encode(), extradata, len(extradata), packets,
        sizes.ctypes.data_as(C.POINTER(C.c_uint64)),
        keys.ctypes.data_as(C.c_char_p),
        pts.ctypes.data_as(C.POINTER(C.c_int64)),
        dts.ctypes.data_as(C.POINTER(C.c_int64)), len(sizes))
    if r < 0:
        raise ScannerException(f"mp4 write failed: {_err()}")
