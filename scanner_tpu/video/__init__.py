from .automata import DecoderAutomata, VideoIndex
from .ingest import (export_mp4, frame_pattern, frame_pattern_id,
                     ingest_videos, load_frames, load_video_meta,
                     open_automata, synthesize_video)
from .lib import Decoder, Encoder, ingest_file, write_mp4

__all__ = [
    "DecoderAutomata", "VideoIndex", "Decoder", "Encoder", "ingest_file",
    "write_mp4", "ingest_videos", "load_frames", "load_video_meta",
    "open_automata", "export_mp4", "synthesize_video", "frame_pattern",
    "frame_pattern_id",
]
