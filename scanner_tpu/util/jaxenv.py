"""JAX backend-selection hardening.

The ambient environment may inject an accelerator PJRT plugin into *every*
Python interpreter via sitecustomize (triggered by its own env vars) and
point ``JAX_PLATFORMS`` at it.  When that accelerator tunnel is wedged, any
``jax.devices()`` call — in this process or any child — hangs.  Tests,
subprocess workers, and the driver's multi-chip dryrun must therefore be
able to force a deterministic CPU backend:

- for *child processes*: strip the plugin trigger vars so the sitecustomize
  block never runs, and set ``JAX_PLATFORMS=cpu`` (`cpu_only_env`);
- for *this process*, before the first backend touch: set the env vars and
  ``jax.config`` override (`force_cpu_platform`).

Reference counterpart: the reference forces device selection per-process
via its own flags (scanner/engine/worker.cpp device registration); on TPU
the equivalent hazard is PJRT plugin registration order.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

# Env vars that trigger ambient accelerator-plugin registration in child
# interpreters (sitecustomize).  Stripping them is the only reliable way to
# keep a wedged tunnel from hanging a child at interpreter start.
_PLUGIN_TRIGGER_VARS = (
    "PALLAS_AXON_POOL_IPS",
    "PALLAS_AXON_TPU_GEN",
    "PALLAS_AXON_REMOTE_COMPILE",
)

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def _set_device_count(flags: str, n: int) -> str:
    """Set (or replace) the virtual CPU device-count flag in XLA_FLAGS."""
    kept = [f for f in flags.split() if not f.startswith(_COUNT_FLAG)]
    kept.append(f"{_COUNT_FLAG}={n}")
    return " ".join(kept)


def cpu_only_env(base: Optional[Dict[str, str]] = None,
                 n_devices: Optional[int] = None) -> Dict[str, str]:
    """Environment for a child Python process that must use JAX on CPU.

    Strips accelerator-plugin trigger vars, sets ``JAX_PLATFORMS=cpu``, and
    (optionally) requests ``n_devices`` virtual CPU devices so sharded code
    paths run without hardware.
    """
    env = dict(os.environ if base is None else base)
    for var in _PLUGIN_TRIGGER_VARS:
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        env["XLA_FLAGS"] = _set_device_count(
            env.get("XLA_FLAGS", ""), n_devices)
    return env


def enable_compilation_cache(cache_dir: Optional[str] = None
                             ) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir`` so
    jitted-kernel executables survive process restarts — a worker that
    restarts (or a new bench/job process on the same host) re-loads its
    bucket-ladder executables from disk instead of paying seconds of
    TPU compile per shape.

    Resolution: explicit argument, else the ``SCANNER_TPU_COMPILATION_CACHE``
    env var (the deploy manifests set it), else the ``[perf]
    compilation_cache_dir`` config knob via the callers that read config.
    Empty/unset = no-op (returns None).  The min-size/min-compile-time
    thresholds are lowered so even small kernel executables are cached
    (the default skips sub-second compiles — exactly the CPU-backend
    ones tests exercise).
    """
    path = cache_dir or os.environ.get("SCANNER_TPU_COMPILATION_CACHE", "")
    if not path:
        return None
    if "://" not in path:
        # local path: expand + create.  Remote prefixes (gs://...) go to
        # JAX verbatim — makedirs on a URL would create a junk local
        # "gs:/bucket" tree (or crash on a read-only root filesystem)
        path = os.path.expanduser(path)
        os.makedirs(path, exist_ok=True)
    import jax
    jax.config.update("jax_compilation_cache_dir", path)
    for knob, val in (("jax_persistent_cache_min_entry_size_bytes", -1),
                      ("jax_persistent_cache_min_compile_time_secs", 0.0)):
        try:
            jax.config.update(knob, val)
        except (AttributeError, ValueError):
            pass  # knob not present on this jax version
    return path


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: Optional[bool]
              = None, **kwargs):
    """Version-compat ``shard_map``: new jax exports it as
    ``jax.shard_map`` (replication checking via ``check_vma``); older
    releases only have ``jax.experimental.shard_map.shard_map`` (same
    knob spelled ``check_rep``).  All parallel/* modules import from
    here so a jax upgrade/downgrade never breaks import-time collection
    again (the ``from jax import shard_map`` regression)."""
    import jax
    impl = getattr(jax, "shard_map", None)
    if impl is None:
        from jax.experimental.shard_map import shard_map as impl
    import inspect
    try:
        params = inspect.signature(impl).parameters
    except (TypeError, ValueError):
        params = {}
    if "check_vma" in params:
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
    elif "check_rep" in params:
        # Pre-vma jax: callers here are written against vma semantics
        # (pvary marks, which are identity on this version), so the old
        # replication checker cannot follow their carries — it trips a
        # known false mismatch under remat/scan whose upstream-advised
        # workaround IS check_rep=False.  Translate: explicit request
        # passes through, unspecified disables the legacy checker.
        kwargs["check_rep"] = bool(check_vma) if check_vma is not None \
            else False
    return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **kwargs)


def axis_size(axis_name) -> int:
    """Version-compat static mesh-axis size inside shard_map/pmap traced
    code: new jax has ``jax.lax.axis_size``; on older releases
    ``jax.core.axis_frame(name)`` returns the bound size directly.
    Companion to the `shard_map` shim above — parallel/* imports both
    from here."""
    import jax
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    from jax import core
    return core.axis_frame(axis_name)


def pvary(x, axis_names):
    """Version-compat device-variance marking for shard_map carries:
    new jax tracks varying-mesh-axes (vma) and wants loop carries marked
    via ``jax.lax.pvary`` (earlier spelled ``pcast(..., to="varying")``);
    old releases have no vma tracking, so marking is a no-op identity."""
    import jax
    fn = getattr(jax.lax, "pvary", None)
    if fn is not None:
        return fn(x, axis_names)
    fn = getattr(jax.lax, "pcast", None)
    if fn is not None:
        return fn(x, axis_names, to="varying")
    return x


def force_cpu_platform(n_devices: Optional[int] = None) -> None:
    """Force THIS process's JAX onto the CPU backend.

    Must run before the first ``jax.devices()`` / backend initialization.
    Safe to call whether or not jax is already imported (the sitecustomize
    may have registered an accelerator plugin, but platform selection is
    still open until a backend is materialized).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        os.environ["XLA_FLAGS"] = _set_device_count(
            os.environ.get("XLA_FLAGS", ""), n_devices)
    import jax
    # an ambient sitecustomize may have set jax_platforms at config level,
    # which outranks the env var — override it the same way
    jax.config.update("jax_platforms", "cpu")
    # Env/config are only read at backend init, so a too-late call would
    # otherwise degrade silently — fail fast instead.  (This materializes
    # the CPU backend, which is fine: that's what we're forcing.)
    plat = jax.devices()[0].platform
    if plat != "cpu":
        raise RuntimeError(
            f"force_cpu_platform() too late: JAX backend already "
            f"initialized on '{plat}'; call it before the first "
            "jax.devices()/computation")
    if n_devices is not None:
        have = len(jax.devices())
        if have < n_devices:
            raise RuntimeError(
                f"force_cpu_platform({n_devices}) too late: JAX backend "
                f"already initialized with {have} CPU device(s); call it "
                "before the first jax.devices()/computation")
