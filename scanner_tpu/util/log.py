"""Engine logging.

The reference logs through glog with VLOG levels at every engine state
transition (scanner/util/glog.h; master.cpp/worker.cpp throughout).  Here
the stdlib `logging` hierarchy plays that role:

    scanner_tpu.master    control-plane transitions (admission, assignment,
                          revocation, blacklisting, worker liveness)
    scanner_tpu.worker    worker lifecycle + task outcomes
    scanner_tpu.engine    local executor pipeline

Like glog, warnings and errors are visible on stderr by DEFAULT — a
cluster worker retrying a failing pipeline must never be silent.
SCANNER_TPU_LOG (debug|info|warning|error) changes the level — the
operator-facing switch for debugging a wedged 16-host job.  Records also
propagate normally, so applications can route them through their own
logging configuration.

SCANNER_TPU_LOG_FORMAT=json switches the default handler to structured
output: one JSON object per line carrying ts/level/logger/msg plus the
active tracing context's trace_id/span_id (util/tracing.py), so logs
join traces in post-mortems — grep a task's trace_id from the straggler
summary and every log line that task's code path emitted lines up.
"""

from __future__ import annotations

import json
import logging
import os
import sys

_ROOT = "scanner_tpu"
_configured = False


class JsonFormatter(logging.Formatter):
    """One JSON object per record; trace_id/span_id pulled from the
    active tracing context so log lines join the assembled traces."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        try:
            # lazy: the formatter must not force tracing (and its
            # metrics registry) into processes that never trace
            from . import tracing
            ctx = tracing.current_context()
            if ctx is not None:
                out["trace_id"] = ctx.trace_id
                out["span_id"] = ctx.span_id
        except Exception:  # noqa: BLE001 — logging must never raise
            pass
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def _configure_once() -> None:
    global _configured
    if _configured:
        return
    _configured = True
    root = logging.getLogger(_ROOT)
    if root.handlers or logging.getLogger().handlers:
        # the application already configured logging (own handler on our
        # tree, or a root handler records propagate to) — don't add a
        # second stderr pipe that would double-print every record
        return
    level = logging.WARNING
    level_name = os.environ.get("SCANNER_TPU_LOG", "").strip()
    if level_name:
        parsed = getattr(logging, level_name.upper(), None)
        if isinstance(parsed, int):
            level = parsed
        else:
            print(f"scanner_tpu: SCANNER_TPU_LOG={level_name!r} is not a "
                  f"valid level", file=sys.stderr)
    handler = logging.StreamHandler(sys.stderr)
    if os.environ.get("SCANNER_TPU_LOG_FORMAT", "").strip().lower() \
            == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s %(message)s",
            datefmt="%H:%M:%S"))
    root.addHandler(handler)
    root.setLevel(level)


def get_logger(name: str) -> logging.Logger:
    """Logger under the scanner_tpu tree (e.g. get_logger('master'))."""
    _configure_once()
    return logging.getLogger(f"{_ROOT}.{name}")
