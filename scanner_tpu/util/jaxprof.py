"""Device-side (XLA/JAX) trace capture for the engine profiler.

SURVEY §5 tracing row: the reference records host-side interval spans
(scanner/util/profiler.cpp); the TPU equivalent must also see the DEVICE
timeline — XLA op execution, h2d/d2h transfers, compilation — or claims
like "h2d rides under decode" stay inferences from wall clocks.  At
``profiler_level >= 2`` the engine wraps a job's execution in
``jax.profiler.start_trace``/``stop_trace`` and records the trace
directory on the host profiler; ``Profile.write_trace`` then merges the
device timeline into the same Chrome-trace JSON so host stage spans and
device op execution land in ONE perfetto view.

Alignment: the XLA trace's ``ts`` values are microseconds relative to
``start_trace``, so events are shifted by the host wall-clock captured at
start (``t0``).  Device processes are offset into a distinct pid range so
they can never collide with the host profiler's node pids.

JAX allows one active trace per process; concurrent jobs (e.g. several
in-process workers in tests) serialize on a module lock — the first job
gets the device trace, the rest run untraced rather than erroring.
"""

from __future__ import annotations

import atexit
import contextlib
import glob
import gzip
import json
import logging
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

_log = logging.getLogger("scanner_tpu.jaxprof")

# one active jax.profiler trace per process
_ACTIVE = threading.Lock()

# Trace dumps are tens-to-hundreds of MB; auto-created dirs (no explicit
# out_dir) are deleted when this process exits so a long session of
# level-2 jobs cannot fill /tmp.  Callers who want to keep a capture
# (e.g. to open in TensorBoard/XProf) pass out_dir.
_AUTO_DIRS: List[str] = []


def _cleanup_auto_dirs() -> None:
    for d in _AUTO_DIRS:
        shutil.rmtree(d, ignore_errors=True)


atexit.register(_cleanup_auto_dirs)

# pid offset for merged device processes (host profiler pids are 1..N)
DEVICE_PID_BASE = 1000


@contextlib.contextmanager
def device_trace(profiler, out_dir: Optional[str] = None):
    """Capture the XLA device trace around a job when the profiler runs
    at level >= 2; no-op otherwise (and on any profiler failure — a
    broken tracer must never take down the job)."""
    if getattr(profiler, "level", 1) < 2:
        yield
        return
    if not _ACTIVE.acquire(blocking=False):
        _log.info("device trace already active in this process; "
                  "running untraced")
        yield
        return
    try:
        trace_dir = None
        auto = out_dir is None
        try:
            import jax
            trace_dir = out_dir or tempfile.mkdtemp(prefix="sc_devtrace_")
            t0 = time.time()
            jax.profiler.start_trace(trace_dir)
            if auto:
                _AUTO_DIRS.append(trace_dir)
        except Exception as e:  # noqa: BLE001
            _log.warning("jax.profiler.start_trace failed: %s", e)
            if auto and trace_dir is not None:
                shutil.rmtree(trace_dir, ignore_errors=True)
            yield
            return
        try:
            yield
        finally:
            try:
                jax.profiler.stop_trace()
                # t0/t1 bound the capture window on the host wall clock;
                # consumers align against THIS window, not the host
                # profiler's first span — under the level-2 python
                # tracer, trace start can precede the first stage span
                # by many seconds (thread bootstrap, instrumented
                # setup), which is trace content, not misalignment
                profiler.device_traces.append(
                    {"dir": trace_dir, "t0": t0, "t1": time.time()})
            except Exception as e:  # noqa: BLE001
                _log.warning("jax.profiler.stop_trace failed: %s", e)
    finally:
        _ACTIVE.release()


def _devtrace_event_cap() -> int:
    try:
        return int(os.environ.get("SCANNER_TPU_DEVTRACE_MAX_EVENTS",
                                  "200000") or 200000)
    except ValueError:
        return 200000


def _read_raw_events(rec: Dict[str, Any],
                     include_python: bool = False) -> List[Dict[str, Any]]:
    """Unshifted device-trace events for one capture record: the
    embedded ``events`` list when present (a profile that crossed
    hosts), else read from the local trace directory."""
    if "events" in rec:
        return rec["events"]
    files = sorted(glob.glob(
        os.path.join(rec["dir"], "**", "*.trace.json.gz"), recursive=True))
    out: List[Dict[str, Any]] = []
    for path in files:
        try:
            with gzip.open(path) as f:
                doc = json.load(f)
        except Exception as e:  # noqa: BLE001
            _log.warning("unreadable device trace %s: %s", path, e)
            continue
        for ev in doc.get("traceEvents", []):
            if not include_python and \
                    str(ev.get("name", "")).startswith("$"):
                continue
            out.append(ev)
    return out


def embed_device_events(rec: Dict[str, Any],
                        max_events: Optional[int] = None
                        ) -> Dict[str, Any]:
    """Serialize the capture's device events INTO the record (mutates
    and returns it) so the profile survives crossing hosts.

    Cross-host fix: only the local trace *directory* path used to
    travel with a shipped profile, so ``load_device_events`` on the
    master returned [] and merged traces silently lost every remote
    device timeline.  Workers call this before ``PostProfile``; bounded
    by SCANNER_TPU_DEVTRACE_MAX_EVENTS (default 200000, longest-first
    truncation recorded in ``events_dropped``) so a verbose capture
    cannot blow the RPC message cap."""
    if "events" in rec:
        return rec
    evs = _read_raw_events(rec)
    cap = _devtrace_event_cap() if max_events is None else max_events
    # Chrome 'M' metadata (process/thread names) is exempt from the
    # cap: dur-less, a handful per capture, and dropping it would
    # render remote device lanes as bare pid numbers
    meta = [e for e in evs if e.get("ph") == "M"]
    rest = [e for e in evs if e.get("ph") != "M"]
    if len(rest) > cap:
        # keep the longest slices: truncation should cost the noise
        # floor, not the dominant kernels
        rest.sort(key=lambda e: -float(e.get("dur", 0.0) or 0.0))
        rec["events_dropped"] = len(rest) - cap
        rest = rest[:cap]
    rec["events"] = meta + rest
    return rec


def load_device_events(rec: Dict[str, Any],
                       pid_base: int = DEVICE_PID_BASE,
                       include_python: bool = False
                       ) -> List[Dict[str, Any]]:
    """Load one recorded device trace as Chrome trace events, shifted to
    the host wall clock and into the device pid range.

    ``rec`` is a ``{"dir": ..., "t0": ...}`` entry from
    ``Profiler.device_traces``; records that crossed hosts carry their
    events inline (``embed_device_events``) and need no filesystem.
    Returns [] when neither embedded events nor a readable local
    directory exist.  The profiler's Python-call spans (names prefixed
    ``$``, tens of thousands per job) drown the device lanes and
    duplicate what the host profiler already records; they are dropped
    unless ``include_python=True``."""
    raw = _read_raw_events(rec, include_python=include_python)
    shift_us = rec["t0"] * 1e6
    out: List[Dict[str, Any]] = []
    for ev in raw:
        if not include_python and str(ev.get("name", "")).startswith("$"):
            continue
        ev = dict(ev)
        if "pid" in ev:
            ev["pid"] = pid_base + int(ev["pid"])
        if "ts" in ev and ev.get("ph") != "M":
            ev["ts"] = float(ev["ts"]) + shift_us
        out.append(ev)
    return out
