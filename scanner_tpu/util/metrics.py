"""Live cluster telemetry: a process-wide metrics registry.

The reference Scanner's observability is post-mortem only — per-thread
interval traces shipped to the master after a job finishes
(scanner/util/profiler.h; our util/profiler.py matches it).  This module
adds the live half: every process keeps one `MetricsRegistry` of
`Counter`/`Gauge`/`Histogram` series that hot paths update as they run,
and three consumers read it

  * a stdlib-http `MetricsServer` serving `/metrics` (Prometheus text
    exposition), `/healthz`, and `/statusz` (JSON) — off by default,
    enabled per process via `metrics_port=` on Client/Master/Worker;
  * the master's `GetMetrics` RPC, which merges worker snapshots into a
    cluster-wide view (`Client.metrics()`);
  * `tools/scanner_top.py`, a polling CLI over both.

Design constraints, in order:

  1. Disabled-path cost ~zero.  Recording always happens (there is no
     global on/off — a gauge nobody scrapes is just a slot write), so
     the fast path must be cheap enough to sit on per-batch code:
     counter/histogram writes go to per-THREAD cells (no lock, no
     contention — the same append-only-per-thread trick as
     util/profiler.py) and are summed only at snapshot time.  Only
     child creation takes a lock.
  2. Names are contracts.  Every series name must match
     ``scanner_tpu_[a-z0-9_]+`` and carry a help string — dashboards
     break silently otherwise; tests/test_metrics.py lints the live
     registry.
  3. Snapshots are plain msgpack-able dicts so they travel over the
     existing RPC plane unchanged.
"""

from __future__ import annotations

import json
import re
import threading
import time
import weakref
from bisect import bisect_left
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

NAME_RE = re.compile(r"scanner_tpu_[a-z0-9_]+\Z")

# default histogram buckets: latency-shaped, 1ms..10s (upper bounds;
# the +Inf bucket is implicit)
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


class MetricsError(Exception):
    pass


# ---------------------------------------------------------------------------
# Metric children (one per label combination; hold the actual cells)
# ---------------------------------------------------------------------------

# once a child holds this many per-thread cells, cell registration (the
# slow path) folds dead-thread cells inline — an unscraped process that
# keeps spawning stage threads must not grow without bound just because
# nobody ever calls value()
_FOLD_THRESHOLD = 64


def _dead(owner) -> bool:
    t = owner() if owner is not None else None
    return owner is not None and (t is None or not t.is_alive())


class _CounterChild:
    """Monotonic float counter.  inc() writes a per-thread cell: the
    cell list is owned by one thread, so `cell[0] += n` never races —
    the lock-free fast path.  Cells of dead threads fold into a retained
    total at read time AND whenever a new cell registers past a size
    threshold, so neither scraped nor unscraped processes leak cells
    (owners are held by weakref — a dead cell must not pin its Thread)."""

    __slots__ = ("_local", "_cells", "_retained", "_lock")

    def __init__(self):
        self._local = threading.local()
        # (weakref-to-owning-thread, cell); owner=None is never folded
        self._cells: List[Tuple[Any, List[float]]] = []
        self._retained = 0.0
        self._lock = threading.Lock()

    def _fold_locked(self) -> None:
        live = []
        for owner, cell in self._cells:
            if _dead(owner):
                # the owner finished: its cell can never change again
                self._retained += cell[0]
            else:
                live.append((owner, cell))
        self._cells = live

    def inc(self, n: float = 1.0) -> None:
        try:
            self._local.cell[0] += n
        except AttributeError:
            cell = [0.0]
            with self._lock:
                if len(self._cells) >= _FOLD_THRESHOLD:
                    self._fold_locked()
                self._cells.append(
                    (weakref.ref(threading.current_thread()), cell))
            self._local.cell = cell
            cell[0] += n

    def value(self) -> float:
        with self._lock:
            self._fold_locked()
            return self._retained + sum(c[0] for _o, c in self._cells)


class _GaugeChild:
    """Point-in-time value.  set() is a single slot write; set_function
    defers to a callable sampled at snapshot time (live queue depths)."""

    __slots__ = ("_value", "_fn", "_lock")

    def __init__(self):
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        # single GIL-atomic slot store; only the read-modify-write
        # paths (inc/dec) need the lock
        self._value = float(v)  # scanner-check: disable=SC203

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def set_function(self, fn: Optional[Callable[[], float]]) -> None:
        """Sample `fn()` at scrape time instead of a stored value; pass
        None to detach (the gauge reverts to its stored value).  Locked
        so clear_function's check-then-clear cannot race a new owner's
        install."""
        with self._lock:
            self._fn = fn

    def clear_function(self, expected: Callable[[], float]) -> bool:
        """Detach only if `expected` is still the installed sampler —
        a finished owner must not blind a newer one that re-bound the
        gauge (== so equal bound methods of one object match)."""
        with self._lock:
            if self._fn is not None and self._fn == expected:
                self._fn = None
                return True
            return False

    def value(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:  # noqa: BLE001 — scrape must never raise
                return 0.0
        return self._value


class _HistCell:
    __slots__ = ("buckets", "sum", "count")

    def __init__(self, n_buckets: int):
        self.buckets = [0] * n_buckets
        self.sum = 0.0
        self.count = 0


class _HistogramChild:
    """Fixed-bucket histogram; per-thread cells (and dead-thread cell
    folding, both at read time and in the registration slow path) like
    _CounterChild."""

    __slots__ = ("_uppers", "_local", "_cells", "_retained", "_lock")

    def __init__(self, uppers: Sequence[float]):
        self._uppers = list(uppers)
        self._local = threading.local()
        self._cells: List[Tuple[Any, _HistCell]] = []
        self._retained = _HistCell(len(uppers) + 1)
        self._lock = threading.Lock()

    def _fold_locked(self) -> None:
        live = []
        for owner, cell in self._cells:
            if _dead(owner):
                self._add(self._retained, cell)
            else:
                live.append((owner, cell))
        self._cells = live

    def observe(self, v: float) -> None:
        try:
            cell = self._local.cell
        except AttributeError:
            cell = _HistCell(len(self._uppers) + 1)
            with self._lock:
                if len(self._cells) >= _FOLD_THRESHOLD:
                    self._fold_locked()
                self._cells.append(
                    (weakref.ref(threading.current_thread()), cell))
            self._local.cell = cell
        # Prometheus buckets are upper-INCLUSIVE: v <= le lands in the
        # bucket; bisect_left finds the first upper >= v, len(uppers)
        # means +Inf
        cell.buckets[bisect_left(self._uppers, v)] += 1
        cell.sum += v
        cell.count += 1

    @staticmethod
    def _add(dst: _HistCell, src: _HistCell) -> None:
        for i, b in enumerate(src.buckets):
            dst.buckets[i] += b
        dst.sum += src.sum
        dst.count += src.count

    def value(self) -> Dict[str, Any]:
        acc = _HistCell(len(self._uppers) + 1)
        with self._lock:
            self._fold_locked()
            self._add(acc, self._retained)
            for _owner, cell in self._cells:
                self._add(acc, cell)
        return {"buckets": acc.buckets, "sum": acc.sum, "count": acc.count}


_CHILD_CLS = {"counter": _CounterChild, "gauge": _GaugeChild}


class Metric:
    """One named series family; children per label combination.  An
    unlabeled metric delegates inc/set/observe to its single child."""

    def __init__(self, name: str, kind: str, help: str,
                 label_names: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        if not NAME_RE.fullmatch(name):
            raise MetricsError(
                f"metric name {name!r} must match {NAME_RE.pattern}")
        if not help or not help.strip():
            raise MetricsError(f"metric {name} needs a help string")
        self.name = name
        self.kind = kind
        self.help = help.strip()
        self.label_names = tuple(label_names)
        self.buckets = list(buckets if buckets is not None
                            else DEFAULT_BUCKETS) \
            if kind == "histogram" else None
        self._children: Dict[Tuple[str, ...], Any] = {}
        self._lock = threading.Lock()
        if not self.label_names:
            self._default = self._make_child()
            self._children[()] = self._default

    def _make_child(self):
        if self.kind == "histogram":
            return _HistogramChild(self.buckets)
        return _CHILD_CLS[self.kind]()

    def labels(self, **kv: str):
        if set(kv) != set(self.label_names):
            raise MetricsError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.label_names)}")
        key = tuple(str(kv[k]) for k in self.label_names)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make_child()
                    self._children[key] = child
        return child

    def remove_labels(self, **kv: str) -> None:
        """Drop one label combination's child (e.g. a departed worker's
        heartbeat-age gauge) so long-lived processes with churning label
        values don't grow the scrape output without bound."""
        if set(kv) != set(self.label_names):
            raise MetricsError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.label_names)}")
        key = tuple(str(kv[k]) for k in self.label_names)
        with self._lock:
            self._children.pop(key, None)

    # unlabeled convenience delegates
    def inc(self, n: float = 1.0) -> None:
        self._default.inc(n)

    def set(self, v: float) -> None:
        self._default.set(v)

    def dec(self, n: float = 1.0) -> None:
        self._default.dec(n)

    def set_function(self, fn: Optional[Callable[[], float]]) -> None:
        self._default.set_function(fn)

    def clear_function(self, expected: Callable[[], float]) -> bool:
        return self._default.clear_function(expected)

    def observe(self, v: float) -> None:
        self._default.observe(v)

    def samples(self) -> List[dict]:
        with self._lock:
            items = list(self._children.items())
        out = []
        for key, child in items:
            labels = dict(zip(self.label_names, key))
            v = child.value()
            if self.kind == "histogram":
                out.append({"labels": labels, **v})
            else:
                out.append({"labels": labels, "value": v})
        return out


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

class MetricsRegistry:
    """Name -> Metric; registration is idempotent (module reloads and
    repeated constructors get the same series) but kind/labels must
    agree — silent redefinition is exactly the dashboard drift the
    name lint exists to prevent."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def _register(self, name: str, kind: str, help: str,
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind or m.label_names != tuple(labels):
                    raise MetricsError(
                        f"metric {name} re-registered as {kind}"
                        f"{tuple(labels)} (was {m.kind}{m.label_names})")
                return m
            m = Metric(name, kind, help, labels, buckets)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str,
                labels: Sequence[str] = ()) -> Metric:
        return self._register(name, "counter", help, labels)

    def gauge(self, name: str, help: str,
              labels: Sequence[str] = ()) -> Metric:
        return self._register(name, "gauge", help, labels)

    def histogram(self, name: str, help: str, labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Metric:
        return self._register(name, "histogram", help, labels, buckets)

    def metrics(self) -> List[Metric]:
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> Dict[str, dict]:
        """All series as one plain (msgpack-able) dict:
        {name: {kind, help, [uppers], samples: [{labels, value|buckets+
        sum+count}]}}."""
        out: Dict[str, dict] = {}
        for m in self.metrics():
            entry: Dict[str, Any] = {"kind": m.kind, "help": m.help,
                                     "samples": m.samples()}
            if m.kind == "histogram":
                entry["uppers"] = list(m.buckets)
            out[m.name] = entry
        return out


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry (one per process, like the reference's
    per-process profiler)."""
    return _REGISTRY


def labeled_samples(snapshot: Dict[str, dict], series: str
                    ) -> Dict[str, float]:
    """Flatten one series of a snapshot to {sorted-label-json: value}.
    The stable keying the per-device utilization digests compare across
    runs and processes (bench.py `multichip`, tools/tpu_window.py, the
    tests/test_multichip.py equivalence suite): label order never leaks
    into the key, so `{"device": "tpu:3", "op": "Histogram"}` is the
    same sample wherever it was produced."""
    return {json.dumps(s["labels"], sort_keys=True): s["value"]
            for s in snapshot.get(series, {}).get("samples", [])}


# process start time: lets consumers turn since-start counter values
# into rates without a second poll (standard Prometheus practice)
_REGISTRY.gauge(
    "scanner_tpu_process_start_time_seconds",
    "Unix time this process's metrics registry was created.",
).set(time.time())


# ---------------------------------------------------------------------------
# Histogram quantile estimation (shared: SLO engine, bench, tools)
# ---------------------------------------------------------------------------

def histogram_quantile(uppers: Sequence[float],
                       buckets: Sequence[float],
                       q: float) -> Optional[float]:
    """Estimate the q-quantile from per-bucket counts (len(buckets) ==
    len(uppers) + 1; the extra final bucket is +Inf).  Linear
    interpolation inside the bucket containing the target rank — the
    same estimate PromQL's histogram_quantile makes.  Returns None for
    an empty histogram; observations landing in the +Inf bucket clamp
    to the highest finite upper bound (there is nothing to interpolate
    toward)."""
    total = float(sum(buckets))
    if total <= 0:
        return None
    target = q * total
    edges = [0.0] + list(uppers)
    acc = 0.0
    for i, c in enumerate(buckets):
        if acc + c >= target and c > 0:
            if i >= len(uppers):
                # +Inf bucket: clamp to the last finite bound
                return float(uppers[-1]) if uppers else None
            lo, hi = edges[i], uppers[i]
            return lo + (hi - lo) * (target - acc) / c
        acc += c
    return float(uppers[-1]) if uppers else None


def snapshot_histogram_quantiles(snapshot: Dict[str, dict], series: str,
                                 qs: Sequence[float] = (0.5, 0.9, 0.99)
                                 ) -> Dict[str, Any]:
    """Aggregate every sample of a histogram series in a (plain or
    merged) snapshot and estimate quantiles: {"count", "mean_s",
    "p50_s", ...}, or {} when the series is absent or empty.  The
    digest shape bench.py banks and tools consume."""
    e = snapshot.get(series)
    if not e or not e.get("samples"):
        return {}
    uppers = list(e.get("uppers") or [])
    buckets: Optional[List[float]] = None
    total, ssum = 0, 0.0
    for smp in e["samples"]:
        b = smp.get("buckets")
        if not b:
            continue
        if buckets is None:
            buckets = [0.0] * len(b)
        for i, v in enumerate(b):
            buckets[i] += v
        total += smp.get("count", 0)
        ssum += smp.get("sum", 0.0)
    if not buckets or not total:
        return {}
    out: Dict[str, Any] = {"count": int(total),
                           "mean_s": round(ssum / total, 4)}
    for q in qs:
        v = histogram_quantile(uppers, buckets, q)
        out[f"p{int(q * 100)}_s"] = round(v, 4) if v is not None else None
    return out


# ---------------------------------------------------------------------------
# Snapshot merging (master aggregates workers)
# ---------------------------------------------------------------------------

def merge_snapshots(by_node: Dict[str, Dict[str, dict]]) -> Dict[str, dict]:
    """Merge per-node snapshots into one cluster view: every sample gains
    a `node` label, so per-node series stay distinguishable (summing
    counters across nodes would hide exactly the per-worker skew live
    debugging is for)."""
    merged: Dict[str, dict] = {}
    for node, snap in by_node.items():
        for name, entry in snap.items():
            tgt = merged.get(name)
            if tgt is None:
                tgt = {k: v for k, v in entry.items() if k != "samples"}
                tgt["samples"] = []
                merged[name] = tgt
            for s in entry.get("samples", []):
                s2 = dict(s)
                s2["labels"] = {"node": str(node), **s.get("labels", {})}
                tgt["samples"].append(s2)
    return merged


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _esc_label(v: str) -> str:
    return str(v).replace("\\", r"\\").replace("\n", r"\n") \
        .replace('"', r'\"')


def _fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_esc_label(v)}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_val(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_prometheus(snapshot: Dict[str, dict]) -> str:
    """Render a snapshot (plain or merged) as Prometheus text exposition
    version 0.0.4."""
    lines: List[str] = []
    for name in sorted(snapshot):
        entry = snapshot[name]
        kind = entry["kind"]
        lines.append(f"# HELP {name} "
                     + entry.get("help", "").replace("\n", " "))
        lines.append(f"# TYPE {name} {kind}")
        for s in entry.get("samples", []):
            labels = s.get("labels", {})
            if kind == "histogram":
                uppers = entry.get("uppers", [])
                cum = 0
                for upper, b in zip(list(uppers) + ["+Inf"],
                                    s.get("buckets", [])):
                    cum += b
                    le = "+Inf" if upper == "+Inf" else _fmt_val(upper)
                    le_label = 'le="' + le + '"'
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, le_label)} {cum}")
                lines.append(f"{name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_val(s.get('sum', 0.0))}")
                lines.append(f"{name}_count{_fmt_labels(labels)} "
                             f"{int(s.get('count', 0))}")
            else:
                lines.append(f"{name}{_fmt_labels(labels)} "
                             f"{_fmt_val(s.get('value', 0.0))}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# HTTP endpoint (stdlib only; one daemon thread per server)
# ---------------------------------------------------------------------------

class MetricsServer:
    """Serves /metrics (Prometheus text), /healthz, /readyz, /alertz
    and /statusz (JSON) on a daemon thread.  Off unless a process
    explicitly constructs one (Client/Master/Worker `metrics_port=`);
    port=0 binds an ephemeral port (see `.port`).  Binds loopback by
    default — the endpoint is unauthenticated and /statusz names db
    paths and cluster topology; Master/Worker pass host="0.0.0.0"
    (overridable via `metrics_host=`) because cross-host Prometheus
    scraping is their point.

    /healthz reflects the health engine's roll-up (util/health.py) in
    its BODY (`status`, reason codes; `ok` flips false on `unhealthy`)
    but always answers 200 while the process is alive — it is the
    liveness surface, and alert states are workload facts a restart
    cannot fix.  /readyz is the gate that goes 503 while the roll-up
    is `unhealthy` or `ready()` is false (a SIGTERM drain: not-ready,
    still-alive), so k8s stops routing instead of restarting.
    /alertz serves the firing alerts plus the full rule table."""

    def __init__(self, port: int = 0,
                 reg: Optional[MetricsRegistry] = None,
                 statusz: Optional[Callable[[], dict]] = None,
                 healthz: Optional[Callable[[], dict]] = None,
                 host: str = "127.0.0.1",
                 health: Optional[Callable[[], dict]] = None,
                 ready: Optional[Callable[[], bool]] = None,
                 alertz: Optional[Callable[[], dict]] = None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        reg = reg or registry()
        outer = self
        self._statusz = statusz
        self._healthz = healthz
        self._health = health
        self._ready = ready
        self._alertz = alertz

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence per-request stderr spam
                pass

            def _send(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib handler API)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        body = render_prometheus(reg.snapshot()).encode()
                        self._send(200, "text/plain; version=0.0.4; "
                                        "charset=utf-8", body)
                    elif path == "/healthz":
                        extra = outer._healthz() if outer._healthz else {}
                        roll = outer._health_rollup()
                        # ALWAYS 200 while the process can answer:
                        # /healthz is the LIVENESS surface, and alert
                        # states (HBM pressure, latency burn) are
                        # workload facts a restart cannot fix — a 503
                        # here would restart-loop pods under legitimate
                        # sustained load.  The body still carries the
                        # roll-up (ok=false on `unhealthy`) for humans
                        # and scripts; /readyz is the surface that
                        # goes 503 so k8s stops ROUTING instead.
                        ok = roll.get("status", "ok") != "unhealthy"
                        self._send(200, "application/json",
                                   json.dumps({"ok": ok, **roll,
                                               **extra}).encode())
                    elif path == "/readyz":
                        roll = outer._health_rollup()
                        rdy = roll.get("status", "ok") != "unhealthy"
                        if rdy and outer._ready is not None:
                            rdy = bool(outer._ready())
                        self._send(200 if rdy else 503,
                                   "application/json",
                                   json.dumps({"ready": rdy, **roll})
                                   .encode())
                    elif path == "/alertz":
                        body = outer._alertz_body()
                        self._send(200, "application/json",
                                   json.dumps(body, default=str)
                                   .encode())
                    elif path == "/statusz":
                        st = outer._statusz() if outer._statusz else {}
                        self._send(200, "application/json",
                                   json.dumps(st, default=str).encode())
                    else:
                        self._send(404, "text/plain", b"not found\n")
                except Exception as e:  # noqa: BLE001 — a scrape bug
                    # must not kill the serving thread
                    try:
                        self._send(500, "text/plain",
                                   f"{type(e).__name__}: {e}\n".encode())
                    except Exception:  # noqa: BLE001
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-http",
            daemon=True)
        self._thread.start()

    def _health_rollup(self) -> dict:
        """status + reason codes for /healthz and /readyz: the injected
        callback, or the process-wide health engine's roll-up (lazy
        import — health builds on this module)."""
        try:
            if self._health is not None:
                return self._health()
            from . import health as _health
            return _health.rollup()
        except Exception:  # noqa: BLE001 — a health bug must not make
            # the liveness probe lie about the process being alive
            return {"status": "ok", "reasons": []}

    def _alertz_body(self) -> dict:
        try:
            if self._alertz is not None:
                return self._alertz()
            from . import health as _health
            return _health.alertz_dict()
        except Exception as e:  # noqa: BLE001
            return {"status": "ok", "error": f"{type(e).__name__}: {e}"}

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
