"""Compute-efficiency observability: per-op cost model, roofline
attribution, and an XLA compile ledger.

The time plane (util/tracing.py, util/profiler.py) says an op took
3.1 ms on chip 2; the memory plane (util/memstats.py) says whose bytes
live there; the health plane (util/health.py) says whether that is
normal.  None of them says whether 3.1 ms is *good* — 80% of what the
chip can do, or 4%.  And the recompile proxy counts new signatures
without ever recording what XLA actually compiled, how long it took, or
whether the persistent cache hit.  This module is the missing
efficiency plane, two halves:

  * **The compile ledger** — every jitted-kernel compile observed at
    the engine's dispatch/warm-up sites (engine/evaluate.py) records
    (op, device, bucket, signature, compile seconds, persistent-cache
    hit|miss|uncached, executable size and XLA's own analytical cost
    where the backend provides them) into a bounded per-process ring,
    the ``scanner_tpu_compile_*`` series, and an ``xla.compile`` event
    on the owning task's trace span.  Served over the
    ``GetCompileLedger`` RPC / ``Client.compile_report()``.  Compile
    facts come from two sources: the *supported* ``jax.monitoring``
    event stream (backend compile durations, persistent-cache
    hit/miss), and a best-effort wrap of jax's internal compile entry
    point that hands us the loaded executable for
    ``cost_analysis()`` / ``memory_analysis()`` — guarded so jax
    version drift degrades ledger entries, never the engine.
  * **Roofline attribution** — an analytical per-op cost descriptor
    (FLOPs and bytes in/out as a function of the call shape, declared
    via the ``Kernel.cost(shapes)`` hook with defaults derived from
    XLA's cost analysis of the compiled executable) joined with the
    measured per-call seconds the dispatch site already takes, into
    achieved FLOP/s, achieved bytes/s, and a compute-vs-memory-bound
    classification per (op, device, bucket) — the
    ``scanner_tpu_op_*`` efficiency gauges.  A slow task then reads as
    *inefficient* (low EFF%) or *overloaded* (high EFF%, long queue),
    which is the question straggler analytics could not answer.

Consumers: the /statusz Efficiency panel, ``scanner_top`` EFF%/bound
columns and compile-cache hit rate, the bench.py ``op_efficiency``
digest in BENCH_DETAIL.json, and ``tools/scanner_cost.py``.

Knobs: ``SCANNER_TPU_COSTSTATS=0`` disables both halves (the dispatch
sites then skip descriptor/ledger work entirely);
``SCANNER_TPU_COMPILE_LEDGER`` sizes the ring (default 1024 entries).
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import metrics as _mx
from . import tracing as _tracing
from .log import get_logger

_log = get_logger("coststats")

# -- live series (docs/observability.md §Efficiency & Compilation) ----------

_M_COMPILES = _mx.registry().counter(
    "scanner_tpu_compile_total",
    "XLA backend compiles observed at the engine's dispatch/warm-up "
    "sites, by op, device and persistent-compilation-cache outcome "
    "(hit = executable deserialized from the cache, miss = cache "
    "configured but cold, uncached = no persistent cache configured).",
    labels=["op", "device", "cache"])
_M_COMPILE_SECONDS = _mx.registry().counter(
    "scanner_tpu_compile_seconds_total",
    "Wall seconds spent inside XLA backend compiles (including "
    "persistent-cache retrieval time on hits) per op and device — the "
    "compile bill the recompile counter only counted.",
    labels=["op", "device"])
_M_COMPILE_EXEC_BYTES = _mx.registry().counter(
    "scanner_tpu_compile_executable_bytes_total",
    "Generated-code bytes of executables minted at observed compiles, "
    "per op and device (0 when the backend reports no code size) — "
    "the executable footprint the bucket ladder bounds.",
    labels=["op", "device"])
_M_OP_FLOPS = _mx.registry().gauge(
    "scanner_tpu_op_achieved_flops",
    "Achieved FLOP/s per (op, device, bucket): analytical FLOPs from "
    "the op's cost descriptor divided by measured kernel-call seconds "
    "(compile-bearing first calls excluded).  0 when the descriptor "
    "declares no FLOPs (pure data movement).",
    labels=["op", "device", "bucket"])
_M_OP_BW = _mx.registry().gauge(
    "scanner_tpu_op_achieved_bandwidth_bytes",
    "Achieved bytes/s per (op, device, bucket): descriptor bytes "
    "in+out over measured kernel-call seconds.",
    labels=["op", "device", "bucket"])
_M_OP_EFF = _mx.registry().gauge(
    "scanner_tpu_op_efficiency_ratio",
    "Roofline efficiency per (op, device, bucket): achieved rate over "
    "the device's peak for the binding resource — FLOP/s over peak "
    "FLOP/s when compute-bound, bytes/s over peak bandwidth when "
    "memory-bound.  1.0 = at the roofline.",
    labels=["op", "device", "bucket"])
_M_OP_BOUND = _mx.registry().gauge(
    "scanner_tpu_op_compute_bound",
    "Roofline classification per (op, device, bucket): 1 = "
    "compute-bound (operational intensity above the device ridge "
    "point), 0 = memory-bound (below it, or FLOPs unknown).",
    labels=["op", "device", "bucket"])

# the series this module owns, in one statically-readable tuple:
# scanner-check SC309 keeps it, the registrations above, and the
# marker-delimited catalog table in docs/observability.md in sync
EFFICIENCY_SERIES = (
    "scanner_tpu_compile_total",
    "scanner_tpu_compile_seconds_total",
    "scanner_tpu_compile_executable_bytes_total",
    "scanner_tpu_op_achieved_flops",
    "scanner_tpu_op_achieved_bandwidth_bytes",
    "scanner_tpu_op_efficiency_ratio",
    "scanner_tpu_op_compute_bound",
)

# same knob semantics as SCANNER_TPU_TRACING / _MEMSTATS (one parser)
_ENABLED = _tracing._env_on("SCANNER_TPU_COSTSTATS")


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Programmatic override (tests, embedders); the
    SCANNER_TPU_COSTSTATS env var is read at import and is the
    per-process default."""
    global _ENABLED
    _ENABLED = bool(on)


def _env_ring_size() -> int:
    import os
    try:
        return max(16, int(os.environ.get("SCANNER_TPU_COMPILE_LEDGER",
                                          "1024") or 1024))
    except ValueError:
        return 1024


# ---------------------------------------------------------------------------
# Cost descriptors
# ---------------------------------------------------------------------------

@dataclass
class CostDescriptor:
    """Analytical cost of ONE kernel call: floating-point operations
    and bytes moved in/out as the kernel's ``cost(shapes)`` hook
    declared them (``source="hook"``), as XLA's cost analysis of the
    compiled executable measured them (``source="derived"``), or as
    the dispatch site observed from live argument bytes when neither
    exists (``source="observed"``: bytes only, FLOPs unknown)."""

    flops: Optional[float] = None
    bytes_in: Optional[float] = None
    bytes_out: Optional[float] = None
    source: str = "hook"

    @property
    def bytes_total(self) -> float:
        return float(self.bytes_in or 0.0) + float(self.bytes_out or 0.0)


# ---------------------------------------------------------------------------
# Device peaks (the roofline)
# ---------------------------------------------------------------------------

# (device_kind substring, peak dense-bf16 FLOP/s, peak HBM bytes/s) per
# chip generation — public spec-sheet numbers, matched case-insensitively
# against jax's device_kind.  The table is a *reference* roofline:
# EFF% compares kernels against each other and across rounds on the
# same chip; absolute calibration rides on these constants.
DEVICE_PEAKS = (
    ("v6e", 918e12, 1.64e12),
    ("v5p", 459e12, 2.765e12),
    ("v5e", 197e12, 8.19e11),
    ("v5 lite", 197e12, 8.19e11),
    ("v4", 275e12, 1.228e12),
    ("v3", 123e12, 9.0e11),
    ("v2", 46e12, 7.0e11),
)
# generic accelerator fallback when no generation substring matches
_GENERIC_TPU_PEAK = (197e12, 8.19e11)
# host fallback: order-of-magnitude for a few AVX cores — CPU EFF% is
# indicative only (tests pin behavior through set_device_peaks)
_CPU_PEAK = (2e11, 5e10)

_peak_lock = threading.Lock()
_peak_overrides: Dict[str, Tuple[float, float]] = {}
_peak_cache: Dict[str, Tuple[float, float]] = {}


def set_device_peaks(device_label: str, peak_flops: float,
                     peak_bytes_per_s: float) -> None:
    """Override the roofline for one device label (calibration from a
    measured microbench, or a synthetic peak in tests)."""
    with _peak_lock:
        _peak_overrides[device_label] = (float(peak_flops),
                                         float(peak_bytes_per_s))
        _peak_cache.pop(device_label, None)


def _device_kind(device_label: str) -> str:
    """jax's device_kind string for a metrics device label ("tpu:3"),
    or "" when unresolvable (no jax, label "default", drift)."""
    try:
        import sys
        if sys.modules.get("jax") is None:
            return ""
        import jax
        from . import memstats as _ms
        for d in jax.local_devices():
            if _ms.device_label(d) == device_label:
                return str(getattr(d, "device_kind", "") or "")
        if device_label == "default" and jax.local_devices():
            return str(getattr(jax.local_devices()[0],
                               "device_kind", "") or "")
    except Exception:  # noqa: BLE001 — peaks must never raise
        pass
    return ""


def device_peaks(device_label: str) -> Tuple[float, float]:
    """(peak FLOP/s, peak bytes/s) for a device label: explicit
    override > generation match on jax's device_kind > platform
    fallback."""
    with _peak_lock:
        if device_label in _peak_overrides:
            return _peak_overrides[device_label]
        if device_label in _peak_cache:
            return _peak_cache[device_label]
    kind = _device_kind(device_label).lower()
    platform = device_label.split(":", 1)[0]
    peak = None
    for sub, f, b in DEVICE_PEAKS:
        if sub in kind:
            peak = (f, b)
            break
    if peak is None:
        if "tpu" in (kind or platform):
            peak = _GENERIC_TPU_PEAK
        else:
            peak = _CPU_PEAK
    with _peak_lock:
        _peak_cache[device_label] = peak
    return peak


def block_until_ready(res: Any) -> Any:
    """Wait for a kernel call's device work before timing it: on async
    backends (TPU) execute() returns at enqueue, and host wall time
    would measure the dispatch overhead, not the op — inflating
    achieved FLOP/s past the roofline.  One sync per MEASURED chunk
    call (compile-bearing calls are not measured); disabling coststats
    removes it.  Pass-through (and guarded) for host-only results."""
    try:
        import jax
        return jax.block_until_ready(res)
    except Exception:  # noqa: BLE001 — timing aid must not fail a task
        return res


def classify(device_label: str, flops: Optional[float],
             bytes_total: float, seconds: float
             ) -> Optional[Dict[str, Any]]:
    """Roofline verdict for measured work: achieved rates plus the
    binding resource and its efficiency.  None when there is nothing
    to judge (no time, or neither FLOPs nor bytes known)."""
    if seconds <= 0:
        return None
    peak_f, peak_b = device_peaks(device_label)
    f_rate = (flops or 0.0) / seconds
    b_rate = bytes_total / seconds
    if flops and bytes_total:
        # operational intensity vs the ridge point decides the bound
        compute = (flops / bytes_total) >= (peak_f / peak_b)
    elif flops:
        compute = True
    elif bytes_total:
        compute = False
    else:
        return None
    eff = (f_rate / peak_f) if compute else (b_rate / peak_b)
    return {"flops_per_s": f_rate, "bytes_per_s": b_rate,
            "bound": "compute" if compute else "memory",
            "eff": eff}


# ---------------------------------------------------------------------------
# Compile observation
# ---------------------------------------------------------------------------

# jax.monitoring event names (stable across the 0.4.x line)
_EV_BACKEND_COMPILE = "/jax/core/compile/backend_compile_duration"
_EV_CACHE_HIT = "/jax/compilation_cache/cache_hits"
_EV_CACHE_MISS = "/jax/compilation_cache/cache_misses"

_tls = threading.local()


class _CompileCtx:
    """Per-observation scratch the global listeners write into: one per
    observe_compiles() block, on the observing thread (XLA compiles run
    synchronously on the calling thread, so thread-local is exact)."""

    __slots__ = ("op", "device", "bucket", "signature", "members",
                 "compiles", "pending_cache", "flops", "bytes_accessed",
                 "arg_bytes", "out_bytes", "temp_bytes", "exec_bytes",
                 "analyzed")

    def __init__(self, op: str, device: str, bucket: int, signature: str,
                 members: Optional[Sequence[str]] = None):
        self.op = op
        self.device = device
        self.bucket = int(bucket)
        self.signature = signature
        self.members = list(members) if members is not None else None
        self.compiles: List[Tuple[float, str]] = []  # (seconds, cache)
        self.pending_cache: Optional[str] = None
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.arg_bytes = 0
        self.out_bytes = 0
        self.temp_bytes = 0
        self.exec_bytes = 0
        self.analyzed = 0

    def absorb_executable(self, ex: Any) -> None:
        """Analytical cost from a freshly-compiled executable
        (best-effort: absent methods / drift leave the fields zero)."""
        try:
            ca = ex.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            self.flops += float(ca.get("flops", 0.0) or 0.0)
            self.bytes_accessed += float(
                ca.get("bytes accessed", 0.0) or 0.0)
        except Exception:  # noqa: BLE001
            pass
        try:
            ms = ex.get_compiled_memory_stats()
            self.arg_bytes += int(
                getattr(ms, "argument_size_in_bytes", 0) or 0)
            self.out_bytes += int(
                getattr(ms, "output_size_in_bytes", 0) or 0)
            self.temp_bytes += int(
                getattr(ms, "temp_size_in_bytes", 0) or 0)
            self.exec_bytes += int(
                getattr(ms, "generated_code_size_in_bytes", 0) or 0)
        except Exception:  # noqa: BLE001
            pass
        self.analyzed += 1


def _on_duration(event: str, duration: float, **_kw: Any) -> None:
    ctx = getattr(_tls, "ctx", None)
    if ctx is None or event != _EV_BACKEND_COMPILE:
        return
    # the cache hit/miss event for this compile fired just before the
    # duration lands (observed ordering of jax's compile path); consume
    ctx.compiles.append((float(duration), ctx.pending_cache or "uncached"))
    ctx.pending_cache = None


def _on_event(event: str, **_kw: Any) -> None:
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return
    if event == _EV_CACHE_HIT:
        ctx.pending_cache = "hit"
    elif event == _EV_CACHE_MISS:
        ctx.pending_cache = "miss"


_install_lock = threading.Lock()
_installed = False


def install() -> None:
    """Register the jax.monitoring listeners (supported API) and wrap
    jax's internal compile entry point for executable capture
    (best-effort).  Idempotent; called lazily from the first
    observe_compiles so importing this module never touches jax.
    Registration happens UNDER the install lock: a second thread
    entering observe_compiles during startup must not proceed to its
    compile before the listeners exist, or that compile would be
    silently missing from the ledger."""
    global _installed
    with _install_lock:
        if _installed:
            return
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_duration)
            monitoring.register_event_listener(_on_event)
        except Exception:  # noqa: BLE001 — no jax, no ledger
            _log.debug("jax.monitoring unavailable; compile ledger off",
                       exc_info=True)
            _installed = True
            return
        # best-effort executable capture: version drift here loses ONLY
        # the analytical-cost fields of entries, never compile timing
        try:
            from jax._src import compiler as _jc
            orig = _jc.compile_or_get_cached
            if not getattr(orig, "_scanner_tpu_coststats", False):
                def _wrapped(*a: Any, **kw: Any):
                    ex = orig(*a, **kw)
                    ctx = getattr(_tls, "ctx", None)
                    if ctx is not None:
                        ctx.absorb_executable(ex)
                    return ex

                _wrapped._scanner_tpu_coststats = True
                _jc.compile_or_get_cached = _wrapped
        except Exception:  # noqa: BLE001
            _log.debug("executable capture unavailable (jax drift); "
                       "ledger entries will lack cost_analysis fields",
                       exc_info=True)
        _installed = True


# ---------------------------------------------------------------------------
# The compile ledger
# ---------------------------------------------------------------------------

_ledger_lock = threading.Lock()
_ledger: deque = deque(maxlen=_env_ring_size())
_ledger_seq = 0
# derived analytical cost per (op, device, bucket), fed by compile
# observations, read by descriptor_for as the hook-less default
_xla_costs: Dict[Tuple[str, str, int], Dict[str, float]] = {}


def set_ring_size(n: int) -> None:
    """Re-bound the ledger ring (tests; production sizes via
    SCANNER_TPU_COMPILE_LEDGER at process start).  Keeps the newest
    entries."""
    global _ledger
    with _ledger_lock:
        _ledger = deque(_ledger, maxlen=max(1, int(n)))


def clear() -> None:
    """Drop ledger + efficiency state (tests)."""
    global _ledger_seq
    with _ledger_lock:
        _ledger.clear()
        _xla_costs.clear()
        _ledger_seq = 0
    with _op_lock:
        _op_stats.clear()


@contextlib.contextmanager
def observe_compiles(op: str, device: str, bucket: int, signature: str,
                     members: Optional[Sequence[str]] = None):
    """Attribute any XLA compile inside the block to (op, device,
    bucket): the engine wraps exactly the calls that can compile — each
    warm-up rung, and the first call of a new (device, shape, dtype)
    signature.  Nothing is recorded when no compile fires.  No-op when
    coststats is disabled.  Fused-chain compiles pass `members` (the
    chain's member op names, graph/fusion.py) so ledger entries under
    the stable chain id stay explainable op by op."""
    if not _ENABLED:
        yield
        return
    install()
    prev = getattr(_tls, "ctx", None)
    ctx = _CompileCtx(op, device, bucket, signature, members=members)
    _tls.ctx = ctx
    try:
        yield
    finally:
        _tls.ctx = prev
        if ctx.compiles:
            _record_compiles(ctx)


def _record_compiles(ctx: _CompileCtx) -> None:
    global _ledger_seq
    total_s = sum(s for s, _c in ctx.compiles)
    caches = [c for _s, c in ctx.compiles]
    # the entry's label: hit only when every compile hit; any cold
    # compile makes the observation a miss; uncached = no cache at all
    cache = ("hit" if all(c == "hit" for c in caches)
             else "miss" if any(c in ("hit", "miss") for c in caches)
             else "uncached")
    task, trace_id = None, None
    attrs = _tracing.current_span_attrs()
    if "task" in attrs:
        task = f"{attrs.get('job')},{attrs.get('task')}"
    cur = _tracing.current_context()
    if cur is not None:
        trace_id = cur.trace_id
    entry = {
        "op": ctx.op, "device": ctx.device, "bucket": ctx.bucket,
        "signature": ctx.signature, "compiles": len(ctx.compiles),
        "compile_s": round(total_s, 6), "cache": cache,
        "exec_bytes": ctx.exec_bytes,
        "flops": ctx.flops or None,
        "bytes_accessed": ctx.bytes_accessed or None,
        "argument_bytes": ctx.arg_bytes or None,
        "output_bytes": ctx.out_bytes or None,
        "temp_bytes": ctx.temp_bytes or None,
        "time": time.time(), "task": task, "trace_id": trace_id,
    }
    if ctx.members is not None:
        entry["members"] = list(ctx.members)
    with _ledger_lock:
        _ledger_seq += 1
        entry["seq"] = _ledger_seq
        _ledger.append(entry)
        if ctx.analyzed:
            # hook-less default descriptor source: XLA's own analysis
            # of what it just compiled for this exact call shape
            _xla_costs[(ctx.op, ctx.device, ctx.bucket)] = {
                "flops": ctx.flops,
                "bytes_in": float(ctx.arg_bytes),
                "bytes_out": float(ctx.out_bytes),
            }
    # metric/tracing work outside the ledger lock (lock-order hygiene,
    # same rule as util/memstats.py)
    for secs, c in ctx.compiles:
        _M_COMPILES.labels(op=ctx.op, device=ctx.device, cache=c).inc()
    _M_COMPILE_SECONDS.labels(op=ctx.op, device=ctx.device).inc(total_s)
    if ctx.exec_bytes:
        _M_COMPILE_EXEC_BYTES.labels(op=ctx.op, device=ctx.device).inc(
            ctx.exec_bytes)
    # the compile lands on the span that paid for it (warm-up runs
    # outside any trace; dispatch-site compiles pin to the task's op
    # span next to the existing xla.recompile event)
    _tracing.add_event("xla.compile", op=ctx.op, device=ctx.device,
                       bucket=ctx.bucket, seconds=round(total_s, 4),
                       cache=cache)


def compile_ledger(n: Optional[int] = None) -> List[Dict[str, Any]]:
    """Ledger entries, oldest first (the newest `n` when given)."""
    with _ledger_lock:
        items = list(_ledger)
    return items[-n:] if n else items


def ledger_summary() -> Dict[str, Any]:
    """Aggregate ledger view: totals, per-cache-outcome counts, and the
    persistent-cache hit rate (None when no cache was configured)."""
    with _ledger_lock:
        items = list(_ledger)
        total_seen = _ledger_seq
    by_cache: Dict[str, int] = {}
    secs = 0.0
    compiles = 0
    for e in items:
        by_cache[e["cache"]] = by_cache.get(e["cache"], 0) + 1
        secs += e["compile_s"]
        compiles += e["compiles"]
    hit, miss = by_cache.get("hit", 0), by_cache.get("miss", 0)
    rate = hit / (hit + miss) if (hit + miss) else None
    return {"entries": len(items), "entries_seen": total_seen,
            "compiles": compiles, "compile_seconds": round(secs, 4),
            "by_cache": by_cache, "cache_hit_rate": rate}


# ---------------------------------------------------------------------------
# Per-op cost descriptors at the dispatch site
# ---------------------------------------------------------------------------

def descriptor_for(kernel: Any, op: str, device: str, bucket: int,
                   args: Sequence[Any]) -> Optional[CostDescriptor]:
    """The cost of one kernel call: the kernel's ``cost(shapes)`` hook
    first; else the derived default from XLA's cost analysis of this
    (op, device, bucket)'s compiled executable; else bytes observed
    from the live args (FLOPs unknown).  None when coststats is off."""
    if not _ENABLED:
        return None
    shapes: List[Any] = []
    for a in args:
        shp = getattr(a, "shape", None)
        shapes.append(tuple(shp) if shp is not None else len(a))
    try:
        d = kernel.cost(shapes)
        if d is not None:
            # conversion stays inside the guard: a hook returning a
            # malformed dict is as broken as one that raises
            if isinstance(d, dict):
                d = CostDescriptor(**d)
            d.source = "hook"
            return d
    except Exception:  # noqa: BLE001 — a broken hook must not fail a task
        _log.debug("cost() hook of %s failed", op, exc_info=True)
    with _ledger_lock:
        xla = _xla_costs.get((op, device, int(bucket)))
    if xla:
        return CostDescriptor(flops=xla["flops"] or None,
                              bytes_in=xla["bytes_in"] or None,
                              bytes_out=xla["bytes_out"] or None,
                              source="derived")
    nb = sum(int(getattr(a, "nbytes", 0) or 0) for a in args)
    if not nb:
        return None
    return CostDescriptor(flops=None, bytes_in=float(nb),
                          bytes_out=None, source="observed")


# ---------------------------------------------------------------------------
# Roofline accumulation
# ---------------------------------------------------------------------------

_op_lock = threading.Lock()
# (op, device, bucket) -> [calls, rows, seconds, flops, bytes_in,
#                          bytes_out, source]
_op_stats: Dict[Tuple[str, str, int], List[Any]] = {}


def record_op_call(op: str, device: str, bucket: int, rows: int,
                   seconds: float, desc: Optional[CostDescriptor]
                   ) -> Optional[Dict[str, Any]]:
    """Fold one measured, compile-free kernel call into the (op,
    device, bucket) aggregate and refresh the efficiency gauges.
    Returns the cumulative classification (classify() shape) or None
    when there is nothing to judge."""
    if not _ENABLED or desc is None or seconds <= 0:
        return None
    key = (op, device, int(bucket))
    with _op_lock:
        st = _op_stats.get(key)
        if st is None:
            st = _op_stats[key] = [0, 0, 0.0, 0.0, 0.0, 0.0, desc.source]
        st[0] += 1
        st[1] += int(rows)
        st[2] += float(seconds)
        st[3] += float(desc.flops or 0.0)
        st[4] += float(desc.bytes_in or 0.0)
        st[5] += float(desc.bytes_out or 0.0)
        st[6] = desc.source
        calls, _rows, secs, flops, b_in, b_out, _src = st
    cls = classify(device, flops or None, b_in + b_out, secs)
    if cls is None:
        return None
    b = str(int(bucket))
    _M_OP_FLOPS.labels(op=op, device=device, bucket=b).set(
        cls["flops_per_s"])
    _M_OP_BW.labels(op=op, device=device, bucket=b).set(
        cls["bytes_per_s"])
    _M_OP_EFF.labels(op=op, device=device, bucket=b).set(cls["eff"])
    _M_OP_BOUND.labels(op=op, device=device, bucket=b).set(
        1.0 if cls["bound"] == "compute" else 0.0)
    return cls


def op_efficiency() -> List[Dict[str, Any]]:
    """The roofline table: one row per (op, device, bucket) with
    measured rates, the bound classification and EFF% — the digest
    bench.py banks and /statusz / scanner_cost render."""
    with _op_lock:
        items = sorted(_op_stats.items())
    out = []
    for (op, device, bucket), (calls, rows, secs, flops, b_in, b_out,
                               src) in items:
        cls = classify(device, flops or None, b_in + b_out, secs)
        if cls is None:
            continue
        peak_f, peak_b = device_peaks(device)
        out.append({
            "op": op, "device": device, "bucket": bucket,
            "calls": calls, "rows": rows, "seconds": round(secs, 4),
            "flops_per_s": round(cls["flops_per_s"], 2),
            "bytes_per_s": round(cls["bytes_per_s"], 2),
            "bound": cls["bound"],
            "efficiency": round(cls["eff"], 6),
            "peak_flops": peak_f, "peak_bytes_per_s": peak_b,
            "cost_source": src,
        })
    return out


def status_dict() -> Dict[str, Any]:
    """The /statusz Efficiency panel: the roofline table plus the
    compile-ledger summary (full entries stay on the RPC path)."""
    return {"enabled": _ENABLED,
            "ops": op_efficiency(),
            "compile": ledger_summary()}


def compile_report() -> Dict[str, Any]:
    """One process's full efficiency report — what GetCompileLedger
    ships: the bounded ledger, its summary, and the roofline table."""
    return {"ledger": compile_ledger(),
            "summary": ledger_summary(),
            "op_efficiency": op_efficiency()}
