"""Memory observability: per-device HBM accounting + allocation ledger.

The time side of the observability stack (util/metrics.py live series,
util/tracing.py causal spans, util/profiler.py post-mortem intervals)
answers *where time went*; nothing answered *where bytes live*.  Staged
source columns (`ColumnBatch.to_device`), bucket-ladder warm-up args
(engine/evaluate.py precompile) and async sink prefetch batches all
allocate HBM invisibly, and an OOM surfaced as an opaque
`RESOURCE_EXHAUSTED` with no owner.  This module is the missing
accountant, with two sources of truth that cross-check each other:

  * **Backend-reported device stats** — `device.memory_stats()` sampled
    per local jax device at scrape time (`bytes_in_use`, peak, limit),
    surfaced as the ``scanner_tpu_device_hbm_*`` gauges.  Gracefully
    absent on backends that report nothing (the CPU backend returns
    None) — the gauges then simply have no samples.
  * **The allocation ledger** — every engine-owned device buffer
    registers ``(bytes, device, kind, task, trace_id)`` on create and
    releases when the buffer object is collected (``track_array`` hangs
    a ``weakref.finalize`` off the array, so a leaked staging batch is
    a *visible* live ledger entry, not a mystery).  Live bytes and a
    high watermark are kept per (device, kind) and mirrored into the
    ``scanner_tpu_ledger_*`` series.

On a RESOURCE_EXHAUSTED (real, or injected through the
``memory.pressure`` fault site on CPU) the staging/dispatch sites call
:func:`note_oom`, which emits a one-shot **memory report** — device
stats, the top-N ledger entries by bytes with their owning task and
trace id, and the tail of the tracing flight recorder — to the log and
stores it for the ``GetMemoryReport`` RPC path
(``Client.memory_report()``).  The failure itself is classified
transient (engine/service.py ``_is_transient_failure``) so the task
requeues strike-free after its staged buffers are freed.

Knobs: ``SCANNER_TPU_MEMSTATS=0`` disables ledger tracking (device
gauges stay — they cost only a scrape-time sample);
``SCANNER_TPU_MEMSTATS_TOPN`` sizes the report's top-entry list
(default 10).  The ``[memory]`` config section carries the deployment
defaults the env vars override (docs/observability.md §Memory).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ..common import DeviceOutOfMemory
from . import metrics as _mx
from . import tracing as _tracing
from .log import get_logger

_log = get_logger("memstats")

# -- live series (docs/observability.md §Memory) ----------------------------

# backend-reported HBM occupancy, sampled from device.memory_stats() at
# scrape time (set_function children installed per device that reports)
_M_HBM_USE = _mx.registry().gauge(
    "scanner_tpu_device_hbm_bytes_in_use",
    "Backend-reported device memory in use (device.memory_stats "
    "bytes_in_use), sampled at scrape time.  Absent on backends that "
    "report no memory stats (CPU).",
    labels=["device"])
_M_HBM_PEAK = _mx.registry().gauge(
    "scanner_tpu_device_hbm_peak_bytes",
    "Backend-reported peak device memory in use since process start "
    "(device.memory_stats peak_bytes_in_use).",
    labels=["device"])
_M_HBM_LIMIT = _mx.registry().gauge(
    "scanner_tpu_device_hbm_limit_bytes",
    "Backend-reported device memory capacity available to this process "
    "(device.memory_stats bytes_limit).",
    labels=["device"])

# the allocation ledger's own view — engine-owned buffers only, so
# (hbm_bytes_in_use - ledger_live_bytes) is the non-engine remainder
# (XLA executables, scratch, framework overhead)
_M_LEDGER_LIVE = _mx.registry().gauge(
    "scanner_tpu_ledger_live_bytes",
    "Bytes of engine-owned device buffers currently registered in the "
    "allocation ledger, per device and buffer kind (staging / warmup / "
    "sink).",
    labels=["device", "kind"])
_M_LEDGER_PEAK = _mx.registry().gauge(
    "scanner_tpu_ledger_peak_bytes",
    "High watermark of ledger live bytes per (device, kind) since "
    "process start.",
    labels=["device", "kind"])
_M_LEDGER_ALLOCS = _mx.registry().counter(
    "scanner_tpu_ledger_allocs_total",
    "Device buffers registered in the allocation ledger, per device "
    "and kind.",
    labels=["device", "kind"])
_M_LEDGER_RELEASES = _mx.registry().counter(
    "scanner_tpu_ledger_releases_total",
    "Ledger entries released (buffer collected or explicitly freed), "
    "per device and kind.  allocs - releases = live entry count.",
    labels=["device", "kind"])
_M_OOM = _mx.registry().counter(
    "scanner_tpu_device_oom_events_total",
    "RESOURCE_EXHAUSTED events observed at engine staging/dispatch "
    "sites and the absorbed frame-cache page-build site (real device "
    "OOMs, or memory.pressure fault injections), by site.",
    labels=["site"])


# same knob semantics as SCANNER_TPU_TRACING (one parser, no drift)
_ENABLED = _tracing._env_on("SCANNER_TPU_MEMSTATS")


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Programmatic override ([memory] enabled config key, tests); the
    SCANNER_TPU_MEMSTATS env var is read at import and wins when set."""
    global _ENABLED
    _ENABLED = bool(on)


def _env_top_n() -> Optional[int]:
    v = os.environ.get("SCANNER_TPU_MEMSTATS_TOPN", "")
    try:
        n = int(v) if v else None
    except ValueError:
        return None
    # clamp like the config path: a report must stay bounded (negative
    # values would flip the top-entries slice into "all but N")
    return max(1, n) if n is not None else None


_REPORT_TOP_N = _env_top_n() or 10


def report_top_n() -> int:
    return _REPORT_TOP_N


def set_report_top_n(n: int) -> None:
    """[memory] report_top_n config wiring; the SCANNER_TPU_MEMSTATS_TOPN
    env var (read at import) wins when set."""
    global _REPORT_TOP_N
    if _env_top_n() is None:
        _REPORT_TOP_N = max(1, int(n))


def device_label(device: Optional[Any]) -> str:
    """Stable label for a jax device ("tpu:3"); "default" when placement
    is jax's choice (affinity off / single chip).  The canonical
    implementation — engine/evaluate.py re-exports it, so metrics,
    ledger entries and trace attrs all key devices identically."""
    if device is None:
        return "default"
    return f"{getattr(device, 'platform', 'dev')}:" \
           f"{getattr(device, 'id', 0)}"


def array_device_label(arr: Any) -> str:
    """Label for the device a jax array actually lives on; "default"
    when it is not determinable (host arrays, sharded arrays, version
    drift)."""
    devs = getattr(arr, "devices", None)
    if callable(devs):
        try:
            ds = list(devs())
            if len(ds) == 1:
                return device_label(ds[0])
        except Exception:  # noqa: BLE001 — accounting must never raise
            pass
    return "default"


# ---------------------------------------------------------------------------
# The allocation ledger
# ---------------------------------------------------------------------------

class _Entry:
    __slots__ = ("eid", "nbytes", "device", "kind", "task", "trace_id",
                 "created")

    def __init__(self, eid: int, nbytes: int, device: str, kind: str,
                 task: Optional[str], trace_id: Optional[str]):
        self.eid = eid
        self.nbytes = int(nbytes)
        self.device = device
        self.kind = kind
        self.task = task
        self.trace_id = trace_id
        self.created = time.time()

    def to_dict(self) -> Dict[str, Any]:
        return {"id": self.eid, "bytes": self.nbytes,
                "device": self.device, "kind": self.kind,
                "task": self.task, "trace_id": self.trace_id,
                "age_s": round(time.time() - self.created, 3)}


# RLock, not Lock: release() runs from weakref finalizers, which the
# cyclic GC may fire at any allocation point — including one inside a
# locked register() on the same thread.  Lock-order rule: NOTHING
# acquires a metrics family/child lock while holding this one (and the
# finalizer path touches no metric locks at all) — a finalizer firing
# inside a metric's own locked allocating region must never wait on a
# thread that holds _lock and wants that same metric lock.
_lock = threading.RLock()
_entries: Dict[int, _Entry] = {}
_next_id = 0
_live: Dict[Tuple[str, str], int] = {}
_peak: Dict[Tuple[str, str], int] = {}
# (device, kind) keys whose ledger gauges already have scrape-time
# samplers installed, and release counts awaiting a counter flush from
# a normal (non-finalizer) thread
_gauged_keys: set = set()
_pending_releases: Dict[Tuple[str, str], int] = {}


def _install_ledger_gauges(key: Tuple[str, str]) -> None:
    """Scrape-time samplers for one (device, kind)'s live/peak gauges —
    plain GIL-atomic dict reads, so scraping never holds the ledger
    lock while a gauge lock is held.  The live sampler also flushes the
    deferred release counts: a raw /metrics scrape of an otherwise-idle
    process must show allocs − releases = live entries (the documented
    leak diagnostic), not counts stranded by the finalizer deferral."""
    d, k = key

    def live_sample(key=key):
        _flush_release_counts()
        return float(_live.get(key, 0))

    _M_LEDGER_LIVE.labels(device=d, kind=k).set_function(live_sample)
    _M_LEDGER_PEAK.labels(device=d, kind=k).set_function(
        lambda key=key: float(_peak.get(key, 0)))


def _flush_release_counts() -> None:
    """Mirror deferred release counts into the releases counter.  The
    finalizer-driven release() path defers this (metric locks are
    unsafe there); any normal-thread entry point flushes."""
    with _lock:
        if not _pending_releases:
            return
        pending = dict(_pending_releases)
        _pending_releases.clear()
    for (d, k), n in pending.items():
        _M_LEDGER_RELEASES.labels(device=d, kind=k).inc(n)


def _current_owner() -> Tuple[Optional[str], Optional[str]]:
    """(task, trace_id) attribution from the active tracing context:
    the stage/task spans on the hot paths carry job/task attrs, so a
    buffer registered under one inherits its owner for free."""
    ctx = _tracing.current_context()
    trace_id = ctx.trace_id if ctx is not None else None
    attrs = _tracing.current_span_attrs()
    task = None
    if "task" in attrs:
        task = f"{attrs.get('job')},{attrs.get('task')}"
    return task, trace_id


def register(nbytes: int, device: str, kind: str,
             task: Optional[str] = None,
             trace_id: Optional[str] = None) -> Optional[int]:
    """Record an engine-owned device buffer; returns the entry id (None
    when memstats is disabled).  Callers that cannot tie release to an
    object's lifetime pair this with :func:`release` explicitly;
    :func:`track_array` is the finalizer-based flavor."""
    if not _ENABLED:
        return None
    if task is None and trace_id is None:
        task, trace_id = _current_owner()
    global _next_id
    key = (device, kind)
    with _lock:
        eid = _next_id
        _next_id += 1
        e = _Entry(eid, nbytes, device, kind, task, trace_id)
        _entries[eid] = e
        live = _live.get(key, 0) + e.nbytes
        _live[key] = live
        if live > _peak.get(key, 0):
            _peak[key] = live
        new_key = key not in _gauged_keys
        if new_key:
            _gauged_keys.add(key)
    # metric work strictly OUTSIDE the ledger lock (see the lock-order
    # rule at _lock); gauges sample the dicts at scrape time instead of
    # being pushed, so release() needs no metric calls at all
    if new_key:
        _install_ledger_gauges(key)
    _M_LEDGER_ALLOCS.labels(device=device, kind=kind).inc()
    _flush_release_counts()
    # the allocation lands on the owning task's trace timeline, so a
    # merged trace shows where this task's bytes came from
    _tracing.add_event("mem.register", kind=kind, bytes=int(nbytes),
                       device=device)
    return eid


def release(eid: Optional[int]) -> None:
    """Drop a ledger entry.  Runs from weakref finalizers: only the
    (reentrant) ledger lock and plain dict/int work in here — metric
    locks are deferred to _flush_release_counts on a normal thread."""
    if eid is None:
        return
    with _lock:
        e = _entries.pop(eid, None)
        if e is None:
            return  # double release (finalizer + explicit): idempotent
        key = (e.device, e.kind)
        _live[key] = max(_live.get(key, 0) - e.nbytes, 0)
        _pending_releases[key] = _pending_releases.get(key, 0) + 1


def track_array(arr: Any, kind: str,
                device: Optional[str] = None) -> Optional[int]:
    """Register `arr`'s bytes and release automatically when the array
    object is collected (weakref.finalize), so the ledger stays
    byte-accurate without manual pairing on the engine hot paths.
    Returns the entry id, or None (disabled / un-weakref-able)."""
    # the HBM gauges are independent of the ledger flag (the docs
    # promise they survive SCANNER_TPU_MEMSTATS=0): this call site has
    # jax demonstrably in use — latch that for _jax_ready and install
    global _jax_in_use
    _jax_in_use = True
    _maybe_install_device_gauges()
    if not _ENABLED:
        return None
    nbytes = getattr(arr, "nbytes", None)
    if not nbytes:
        return None
    try:
        # probe BEFORE registering: an un-weakref-able array would
        # leave a ledger entry nothing can ever release (call sites
        # discard the eid by design — release is the finalizer's job)
        weakref.ref(arr)
    except TypeError:
        return None
    eid = register(int(nbytes), device or array_device_label(arr), kind)
    if eid is not None:
        weakref.finalize(arr, release, eid)
    return eid


def live_bytes(device: Optional[str] = None,
               kind: Optional[str] = None) -> int:
    with _lock:
        return sum(v for (d, k), v in _live.items()
                   if (device is None or d == device)
                   and (kind is None or k == kind))


def watermark_bytes(device: Optional[str] = None,
                    kind: Optional[str] = None) -> int:
    with _lock:
        return sum(v for (d, k), v in _peak.items()
                   if (device is None or d == device)
                   and (kind is None or k == kind))


def entries() -> List[Dict[str, Any]]:
    """Live ledger entries as plain dicts (leak-guard fixture, tests)."""
    _flush_release_counts()
    with _lock:
        return [e.to_dict() for e in _entries.values()]


def top_entries(n: Optional[int] = None) -> List[Dict[str, Any]]:
    """The N largest live entries by bytes — the "who owns the HBM"
    answer an OOM report leads with."""
    with _lock:
        es = sorted(_entries.values(), key=lambda e: -e.nbytes)
        return [e.to_dict() for e in es[:n or _REPORT_TOP_N]]


def ledger_summary() -> List[Dict[str, Any]]:
    _flush_release_counts()
    with _lock:
        keys = sorted(set(_live) | set(_peak))
        counts: Dict[Tuple[str, str], int] = {}
        for e in _entries.values():
            k = (e.device, e.kind)
            counts[k] = counts.get(k, 0) + 1
        return [{"device": d, "kind": k,
                 "live_bytes": _live.get((d, k), 0),
                 "peak_bytes": _peak.get((d, k), 0),
                 "entries": counts.get((d, k), 0)}
                for d, k in keys]


# ---------------------------------------------------------------------------
# Backend-reported device stats
# ---------------------------------------------------------------------------

# memory_stats key aliases across jax backends/versions
_STAT_KEYS = (("bytes_in_use", ("bytes_in_use",)),
              ("peak_bytes", ("peak_bytes_in_use", "peak_bytes")),
              ("limit_bytes", ("bytes_limit", "bytes_reservable_limit")))


def _read_stats(dev: Any) -> Optional[Dict[str, int]]:
    try:
        st = dev.memory_stats()
    except Exception:  # noqa: BLE001 — version drift / unsupported
        return None
    if not st:
        return None
    out = {}
    for name, aliases in _STAT_KEYS:
        for a in aliases:
            if a in st:
                out[name] = int(st[a])
                break
        else:
            out[name] = 0
    return out


# latched the first time the engine hands us a real jax array
# (track_array): from then on the backend is provably up, independent
# of any private-API probe
_jax_in_use = False


def _jax_ready() -> bool:
    """True only when this process has provably brought a jax backend
    up.  Sampling device stats must never be the thing that INITIALIZES
    a backend: a master co-located with worker processes would grab the
    exclusive TPU runtime (or stall its status handler behind a
    multi-second init) just to answer /statusz.  Evidence, in order:
    the engine already handed us a device array (_jax_in_use), or the
    backend registry is non-empty.  FAIL CLOSED when the (private)
    registry cannot be read — missing gauges on a drifted jax beat a
    master seizing the TPU runtime."""
    if _jax_in_use:
        return True
    if sys.modules.get("jax") is None:
        return False
    try:
        from jax._src import xla_bridge as xb
        backs = getattr(xb, "_backends", None)
        if isinstance(backs, dict):
            return bool(backs)
    except Exception:  # noqa: BLE001 — private-API drift
        pass
    return False


def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """{device_label: {bytes_in_use, peak_bytes, limit_bytes}} from the
    backend, for every local device that reports stats.  {} on
    backends that report none (CPU) — gracefully absent by design —
    and in processes that never initialized jax (see _jax_ready)."""
    if not _jax_ready():
        return {}
    try:
        import jax
        devs = list(jax.local_devices())
    except Exception:  # noqa: BLE001 — no jax, no stats
        return {}
    out = {}
    for d in devs:
        st = _read_stats(d)
        if st is not None:
            out[device_label(d)] = st
    if out:
        _maybe_install_device_gauges()
    return out


_gauges_installed = False


def _maybe_install_device_gauges() -> None:
    """Install scrape-time samplers for the HBM gauges, once, for every
    local device that reports memory stats.  Lazy (first ledger-path
    array or stats read) so importing this module never touches jax,
    and guarded by _jax_ready so it never initializes a backend.  No
    ledger lock held here (lock-order rule); a racing double install
    re-binds identical samplers, which is idempotent."""
    global _gauges_installed
    if _gauges_installed or not _jax_ready():
        return
    try:
        import jax
        devs = list(jax.local_devices())
    except Exception:  # noqa: BLE001
        return
    _gauges_installed = True
    for d in devs:
        if _read_stats(d) is None:
            continue
        lbl = device_label(d)
        for gauge, stat in ((_M_HBM_USE, "bytes_in_use"),
                            (_M_HBM_PEAK, "peak_bytes"),
                            (_M_HBM_LIMIT, "limit_bytes")):
            gauge.labels(device=lbl).set_function(
                lambda dev=d, s=stat:
                float((_read_stats(dev) or {}).get(s, 0)))


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM")


def is_oom(exc: BaseException) -> bool:
    """True for device memory exhaustion: the engine's own
    DeviceOutOfMemory (also what the memory.pressure fault site
    raises), or an XLA RESOURCE_EXHAUSTED runtime error."""
    if isinstance(exc, DeviceOutOfMemory):
        return True
    if type(exc).__name__ == "XlaRuntimeError":
        msg = str(exc)
        return any(m in msg for m in _OOM_MARKERS)
    return False


# the flight recorder an OOM report snapshots; components with their own
# tracer (the cluster Worker) install it so the report shows what THAT
# process was doing, not the default client tracer
_tracer: Optional[Any] = None


def set_tracer(tracer: Any) -> None:
    global _tracer
    _tracer = tracer


_report_lock = threading.Lock()
_last_report: Optional[Dict[str, Any]] = None
_report_seq = 0
_last_log_time = 0.0
LOG_INTERVAL = 60.0  # full-report log lines at most this often


def memory_report(reason: str = "",
                  site: str = "") -> Dict[str, Any]:
    """One forensic snapshot: backend device stats, the ledger summary,
    the top-N live entries by bytes (with owning task + trace id), and
    the tail of the flight recorder.  Plain msgpack-able dict — it
    crosses the ShipMemoryReport / GetMemoryReport RPC path."""
    # prefer the tracer owning the CALLING thread's trace context (an
    # OOM on worker 1's executor thread reports as worker 1 even when a
    # later-constructed sibling re-bound the module default)
    tracer = _tracing.current_tracer() or _tracer \
        or _tracing.default_tracer()
    recent = [{"name": d.get("name"), "trace_id": d.get("trace_id"),
               "span_id": d.get("span_id"), "node": d.get("node"),
               "start": d.get("start"), "end": d.get("end"),
               "status": d.get("status")}
              for d in tracer.recent(20)]
    return {
        "time": time.time(),
        "reason": reason,
        "site": site,
        # stamped at the source: the shipper's worker_id is not a
        # reliable origin when several in-process Workers share this
        # module (whoever polls first ships)
        "node": getattr(tracer, "node", None),
        "devices": device_memory_stats(),
        "ledger": ledger_summary(),
        "top_entries": top_entries(),
        "recent_spans": recent,
    }


def note_oom(exc: BaseException, site: str,
             detail: str = "") -> Dict[str, Any]:
    """Record one RESOURCE_EXHAUSTED observation: count it, attach it to
    the current task's trace span, build the memory report, store it
    for the RPC pull/ship path, and log it — the full report at most
    once per LOG_INTERVAL (an OOM storm across pipeline instances must
    not drown the log), a one-liner always."""
    global _last_report, _report_seq, _last_log_time
    _M_OOM.labels(site=site).inc()
    _tracing.add_event("mem.oom", site=site,
                       error=f"{type(exc).__name__}: {str(exc)[:200]}")
    report = memory_report(
        reason=f"{type(exc).__name__}: {str(exc)[:300]}", site=site)
    if detail:
        report["detail"] = detail
    with _report_lock:
        _report_seq += 1
        report["seq"] = _report_seq
        _last_report = report
        now = time.time()
        log_full = now - _last_log_time >= LOG_INTERVAL
        if log_full:
            _last_log_time = now
    top = report["top_entries"][:3]
    _log.error(
        "device memory exhausted at %s (%s); ledger live=%d bytes, "
        "top entries: %s",
        site, report["reason"], live_bytes(),
        ", ".join(f"{e['bytes']}B {e['kind']}@{e['device']} "
                  f"task={e['task']}" for e in top) or "none")
    if log_full:
        _log.error("memory report: %s", json.dumps(report, default=str))
    return report


def last_report() -> Optional[Dict[str, Any]]:
    with _report_lock:
        return dict(_last_report) if _last_report else None


_shipped_seq = 0


def take_unshipped_report() -> Optional[Dict[str, Any]]:
    """The newest report, handed out at most once (a GLOBAL claim-once
    cursor: report state is process-wide, so when several in-process
    Workers poll, exactly one ships each report instead of each
    duplicating it)."""
    global _shipped_seq
    with _report_lock:
        if _last_report is not None and _report_seq > _shipped_seq:
            _shipped_seq = _report_seq
            return dict(_last_report)
        return None


def status_dict() -> Dict[str, Any]:
    """The /statusz Memory panel: compact live view (full top-entries
    detail stays on the report path)."""
    with _report_lock:
        last = ({"time": _last_report["time"],
                 "site": _last_report.get("site"),
                 "reason": _last_report.get("reason")}
                if _last_report else None)
        oom_events = _report_seq
    return {
        "enabled": _ENABLED,
        "devices": device_memory_stats(),
        "ledger": ledger_summary(),
        "ledger_live_bytes": live_bytes(),
        "oom_events": oom_events,
        "last_oom": last,
    }
