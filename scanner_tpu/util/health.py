"""Cluster health & SLO engine: declarative alert rules over live metrics.

The observability stack so far answers *what happened* — the metrics
registry (util/metrics.py) records, tracing (util/tracing.py) connects,
memstats (util/memstats.py) accounts.  Nothing renders a *judgment*: a
master serving heavy traffic must know, online, that a stage is
backpressured, a worker is degraded, or p99 task latency is burning its
budget.  This module is that judgment layer:

  * **Rules** are declarative: (series selector, window, predicate) over
    the in-process ``MetricsRegistry``, supporting threshold (``value``),
    rate-of-change (``rate``), histogram-quantile (``p50``/``p90``/
    ``p99``, estimated from bucket counts via
    ``metrics.histogram_quantile``), multi-window burn-rate (``burn``)
    and the composite ``backpressure`` form (queue-depth watermark +
    producer/consumer fps imbalance).
  * A built-in **default ruleset** (``DEFAULT_RULES``) covers stage
    backpressure, worker liveness, per-device saturation and HBM
    pressure, task-latency SLO burn, and recompile storms; user rules
    ride in via the ``[alerts] rules`` config clause grammar (see
    docs/observability.md §Health & SLOs).
  * **Firing/resolving alerts are first-class**: counted as
    ``scanner_tpu_alerts_firing`` / ``scanner_tpu_alerts_transitions_total``,
    recorded as instants on the tracing flight recorder, served on the
    ``/alertz`` endpoint, rolled up into the ``ok|degraded|unhealthy``
    status ``/healthz`` and ``/readyz`` report, and aggregated
    master-side across workers (``GetHealth`` → ``Client.health()``).

One engine per process (like the registry it reads), sampling on a
daemon thread.  ``SCANNER_TPU_HEALTH=0`` disables it; the ``[alerts]``
config section carries the deployment defaults the env var overrides.
Everything later autoscaling/serving work needs — "is stage X the
bottleneck", "is the latency SLO burning" — reads this layer instead of
raw series.
"""

from __future__ import annotations

import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import (Any, Callable, Deque, Dict, List, Optional, Sequence,
                    Tuple)

from ..common import ScannerException
from . import metrics as _mx
from . import tracing as _tr
from .log import get_logger

_log = get_logger("health")

# alert-state telemetry (docs/observability.md §Health & SLOs): the
# gauge holds how many instances of each rule fire right now; the
# counter records every state transition so dashboards can rate() on
# flappiness even between scrapes
_M_FIRING = _mx.registry().gauge(
    "scanner_tpu_alerts_firing",
    "Alert instances currently firing per rule (health engine; 0 = "
    "the rule is quiet).",
    labels=["rule", "severity"])
_M_TRANSITIONS = _mx.registry().counter(
    "scanner_tpu_alerts_transitions_total",
    "Alert state transitions (pending->firing and firing->resolved) "
    "per rule.",
    labels=["rule", "state"])

# the [alerts] config section contract — config.default_config() must
# declare exactly these keys (scanner-check SC308 enforces both
# directions, like the RPC_CONTRACTS table)
CONFIG_KEYS = ("enabled", "rules")

SEVERITIES = ("warning", "critical")
FORMS = ("value", "rate", "p50", "p90", "p99", "burn", "backpressure")
# clause option keys the [alerts] rules grammar accepts
RULE_OPTION_KEYS = ("window", "for", "severity", "by", "objective",
                    "budget", "short")

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

# backpressure form: the producer stage whose completion rate is
# compared against each queued stage's own
_BP_TASKS_SERIES = "scanner_tpu_stage_tasks_total"
_BP_UPSTREAM = {"evaluate": "load", "save": "evaluate"}
_BP_IMBALANCE = 1.5   # producer fps > 1.5x consumer fps counts as skew


class HealthConfigError(ScannerException):
    """Malformed [alerts] rule spec."""


def _env_on(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default) not in ("0", "false", "")


_ENABLED = _env_on("SCANNER_TPU_HEALTH")


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """The programmatic override ([alerts] enabled config key); the
    SCANNER_TPU_HEALTH env var is read at import and wins when set."""
    global _ENABLED
    _ENABLED = bool(on)


def _env_interval() -> float:
    try:
        return max(0.05, float(os.environ.get(
            "SCANNER_TPU_HEALTH_INTERVAL", "1.0") or 1.0))
    except ValueError:
        return 1.0


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

@dataclass
class AlertRule:
    """One declarative alert: evaluate `form` over `series` (filtered by
    `match`, grouped by `by`), compare with `op value`, hold the verdict
    `for_seconds` before firing."""

    name: str
    series: str = ""
    form: str = "value"
    op: str = ">"
    value: float = 0.0
    # lookback for rate/quantile forms; the LONG window for burn
    window: float = 60.0
    # hold-down: the condition must stay true this long before firing
    for_seconds: float = 0.0
    severity: str = "warning"
    # label names each alert instance is keyed by (one alert per group)
    by: Tuple[str, ...] = ()
    # label filters applied before grouping
    match: Dict[str, str] = field(default_factory=dict)
    # value form only: divide by this series' matching group (ratios
    # like hbm_in_use / hbm_limit)
    ratio_to: str = ""
    # burn form: latency objective (seconds), allowed error-budget
    # fraction, and the SHORT window (window doubles as the long one);
    # `value` is the burn-rate multiple both windows must exceed
    objective: float = 0.0
    budget: float = 0.05
    short_window: float = 60.0
    description: str = ""

    def validate(self) -> "AlertRule":
        if not re.fullmatch(r"[a-z0-9_]+", self.name or ""):
            raise HealthConfigError(
                f"alert rule name {self.name!r} must be [a-z0-9_]+")
        if self.form not in FORMS:
            raise HealthConfigError(
                f"rule {self.name}: unknown form {self.form!r} "
                f"(known: {', '.join(FORMS)})")
        if self.op not in _OPS:
            raise HealthConfigError(
                f"rule {self.name}: unknown op {self.op!r}")
        if self.severity not in SEVERITIES:
            raise HealthConfigError(
                f"rule {self.name}: severity must be one of "
                f"{', '.join(SEVERITIES)}")
        if not self.series:
            raise HealthConfigError(f"rule {self.name}: needs a series")
        return self


# The built-in ruleset every process evaluates.  Names are a contract:
# the docs/observability.md default-ruleset table and this tuple may
# not drift (scanner-check SC308, both directions).
DEFAULT_RULES = (
    AlertRule(
        name="stage_backpressure", form="backpressure",
        series="scanner_tpu_stage_queue_depth",
        op=">=", value=3.0, window=10.0, for_seconds=1.5,
        severity="warning", by=("stage",),
        description="a pipeline stage's input queue sits at its high "
                    "watermark (or its producer sustainably outruns it "
                    "with a backlog standing): the stage is the "
                    "bottleneck and upstream work is piling up"),
    AlertRule(
        name="worker_heartbeat_stale",
        series="scanner_tpu_worker_heartbeat_age_seconds",
        form="value", op=">", value=4.0, window=10.0, for_seconds=0.0,
        severity="critical", by=("worker",),
        description="a registered worker has missed several heartbeats "
                    "(master view); past WORKER_STALE_AFTER it will be "
                    "deactivated and its tasks requeued"),
    AlertRule(
        name="device_saturation",
        series="scanner_tpu_device_busy_seconds_total",
        form="rate", op=">", value=0.9, window=15.0, for_seconds=5.0,
        severity="warning", by=("device",),
        description="a chip's evaluate-stage busy fraction is ~1.0 "
                    "sustained: the device is compute-saturated (the "
                    "autoscaling up-signal, not by itself a fault)"),
    AlertRule(
        name="hbm_pressure",
        series="scanner_tpu_device_hbm_bytes_in_use",
        ratio_to="scanner_tpu_device_hbm_limit_bytes",
        form="value", op=">", value=0.92, window=10.0, for_seconds=2.0,
        severity="critical", by=("device",),
        description="backend-reported HBM occupancy is within ~8% of "
                    "the device limit: the next staging or dispatch is "
                    "likely to RESOURCE_EXHAUSTED (see the memstats "
                    "ledger for who owns the bytes)"),
    AlertRule(
        name="task_latency_slo_burn",
        series="scanner_tpu_task_latency_seconds",
        form="burn", op=">", value=2.0, objective=30.0, budget=0.05,
        short_window=60.0, window=300.0, for_seconds=0.0,
        severity="critical",
        description="end-to-end task latency is burning its error "
                    "budget (share of tasks over the objective exceeds "
                    "burn_rate x budget in BOTH the short and the long "
                    "window — sustained burn, not a transient spike)"),
    AlertRule(
        name="recompile_storm",
        series="scanner_tpu_op_recompiles_total",
        form="rate", op=">", value=0.5, window=30.0, for_seconds=5.0,
        severity="warning",
        description="XLA recompiles are arriving continuously — "
                    "bucketed dispatch should bound them at one ladder "
                    "per (op, device); a sustained rate means a ragged "
                    "call path is re-tracing (PERF.md §5)"),
)


def default_rules() -> List[AlertRule]:
    return list(DEFAULT_RULES)


# -- [alerts] rules clause grammar ------------------------------------------
#
#   name:form(series[{label=v,...}][/ratio_series])OP VALUE[:opt=v...]
#
# clauses separated by ';'.  Example:
#   eval_hot:value(scanner_tpu_stage_queue_depth{stage=evaluate})>=8
#       :for=5:severity=critical
#   slow_rpc:p99(scanner_tpu_rpc_latency_seconds)>0.5:window=120

_EXPR_RE = re.compile(
    r"^(?P<form>" + "|".join(FORMS) + r")\("
    r"(?P<series>scanner_tpu_[a-z0-9_]+)"
    r"(?:\{(?P<match>[^}]*)\})?"
    r"(?:/(?P<ratio>scanner_tpu_[a-z0-9_]+))?"
    r"\)(?P<op>>=|<=|>|<)(?P<val>-?[0-9.]+(?:e-?[0-9]+)?)$")


def parse_rules(spec: str) -> List[AlertRule]:
    """Parse an [alerts] rules spec into AlertRules; raises
    HealthConfigError on anything malformed (a typo'd rule must fail at
    configure time, not silently alert on nothing)."""
    rules: List[AlertRule] = []
    for clause in (spec or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        if len(parts) < 2:
            raise HealthConfigError(
                f"alert clause {clause!r} needs name:expr")
        name, expr, opts = parts[0].strip(), parts[1].strip(), parts[2:]
        m = _EXPR_RE.match(expr.replace(" ", ""))
        if m is None:
            raise HealthConfigError(
                f"alert clause {name!r}: cannot parse expr {expr!r} "
                "(want form(series[{l=v}][/ratio])OP VALUE)")
        match: Dict[str, str] = {}
        for pair in (m.group("match") or "").split(","):
            pair = pair.strip()
            if not pair:
                continue
            k, sep, v = pair.partition("=")
            if not sep or not k:
                raise HealthConfigError(
                    f"alert clause {name!r}: bad label filter {pair!r}")
            match[k.strip()] = v.strip()
        rule = AlertRule(
            name=name, form=m.group("form"), series=m.group("series"),
            match=match, ratio_to=m.group("ratio") or "",
            op=m.group("op"), value=float(m.group("val")))
        if rule.form == "backpressure":
            rule.by = ("stage",)
        for opt in opts:
            k, sep, v = opt.partition("=")
            k = k.strip()
            if not sep or k not in RULE_OPTION_KEYS:
                raise HealthConfigError(
                    f"alert clause {name!r}: unknown option {opt!r} "
                    f"(known: {', '.join(RULE_OPTION_KEYS)})")
            try:
                if k == "window":
                    rule.window = float(v)
                elif k == "for":
                    rule.for_seconds = float(v)
                elif k == "severity":
                    rule.severity = v.strip()
                elif k == "by":
                    rule.by = tuple(x for x in v.split("+") if x)
                elif k == "objective":
                    rule.objective = float(v)
                elif k == "budget":
                    rule.budget = float(v)
                elif k == "short":
                    rule.short_window = float(v)
            except ValueError as e:
                raise HealthConfigError(
                    f"alert clause {name!r}: bad value for {k}: {v!r}"
                ) from e
        rules.append(rule.validate())
    return rules


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------

_ROLLUP_ORDER = {"ok": 0, "degraded": 1, "unhealthy": 2}
# severity of a firing alert -> health status it degrades the roll-up to
_SEVERITY_STATUS = {"warning": "degraded", "critical": "unhealthy"}

# hard bound on retained samples regardless of window math — a
# mis-configured tiny interval with an hour-long window must not grow
# process memory without bound
_MAX_SAMPLES = 10_000


def _hist_zero(n: int) -> Dict[str, Any]:
    return {"buckets": [0] * n, "sum": 0.0, "count": 0}


class HealthEngine:
    """Evaluates a ruleset over windowed registry samples; tracks alert
    state (pending -> firing -> resolved) with hold-downs; exposes the
    ok|degraded|unhealthy roll-up.  One per process via `engine()`;
    tests build private ones over private registries and drive `tick`
    by hand."""

    def __init__(self, reg: Optional[_mx.MetricsRegistry] = None,
                 rules: Optional[Sequence[AlertRule]] = None,
                 interval: Optional[float] = None):
        self._reg = reg if reg is not None else _mx.registry()
        self._rules: List[AlertRule] = (list(rules) if rules is not None
                                        else default_rules())
        self._user_rules: List[AlertRule] = []
        self._interval = interval if interval is not None \
            else _env_interval()
        # (t, {series: snapshot-entry}) ring; only series the ruleset
        # references are retained, trimmed to the longest rule window
        self._samples: Deque[Tuple[float, Dict[str, dict]]] = deque()
        # (rule name, group key) -> {"state", "since", "fired_at",
        #                            "value", "labels"}
        self._states: Dict[Tuple[str, Tuple[str, ...]], Dict[str, Any]] = {}
        # reentrant: evaluate() holds it across rule evaluation, which
        # reads the sample ring through the same-locked accessors
        self._lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_tick = 0.0
        self._tracer: Optional[Any] = None
        # alert-transition listeners: the alerts -> actuation seam
        # (ROADMAP item 5).  Called OUTSIDE the state lock with each
        # transition dict; an actuator (e.g. the frame cache's
        # hbm_pressure shrink, engine/framecache.py) reacts here
        # instead of polling the firing list.
        self._listeners: List[Callable[[dict], None]] = []

    # -- configuration ------------------------------------------------------

    def set_user_rules(self, rules: Sequence[AlertRule]) -> None:
        """Replace the user (config-supplied) rules; the built-in
        defaults stay.  Alert states of rules no longer in the ruleset
        are resolved on the spot — evaluate() only visits current
        rules, so without this a removed rule's firing state would
        degrade the roll-up forever."""
        removed: List[Tuple[str, str, Dict[str, Any]]] = []
        with self._lock:
            old_sev = {r.name: r.severity for r in self._user_rules}
            self._user_rules = list(rules)
            keep = {r.name for r in self._rules} \
                | {r.name for r in self._user_rules}
            for skey in [k for k in self._states if k[0] not in keep]:
                st = self._states.pop(skey)
                if st["state"] == "firing":
                    removed.append((skey[0],
                                    old_sev.get(skey[0], "warning"),
                                    st["labels"]))
        for name, sev, labels in removed:
            _M_FIRING.labels(rule=name, severity=sev).set(0)
            _M_TRANSITIONS.labels(rule=name, state="resolved").inc()
            _log.info("alert resolved (rule removed): %s%s", name,
                      labels or "")

    def set_interval(self, seconds: float) -> None:
        self._interval = max(0.05, float(seconds))

    def set_tracer(self, tracer: Any) -> None:
        """Route alert transition instants to a specific component's
        flight recorder (a Worker's tracer labels them with its node)."""
        self._tracer = tracer

    def add_listener(self, fn: Callable[[dict], None]) -> None:
        """Register an alert-transition actuator (idempotent per
        function object).  `fn` receives each transition dict
        ({"state", "rule", "severity", "labels", "value"}) after the
        metric/tracer side effects, outside the engine lock; exceptions
        are swallowed (a broken actuator must not kill alerting)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def rules(self) -> List[AlertRule]:
        with self._lock:
            return list(self._rules) + list(self._user_rules)

    # -- sampling -----------------------------------------------------------

    def _needed_series(self, rules: Sequence[AlertRule]) -> set:
        need = set()
        for r in rules:
            need.add(r.series)
            if r.ratio_to:
                need.add(r.ratio_to)
            if r.form == "backpressure":
                need.add(_BP_TASKS_SERIES)
        return need

    def _max_window(self, rules: Sequence[AlertRule]) -> float:
        w = 30.0
        for r in rules:
            w = max(w, r.window, r.short_window if r.form == "burn"
                    else 0.0)
        return w

    def sample(self, now: Optional[float] = None) -> None:
        """Record one observation of every rule-referenced series."""
        now = now if now is not None else time.time()
        rules = self.rules()
        need = self._needed_series(rules)
        snap = self._reg.snapshot()
        data = {name: snap[name] for name in need if name in snap}
        keep_after = now - (self._max_window(rules)
                            + 5 * self._interval + 5.0)
        with self._lock:
            self._samples.append((now, data))
            while self._samples and (
                    self._samples[0][0] < keep_after
                    or len(self._samples) > _MAX_SAMPLES):
                self._samples.popleft()

    # -- windowed series access (callers hold no locks; samples are
    # snapshots, append-only per tick) --------------------------------------

    def _latest(self) -> Optional[Tuple[float, Dict[str, dict]]]:
        with self._lock:
            return self._samples[-1] if self._samples else None

    def _at_or_before(self, t: float) \
            -> Optional[Tuple[float, Dict[str, dict]]]:
        """Newest sample taken at or before `t`; the oldest retained one
        when the window predates the history (rates then cover the
        actually-observed span)."""
        with self._lock:
            best = None
            for ts, data in self._samples:
                if ts <= t:
                    best = (ts, data)
                else:
                    break
            if best is None and self._samples:
                best = self._samples[0]
            return best

    @staticmethod
    def _groups(entry: Optional[dict], match: Dict[str, str],
                by: Tuple[str, ...]) -> Dict[Tuple[str, ...], Any]:
        """Aggregate a series entry's samples into by-label groups:
        scalars sum; histograms merge buckets/sum/count."""
        out: Dict[Tuple[str, ...], Any] = {}
        if not entry:
            return out
        is_hist = entry.get("kind") == "histogram"
        n_b = len(entry.get("uppers") or ()) + 1
        for s in entry.get("samples", []):
            lbls = s.get("labels") or {}
            if any(lbls.get(k) != v for k, v in match.items()):
                continue
            key = tuple(str(lbls.get(b, "")) for b in by)
            if is_hist:
                acc = out.setdefault(key, _hist_zero(n_b))
                for i, b in enumerate(s.get("buckets") or ()):
                    if i < n_b:
                        acc["buckets"][i] += b
                acc["sum"] += s.get("sum", 0.0)
                acc["count"] += s.get("count", 0)
            else:
                out[key] = out.get(key, 0.0) + float(s.get("value", 0.0))
        return out

    def _series_groups(self, sample, series: str, rule: AlertRule
                       ) -> Dict[Tuple[str, ...], Any]:
        return self._groups(sample[1].get(series), rule.match, rule.by)

    # -- rule forms ---------------------------------------------------------

    def _eval_value(self, rule: AlertRule, now_s) \
            -> Dict[Tuple[str, ...], float]:
        groups = self._series_groups(now_s, rule.series, rule)
        if not rule.ratio_to:
            return groups
        denom = self._series_groups(now_s, rule.ratio_to, rule)
        out = {}
        for key, num in groups.items():
            d = denom.get(key)
            if d:
                out[key] = num / d
        return out

    def _eval_rate(self, rule: AlertRule, now_s, then_s) \
            -> Dict[Tuple[str, ...], float]:
        if then_s is None:
            return {}
        dt = now_s[0] - then_s[0]
        if dt < max(0.5, self._interval / 2):
            return {}
        cur = self._series_groups(now_s, rule.series, rule)
        old = self._series_groups(then_s, rule.series, rule)
        return {key: max(v - old.get(key, 0.0), 0.0) / dt
                for key, v in cur.items()}

    def _eval_quantile(self, rule: AlertRule, q: float, now_s, then_s) \
            -> Dict[Tuple[str, ...], float]:
        """Quantile over the observations that arrived inside the
        window (bucket deltas); cumulative-since-start when the history
        is younger than the window."""
        entry = now_s[1].get(rule.series)
        if not entry or entry.get("kind") != "histogram":
            return {}
        uppers = list(entry.get("uppers") or ())
        cur = self._series_groups(now_s, rule.series, rule)
        old = self._series_groups(then_s, rule.series, rule) \
            if then_s is not None else {}
        out = {}
        for key, h in cur.items():
            o = old.get(key)
            buckets = [b - (o["buckets"][i] if o else 0)
                       for i, b in enumerate(h["buckets"])]
            v = _mx.histogram_quantile(uppers, buckets, q)
            if v is not None:
                out[key] = v
        return out

    @staticmethod
    def _count_over(uppers: Sequence[float], buckets: Sequence[float],
                    objective: float) -> float:
        """Observations above `objective`, interpolating inside the
        bucket that straddles it (same estimate histogram_quantile
        makes, inverted)."""
        total = float(sum(buckets))
        if total <= 0:
            return 0.0
        below = 0.0
        lo = 0.0
        for i, upper in enumerate(uppers):
            c = float(buckets[i])
            if upper <= objective:
                below += c
                lo = upper
                continue
            if lo < objective:
                span = upper - lo
                if span > 0:
                    below += c * (objective - lo) / span
            break
        return max(total - below, 0.0)

    def _eval_burn(self, rule: AlertRule, now, now_s) \
            -> Dict[Tuple[str, ...], float]:
        """Multi-window burn-rate: the share of observations over the
        latency objective, in BOTH the short and the long window, must
        exceed `value` x `budget` — the short window triggers fast, the
        long window keeps one spike from paging.  Returned value is the
        short-window burn multiple (error_frac / budget)."""
        entry = now_s[1].get(rule.series)
        if not entry or entry.get("kind") != "histogram":
            return {}
        uppers = list(entry.get("uppers") or ())
        out = {}
        cur = self._series_groups(now_s, rule.series, rule)
        windows = (rule.short_window, rule.window)
        for key, h in cur.items():
            burns = []
            for w in windows:
                then_s = self._at_or_before(now - w)
                if then_s is None \
                        or then_s[0] > now - w + 2 * self._interval:
                    # the history doesn't actually span this window
                    # (young engine: _at_or_before fell back to the
                    # oldest sample).  Without the check, both burn
                    # windows would collapse onto the same short
                    # delta and a transient spike would page as a
                    # "sustained" burn — exactly what the long
                    # window exists to veto.
                    burns = None
                    break
                o = self._series_groups(then_s, rule.series, rule) \
                    .get(key)
                buckets = [b - (o["buckets"][i] if o else 0)
                           for i, b in enumerate(h["buckets"])]
                n = sum(buckets)
                if n <= 0:
                    burns = None   # no traffic in this window: no burn
                    break
                frac = self._count_over(uppers, buckets, rule.objective) / n
                burns.append(frac / rule.budget if rule.budget > 0
                             else 0.0)
            if burns is not None:
                # fires only when every window exceeds the multiple;
                # report the short-window burn (the actionable number)
                out[key] = burns[0] if min(burns) > rule.value \
                    else min(burns)
        return out

    def _eval_backpressure(self, rule: AlertRule, now_s, then_s) \
            -> Dict[Tuple[str, ...], Tuple[float, bool]]:
        """Composite: per stage, fires when the stage's input queue sits
        at the watermark, OR a backlog is standing (depth >= 1) while
        the producer stage completes tasks > _BP_IMBALANCE x faster —
        either way, downstream cannot keep up.  Returns
        {key: (depth, fired)}."""
        depths = self._series_groups(now_s, rule.series, rule)
        rates: Dict[Tuple[str, ...], float] = {}
        if then_s is not None:
            dt = now_s[0] - then_s[0]
            if dt >= max(0.5, self._interval / 2):
                cur = self._groups(now_s[1].get(_BP_TASKS_SERIES),
                                   rule.match, ("stage",))
                old = self._groups(then_s[1].get(_BP_TASKS_SERIES),
                                   rule.match, ("stage",))
                rates = {k: max(v - old.get(k, 0.0), 0.0) / dt
                         for k, v in cur.items()}
        out = {}
        for key, depth in depths.items():
            stage = key[rule.by.index("stage")] if "stage" in rule.by \
                else (key[0] if key else "")
            fired = _OPS[rule.op](depth, rule.value)
            up = _BP_UPSTREAM.get(stage)
            if not fired and depth >= 1 and up is not None:
                up_rate = rates.get((up,), 0.0)
                my_rate = rates.get((stage,), 0.0)
                fired = up_rate > 0 \
                    and up_rate > my_rate * _BP_IMBALANCE
            out[key] = (depth, fired)
        return out

    # -- evaluation + state machine -----------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Run every rule against the sample history; update alert
        states; bump metrics and record flight-recorder instants for
        each transition.  Returns the transition list (tests)."""
        now = now if now is not None else time.time()
        now_s = self._latest()
        if now_s is None:
            return []
        rules = self.rules()
        transitions: List[dict] = []
        with self._lock:
            states = self._states
            for rule in rules:
                then_s = self._at_or_before(now - rule.window)
                if rule.form == "backpressure":
                    results = self._eval_backpressure(rule, now_s, then_s)
                else:
                    if rule.form == "value":
                        vals = self._eval_value(rule, now_s)
                    elif rule.form == "rate":
                        vals = self._eval_rate(rule, now_s, then_s)
                    elif rule.form in ("p50", "p90", "p99"):
                        q = {"p50": 0.5, "p90": 0.9, "p99": 0.99}[rule.form]
                        vals = self._eval_quantile(rule, q, now_s, then_s)
                    elif rule.form == "burn":
                        vals = self._eval_burn(rule, now, now_s)
                    else:   # unreachable post-validate
                        vals = {}
                    results = {k: (v, _OPS[rule.op](v, rule.value))
                               for k, v in vals.items()}
                seen = set()
                for key, (val, fired) in results.items():
                    skey = (rule.name, key)
                    seen.add(skey)
                    st = states.get(skey)
                    if fired:
                        if st is None:
                            st = states[skey] = {
                                "state": "pending", "since": now,
                                "labels": dict(zip(rule.by, key))}
                        st["value"] = val
                        if st["state"] == "pending" \
                                and now - st["since"] >= rule.for_seconds:
                            st["state"] = "firing"
                            st["fired_at"] = now
                            transitions.append({
                                "state": "firing", "rule": rule.name,
                                "severity": rule.severity,
                                "labels": st["labels"], "value": val})
                    elif st is not None:
                        if st["state"] == "firing":
                            transitions.append({
                                "state": "resolved", "rule": rule.name,
                                "severity": rule.severity,
                                "labels": st["labels"], "value": val})
                        del states[skey]
                # groups that vanished from the series (a departed
                # worker's gauge child, a finished pipeline's queue
                # sampler) resolve like any condition going false
                for skey in [k for k in states
                             if k[0] == rule.name and k not in seen]:
                    st = states[skey]
                    if st["state"] == "firing":
                        transitions.append({
                            "state": "resolved", "rule": rule.name,
                            "severity": rule.severity,
                            "labels": st["labels"],
                            "value": st.get("value")})
                    del states[skey]
                n_firing = sum(1 for (rn, _k), st in states.items()
                               if rn == rule.name
                               and st["state"] == "firing")
                _M_FIRING.labels(rule=rule.name,
                                 severity=rule.severity).set(n_firing)
            self._last_tick = now
        # transition side effects outside the state lock: the metric
        # children and the tracer ring have locks of their own
        tracer = self._tracer or _tr.default_tracer()
        for t in transitions:
            _M_TRANSITIONS.labels(rule=t["rule"], state=t["state"]).inc()
            _tr.record_instant(tracer, f"alert.{t['state']}",
                               rule=t["rule"], severity=t["severity"],
                               **(t["labels"] or {}))
            if t["state"] == "firing":
                _log.warning("ALERT firing: %s%s (value=%s)", t["rule"],
                             t["labels"] or "", t.get("value"))
            else:
                _log.info("alert resolved: %s%s", t["rule"],
                          t["labels"] or "")
        if transitions:
            with self._lock:
                listeners = list(self._listeners)
            for fn in listeners:
                for t in transitions:
                    try:
                        fn(t)
                    except Exception:  # noqa: BLE001 — actuator bug
                        # must not kill the alerting loop
                        _log.exception("alert listener failed")
        return transitions

    def tick(self, now: Optional[float] = None) -> List[dict]:
        now = now if now is not None else time.time()
        self.sample(now)
        return self.evaluate(now)

    # -- consumers ----------------------------------------------------------

    def firing(self) -> List[dict]:
        sev = {r.name: r.severity for r in self.rules()}
        desc = {r.name: r.description for r in self.rules()}
        with self._lock:
            out = []
            for (rn, _key), st in sorted(self._states.items()):
                if st["state"] != "firing":
                    continue
                out.append({
                    "rule": rn,
                    "severity": sev.get(rn, "warning"),
                    "labels": dict(st["labels"]),
                    "since": st.get("fired_at", st["since"]),
                    "value": st.get("value"),
                    "description": desc.get(rn, "")})
        return out

    def status_dict(self) -> Dict[str, Any]:
        """The health roll-up + firing alerts: /statusz Health panels,
        GetHealth, Client.health()."""
        firing = self.firing()
        status = "ok"
        reasons = []
        for f in firing:
            s = _SEVERITY_STATUS.get(f["severity"], "degraded")
            if _ROLLUP_ORDER[s] > _ROLLUP_ORDER[status]:
                status = s
            lbl = ",".join(f"{k}={v}"
                           for k, v in sorted(f["labels"].items()))
            reasons.append(f"{f['rule']}[{lbl}]" if lbl else f["rule"])
        return {"status": status, "reasons": sorted(reasons),
                "firing": firing, "enabled": _ENABLED,
                "rules": len(self.rules()),
                "last_tick": self._last_tick}

    def alertz_dict(self) -> Dict[str, Any]:
        """The /alertz body: the roll-up plus the full rule table (so
        an operator can see what WOULD fire, not just what is)."""
        out = self.status_dict()
        out["rule_table"] = [{
            "name": r.name, "form": r.form, "series": r.series,
            "op": r.op, "value": r.value, "window": r.window,
            "for": r.for_seconds, "severity": r.severity,
            "by": list(r.by), "description": r.description,
        } for r in self.rules()]
        return out

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="health-engine", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a rule bug must not
                # kill the engine thread (and with it all alerting)
                _log.exception("health tick failed")


# ---------------------------------------------------------------------------
# Process-wide singleton (mirrors metrics.registry())
# ---------------------------------------------------------------------------

_ENGINE: Optional[HealthEngine] = None
_ENGINE_LOCK = threading.Lock()


def engine() -> HealthEngine:
    """The process-wide engine (created on first use; started by
    ensure_started)."""
    global _ENGINE
    with _ENGINE_LOCK:
        if _ENGINE is None:
            _ENGINE = HealthEngine()
        return _ENGINE


def ensure_started() -> Optional[HealthEngine]:
    """Start the process engine's sampling thread (idempotent); no-op
    when SCANNER_TPU_HEALTH=0 / [alerts] enabled=false."""
    if not _ENABLED:
        return None
    e = engine()
    e.start()
    return e


def configure(rules_spec: str) -> None:
    """Install user rules from an [alerts] rules spec (replacing any
    previously configured user rules)."""
    engine().set_user_rules(parse_rules(rules_spec))


def set_interval(seconds: float) -> None:
    engine().set_interval(seconds)


def set_tracer(tracer: Any) -> None:
    engine().set_tracer(tracer)


def add_listener(fn: Callable[[dict], None]) -> None:
    """Register an alert-transition actuator with the process engine
    (see HealthEngine.add_listener)."""
    engine().add_listener(fn)


def remove_listener(fn: Callable[[dict], None]) -> None:
    engine().remove_listener(fn)


def _quiet(extra_enabled: bool) -> Dict[str, Any]:
    return {"status": "ok", "reasons": [], "firing": [],
            "enabled": extra_enabled, "rules": 0, "last_tick": 0.0}


def status_dict() -> Dict[str, Any]:
    """Process health status; quiet-ok when the engine never started
    (a scrape must not spin one up as a side effect)."""
    if _ENGINE is None:
        return _quiet(_ENABLED)
    return _ENGINE.status_dict()


def rollup() -> Dict[str, Any]:
    """The minimal /healthz payload: status + reason codes."""
    st = status_dict()
    return {"status": st["status"], "reasons": st["reasons"]}


def firing_rules() -> List[str]:
    """Names of the rules currently firing in this process (sorted,
    deduped; [] when no engine ever started).  The compact form worker
    heartbeats advertise every beat so the master can fold worker-side
    alerts into cluster-level remediation transitions without a second
    RPC (engine/service.py; engine/controller.py acts on them)."""
    if _ENGINE is None:
        return []
    return sorted({f["rule"] for f in _ENGINE.firing()
                   if f.get("rule")})


def alertz_dict() -> Dict[str, Any]:
    if _ENGINE is None:
        out = _quiet(_ENABLED)
        out["rule_table"] = []
        return out
    return _ENGINE.alertz_dict()


def merge_status(nodes: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-node status dicts into one cluster view: worst-of
    status, node-prefixed reason codes, each node's firing alerts
    stamped with their node.  The ONE place the ok<degraded<unhealthy
    ordering lives for aggregation — the master's GetHealth and the
    local-mode Client.health() both use it."""
    status = "ok"
    reasons: List[str] = []
    firing: List[Dict[str, Any]] = []
    for node in sorted(nodes):
        h = nodes[node]
        s = h.get("status", "ok")
        if _ROLLUP_ORDER.get(s, 0) > _ROLLUP_ORDER.get(status, 0):
            status = s
        reasons.extend(f"{node}:{r}" for r in h.get("reasons", ()))
        firing.extend(dict(f, node=node) for f in h.get("firing", ()))
    return {"status": status, "reasons": reasons, "firing": firing,
            "nodes": nodes}
