"""Exponential backoff with jitter for transient failures.

Capability parity: the reference wraps every cross-process call in
``GRPC_BACKOFF`` (reference scanner/util/grpc.h, used e.g.
worker.cpp:886) and its storehouse layer retries transient storage
errors.  One shared helper serves both the RPC client (UNAVAILABLE
channels) and the GCS backend (429/5xx).

Retries are no longer silent: each retry increments the live
``scanner_tpu_retry_attempts_total{site=...}`` counter (util/metrics.py),
and a final give-up after real retries logs at WARNING with the
accumulated backoff wait — an operator watching /metrics or the log sees
a flapping dependency before it becomes a job failure.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional, TypeVar

from . import metrics as _mx
from .log import get_logger

T = TypeVar("T")

_log = get_logger("retry")

_M_RETRIES = _mx.registry().counter(
    "scanner_tpu_retry_attempts_total",
    "Transient-failure retries by call site (rpc:<method>, gcs, ...).",
    labels=["site"])
_M_BUDGET_DENIED = _mx.registry().counter(
    "scanner_tpu_retry_budget_exhausted_total",
    "Retries refused by the per-process retry budget (token bucket): "
    "the call fails fast instead of joining a retry storm.  Nonzero "
    "means the process is burning retries faster than successes "
    "replenish them — a dependency is down, not flapping.",
    labels=["site"])


class RetryBudget:
    """Per-process retry token bucket (the gRPC retry-throttling
    scheme): every retry withdraws one token, every overall success
    deposits `token_ratio`; retries are only allowed while the bucket
    sits above half capacity.  Per-call backoff handles *politeness*
    for an individual flap — the budget handles *aggregate* sanity: a
    whole worker fleet re-dialing a restarting master must converge to
    fail-fast instead of multiplying a storm, and the full-jitter
    delays (backoff_delays) decorrelate the survivors."""

    def __init__(self, max_tokens: float = 500.0,
                 token_ratio: float = 0.5):
        self.max_tokens = float(max_tokens)
        self.token_ratio = float(token_ratio)
        self._tokens = self.max_tokens
        self._lock = threading.Lock()

    def on_success(self) -> None:
        with self._lock:
            self._tokens = min(self.max_tokens,
                               self._tokens + self.token_ratio)

    def take(self) -> bool:
        """Withdraw one retry token; False (no withdrawal) when the
        bucket is at or below half capacity — the caller should fail
        fast."""
        with self._lock:
            if self._tokens <= self.max_tokens / 2:
                return False
            self._tokens -= 1.0
            return True

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def reset(self) -> None:
        with self._lock:
            self._tokens = self.max_tokens


# the process-wide default budget every call_with_backoff shares;
# capacity 500 / floor 250 is far above anything a healthy process
# retries, while a sustained storm (thousands of retries, no
# successes) trips fail-fast within seconds
_BUDGET = RetryBudget()


def process_budget() -> RetryBudget:
    return _BUDGET


def backoff_delays(retries: int, base: float = 0.05, cap: float = 2.0,
                   rng: Optional[random.Random] = None):
    """Yield `retries` sleep durations: full-jitter exponential backoff
    (delay_i uniform in [0, min(cap, base * 2**i)]) — the AWS
    'full jitter' scheme, which decorrelates thundering herds."""
    r = rng or random
    for i in range(retries):
        yield r.uniform(0.0, min(cap, base * (2.0 ** i)))


def retry_until_deadline(fn: Callable[[], T], *,
                         is_transient: Callable[[Exception], bool],
                         deadline: float, base: float = 0.25,
                         cap: float = 2.0,
                         sleep: Callable[[float], None] = time.sleep,
                         rng: Optional[random.Random] = None,
                         label: str = "",
                         budget: Optional[RetryBudget] = None) -> T:
    """Like call_with_backoff, but bounded by a wall-clock `deadline`
    instead of an attempt count — for calls that must ride out a peer
    restart of UNKNOWN duration and are safe to repeat end-to-end
    (idempotent by design, e.g. the token-deduped NewJob admission:
    the server returns the already-admitted bulk on a repeat).  The
    shared process retry budget still applies, so a fleet-wide outage
    converges to fail-fast instead of a deadline-long storm."""
    r = rng or random
    budget = _BUDGET if budget is None else budget
    attempt = 0
    while True:
        try:
            result = fn()
            budget.on_success()
            return result
        except Exception as e:  # noqa: BLE001
            if not is_transient(e) or time.time() >= deadline:
                raise
            if not budget.take():
                _M_BUDGET_DENIED.labels(site=label or "other").inc()
                _log.warning(
                    "retry budget exhausted%s: failing fast after %d "
                    "deadline-bounded retries: %s: %s",
                    f" [{label}]" if label else "", attempt,
                    type(e).__name__, e)
                raise e from None
            attempt += 1
            _M_RETRIES.labels(site=label or "other").inc()
            from . import tracing as _tracing
            _tracing.add_event("retry", site=label or "other",
                               attempt=attempt,
                               error=f"{type(e).__name__}: "
                                     f"{str(e)[:120]}")
            # full jitter, capped — and never sleeping past the
            # deadline itself
            delay = r.uniform(0.0, min(cap, base * (2.0 ** min(
                attempt, 8))))
            sleep(min(delay, max(0.0, deadline - time.time())))


def call_with_backoff(fn: Callable[[], T], *,
                      is_transient: Callable[[Exception], bool],
                      retries: int = 4, base: float = 0.05,
                      cap: float = 2.0,
                      sleep: Callable[[float], None] = time.sleep,
                      rng: Optional[random.Random] = None,
                      label: str = "",
                      budget: Optional[RetryBudget] = None) -> T:
    """Run fn(); on a transient exception retry up to `retries` times with
    full-jitter exponential backoff.  Non-transient exceptions and the
    final transient failure propagate unchanged.  `label` names the call
    site in the retry counter and the give-up log line.  Every retry
    withdraws from `budget` (default: the shared process budget) and
    every overall success deposits back: when the process as a whole is
    retrying faster than it succeeds, remaining calls fail fast instead
    of stampeding a recovering dependency."""
    delays = backoff_delays(retries, base=base, cap=cap, rng=rng)
    budget = _BUDGET if budget is None else budget
    attempts = 0
    waited = 0.0
    while True:
        try:
            result = fn()
            budget.on_success()
            return result
        except Exception as e:  # noqa: BLE001
            if not is_transient(e):
                raise
            try:
                delay = next(delays)
            except StopIteration:
                if attempts:
                    # only after real retries: retries=0 callers (e.g.
                    # wait_for_server's own poll loop) stay quiet
                    _log.warning(
                        "giving up%s after %d retries (%.2fs accumulated "
                        "backoff): %s: %s",
                        f" [{label}]" if label else "", attempts, waited,
                        type(e).__name__, e)
                raise e from None
            if not budget.take():
                # the PROCESS is out of retry budget (a storm, not a
                # flap): fail fast instead of piling more redials onto
                # a recovering dependency
                _M_BUDGET_DENIED.labels(site=label or "other").inc()
                _log.warning(
                    "retry budget exhausted%s: failing fast after %d "
                    "local retries: %s: %s",
                    f" [{label}]" if label else "", attempts,
                    type(e).__name__, e)
                raise e from None
            attempts += 1
            waited += delay
            _M_RETRIES.labels(site=label or "other").inc()
            # transient retries become events on the active trace span:
            # the merged timeline shows which task's call flapped
            from . import tracing as _tracing
            _tracing.add_event("retry", site=label or "other",
                               attempt=attempts,
                               error=f"{type(e).__name__}: {str(e)[:120]}")
            sleep(delay)
