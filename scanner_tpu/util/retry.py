"""Exponential backoff with jitter for transient failures.

Capability parity: the reference wraps every cross-process call in
``GRPC_BACKOFF`` (reference scanner/util/grpc.h, used e.g.
worker.cpp:886) and its storehouse layer retries transient storage
errors.  One shared helper serves both the RPC client (UNAVAILABLE
channels) and the GCS backend (429/5xx).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar

T = TypeVar("T")


def backoff_delays(retries: int, base: float = 0.05, cap: float = 2.0,
                   rng: Optional[random.Random] = None):
    """Yield `retries` sleep durations: full-jitter exponential backoff
    (delay_i uniform in [0, min(cap, base * 2**i)]) — the AWS
    'full jitter' scheme, which decorrelates thundering herds."""
    r = rng or random
    for i in range(retries):
        yield r.uniform(0.0, min(cap, base * (2.0 ** i)))


def call_with_backoff(fn: Callable[[], T], *,
                      is_transient: Callable[[Exception], bool],
                      retries: int = 4, base: float = 0.05,
                      cap: float = 2.0,
                      sleep: Callable[[float], None] = time.sleep,
                      rng: Optional[random.Random] = None) -> T:
    """Run fn(); on a transient exception retry up to `retries` times with
    full-jitter exponential backoff.  Non-transient exceptions and the
    final transient failure propagate unchanged."""
    delays = backoff_delays(retries, base=base, cap=cap, rng=rng)
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            if not is_transient(e):
                raise
            try:
                delay = next(delays)
            except StopIteration:
                raise e from None
            sleep(delay)
