"""Exponential backoff with jitter for transient failures.

Capability parity: the reference wraps every cross-process call in
``GRPC_BACKOFF`` (reference scanner/util/grpc.h, used e.g.
worker.cpp:886) and its storehouse layer retries transient storage
errors.  One shared helper serves both the RPC client (UNAVAILABLE
channels) and the GCS backend (429/5xx).

Retries are no longer silent: each retry increments the live
``scanner_tpu_retry_attempts_total{site=...}`` counter (util/metrics.py),
and a final give-up after real retries logs at WARNING with the
accumulated backoff wait — an operator watching /metrics or the log sees
a flapping dependency before it becomes a job failure.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar

from . import metrics as _mx
from .log import get_logger

T = TypeVar("T")

_log = get_logger("retry")

_M_RETRIES = _mx.registry().counter(
    "scanner_tpu_retry_attempts_total",
    "Transient-failure retries by call site (rpc:<method>, gcs, ...).",
    labels=["site"])


def backoff_delays(retries: int, base: float = 0.05, cap: float = 2.0,
                   rng: Optional[random.Random] = None):
    """Yield `retries` sleep durations: full-jitter exponential backoff
    (delay_i uniform in [0, min(cap, base * 2**i)]) — the AWS
    'full jitter' scheme, which decorrelates thundering herds."""
    r = rng or random
    for i in range(retries):
        yield r.uniform(0.0, min(cap, base * (2.0 ** i)))


def call_with_backoff(fn: Callable[[], T], *,
                      is_transient: Callable[[Exception], bool],
                      retries: int = 4, base: float = 0.05,
                      cap: float = 2.0,
                      sleep: Callable[[float], None] = time.sleep,
                      rng: Optional[random.Random] = None,
                      label: str = "") -> T:
    """Run fn(); on a transient exception retry up to `retries` times with
    full-jitter exponential backoff.  Non-transient exceptions and the
    final transient failure propagate unchanged.  `label` names the call
    site in the retry counter and the give-up log line."""
    delays = backoff_delays(retries, base=base, cap=cap, rng=rng)
    attempts = 0
    waited = 0.0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            if not is_transient(e):
                raise
            try:
                delay = next(delays)
            except StopIteration:
                if attempts:
                    # only after real retries: retries=0 callers (e.g.
                    # wait_for_server's own poll loop) stay quiet
                    _log.warning(
                        "giving up%s after %d retries (%.2fs accumulated "
                        "backoff): %s: %s",
                        f" [{label}]" if label else "", attempts, waited,
                        type(e).__name__, e)
                raise e from None
            attempts += 1
            waited += delay
            _M_RETRIES.labels(site=label or "other").inc()
            # transient retries become events on the active trace span:
            # the merged timeline shows which task's call flapped
            from . import tracing as _tracing
            _tracing.add_event("retry", site=label or "other",
                               attempt=attempts,
                               error=f"{type(e).__name__}: {str(e)[:120]}")
            sleep(delay)
