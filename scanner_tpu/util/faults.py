"""Deterministic fault injection for chaos testing.

The robustness machinery in this repo — task reassignment on worker
death, job blacklisting, straggler revocation (engine/service.py),
bulk checkpoint/recovery, storage retries — is only trustworthy if it
actually runs under failures.  This module is the process-wide switch
that makes failures happen on demand, deterministically:

  * a registry of named **injection sites** hooked into the RPC plane,
    the storage backends, and the worker pipeline stages (see SITES);
  * **fault rules** bound to sites, with seeded/counted triggers so a
    run is reproducible: "raise StorageException on the 3rd storage
    write", "crash the process on the 2nd task evaluation", "fail 50%
    of RPC attempts (seed 7) for the first 40 attempts";
  * a live counter ``scanner_tpu_faults_injected_total{site,mode}`` so
    tests assert a fault actually fired instead of passing vacuously.

Disabled-path contract: when no plan is armed, every hook is a single
module-level flag check (``faults.ACTIVE``) — zero allocation, zero
behavior change.  Hot call sites guard with::

    from ..util import faults as _faults
    ...
    if _faults.ACTIVE:
        data = _faults.inject("storage.read", data, detail=path)

Arming (any of):
  * programmatic: ``faults.install("storage.write:raise:n=3")``
  * environment:  ``SCANNER_TPU_FAULTS`` (read at import, so spawned
    worker/master subprocesses arm themselves before serving)
  * config:       ``[faults] plan = "..."`` (Client wires it through)

Plan syntax — clauses joined by ";", fields joined by ":"::

    <site>:<mode>[:key=value]...

modes:
    raise      raise an exception (key ``exc`` picks the type, see _EXC)
    delay      sleep ``seconds`` (a hang, from the caller's view)
    corrupt    flip bytes in the data passing through the site
    crash      os._exit(CRASH_EXIT_CODE) — worker/master death mid-call
    duplicate  deliver the call TWICE (rpc.client.call only): the
               at-least-once model — request arrived, reply lost,
               caller repeats ("partitioned ≠ dead")

trigger keys (default: fire on every matching call):
    n=K       fire on exactly the Kth matching call (1-based)
    after=K   fire on every matching call past the Kth
    every=K   fire on every Kth matching call
    p=F       fire with probability F per call, drawn from a
              ``seed``-ed private RNG (reproducible sequence)
    times=K   stop after K fires (0 = unlimited)
    match=S   only calls whose detail string contains S (e.g. an RPC
              method name or a storage path)
    method=S  rpc.client.call detail is "<method>@<peer>": select one
              RPC method regardless of peer
    peer=S    select one remote address — asymmetric-partition plans
              ("calls to THIS peer fail, others succeed")

other keys: ``exc`` (raise mode), ``msg``, ``seconds`` (delay mode),
``seed`` (p mode).

Example: fail the worker's sink-item writes twice, transiently::

    SCANNER_TPU_FAULTS="storage.write:raise:exc=storage:match=output_:n=2:times=1"

See docs/robustness.md for the full matrix and tests/test_chaos.py for
the suite that drives every site.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Union

from ..common import (DeviceOutOfMemory, ScannerException,
                      StorageException)
from . import metrics as _mx
from .log import get_logger

_log = get_logger("faults")

# every hook point wired into the codebase; install() rejects unknown
# sites so a typo'd plan fails loudly instead of injecting nothing
SITES = (
    "rpc.client.call",    # engine/rpc.py RpcClient.call, per attempt
    "rpc.server.handle",  # engine/rpc.py server handler, per request
    "storage.read",       # storage/backend.py read/read_range (data)
    "storage.write",      # storage/backend.py write/write_exclusive
    "gcs.request",        # storage/gcs.py, per retried API attempt
    "pipeline.decode",    # engine/executor.py load stage, per task
    "pipeline.eval",      # engine/executor.py evaluate stage, per task
    "pipeline.save",      # engine/executor.py save stage, per task
    "worker.heartbeat",   # engine/service.py heartbeat loop, per beat
    "worker.preempt",     # engine/service.py heartbeat loop, per beat:
                          # a raise models a spot/preemptible reclaim
                          # notice -> Worker.preempt() routine drain
    "memory.pressure",    # engine/batch.py to_device staging, per h2d
    "gang.rendezvous",    # engine/gang.py spawn_member, before the
                          # member runner starts: raise models a member
                          # that cannot join (transient GangFailed),
                          # crash kills the host pre-rendezvous
    "gang.collective",    # engine/gang.py spawn_member, fired the
                          # moment the member's runner has rendezvoused
                          # and enters the collective: crash = host
                          # death mid-collective (the runner dies with
                          # its worker via PR_SET_PDEATHSIG), raise =
                          # collective failure reported transient
)

MODES = ("raise", "delay", "corrupt", "crash", "duplicate")

# sites whose hook passes payload bytes through inject() — the only
# sites corrupt-mode can act on; install() rejects it elsewhere so a
# plan like "storage.write:corrupt" fails loudly instead of counting
# phantom fires that injected nothing
DATA_SITES = ("storage.read",)

# sites whose hook supports duplicate-delivery mode (the call is made
# TWICE against the peer, modeling at-least-once delivery after an
# ambiguous timeout — "partitioned ≠ dead"); the site's call path must
# ask take_duplicate() explicitly, so install() rejects the mode
# anywhere else
DUPLICATE_SITES = ("rpc.client.call",)

# sites whose detail string is "<method>@<peer>" — the only sites the
# structured method=/peer= selectors can meaningfully match; install()
# rejects them elsewhere (a peer= clause on storage.read would parse
# and then silently never fire)
SELECTOR_SITES = ("rpc.client.call",)

# distinctive exit status for crash-mode so tests can tell an injected
# death from a real one
CRASH_EXIT_CODE = 117

# the disabled-path flag: hooks check this module attribute and nothing
# else when no plan is armed
ACTIVE = False


class FaultInjected(ScannerException):
    """Default exception raised by raise-mode rules."""


class FaultPlanError(ScannerException):
    """Malformed fault-plan spec."""


def _unavailable_exc(msg: str):
    """A grpc.RpcError that the RPC client's backoff treats as a
    transient UNAVAILABLE transport failure — the 'server unreachable'
    storm, injectable without touching the network."""
    import grpc

    class _InjectedUnavailable(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.UNAVAILABLE

        def details(self):
            return msg

        def __str__(self):
            return f"injected UNAVAILABLE: {msg}"

    return _InjectedUnavailable()


# raise-mode exception constructors by `exc=` key.  `storage` and
# `connection` matter most: engine/service.py classifies those as
# transient (requeue without a blacklist strike).
_EXC = {
    "fault": lambda m: FaultInjected(m),
    "scanner": lambda m: ScannerException(m),
    "storage": lambda m: StorageException(m),
    "runtime": lambda m: RuntimeError(m),
    "connection": lambda m: ConnectionError(m),
    "timeout": lambda m: TimeoutError(m),
    "oserror": lambda m: OSError(m),
    "unavailable": _unavailable_exc,
    # device memory exhaustion: what util/memstats.is_oom recognizes —
    # a memory.pressure:raise:exc=oom plan forces the OOM-forensics +
    # transient-requeue path deterministically on CPU
    "oom": lambda m: DeviceOutOfMemory(m),
}

_M_FAULTS = _mx.registry().counter(
    "scanner_tpu_faults_injected_total",
    "Faults fired by the chaos-injection registry (util/faults.py), by "
    "injection site and fault mode.  Zero unless a fault plan is armed.",
    labels=["site", "mode"])


@dataclass
class FaultRule:
    """One armed fault: a site, a mode, and a deterministic trigger."""

    site: str
    mode: str
    exc: str = "fault"
    msg: str = "injected fault"
    seconds: float = 0.0
    n: int = 0
    after: int = 0
    every: int = 0
    p: float = 0.0
    seed: int = 0
    times: int = 0
    match: str = ""
    # structured selectors over the "<method>@<peer>" detail the RPC
    # client site passes (match= stays a raw substring): method=
    # selects one RPC method, peer= one remote address — together they
    # model ASYMMETRIC partitions ("calls to THIS peer fail, others
    # succeed") that a plain substring cannot express safely
    method: str = ""
    peer: str = ""
    # runtime state (not part of the spec)
    calls: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)
    _rng: Optional[random.Random] = field(default=None, compare=False,
                                          repr=False)

    def __post_init__(self):
        if self.site not in SITES:
            raise FaultPlanError(
                f"unknown fault site {self.site!r} (known: "
                f"{', '.join(SITES)})")
        if self.mode not in MODES:
            raise FaultPlanError(
                f"unknown fault mode {self.mode!r} (known: "
                f"{', '.join(MODES)})")
        if self.mode == "raise" and self.exc not in _EXC:
            raise FaultPlanError(
                f"unknown exc {self.exc!r} (known: "
                f"{', '.join(sorted(_EXC))})")
        if self.mode == "corrupt" and self.site not in DATA_SITES:
            raise FaultPlanError(
                f"corrupt mode needs a data-carrying site "
                f"({', '.join(DATA_SITES)}); {self.site} passes no "
                f"bytes through inject()")
        if self.mode == "duplicate" and self.site not in DUPLICATE_SITES:
            raise FaultPlanError(
                f"duplicate mode needs a duplicating call site "
                f"({', '.join(DUPLICATE_SITES)}); {self.site} never "
                f"asks take_duplicate()")
        if (self.method or self.peer) \
                and self.site not in SELECTOR_SITES:
            raise FaultPlanError(
                f"method=/peer= selectors need a '<method>@<peer>' "
                f"detail site ({', '.join(SELECTOR_SITES)}); "
                f"{self.site} details carry no peer — use match=")
        if self.p:
            self._rng = random.Random(self.seed)

    def should_fire(self, detail: str) -> bool:
        """Trigger decision for one matching call.  Caller holds the
        registry lock, so counter updates and the RNG draw are atomic
        — the draw sequence is deterministic per rule per process."""
        if self.match and self.match not in detail:
            return False
        if self.method or self.peer:
            m, _sep, p = detail.partition("@")
            if self.method and self.method not in m:
                return False
            if self.peer and self.peer not in p:
                return False
        self.calls += 1
        if self.times and self.fired >= self.times:
            return False
        if self.n:
            hit = self.calls == self.n
        elif self.after:
            hit = self.calls > self.after
        elif self.every:
            hit = self.calls % self.every == 0
        elif self.p:
            hit = self._rng.random() < self.p
        else:
            hit = True
        if hit:
            self.fired += 1
        return hit


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._rules: Dict[str, List[FaultRule]] = {}

    def install(self, rules: Sequence[FaultRule]) -> None:
        by_site: Dict[str, List[FaultRule]] = {}
        for r in rules:
            by_site.setdefault(r.site, []).append(r)
        with self._lock:
            self._rules = by_site

    def clear(self) -> None:
        with self._lock:
            self._rules = {}

    def rules(self) -> List[FaultRule]:
        with self._lock:
            return [r for rs in self._rules.values() for r in rs]

    def take_duplicate(self, site: str, detail: str) -> bool:
        """Trigger decision for the duplicate-delivery rules of a site
        — asked by the call site AFTER a successful call, because only
        the site itself can re-issue the request (inject() cannot)."""
        with self._lock:
            hits = [r for r in self._rules.get(site, ())
                    if r.mode == "duplicate" and r.should_fire(detail)]
        for r in hits:
            _M_FAULTS.labels(site=site, mode="duplicate").inc()
            from . import tracing as _tracing
            _tracing.add_event("fault.injected", site=site,
                               mode="duplicate", detail=detail)
            _log.warning("injecting duplicate delivery at %s "
                         "(detail=%r, fire %d)", site, detail, r.fired)
        return bool(hits)

    def fire(self, site: str, data, detail: str):
        with self._lock:
            # duplicate-mode rules are actioned by take_duplicate()
            # at the call site, never here — inject() must not tick
            # their trigger counters
            hits = [r for r in self._rules.get(site, ())
                    if r.mode != "duplicate" and r.should_fire(detail)]
        for i, r in enumerate(hits):
            try:
                _M_FAULTS.labels(site=site, mode=r.mode).inc()
                # the injection lands on the affected task's trace span
                # (when one is active): a chaos run's merged trace shows
                # WHICH task ate the fault, not just that one fired
                from . import tracing as _tracing
                _tracing.add_event("fault.injected", site=site,
                                   mode=r.mode, detail=detail)
                _log.warning("injecting fault at %s: %s (detail=%r, "
                             "fire %d)", site, r.mode, detail, r.fired)
                if r.mode == "delay":
                    time.sleep(r.seconds)
                elif r.mode == "corrupt":
                    data = _corrupt(data)
                elif r.mode == "crash":
                    # immediate process death — the SIGKILL-grade fault
                    # the cluster's stale-worker scan and bulk recovery
                    # exist for.  os._exit skips atexit/finally:
                    # nothing gets a chance to clean up, exactly like a
                    # real crash.
                    os._exit(CRASH_EXIT_CODE)
                else:  # raise
                    raise _EXC[r.exc](
                        f"{r.msg} [site={site} detail={detail!r}]")
            except BaseException:
                # an earlier rule raising aborts this call: later rules
                # were tentatively marked fired by should_fire but never
                # acted — un-mark them so fired()/the metric never claim
                # an injection that didn't happen
                with self._lock:
                    for later in hits[i + 1:]:
                        later.fired -= 1
                raise
        return data


_registry = _Registry()


def _corrupt(data):
    """Flip every bit of one mid-buffer byte — the silent single-byte
    rot that magic/length checks miss and only a checksum catches.
    (Deliberately not the first byte: flipping a magic number is the
    EASY corruption; the crc32c hardening exists for the rest.)
    Empty/non-bytes data passes through."""
    if not isinstance(data, (bytes, bytearray, memoryview)) or not len(data):
        return data
    b = bytearray(data)
    b[len(b) // 2] ^= 0xFF
    return bytes(b)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def parse_plan(spec: str) -> List[FaultRule]:
    """Parse the ';'-joined clause syntax (module docstring) into rules."""
    rules: List[FaultRule] = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        fields = clause.split(":")
        if len(fields) < 2:
            raise FaultPlanError(
                f"fault clause needs at least site:mode — {clause!r}")
        kw: Dict[str, Union[str, int, float]] = {}
        for f in fields[2:]:
            k, sep, v = f.partition("=")
            if not sep:
                raise FaultPlanError(
                    f"fault clause field {f!r} is not key=value "
                    f"({clause!r})")
            if k in ("n", "after", "every", "times", "seed"):
                kw[k] = int(v)
            elif k in ("p", "seconds"):
                kw[k] = float(v)
            elif k in ("exc", "msg", "match", "method", "peer"):
                kw[k] = v
            else:
                raise FaultPlanError(
                    f"unknown fault clause key {k!r} ({clause!r})")
        rules.append(FaultRule(site=fields[0], mode=fields[1], **kw))
    return rules


def install(plan: Union[str, FaultRule, Sequence[FaultRule]]) -> None:
    """Arm a fault plan (replacing any previous one) and set ACTIVE."""
    global ACTIVE
    if isinstance(plan, str):
        rules = parse_plan(plan)
    elif isinstance(plan, FaultRule):
        rules = [plan]
    else:
        rules = list(plan)
    _registry.install(rules)
    ACTIVE = bool(rules)
    if rules:
        _log.warning("fault plan armed: %d rule(s) across sites %s",
                     len(rules), sorted({r.site for r in rules}))


def clear() -> None:
    """Disarm all faults; hooks return to the single-flag fast path."""
    global ACTIVE
    _registry.clear()
    ACTIVE = False


def inject(site: str, data=None, detail: str = ""):
    """Run the armed rules for `site` against this call.

    Returns `data` (possibly corrupted), raises, sleeps, or kills the
    process per the matching rules.  Hooks should guard the call with
    ``if faults.ACTIVE`` so the disarmed path costs one flag check."""
    if not ACTIVE:
        return data
    return _registry.fire(site, data, detail)


def take_duplicate(site: str, detail: str = "") -> bool:
    """Should this call be delivered a second time?  Asked by sites in
    DUPLICATE_SITES after a successful call — the fault model for
    at-least-once delivery ("partitioned ≠ dead": the first request
    arrived, its reply was lost, the caller repeats)."""
    if not ACTIVE:
        return False
    return _registry.take_duplicate(site, detail)


def fired(site: Optional[str] = None) -> int:
    """Total fault fires (optionally for one site) — the in-process
    twin of scanner_tpu_faults_injected_total, for test assertions."""
    return sum(r.fired for r in _registry.rules()
               if site is None or r.site == site)


def rules() -> List[FaultRule]:
    return _registry.rules()


# canned plans for tools/chaos_run.py and ad-hoc cluster abuse; each
# reproduces one failure class from docs/robustness.md's matrix
NAMED_PLANS = {
    # worker process dies mid-task -> stale scan + task reassignment
    "worker-crash": "pipeline.eval:crash:n=2",
    # worker wedges mid-eval while its heartbeat stays live ->
    # task_timeout revocation, not stale removal
    "worker-hang": "pipeline.eval:delay:seconds=8:n=1",
    # sink item write fails transiently -> requeue without a
    # blacklist strike
    "sink-write-fail":
        "storage.write:raise:exc=storage:msg=injected sink "
        "failure:match=output_:n=2:times=1",
    # stored item bytes flip -> crc32c detection at read -> retry
    "read-corrupt": "storage.read:corrupt:match=tables/:n=1:times=1",
    # RPC plane UNAVAILABLE storm -> client backoff rides it out
    "unavailable-storm":
        "rpc.client.call:raise:exc=unavailable:p=0.5:seed=7:times=40",
    # master dies handling a completion -> restart + _recover_bulk
    "master-crash": "rpc.server.handle:crash:match=FinishedWork:n=4",
    # every heartbeat after the first is dropped -> stale-worker removal
    "heartbeat-drop": "worker.heartbeat:raise:after=1",
    # device HBM exhausted during h2d staging -> one-shot memory report
    # (top ledger entries with owning task/trace), staged buffers freed,
    # strike-free transient requeue, bit-exact completion
    "memory-pressure": "memory.pressure:raise:exc=oom:n=1:times=1",
    # spot reclaim notice on the armed worker's 2nd heartbeat ->
    # Worker.preempt(): master fences assignment from the notice,
    # in-flight tasks drain, leftovers requeue strike-free, siblings
    # re-absorb the work (chaos_run arms ONE of N workers, so N=3 is
    # the headline "preempt ~30% of workers mid-bulk" plan)
    "worker-preempt": "worker.preempt:raise:n=2:times=1",
    # the headline control-plane drill (docs/robustness.md §Durable
    # control plane): the master is killed handling a FinishedWork
    # mid-bulk AND the client's NewJob is delivered twice (ambiguous-
    # timeout retry).  The successor must recover via checkpoint +
    # journal replay with zero acknowledged completions lost, the
    # duplicate admission must dedupe on the token, and chaos_run
    # additionally spawns a forced-stale master and asserts it is
    # fenced with zero accepted mutations.
    "master-failover":
        "rpc.server.handle:crash:match=FinishedWork:n=4;"
        "rpc.client.call:duplicate:method=NewJob:n=1:times=1",
    # the sharded-control-plane drill (docs/robustness.md §Sharded
    # control plane): one of three master shards is SIGKILLed while it
    # handles a FinishedWork mid-bulk — the fault arms in every shard
    # process, but only the shard that owns the bulk ever handles
    # completions, so exactly the bulk-owning shard dies.  chaos_run
    # respawns that shard (same shard id + port, no plan): the respawn
    # CAS-claims the next generation IN ITS SHARD'S NAMESPACE, replays
    # its journal (failover replay > 0, zero re-executed journaled
    # tasks), re-publishes the shard map at a bumped epoch, and the
    # bulk completes bit-exact with zero strikes while the SURVIVING
    # shards' health roll-ups never leave ok/degraded.
    "master-shard-loss":
        "rpc.server.handle:crash:match=FinishedWork:n=4",
    # the gang drill (docs/robustness.md §Gang scheduling): the armed
    # worker dies the moment its first gang member enters the
    # cross-host collective (the runner dies with it via pdeathsig) ->
    # the gang aborts on member loss, the epoch bumps, and the task
    # re-forms on the surviving workers with zero blacklist strikes.
    # Gangs evaluate SHARDED by default (engine/gang.py _sharded_body),
    # so the member dies mid-collective holding undelivered shard rows
    # and the re-formed smaller mesh recomputes shard_range from
    # scratch; chaos_run.py runs a gang_hosts bulk under this plan and
    # requires bit-exact output, a reform at epoch+1, and zero non-ok
    # shard commit folds
    "gang-host-loss": "gang.collective:crash:n=1:times=1",
}


# spawned subprocesses (tests/spawn_worker.py, deploy manifests) arm
# themselves from the environment before serving anything
_env_plan = os.environ.get("SCANNER_TPU_FAULTS", "")
if _env_plan:
    install(_env_plan)
