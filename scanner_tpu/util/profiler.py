"""Interval profiler with Chrome-trace export.

Capability parity: reference scanner/util/profiler.{h,cpp} (per-thread
interval recorder, nanosecond timestamps) + scannerpy/profiler.py
(Profile.write_trace Chrome trace JSON :57-199, statistics :214).
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from . import metrics as _mx
from . import tracing as _tracing

# every profiler counter event mirrors into this live series, so the
# post-mortem trace counters and the /metrics endpoint can never
# disagree on event counts (docs/observability.md)
_M_EVENTS = _mx.registry().counter(
    "scanner_tpu_profiler_events_total",
    "Profiler counter events (state_carry_miss, stream_chunks, ...); "
    "mirrors Profiler.count so traces and live metrics agree.",
    labels=["event"])


@dataclass
class Interval:
    name: str
    start: float
    end: float
    thread: str
    args: Optional[Dict[str, Any]] = None


class Profiler:
    """Low-overhead interval/counter recorder; one instance per process,
    safe for concurrent threads (append-only per-thread lists).

    `level` filters recording like the reference's profiler_level
    (rpc.proto:270-275): spans declare a detail level (0 = coarse stage
    spans, 1 = per-task detail, 2 = verbose) and only spans at or below
    the active level are kept.  `max_intervals` bounds memory for
    long-running jobs — overflow increments the `profiler_dropped`
    counter instead of growing without limit (the reference streams to
    per-thread binary files; here the master ships profiles over RPC, so
    a hard cap is the honest contract)."""

    def __init__(self, node: str = "0", base_time: Optional[float] = None,
                 level: int = 1, max_intervals: int = 200_000):
        self.node = node
        self.base_time = base_time if base_time is not None else time.time()
        self.level = level
        self.max_intervals = max_intervals
        # XLA device-trace captures recorded around jobs at level >= 2
        # ({"dir": trace_dir, "t0": host_start}; util/jaxprof.py)
        self.device_traces: List[Dict[str, Any]] = []
        self._local = threading.local()
        self._all_lists: List[List[Interval]] = []
        self._counters: Dict[str, int] = defaultdict(int)
        self._lock = threading.Lock()

    def _list(self) -> List[Interval]:
        lst = getattr(self._local, "intervals", None)
        if lst is None:
            lst = []
            self._local.intervals = lst
            with self._lock:
                self._all_lists.append(lst)
        return lst

    def _room(self) -> bool:
        # approximate (per-thread lists are append-only; len is O(1))
        if sum(len(lst) for lst in self._all_lists) < self.max_intervals:
            return True
        self.count("profiler_dropped")
        return False

    def span(self, name: str, level: int = 1, **args):
        if level > self.level:
            return _NULL_SPAN
        return _Span(self, name, args or None)

    def add_interval(self, name: str, start: float, end: float,
                     level: int = 1, **args) -> None:
        if level > self.level or not self._room():
            return
        self._list().append(Interval(
            name, start, end, threading.current_thread().name, args or None))

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n
        _M_EVENTS.labels(event=name).inc(n)

    def intervals(self) -> List[Interval]:
        with self._lock:
            out: List[Interval] = []
            for lst in self._all_lists:
                out.extend(lst)
        return sorted(out, key=lambda iv: iv.start)

    @property
    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    # -- serialization (profiles travel from workers to the master) --------

    def to_dict(self) -> dict:
        return {
            "node": self.node,
            "base_time": self.base_time,
            "level": self.level,
            "max_intervals": self.max_intervals,
            "counters": self.counters,
            "device_traces": list(self.device_traces),
            "intervals": [
                {"name": iv.name, "start": iv.start, "end": iv.end,
                 "thread": iv.thread, "args": iv.args}
                for iv in self.intervals()],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Profiler":
        # level/max_intervals must survive the round-trip: a merged
        # worker profile re-filtered or re-capped on the master would
        # silently drop spans the worker already admitted (older
        # serializations lack the keys; keep their recording intact)
        p = cls(node=d["node"], base_time=d["base_time"],
                level=int(d.get("level", 99)),
                max_intervals=int(d.get("max_intervals", 2 ** 63 - 1)))
        p.device_traces = list(d.get("device_traces", []))
        lst = p._list()
        for iv in d["intervals"]:
            lst.append(Interval(iv["name"], iv["start"], iv["end"],
                                iv["thread"], iv.get("args")))
        for k, v in d["counters"].items():
            p._counters[k] = v
        return p


class _NullSpan:
    """Span filtered out by the active profiler level."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("prof", "name", "args", "start", "_trace")

    def __init__(self, prof: Profiler, name: str, args):
        self.prof = prof
        self.name = name
        self.args = args

    def __enter__(self):
        self.start = time.time()
        # hot paths are instrumented ONCE: when a trace context is
        # active on this thread (util/tracing.py), the same with-block
        # also records a distributed-trace span — the stage/op timings
        # in the flight recorder and the profile can never disagree
        self._trace = _tracing.begin_interval(self.name, self.args) \
            if _tracing.enabled() else None
        return self

    def __exit__(self, exc_type, exc, tb):
        if self.prof._room():
            self.prof._list().append(Interval(
                self.name, self.start, time.time(),
                threading.current_thread().name, self.args))
        if self._trace is not None:
            _tracing.end_interval(self._trace, exc)
        return False


class Profile:
    """Aggregated job profile (reference scannerpy/profiler.py Profile)."""

    def __init__(self, profilers: List[Profiler]):
        self.profilers = profilers

    def write_trace(self, path: str, merge_device: bool = True) -> None:
        """Emit Chrome trace JSON (chrome://tracing, perfetto).

        Device traces captured at profiler_level >= 2 (util/jaxprof.py)
        are merged into the same file — host stage spans and the XLA
        device timeline in one view — unless merge_device=False or the
        trace directory is not readable from this host."""
        events = []
        pids = {}
        for p in self.profilers:
            pid = pids.setdefault(p.node, len(pids) + 1)
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "args": {"name": f"node {p.node}"}})
            tids: Dict[str, int] = {}
            for iv in p.intervals():
                tid = tids.setdefault(iv.thread, len(tids) + 1)
                ev = {"name": iv.name, "ph": "X", "pid": pid, "tid": tid,
                      "ts": iv.start * 1e6, "dur": (iv.end - iv.start) * 1e6}
                if iv.args:
                    ev["args"] = {k: str(v) for k, v in iv.args.items()}
                events.append(ev)
            for thread, tid in tids.items():
                events.append({"name": "thread_name", "ph": "M", "pid": pid,
                               "tid": tid, "args": {"name": thread}})
        if merge_device:
            from .jaxprof import DEVICE_PID_BASE, load_device_events
            base = DEVICE_PID_BASE
            for p in self.profilers:
                for rec in getattr(p, "device_traces", []):
                    got = load_device_events(rec, pid_base=base)
                    events.extend(got)
                    if got:
                        # disjoint pid block per capture
                        base += 1000
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)

    def statistics(self) -> Dict[str, Dict[str, float]]:
        """Total/mean seconds per interval label across all nodes."""
        totals: Dict[str, List[float]] = defaultdict(list)
        for p in self.profilers:
            for iv in p.intervals():
                totals[iv.name].append(iv.end - iv.start)
        out = {}
        for name, durs in sorted(totals.items()):
            out[name] = {"count": len(durs), "total_s": sum(durs),
                         "mean_s": sum(durs) / len(durs)}
        counters: Dict[str, int] = defaultdict(int)
        for p in self.profilers:
            for k, v in p.counters.items():
                counters[k] += v
        if counters:
            out["_counters"] = dict(counters)  # type: ignore[assignment]
        return out
