"""Distributed tracing: end-to-end task spans with cross-host assembly.

The Profiler (util/profiler.py) answers "where did this NODE's time go";
the metrics registry (util/metrics.py) answers "what is the cluster doing
right now".  Neither answers the causal question a distributed system
actually debug-loops on: *which* task was slow, and *where* its time went
across client → master → worker → pipeline stage → device op.  This
module adds that third leg:

  * A low-overhead span API: every span carries a 128-bit ``trace_id``
    shared by everything one job caused, a 64-bit ``span_id``, and its
    parent's span id — the assembled tree is the job's causal timeline.
  * W3C-traceparent-style context propagation: ``RpcClient.call``
    injects the current span context into call metadata
    (``_traceparent`` payload key) and the server side re-establishes it
    around the handler, so one trace_id follows a job from
    ``Client.run`` through master scheduling, worker task pull and
    every pipeline stage without any handler changing its signature.
  * A bounded in-memory ring buffer — the **flight recorder** — that
    always holds the most recent completed spans, even when no
    collector is configured: after an incident you can still dump what
    the process was doing (``Tracer.recent``, tools/scanner_trace.py).
  * Export buffers workers drain to ship completed spans to the master
    (engine/service.py ``ShipSpans``), which assembles one merged
    Perfetto/Chrome trace per bulk and computes straggler analytics.

Hot paths are instrumented ONCE: ``Profiler.span`` interval recording
doubles as trace-span recording whenever a trace context is active on
the current thread (see util/profiler.py), so the existing
load/evaluate/save/per-op instrumentation emits both views.

Knobs: ``SCANNER_TPU_TRACING=0`` disables span recording process-wide
(propagation headers stop being injected too); ``SCANNER_TPU_TRACE_RING``
sizes the flight recorder (default 8192 spans); the ``[trace] enabled``
config key is the per-deployment default the env var overrides
(docs/observability.md).
"""

from __future__ import annotations

import contextlib
import json
import os
import random
import re
import threading
import time
from collections import deque
from contextvars import ContextVar
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

from . import metrics as _mx

# every recorded span counts here, so span volume (and ring/export
# overflow) is visible on /metrics next to everything else
_M_SPANS = _mx.registry().counter(
    "scanner_tpu_trace_spans_total",
    "Trace spans completed and recorded by this process's tracers "
    "(flight recorder and/or export buffer).")
_M_SPAN_DROPS = _mx.registry().counter(
    "scanner_tpu_trace_spans_dropped_total",
    "Trace spans evicted from a full flight-recorder ring or dropped "
    "from a full export buffer before shipping.",
    labels=["buffer"])

# payload key RpcClient/RpcServer use to carry the context; popped by
# the server glue before the handler sees the request
TRACEPARENT_KEY = "_traceparent"

_TP_RE = re.compile(r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


def _env_on(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default) not in ("0", "false", "")


_ENABLED = _env_on("SCANNER_TPU_TRACING")


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Flip recording process-wide.  The env var is read at import; this
    is the programmatic override (config key, tests, A/B runs)."""
    global _ENABLED
    _ENABLED = bool(on)


def _ring_capacity() -> int:
    try:
        return max(64, int(os.environ.get("SCANNER_TPU_TRACE_RING",
                                          "8192") or 8192))
    except ValueError:
        return 8192


def new_trace_id() -> str:
    return "%032x" % random.getrandbits(128)


def new_span_id() -> str:
    return "%016x" % random.getrandbits(64)


class SpanContext:
    """The (trace_id, span_id) pair that travels; a remote parent."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def __repr__(self) -> str:  # debugging aid only
        return f"SpanContext({self.trace_id[:8]}…, {self.span_id[:8]}…)"


def parse_traceparent(s: Optional[str]) -> Optional[SpanContext]:
    """W3C-shaped ``00-<32hex>-<16hex>-<2hex>`` -> SpanContext, or None
    for anything malformed (a bad header must never fail a call)."""
    if not s or not isinstance(s, str):
        return None
    m = _TP_RE.match(s)
    if m is None:
        return None
    return SpanContext(m.group(1), m.group(2))


class Span:
    """One timed operation.  Completed spans are recorded as plain dicts
    (msgpack-able — they cross RPC) via :meth:`to_dict`."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "end", "node", "thread", "attrs", "events", "status")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str], start: float, node: str,
                 thread: str, attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end = 0.0
        self.node = node
        self.thread = thread
        self.attrs = attrs
        self.events: List[Dict[str, Any]] = []
        self.status = "ok"

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def add_event(self, name: str, **attrs: Any) -> None:
        ev: Dict[str, Any] = {"name": name, "t": time.time()}
        if attrs:
            ev["attrs"] = attrs
        self.events.append(ev)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "start": self.start, "end": self.end, "node": self.node,
            "thread": self.thread, "status": self.status,
        }
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.events:
            d["events"] = list(self.events)
        return d


class Tracer:
    """Per-component span sink: a bounded flight-recorder ring (always
    on) plus an optional export buffer a shipper drains (workers ship to
    the master; the master drains its own into the bulk's span store).
    One Master/Worker/Client each own a Tracer so in-process clusters
    (tests) keep their components' spans separate."""

    EXPORT_CAP = 65536

    def __init__(self, node: str = "proc", export: bool = False,
                 ring: Optional[int] = None):
        self.node = node
        self._ring: deque = deque(maxlen=ring or _ring_capacity())
        self._export: Optional[List[dict]] = [] if export else None
        self._lock = threading.Lock()

    def record(self, span: Span) -> None:
        d = span.to_dict()
        _M_SPANS.inc()
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                _M_SPAN_DROPS.labels(buffer="ring").inc()
            self._ring.append(d)
            if self._export is not None:
                if len(self._export) < self.EXPORT_CAP:
                    self._export.append(d)
                else:
                    _M_SPAN_DROPS.labels(buffer="export").inc()

    def drain_export(self) -> List[dict]:
        """Take (and clear) the export buffer — the shipper's pull."""
        if self._export is None:
            return []
        with self._lock:
            out, self._export = self._export, []
        return out

    def recent(self, n: int = 50) -> List[dict]:
        """Newest-first tail of the flight recorder."""
        with self._lock:
            items = list(self._ring)
        return list(reversed(items[-n:]))

    def spans_for_trace(self, trace_id: str) -> List[dict]:
        with self._lock:
            return [d for d in self._ring if d["trace_id"] == trace_id]


_DEFAULT = Tracer(node="client")


def default_tracer() -> Tracer:
    """The process-default tracer (local-mode client/executor spans)."""
    return _DEFAULT


# ---------------------------------------------------------------------------
# Context propagation (per-thread via contextvars)
# ---------------------------------------------------------------------------

# (tracer, Span-or-SpanContext); None = not inside any trace
_CURRENT: ContextVar[Optional[Tuple[Tracer,
                                    Union[Span, SpanContext]]]] = \
    ContextVar("scanner_tpu_trace", default=None)


def _ids(obj: Union[Span, SpanContext]) -> Tuple[str, str]:
    return obj.trace_id, obj.span_id


def current_context() -> Optional[SpanContext]:
    cur = _CURRENT.get()
    if cur is None:
        return None
    t, s = _ids(cur[1])
    return SpanContext(t, s)


def current_tracer() -> Optional[Tracer]:
    """The tracer owning the current context, or None.  The calling
    thread's component identity: a span opened by a Worker's executor
    runs under THAT worker's tracer, so ambient consumers (memstats OOM
    reports) can attribute work to the right node even when several
    components share a process."""
    cur = _CURRENT.get()
    return cur[0] if cur is not None else None


def current_span_attrs() -> Dict[str, Any]:
    """Attrs of the current LIVE span, or {} (no span / remote context).
    The stage/task spans on the engine hot paths carry job/task attrs,
    so ambient consumers (the memstats allocation ledger) can attribute
    work to its owning task without new plumbing."""
    cur = _CURRENT.get()
    if cur is None or not isinstance(cur[1], Span):
        return {}
    return dict(cur[1].attrs or {})


def current_traceparent() -> Optional[str]:
    """The header to inject, or None (disabled / outside any trace)."""
    if not _ENABLED:
        return None
    cur = _CURRENT.get()
    if cur is None:
        return None
    t, s = _ids(cur[1])
    return f"00-{t}-{s}-01"


def add_event(name: str, **attrs: Any) -> None:
    """Attach an event to the current live span, if any — the hook fault
    injection (util/faults.py) and transient retries (util/retry.py)
    use, so failures land ON the affected task's timeline."""
    if not _ENABLED:
        return
    cur = _CURRENT.get()
    if cur is None or not isinstance(cur[1], Span):
        return
    cur[1].add_event(name, **attrs)


@contextlib.contextmanager
def use_span(tracer: Tracer, span: Optional[Span]):
    """Make an already-open span current on this thread (stage threads
    resume a task span that was opened on another thread)."""
    if span is None or not _ENABLED:
        yield
        return
    tok = _CURRENT.set((tracer, span))
    try:
        yield
    finally:
        _CURRENT.reset(tok)


@contextlib.contextmanager
def use_context(tracer: Tracer, ctx: Optional[SpanContext]):
    """Make a remote parent current (children attach under it)."""
    if ctx is None or not _ENABLED:
        yield
        return
    tok = _CURRENT.set((tracer, ctx))
    try:
        yield
    finally:
        _CURRENT.reset(tok)


def open_span(tracer: Tracer, name: str,
              parent: Optional[Union[Span, SpanContext]] = None,
              **attrs: Any) -> Optional[Span]:
    """Manually open a span (caller closes with :func:`close_span`).
    ``parent=None`` starts a new root trace.  Returns None when tracing
    is disabled — every consumer treats that as "no span"."""
    if not _ENABLED:
        return None
    if parent is None:
        trace_id, parent_id = new_trace_id(), None
    else:
        # a SpanContext with an empty span_id joins an existing trace
        # as a root-level span (e.g. the master scheduling for a bulk
        # whose submitting client was untraced)
        trace_id, parent_id = _ids(parent)
        parent_id = parent_id or None
    return Span(name, trace_id, new_span_id(), parent_id, time.time(),
                node=tracer.node,
                thread=threading.current_thread().name,
                attrs=attrs or None)


def record_instant(tracer: Tracer, name: str, **attrs: Any) -> None:
    """Record a zero-duration span directly into a tracer's flight
    recorder — point-in-time facts with no span of their own (alert
    firing/resolving transitions from util/health.py land here, so a
    post-incident `tracer.recent()` shows the judgment next to the
    work).  Attaches under the current trace context when one is
    active; otherwise records as a standalone root."""
    if not _ENABLED:
        return
    cur = _CURRENT.get()
    span = open_span(tracer, name, parent=cur[1] if cur else None,
                     **attrs)
    close_span(tracer, span)


def close_span(tracer: Tracer, span: Optional[Span],
               status: Optional[str] = None) -> None:
    if span is None:
        return
    span.end = time.time()
    if status is not None:
        span.status = status
    tracer.record(span)


@contextlib.contextmanager
def start_span(tracer: Tracer, name: str,
               parent: Optional[Union[Span, SpanContext]] = None,
               **attrs: Any):
    """Open a span, make it current, close on exit (status=error on an
    exception).  The ``with``-shaped API for single-thread spans.
    ``parent=None`` nests under the current context when one is active
    (a fresh root otherwise); pass an explicit parent to override —
    use :func:`open_span` when a root is wanted unconditionally."""
    if parent is None:
        cur = _CURRENT.get()
        if cur is not None:
            parent = cur[1]
    span = open_span(tracer, name, parent=parent, **attrs)
    if span is None:
        yield None
        return
    tok = _CURRENT.set((tracer, span))
    try:
        yield span
    except BaseException as e:
        span.status = "error"
        span.add_event("error", type=type(e).__name__, message=str(e)[:200])
        raise
    finally:
        _CURRENT.reset(tok)
        close_span(tracer, span)


# -- the Profiler integration (one instrumentation, two views) --------------

def begin_interval(name: str, attrs: Optional[Dict[str, Any]]):
    """Called by Profiler._Span.__enter__: open a child span of the
    current context (or nothing when there is none — profiler spans
    outside any trace stay trace-free).  Returns an opaque token for
    :func:`end_interval`."""
    cur = _CURRENT.get()
    if cur is None:
        return None
    tracer, parent = cur
    trace_id, parent_id = _ids(parent)
    span = Span(name, trace_id, new_span_id(), parent_id, time.time(),
                node=tracer.node,
                thread=threading.current_thread().name,
                attrs=dict(attrs) if attrs else None)
    tok = _CURRENT.set((tracer, span))
    return (tracer, span, tok)


def end_interval(token, exc: Optional[BaseException] = None) -> None:
    if token is None:
        return
    tracer, span, tok = token
    _CURRENT.reset(tok)
    if exc is not None:
        span.status = "error"
        span.add_event("error", type=type(exc).__name__,
                       message=str(exc)[:200])
    close_span(tracer, span)


# ---------------------------------------------------------------------------
# Assembly: Chrome/Perfetto export + straggler analytics
# ---------------------------------------------------------------------------

def chrome_events(span_dicts: Iterable[dict]) -> List[dict]:
    """Span dicts -> Chrome trace events: one pid per node, one tid per
    (node, thread); span events become instant events on the same row;
    trace/span/parent ids ride in args so Perfetto queries can rebuild
    the tree."""
    events: List[dict] = []
    pids: Dict[str, int] = {}
    tids: Dict[Tuple[str, str], int] = {}
    for d in span_dicts:
        node = d.get("node", "?")
        pid = pids.get(node)
        if pid is None:
            pid = pids[node] = len(pids) + 1
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "args": {"name": f"node {node}"}})
        tkey = (node, d.get("thread", "?"))
        tid = tids.get(tkey)
        if tid is None:
            tid = tids[tkey] = \
                sum(1 for k in tids if k[0] == node) + 1
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": tkey[1]}})
        args = {"trace_id": d["trace_id"], "span_id": d["span_id"],
                "parent_id": d.get("parent_id") or ""}
        for k, v in (d.get("attrs") or {}).items():
            args[k] = str(v)
        if d.get("status") and d["status"] != "ok":
            args["status"] = d["status"]
        events.append({
            "name": d["name"], "ph": "X", "pid": pid, "tid": tid,
            "ts": d["start"] * 1e6,
            "dur": max(d.get("end", 0.0) - d["start"], 0.0) * 1e6,
            "args": args})
        for ev in d.get("events", ()):
            events.append({
                "name": ev.get("name", "event"), "ph": "i", "s": "t",
                "pid": pid, "tid": tid, "ts": ev.get("t", d["start"]) * 1e6,
                "args": {k: str(v)
                         for k, v in (ev.get("attrs") or {}).items()}})
    return events


def write_chrome_trace(span_dicts: Iterable[dict], path: str,
                       device_events: Iterable[dict] = ()) -> str:
    """One merged Perfetto/Chrome JSON: assembled spans from every node,
    plus (optionally) XLA device timelines (util/jaxprof.py)."""
    events = chrome_events(span_dicts)
    events.extend(device_events)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return path


def fold_op_efficiency(span_dict: dict,
                       acc: Dict[str, List[float]]) -> None:
    """Fold one span's `op.efficiency` events (the roofline verdicts
    engine/evaluate.py stamps on evaluate:<op> spans) into the shared
    [eff_sum, n, memory_bound_n] aggregate — used both by the master's
    incremental per-bulk folding (engine/service.py) and the full-dump
    path below, so the two consumers cannot drift."""
    name = span_dict.get("name", "")
    if not isinstance(name, str) or not name.startswith("evaluate:"):
        return
    for ev in span_dict.get("events", ()):
        if ev.get("name") != "op.efficiency":
            continue
        a = ev.get("attrs") or {}
        try:
            eff = float(a.get("eff") or 0.0)
        except (TypeError, ValueError):
            continue
        es = acc.setdefault(name, [0.0, 0, 0])
        es[0] += eff
        es[1] += 1
        if a.get("bound") == "memory":
            es[2] += 1


def op_efficiency_summary(es: Optional[List[float]]) -> Dict[str, float]:
    """One aggregate's reporting shape ({} when nothing was folded)."""
    if not es or not es[1]:
        return {}
    return {"eff_mean": round(es[0] / es[1], 4),
            "memory_bound_frac": round(es[2] / es[1], 4)}


def straggler_summary(span_dicts: Iterable[dict],
                      top_n: int = 10) -> Dict[str, Any]:
    """Per-span-name duration stats + the top-N slowest task spans (with
    their trace ids, so one jump lands in the merged trace).  Used by
    tools/scanner_trace.py on full dumps; the master maintains the same
    shape incrementally (engine/service.py) for GetJobStatus//statusz."""
    span_dicts = list(span_dicts)  # iterated twice (gang fold below)
    per: Dict[str, List[float]] = {}
    tasks: List[Tuple[float, dict]] = []
    # roofline verdicts from op.efficiency events on evaluate:<op>
    # spans — the same fold the master maintains incrementally
    # (engine/service.py uses these exact helpers)
    eff: Dict[str, List[float]] = {}
    for d in span_dicts:
        dur = max(d.get("end", 0.0) - d.get("start", 0.0), 0.0)
        per.setdefault(d["name"], []).append(dur)
        if d["name"] == "task":
            tasks.append((dur, d))
        fold_op_efficiency(d, eff)
    tasks.sort(key=lambda x: -x[0])
    out_stages = {}
    for name, durs in sorted(per.items()):
        out_stages[name] = {
            "count": len(durs), "total_s": round(sum(durs), 4),
            "max_s": round(max(durs), 4),
            "mean_s": round(sum(durs) / len(durs), 4)}
        out_stages[name].update(op_efficiency_summary(eff.get(name)))
    slowest = []
    for dur, d in tasks[:top_n]:
        a = d.get("attrs") or {}
        row = {"job": a.get("job"), "task": a.get("task"),
               "seconds": round(dur, 4), "node": d.get("node"),
               "trace_id": d["trace_id"],
               "span_id": d["span_id"]}
        # gang member task spans carry their gang/epoch/member rank
        # (engine/gang.py): surfacing them keeps a slow HOST inside a
        # co-scheduled gang attributable, not just a slow task
        if a.get("gang") is not None:
            row["gang"] = a.get("gang")
            row["member"] = a.get("member")
        slowest.append(row)
    out = {"per_stage": out_stages, "slowest_tasks": slowest}
    gangs = gang_skew_summary(
        d for d in span_dicts
        if d.get("name") in ("gang.barrier", "gang.collective"))
    if gangs:
        out["gangs"] = gangs
    return out


def gang_skew_summary(span_dicts: Iterable[dict]) -> List[dict]:
    """Per-(gang, epoch) straggler attribution from a full span dump —
    the same rows the master folds incrementally from absorbed
    gang.barrier/gang.collective spans (engine/service.py
    `_fold_gang_phase_locked`): barrier-arrival skew (max - min member
    entry), the slowest member's node and lag vs the median arrival,
    and whether the gang step was barrier-bound or collective-bound.
    Assumes timestamps are already on one clock (the master rebases
    remote spans before handing out the dump); newest epochs last."""
    folds: Dict[Tuple[Any, Any], dict] = {}
    for d in span_dicts:
        name = d.get("name")
        if name not in ("gang.barrier", "gang.collective"):
            continue
        a = d.get("attrs") or {}
        if a.get("gang") is None or a.get("member") is None:
            continue
        rec = folds.setdefault((a.get("gang"), a.get("epoch")), {
            "num": a.get("num"), "job": a.get("job"),
            "task": a.get("task"),
            "arrive": {}, "wait": {}, "collective": {}, "node": {}})
        m = a.get("member")
        rec["node"][m] = d.get("node")
        dur = max(float(d.get("end") or 0.0)
                  - float(d.get("start") or 0.0), 0.0)
        if name == "gang.barrier":
            rec["arrive"][m] = float(d.get("start") or 0.0)
            rec["wait"][m] = dur
        else:
            rec["collective"][m] = dur
    rows = []
    for (gid, ep), rec in sorted(folds.items(),
                                 key=lambda kv: (str(kv[0][0]),
                                                 str(kv[0][1]))):
        num = rec.get("num")
        if not rec["arrive"] or not rec["collective"] \
                or (num and (len(rec["arrive"]) < num
                             or len(rec["collective"]) < num)):
            continue  # incomplete fold (aborted gang / partial dump)
        arrivals = sorted(rec["arrive"].items(), key=lambda kv: kv[1])
        vals = [t for _, t in arrivals]
        skew = vals[-1] - vals[0]
        median = vals[len(vals) // 2] if len(vals) % 2 \
            else (vals[len(vals) // 2 - 1] + vals[len(vals) // 2]) / 2.0
        slow_member, slow_t = arrivals[-1]
        coll_max = max(rec["collective"].values())
        rows.append({
            "gang": gid, "epoch": ep,
            "job": rec["job"], "task": rec["task"],
            "skew_s": round(skew, 4),
            "slowest": rec["node"].get(slow_member),
            "member": slow_member,
            "lag_s": round(slow_t - median, 4),
            "bound": "barrier" if skew >= coll_max else "collective",
            "barrier_wait_max_s": round(max(rec["wait"].values()), 4),
            "collective_max_s": round(coll_max, 4),
        })
    return rows


def verify_chain(span_dicts: Iterable[dict]) -> Dict[str, Any]:
    """Audit an assembled trace: for every task span, is its parent
    chain unbroken back to the root under one trace_id, and does it own
    stage children (load/evaluate/save) and at least one op span?
    Returns {tasks, complete, broken: [...]} — the test suite and
    scanner_trace --verify share this."""
    by_id = {d["span_id"]: d for d in span_dicts}
    trace_ids = {d["trace_id"] for d in by_id.values()}
    kids: Dict[str, List[dict]] = {}
    for d in by_id.values():
        if d.get("parent_id"):
            kids.setdefault(d["parent_id"], []).append(d)
    # per-op spans inherit the profiler's level filter (hot paths are
    # instrumented once): at profiler_level=0 no op span exists
    # anywhere, and their absence is a recording choice, not a break
    has_op_spans = any(d["name"].startswith("evaluate:")
                       for d in by_id.values())
    broken = []
    n_tasks = 0
    for d in by_id.values():
        if d["name"] != "task":
            continue
        n_tasks += 1
        a = d.get("attrs") or {}
        label = f"({a.get('job')},{a.get('task')})"
        # walk to the root
        seen = set()
        cur = d
        while cur.get("parent_id"):
            if cur["span_id"] in seen:
                broken.append(f"task {label}: parent cycle")
                break
            seen.add(cur["span_id"])
            nxt = by_id.get(cur["parent_id"])
            if nxt is None:
                broken.append(
                    f"task {label}: parent {cur['parent_id'][:8]} of "
                    f"`{cur['name']}` missing from the assembled trace")
                break
            cur = nxt
        if d.get("status") != "ok":
            # an errored/revoked attempt legitimately stops mid-chain
            # (a fault during evaluate leaves no save span); only its
            # ancestry is audited
            continue
        stages = {k["name"] for k in kids.get(d["span_id"], ())}
        for want in ("load", "evaluate", "save"):
            if want not in stages:
                broken.append(f"task {label}: no `{want}` stage span")
        evs = [k for k in kids.get(d["span_id"], ())
               if k["name"] == "evaluate"]
        if has_op_spans and evs and not any(
                k["name"].startswith("evaluate:")
                for e in evs for k in kids.get(e["span_id"], ())):
            broken.append(f"task {label}: no per-op span under evaluate")
    # an EMPTY trace must not audit as complete: "100% of zero tasks"
    # is exactly the vacuous pass a tracing outage would produce
    return {"tasks": n_tasks, "trace_ids": sorted(trace_ids),
            "complete": n_tasks > 0 and not broken
            and len(trace_ids) == 1,
            "broken": broken}
