"""Cross-host clock synchronization (docs/observability.md §Cross-host
time).

A gang's merged trace interleaves spans stamped by N unsynchronized
wall clocks: two hosts whose clocks disagree by 80 ms render a barrier
that "ends before it starts".  This module estimates each worker's
clock offset relative to the master with the classic NTP four-timestamp
exchange, piggybacked on the heartbeat RPC the worker already sends
every second — no new control-plane traffic:

    worker stamps t0 just before the Heartbeat call
    master stamps t1 on arrival and t2 when it builds the reply
    worker stamps t3 on receipt

    offset = ((t1 - t0) + (t2 - t3)) / 2     # master_time - worker_time
    rtt    = (t3 - t0) - (t2 - t1)

The offset estimate assumes symmetric network delay; the error from
asymmetry is bounded by rtt/2, so the estimator keeps only the K
lowest-RTT samples from a sliding window (low-RTT exchanges are the
least likely to have been queued asymmetrically) and EWMA-smooths the
offset over them.  The published uncertainty is max(rtt_best/2,
offset spread across the kept samples) — an honest bound, not a
variance estimate.

Consumers:
  * the master publishes `scanner_tpu_clock_offset_seconds{node}` /
    `scanner_tpu_clock_offset_uncertainty_seconds{node}` gauges from
    the estimate each worker advertises on its next heartbeat;
  * every ShipSpans/FinishedWork span batch carries the shipping
    worker's contemporaneous estimate, so trace assembly
    (engine/service.py GetTrace) can rebase remote span timestamps
    onto master time (`rebase_spans` below) — unless the uncertainty
    exceeds `rebase_max_uncertainty_s`, in which case the raw
    timestamps are kept (a wrong correction is worse than none);
  * the master's barrier-skew histogram corrects member arrival
    timestamps with these offsets before computing max-min.

Knobs: env `SCANNER_TPU_CLOCKSYNC` (0 disables estimation; wins over
config), `[trace] clocksync_enabled`, `[trace] rebase_clocks` (default
on; `--raw-clocks` on the CLI / `raw_clocks=True` on GetTrace is the
per-call escape hatch).
"""

import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from scanner_tpu.util import metrics as _mx

# [trace] keys owned by this module (scanner-check SC314 cross-checks
# these against config.py's [trace] section and docs/guide.md rows)
CONFIG_KEYS = ("clocksync_enabled", "rebase_clocks")

# series owned by this module (SC314 cross-checks registrations and the
# observability.md clocksync-series marker table against this tuple)
CLOCKSYNC_SERIES = (
    "scanner_tpu_clock_offset_seconds",
    "scanner_tpu_clock_offset_uncertainty_seconds",
)

_G_OFFSET = _mx.registry().gauge(
    "scanner_tpu_clock_offset_seconds",
    "Estimated clock offset of a worker vs the master "
    "(master_time - worker_time), from the NTP-style heartbeat "
    "exchange", labels=["node"])
_G_UNCERT = _mx.registry().gauge(
    "scanner_tpu_clock_offset_uncertainty_seconds",
    "Uncertainty bound on the worker clock-offset estimate "
    "(max of best-RTT/2 and kept-sample spread)", labels=["node"])

# estimation on/off: env wins over config (mirrors SCANNER_TPU_TRACING)
_env = os.environ.get("SCANNER_TPU_CLOCKSYNC")
_ENABLED = _env != "0" if _env is not None else True

# rebase-at-read-time default (GetTrace); per-call raw_clocks overrides
_REBASE = True

# above this uncertainty a rebase would smear spans by more than it
# aligns them — trace assembly falls back to raw timestamps per node
REBASE_MAX_UNCERTAINTY_S = 0.25


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def rebase_enabled() -> bool:
    return _REBASE


def set_rebase_enabled(on: bool) -> None:
    global _REBASE
    _REBASE = bool(on)


class OffsetEstimator:
    """Per-peer NTP offset estimator over piggybacked heartbeat stamps.

    Keeps a sliding window of (offset, rtt) samples, selects the K
    lowest-RTT ones, and EWMA-smooths the offset over them.  A step
    change in the peer clock (VM migration, ntpd slew) flushes the
    window once the new samples disagree with the old estimate by more
    than the uncertainty bound, so convergence after a step is one
    window, not one EWMA half-life.
    """

    WINDOW = 32          # sliding window of recent exchanges
    KEEP = 8             # K lowest-RTT samples the estimate uses
    ALPHA = 0.25         # EWMA weight of the newest best-K mean

    def __init__(self):
        self._lock = threading.Lock()
        self._samples: List[Tuple[float, float]] = []  # (offset, rtt)
        self._offset: Optional[float] = None
        self._uncertainty: Optional[float] = None
        self._at: float = 0.0

    def add_sample(self, t0: float, t1: float, t2: float,
                   t3: float) -> None:
        rtt = (t3 - t0) - (t2 - t1)
        if rtt < 0:
            return            # non-causal stamps: clock stepped mid-RPC
        offset = ((t1 - t0) + (t2 - t3)) / 2.0
        with self._lock:
            # step-change detection: if the new sample disagrees with
            # the converged estimate by far more than the bound, the
            # peer clock moved — restart from the new regime instead of
            # EWMA-dragging through stale samples for a whole window
            if (self._offset is not None
                    and self._uncertainty is not None
                    and abs(offset - self._offset)
                    > 4 * max(self._uncertainty, rtt / 2.0, 1e-4)):
                self._samples = []
                self._offset = None
                self._uncertainty = None
            self._samples.append((offset, rtt))
            if len(self._samples) > self.WINDOW:
                self._samples = self._samples[-self.WINDOW:]
            best = sorted(self._samples, key=lambda s: s[1])[:self.KEEP]
            mean = sum(o for o, _ in best) / len(best)
            spread = max(o for o, _ in best) - min(o for o, _ in best) \
                if len(best) > 1 else 0.0
            # asymmetry error bound: half the best (smallest) RTT kept
            bound = max(best[0][1] / 2.0, spread)
            if self._offset is None:
                self._offset = mean
            else:
                self._offset += self.ALPHA * (mean - self._offset)
            self._uncertainty = bound
            self._at = t3

    def estimate(self) -> Optional[dict]:
        """{"offset", "uncertainty", "at"} or None before any sample."""
        with self._lock:
            if self._offset is None:
                return None
            return {"offset": self._offset,
                    "uncertainty": self._uncertainty,
                    "at": self._at}


def publish(node: str, est: Optional[dict]) -> None:
    """Publish a worker's advertised estimate as the two gauges (called
    on the master, which is the scrape point for cluster metrics)."""
    if not est:
        return
    _G_OFFSET.labels(node=node).set(float(est.get("offset", 0.0)))
    _G_UNCERT.labels(node=node).set(
        float(est.get("uncertainty", 0.0)))


def unpublish(node: str) -> None:
    """Drop a departed node's gauge children.  Worker ids are never
    reused, so a stale offset sample would sit in every scrape of a
    long-lived master — and in an embedding process that outlives the
    master (test suites), the node-labeled children would leak into a
    later owner's view of the shared registry."""
    _G_OFFSET.remove_labels(node=node)
    _G_UNCERT.remove_labels(node=node)


def should_rebase(est: Optional[dict],
                  max_uncertainty_s: Optional[float] = None) -> bool:
    """True when an estimate is trustworthy enough to correct spans
    with: present, and uncertainty within the alignment threshold."""
    if not est:
        return False
    limit = REBASE_MAX_UNCERTAINTY_S if max_uncertainty_s is None \
        else max_uncertainty_s
    try:
        return float(est.get("uncertainty", float("inf"))) <= limit
    except (TypeError, ValueError):
        return False


def rebase_spans(span_dicts: Sequence[dict],
                 offsets: Dict[str, dict],
                 max_uncertainty_s: Optional[float] = None) -> list:
    """Return copies of span dicts with start/end (and event "t"
    stamps) shifted onto master time by each span's node offset.

    `offsets` maps node -> {"offset", "uncertainty", "at"}.  Nodes
    without a trustworthy estimate (missing, or uncertainty above the
    threshold) keep raw timestamps; the caller reports which nodes were
    corrected.  Durations are offset-invariant, so per-stage stats
    computed from raw spans stay valid.
    """
    out = []
    for d in span_dicts:
        est = offsets.get(d.get("node"))
        if not should_rebase(est, max_uncertainty_s):
            out.append(d)
            continue
        off = float(est["offset"])
        c = dict(d)
        if c.get("start") is not None:
            c["start"] = c["start"] + off
        if c.get("end") is not None:
            c["end"] = c["end"] + off
        if c.get("events"):
            c["events"] = [dict(ev, t=ev["t"] + off) if "t" in ev
                           else dict(ev) for ev in c["events"]]
        c["clock_rebased"] = True
        out.append(c)
    return out
