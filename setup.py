import os
import subprocess

from setuptools import find_packages, setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    """Builds libscvid.so (the native video layer) before the Python
    package so ctypes finds it inside scanner_tpu/video/."""

    def run(self):
        here = os.path.dirname(os.path.abspath(__file__))
        subprocess.check_call(["make", "-C", os.path.join(here, "cpp")])
        super().run()


setup(
    name="scanner_tpu",
    version="0.1.0",
    description=("TPU-native framework for efficient analysis of large "
                 "video datasets (scanner-research/scanner capabilities, "
                 "JAX/XLA execution)"),
    packages=find_packages(include=["scanner_tpu", "scanner_tpu.*"]),
    package_data={"scanner_tpu.video": ["libscvid.so"]},
    python_requires=">=3.10",
    install_requires=[
        "jax", "flax", "optax", "numpy", "msgpack", "cloudpickle",
        "grpcio",
        # item-file integrity: crc32c checksums (storage/items.py).
        # Load-bearing — without it writers fall back to zlib.crc32
        # (format version 3) and readers skip crc32c verification.
        "google-crc32c",
        # config.py falls back to tomli where stdlib tomllib is absent
        'tomli; python_version < "3.11"',
    ],
    entry_points={
        "console_scripts": [
            # repo-native static analysis (docs/static-analysis.md);
            # tools/scanner_check.py is the in-checkout equivalent
            "scanner-check=scanner_tpu.analysis.static.cli:main",
        ],
    },
    cmdclass={"build_py": BuildWithNative},
)
