"""Durable control plane: write-ahead bulk journal, master generation
fencing, idempotent admission (docs/robustness.md §Durable control
plane; scanner_tpu/engine/journal.py).

Layers:
  * journal units — record framing, torn-tail tolerance, mid-stream
    corruption, rotation, cut/compaction;
  * generation units — CAS claim races (exactly one winner), the
    worker-side latch NACKing stale replies;
  * in-process master units — NewJob token dedupe, journal-only
    recovery (checkpoint_frequency=0), corrupt-checkpoint fallback to
    journal replay, a superseded master fencing itself;
  * the spawned failover drill (slow) — SIGKILL the master mid-bulk
    with a duplicate-delivered NewJob and a forced-stale master alive:
    zero journaled completions re-executed, dedupe to the same bulk,
    stale master fenced, output bit-exact, zero strikes.
"""

import os
import struct
import subprocess
import sys
import threading
import time

import cloudpickle
import pytest

from scanner_tpu import (CacheMode, Client, Kernel, NamedStream,
                         PerfParams, register_op)
from scanner_tpu.engine import journal
from scanner_tpu.engine.service import (MASTER_SERVICE, Master, Worker)
from scanner_tpu.storage import metadata as smd
from scanner_tpu.storage.backend import MemoryStorage, PosixStorage
from scanner_tpu.storage.items import (ItemCorruptionError, open_blob,
                                       seal_blob)
from scanner_tpu.util import faults
from scanner_tpu.util import metrics as _mx

# test kernels travel to worker subprocesses inside the job spec
cloudpickle.register_pickle_by_value(sys.modules[__name__])

pytestmark = pytest.mark.chaos

N_ROWS = 24


def _pk(v: int) -> bytes:
    return struct.pack("<q", v)


@register_op(name="FailoverDouble")
class FailoverDouble(Kernel):
    def execute(self, x: bytes) -> bytes:
        return _pk(2 * struct.unpack("<q", x)[0])


@register_op(name="FailoverRowLog")
class FailoverRowLog(Kernel):
    """Doubles the packed int AND appends it to a shared log file, so
    the drill can assert exactly which rows were (re)executed."""

    def __init__(self, config, log_path: str = ""):
        super().__init__(config)
        self._log = log_path

    def execute(self, x: bytes) -> bytes:
        v = struct.unpack("<q", x)[0]
        time.sleep(0.1)
        with open(self._log, "a") as fh:
            fh.write(f"{v}\n")
        return _pk(2 * v)


EXPECT = [_pk(2 * (100 + i)) for i in range(N_ROWS)]


def _counter(name: str, **labels) -> float:
    entry = _mx.registry().snapshot().get(name, {})
    for s in entry.get("samples", []):
        if s["labels"] == labels:
            return s["value"]
    return 0.0


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# journal units
# ---------------------------------------------------------------------------

def test_sealed_blob_roundtrip_and_corruption():
    payload = b"control-plane state" * 10
    blob = seal_blob(payload)
    assert open_blob(blob, "x") == payload
    # a flipped payload byte is DETECTED, not silently accepted
    rotten = bytearray(blob)
    rotten[len(rotten) // 2] ^= 0xFF
    with pytest.raises(ItemCorruptionError):
        open_blob(bytes(rotten), "x")
    # non-sealed data is distinguishable (legacy fallback path)
    from scanner_tpu.common import StorageException
    with pytest.raises(StorageException):
        open_blob(b"just a pickle blob, no magic", "x")


def test_journal_roundtrip_rotation_and_compaction():
    s = MemoryStorage()
    j = journal.BulkJournal(s, generation=3, rotate=4)
    for i in range(10):
        j.append({"t": "done", "j": 0, "k": i})
    # 10 records at rotate=4 -> segments 0,1 sealed + open segment 2
    segs = s.list_prefix(smd.journal_dir(3))
    assert len(segs) == 3, segs
    recs, stats = journal.replay(s, 3)
    assert [r["k"] for r in recs] == list(range(10))
    assert stats["records"] == 10 and stats["corrupt"] == 0

    # cut seals the open segment; compaction below the cut drops
    # everything a snapshot at the cut point covers
    cut = j.cut()
    j.append({"t": "done", "j": 0, "k": 99})
    j.compact_below(cut)
    recs, _stats = journal.replay(s, 3)
    assert [r["k"] for r in recs] == [99]
    # reset drops the whole generation's journal
    j.reset()
    assert s.list_prefix(smd.journal_dir(3)) == []


def test_journal_torn_tail_tolerated():
    s = MemoryStorage()
    j = journal.BulkJournal(s, generation=1, rotate=100)
    for i in range(5):
        j.append({"t": "done", "j": 0, "k": i})
    path = smd.journal_segment_path(1, 0)
    blob = s.read(path)
    # truncate mid-way through the final record: the torn-tail a crash
    # mid-append leaves on a non-atomic backend
    s.write(path, blob[:-7])
    recs, stats = journal.replay(s, 1)
    assert [r["k"] for r in recs] == [0, 1, 2, 3]
    assert stats["torn"] == 1 and stats["corrupt"] == 0


def test_journal_corrupt_mid_stream_stops_at_error(caplog):
    import logging

    s = MemoryStorage()
    j = journal.BulkJournal(s, generation=1, rotate=3)
    for i in range(6):  # two sealed segments
        j.append({"t": "done", "j": 0, "k": i})
    path = smd.journal_segment_path(1, 0)
    blob = bytearray(s.read(path))
    # rot INSIDE the first record's payload (frame header is 12 bytes):
    # a checksum mismatch on a non-final record, not a torn tail
    blob[14] ^= 0xFF
    s.write(path, bytes(blob))
    with caplog.at_level(logging.ERROR, logger="scanner_tpu.journal"):
        recs, stats = journal.replay(s, 1)
    assert stats["corrupt"] == 1
    # replay stopped at the corruption: segment 1's records not applied
    assert all(r["k"] < 3 for r in recs)
    assert "corrupt record" in caplog.text.lower()


def test_generation_cas_exactly_one_winner():
    s = MemoryStorage()
    wins = []
    barrier = threading.Barrier(8)

    def racer():
        barrier.wait()
        if journal.try_claim(s, 5, note="racer"):
            wins.append(1)

    threads = [threading.Thread(target=racer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1
    assert journal.highest_claimed(s) == 5
    # claim_generation is monotonic past existing claims
    assert journal.claim_generation(s) == 6
    assert journal.claim_generation(s) == 7


def test_claim_generation_forced_attach(monkeypatch):
    s = MemoryStorage()
    assert journal.claim_generation(s) == 1
    monkeypatch.setenv("SCANNER_TPU_MASTER_GENERATION", "1")
    # forced attach: no new claim is minted
    assert journal.claim_generation(s) == 1
    assert journal.highest_claimed(s) == 1


def test_generation_latch_nacks_stale():
    latch = journal.GenerationLatch()
    base = _counter("scanner_tpu_stale_master_rejections_total",
                    side="worker")
    assert latch.observe({"generation": 2})       # latches
    assert latch.observe({"generation": 2})       # same gen ok
    assert latch.observe({"no_generation": True})  # legacy passes
    assert latch.observe(None)
    assert not latch.observe({"generation": 1})   # stale -> NACK
    assert latch.highest() == 2
    assert _counter("scanner_tpu_stale_master_rejections_total",
                    side="worker") == base + 1


# ---------------------------------------------------------------------------
# in-process master units
# ---------------------------------------------------------------------------

def _seed_db(tmp_path, table="fo_src"):
    db_path = str(tmp_path / "db")
    sc = Client(db_path=db_path)
    sc.new_table(table, ["output"],
                 [[_pk(100 + i)] for i in range(N_ROWS)])
    return sc, db_path


def _spec_blob(sc, out_name, **perf_kw):
    col = sc.io.Input([NamedStream(sc, "fo_src")])
    col = sc.ops.FailoverDouble(x=col)
    out = NamedStream(sc, out_name)
    node = sc.io.Output(col, [out])
    return cloudpickle.dumps({
        "outputs": [node],
        "perf": PerfParams.manual(2, 2, **perf_kw),
        "cache_mode": CacheMode.Overwrite.value})


def _finish_tasks(master, bulk_id, wid, n):
    """Drive n assign->finish cycles through the real handlers."""
    done = []
    for _ in range(n):
        r = master._rpc_next_work({"worker_id": wid, "bulk_id": bulk_id})
        assert r["status"] == "task", r
        ok = master._rpc_finished_work({
            "worker_id": wid, "bulk_id": bulk_id,
            "job_idx": r["job_idx"], "task_idx": r["task_idx"],
            "attempt": r["attempt"]})
        assert ok["ok"]
        done.append((r["job_idx"], r["task_idx"]))
    return done


def test_newjob_token_dedupe(tmp_path):
    sc, db_path = _seed_db(tmp_path)
    master = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        base = _counter("scanner_tpu_admission_dedup_total")
        spec = _spec_blob(sc, "fo_dedupe")
        r1 = master._rpc_new_job({"spec": spec, "token": "tok-A"})
        assert "bulk_id" in r1 and not r1.get("dedup")
        # the ambiguous-timeout retry: same token -> same bulk, no
        # "already active" error, no second admission
        r2 = master._rpc_new_job({"spec": spec, "token": "tok-A"})
        assert r2 == {"bulk_id": r1["bulk_id"], "dedup": True}
        assert _counter("scanner_tpu_admission_dedup_total") == base + 1
        # a DIFFERENT token while the bulk is active is a real second
        # job: rejected as before
        r3 = master._rpc_new_job({"spec": spec, "token": "tok-B"})
        assert "error" in r3 and not r3.get("dedup")
    finally:
        master.stop()
        sc.stop()


def test_recovery_via_journal_only(tmp_path):
    """checkpoint_frequency=0: the progress snapshot is never written —
    with the journal, a successor still restores every acknowledged
    completion (the pre-journal code lost ALL of them here)."""
    sc, db_path = _seed_db(tmp_path)
    m1 = Master(db_path=db_path, no_workers_timeout=60.0)
    spec = _spec_blob(sc, "fo_jr")
    bid = m1._rpc_new_job({"spec": spec, "token": "tok-R"})["bulk_id"]
    wid = m1._rpc_register_worker({"address": ""})["worker_id"]
    done = _finish_tasks(m1, bid, wid, 3)
    m1.stop()  # no checkpoint clear: the bulk is still active

    replayed0 = _counter("scanner_tpu_journal_replayed_records_total")
    m2 = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        assert m2.generation > m1.generation
        with m2._lock:
            bulk = m2._bulk
            assert bulk is not None and bulk.bulk_id == bid
            assert set(done) <= bulk.done, \
                "journaled completions lost on recovery"
            assert len(bulk.done) == len(done)
        assert _counter("scanner_tpu_journal_replayed_records_total") \
            > replayed0
        # the admission token rode the journal/checkpoint: a retried
        # NewJob against the SUCCESSOR dedupes to the recovered bulk
        base = _counter("scanner_tpu_admission_dedup_total")
        r = m2._rpc_new_job({"spec": spec, "token": "tok-R"})
        assert r == {"bulk_id": bid, "dedup": True}
        assert _counter("scanner_tpu_admission_dedup_total") == base + 1
        # the predecessor's generation directory was dropped after the
        # state migrated under m2's generation
        assert not m2.db.backend.exists(
            smd.bulk_checkpoint_path(m1.generation))
        assert m2.db.backend.exists(
            smd.bulk_checkpoint_path(m2.generation))
    finally:
        m2.stop()
        sc.stop()


def test_corrupt_checkpoint_falls_back_to_journal(tmp_path, caplog):
    """Satellite: an unreadable checkpoint no longer silently drops the
    bulk — admission state comes from the journaled admit record, at
    ERROR."""
    import logging

    sc, db_path = _seed_db(tmp_path)
    m1 = Master(db_path=db_path, no_workers_timeout=60.0)
    spec = _spec_blob(sc, "fo_ck")
    bid = m1._rpc_new_job({"spec": spec, "token": "tok-C"})["bulk_id"]
    wid = m1._rpc_register_worker({"address": ""})["worker_id"]
    done = _finish_tasks(m1, bid, wid, 2)
    m1.stop()
    # rot the sealed checkpoint payload in place
    ck = smd.bulk_checkpoint_path(m1.generation)
    backend = PosixStorage(db_path)
    blob = bytearray(backend.read(ck))
    blob[-3] ^= 0xFF
    backend.write(ck, bytes(blob))

    with caplog.at_level(logging.ERROR):
        m2 = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        assert "falling back to journal replay" in caplog.text
        with m2._lock:
            bulk = m2._bulk
            assert bulk is not None and bulk.bulk_id == bid, \
                "corrupt checkpoint dropped the bulk"
            assert set(done) <= bulk.done
    finally:
        m2.stop()
        sc.stop()


def test_superseded_master_fences_itself(tmp_path):
    _sc, db_path = _seed_db(tmp_path)
    _sc.stop()
    m1 = Master(db_path=db_path, no_workers_timeout=60.0)
    m2 = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        assert m2.generation == m1.generation + 1
        assert not m2._fence.is_set()
        # m1 discovers the newer claim on its next fence poll
        assert m1._check_fence() is True
        base = _counter("scanner_tpu_stale_master_rejections_total",
                        side="master")
        wrapped = m1._fenced(m1._rpc_new_job)
        reply = wrapped({"spec": b"ignored", "token": "t"})
        assert reply.get("fenced") and "error" in reply
        assert reply["generation"] == m1.generation
        assert _counter("scanner_tpu_stale_master_rejections_total",
                        side="master") == base + 1
        # the live master's fenced wrapper stamps its generation on
        # ordinary replies (what workers latch)
        live = m2._fenced(lambda req: {"ok": True})
        assert live({})["generation"] == m2.generation
    finally:
        m1.stop()
        m2.stop()


def test_worker_nacks_stale_assignment(tmp_path):
    """A worker that has latched generation G refuses assignments (and
    ignores revocation verdicts) stamped with anything older."""
    _sc, db_path = _seed_db(tmp_path)
    _sc.stop()
    master = Master(db_path=db_path, no_workers_timeout=60.0)
    addr = f"localhost:{master.port}"
    worker = Worker(addr, db_path=db_path)
    try:
        gen = master.generation
        orig = worker.master.try_call

        def fake(method, timeout=None, retries=None, **kw):
            if method == "Heartbeat":
                # the successor's view: a NEWER generation
                return {"reregister": False, "active_bulk": 7,
                        "generation": gen + 1}
            if method == "NextWork":
                # ...but the stale master still answers assignments
                return {"status": "task", "job_idx": 0, "task_idx": 0,
                        "attempt": 0, "generation": gen}
            if method == "StartedWork":
                # a stale master's revocation verdict
                return {"ok": False, "revoked": True,
                        "generation": gen}
            return orig(method, timeout=timeout, retries=retries, **kw)

        worker.master.try_call = fake
        # let the heartbeat latch the newer generation
        deadline = time.time() + 10
        while time.time() < deadline \
                and worker._gen.highest() <= gen:
            time.sleep(0.05)
        assert worker._gen.highest() == gen + 1
        base = _counter("scanner_tpu_stale_master_rejections_total",
                        side="worker")
        worker._hb_reply = {"active_bulk": 7, "generation": gen + 1}
        assert worker._pull_next(7) == "wait", \
            "stale-generation assignment was accepted"
        assert _counter("scanner_tpu_stale_master_rejections_total",
                        side="worker") > base
    finally:
        worker.master.try_call = orig
        worker.stop()
        master.stop()


def test_fenced_master_unregister_skips_requeue(tmp_path):
    """Regression (scanner-check SC402): a superseded master receiving
    UnregisterWorker still deactivates the worker — volatile liveness,
    every master may observe its own drain — but must NOT requeue its
    tasks: the requeue path escalates through transient-failure counts
    and gang aborts (journaled durable state the successor owns now)."""
    sc, db_path = _seed_db(tmp_path)
    master = Master(db_path=db_path, no_workers_timeout=60.0)
    try:
        w0 = master._rpc_register_worker({"address": ""})["worker_id"]
        w1 = master._rpc_register_worker({"address": ""})["worker_id"]
        bid = master._rpc_new_job({"spec": _spec_blob(sc, "fo_fence_rq"),
                                   "token": "tok-F"})["bulk_id"]
        for wid in (w0, w1):
            r = master._rpc_next_work({"worker_id": wid,
                                       "bulk_id": bid})
            assert r["status"] == "task", r
        master._fence.set()
        assert master._rpc_unregister_worker({"worker_id": w0})["ok"]
        with master._lock:
            bulk = master._bulk
            assert not master._workers[w0].active
            assert any(o[0] == w0
                       for o in bulk.outstanding.values()), \
                "fenced master requeued a departing worker's tasks " \
                "(durable scheduling mutation past the fence)"
        # the live twin: with the fence down the requeue happens
        master._fence.clear()
        assert master._rpc_unregister_worker({"worker_id": w1})["ok"]
        with master._lock:
            bulk = master._bulk
            assert not master._workers[w1].active
            assert not any(o[0] == w1
                           for o in bulk.outstanding.values())
    finally:
        master.stop()
        sc.stop()


def test_duplicate_delivery_fault_mode():
    """The rpc.client.call duplicate mode delivers the request twice;
    method=/peer= selectors scope it."""
    from scanner_tpu.engine.rpc import RpcClient, RpcServer

    calls = []
    srv = RpcServer("FoTest", {"Echo": lambda req: (
        calls.append(req.get("v")) or {"v": req["v"]})})
    srv.start()
    client = RpcClient(f"localhost:{srv.port}", "FoTest", timeout=5.0)
    try:
        faults.install(
            "rpc.client.call:duplicate:method=Echo:n=1:times=1")
        assert client.call("Echo", v=7)["v"] == 7
        assert calls == [7, 7], "duplicate delivery did not happen"
        assert faults.fired("rpc.client.call") == 1
        assert _counter("scanner_tpu_faults_injected_total",
                        site="rpc.client.call", mode="duplicate") >= 1
        faults.clear()
        # peer selector: a non-matching peer never fires
        faults.install("rpc.client.call:duplicate:method=Echo:"
                       "peer=nonexistent-host:n=1")
        calls.clear()
        assert client.call("Echo", v=9)["v"] == 9
        assert calls == [9]
        assert faults.fired("rpc.client.call") == 0
    finally:
        client.close()
        srv.stop()


def test_failover_plan_parses():
    rules = faults.parse_plan(faults.NAMED_PLANS["master-failover"])
    assert {r.mode for r in rules} == {"crash", "duplicate"}
    # duplicate mode is rejected on sites that never ask for it
    with pytest.raises(faults.FaultPlanError):
        faults.parse_plan("storage.write:duplicate")
    # method=/peer= selectors are rejected on sites whose detail
    # carries no "<method>@<peer>" (they would parse and never fire)
    with pytest.raises(faults.FaultPlanError):
        faults.parse_plan("storage.read:raise:peer=otherhost")
    with pytest.raises(faults.FaultPlanError):
        faults.parse_plan("pipeline.eval:raise:method=NewJob")


# ---------------------------------------------------------------------------
# the spawned failover drill (slow)
# ---------------------------------------------------------------------------

def _spawn_env(extra=None):
    from scanner_tpu.util.jaxenv import cpu_only_env
    env = cpu_only_env()
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("SCANNER_TPU_FAULTS", None)
    env.pop("SCANNER_TPU_MASTER_GENERATION", None)
    env.update(extra or {})
    return env


@pytest.mark.slow
def test_failover_drill_spawned(tmp_path):
    """The headline drill: SIGKILL-grade master death mid-bulk under
    load (injected crash in FinishedWork, checkpoint_frequency=0 so the
    journal is the ONLY durability), the client's NewJob delivered
    twice, and — after the successor recovers — a forced-stale master
    still alive.  Zero journaled completions re-execute, the duplicate
    admission dedupes, the stale master accepts nothing, the output is
    bit-exact, zero blacklist strikes."""
    import socket

    db_path = str(tmp_path / "db")
    log = str(tmp_path / "rows.log")
    seed = Client(db_path=db_path)
    seed.new_table("fo_src", ["output"],
                   [[_pk(100 + i)] for i in range(N_ROWS)])
    seed.stop()

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    addr = f"localhost:{port}"
    spawn = os.path.join(os.path.dirname(__file__), "spawn_master.py")

    def spawn_master(extra=None):
        return subprocess.Popen(
            [sys.executable, spawn, db_path, str(port)],
            env=_spawn_env(extra),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    # master dies handling the 4th FinishedWork: 3 completions are
    # acknowledged (and therefore journaled), the 4th crashed
    # mid-handler and legitimately re-runs
    m1 = spawn_master(
        extra={"SCANNER_TPU_FAULTS":
               "rpc.server.handle:crash:match=FinishedWork:n=4"})
    state = {}
    backend = PosixStorage(db_path)

    def respawner():
        state["rc1"] = m1.wait(timeout=120)
        # the journal on disk at the moment of death = exactly the
        # acknowledged completions (checkpoint_frequency=0: there is
        # NO progress snapshot to lean on)
        recs, _stats = journal.replay(backend, 1)
        state["journaled_done"] = {
            (r["j"], r["k"]) for r in recs if r.get("t") == "done"}
        state["rows_at_crash"] = open(log).read().splitlines()
        time.sleep(0.5)
        state["m2"] = spawn_master()

    worker = None
    sc = None
    stale = None
    try:
        sc = Client(db_path=db_path, master=addr)
        worker = Worker(addr, db_path=db_path)
        rt = threading.Thread(target=respawner)
        rt.start()
        # the client's FIRST NewJob is delivered twice (reply of the
        # first delivery dropped): the admission token must dedupe
        faults.install(
            "rpc.client.call:duplicate:method=NewJob:n=1:times=1")
        col = sc.io.Input([NamedStream(sc, "fo_src")])
        col = sc.ops.FailoverRowLog(x=col, log_path=log)
        out = NamedStream(sc, "fo_drill_out")
        sc.run(sc.io.Output(col, [out]),
               PerfParams.manual(2, 2, checkpoint_frequency=0),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        dup_fired = faults.fired("rpc.client.call")
        faults.clear()
        rt.join(timeout=60)
        assert not rt.is_alive(), "master never crashed/respawned"
        assert state["rc1"] == faults.CRASH_EXIT_CODE
        assert dup_fired == 1, "duplicate NewJob never fired"
        assert state["journaled_done"], \
            "no completions journaled before the crash"

        # output bit-exact despite the kill + duplicate admission
        assert [bytes(r) for r in out.load()] == EXPECT
        assert out.committed()

        # ZERO journaled completions re-executed: rows of tasks whose
        # done record reached the journal ran exactly once
        counts = {}
        for line in open(log).read().splitlines():
            counts[int(line)] = counts.get(int(line), 0) + 1
        for (_j, t) in state["journaled_done"]:
            for row in (100 + 2 * t, 100 + 2 * t + 1):
                assert counts.get(row, 0) == 1, \
                    f"row {row} of journaled task {t} ran " \
                    f"{counts.get(row, 0)} times"
        assert all(counts.get(100 + i, 0) >= 1 for i in range(N_ROWS))

        # the successor replayed the journal, and zero strikes were
        # counted anywhere in the cluster
        snap = sc.metrics()

        def _tot(name):
            return sum(s.get("value", 0) for s in
                       snap.get(name, {}).get("samples", []))

        assert _tot("scanner_tpu_journal_replayed_records_total") > 0
        assert _tot("scanner_tpu_blacklist_strikes_total") == 0

        # a retried NewJob with the original token dedupes on the
        # SUCCESSOR (tokens rode the journal/checkpoint across death)
        token = sc._cluster.last_admission_token
        r = sc._cluster.master.call("NewJob", spec=b"", token=token)
        assert r.get("dedup") and r.get("bulk_id") is not None

        # the stale-master leg: a forced-generation-1 master comes up
        # while the gen-2 successor serves.  It must fence at startup
        # and accept zero mutations.
        with socket.socket() as s2:
            s2.bind(("localhost", 0))
            port2 = s2.getsockname()[1]
        stale = subprocess.Popen(
            [sys.executable, spawn, db_path, str(port2)],
            env=_spawn_env({"SCANNER_TPU_MASTER_GENERATION": "1"}),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        from scanner_tpu.engine.rpc import RpcClient, wait_for_server
        wait_for_server(f"localhost:{port2}", MASTER_SERVICE,
                        timeout=60.0)
        probe = RpcClient(f"localhost:{port2}", MASTER_SERVICE,
                          timeout=10.0)
        try:
            for method, payload in (
                    ("NewJob", {"spec": b"", "token": "t"}),
                    ("FinishedWork", {"worker_id": 0, "bulk_id": 0,
                                      "job_idx": 0, "task_idx": 0,
                                      "attempt": 0}),
                    ("NextWork", {"worker_id": 0, "bulk_id": 0})):
                reply = probe.call(method, **payload)
                assert reply.get("fenced"), \
                    f"stale master accepted {method}: {reply}"
        finally:
            probe.close()
    finally:
        faults.clear()
        if worker is not None:
            worker.stop()
        if sc is not None:
            sc.stop()
        for p in (m1, state.get("m2"), stale):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
