"""Whole-pipeline XLA fusion (graph/fusion.py planner +
engine/evaluate.py FusedKernelInstance).

Contracts pinned here:

1. **Planner** — maximal runs of fusable device ops form chains; host
   ops, stateful kernels, explicit ``fuse=False`` overrides, missing
   cost() models, and externally-consumed intermediates break chains;
   ``fusion_min_chain`` and the cost-driven all-compute-bound no-fuse
   verdict drop candidates.
2. **Bit-exact equivalence** — the fused chain program produces exactly
   the staged per-op pipeline's rows: stateless chains, stencil
   composition (head and tail stencils), null-interleaved domains,
   bucket-boundary/tail geometries, Gather-sampled domains, and the
   virtual multi-chip staging path.
3. **One ladder per chain** — a fused run mints recompile signatures
   under the CHAIN id only (bounded by the chain's ladder), members
   mint none; the compile ledger records the member list; precompile
   warms the chain ladder.
"""

from typing import Any, Sequence

import numpy as np
import pytest

from scanner_tpu import (CacheMode, Client, DeviceType, FrameType, Kernel,
                         NamedStream, NamedVideoStream, NullElement,
                         PerfParams, register_op)
import scanner_tpu.kernels  # noqa: F401  (registers the stdlib ops)
from scanner_tpu import video as scv
from scanner_tpu.engine.evaluate import bucket_ladder
from scanner_tpu.graph import analysis as A
from scanner_tpu.graph import fusion
from scanner_tpu.graph import ops as O
from scanner_tpu.graph.streams_dsl import IOGenerator
from scanner_tpu.util import coststats as _cs
from scanner_tpu.util.metrics import registry

N_FRAMES = 50
W, H = 64, 48

io = IOGenerator()
ops = O.OpGenerator()


@pytest.fixture(autouse=True)
def _drop_cache_pages():
    """The e2e runs stage through the global frame cache; drop its
    resident pages afterwards so this module's deliberate residency
    doesn't dominate later modules' ledger-top assertions
    (tests/test_memstats.py reads global top_entries)."""
    yield
    import scanner_tpu.engine.framecache as _fc
    if _fc._CACHE is not None:
        _fc._CACHE.clear()


class FakeStream:
    is_video = False

    def __init__(self, n):
        self.n = n


# -- planner fixtures: minimal fusable / non-fusable op classes -------------

@register_op(name="FzA", device=DeviceType.TPU, batch=8)
class _FzA(Kernel):
    def cost(self, shapes):
        return {"flops": 1.0, "bytes_in": 1.0, "bytes_out": 1.0}

    def execute(self, frame: Sequence[FrameType]) -> Sequence[FrameType]:
        return np.asarray(frame)  # pragma: no cover


@register_op(name="FzB", device=DeviceType.TPU, batch=8)
class _FzB(Kernel):
    def cost(self, shapes):
        return {"flops": 1.0, "bytes_in": 1.0, "bytes_out": 1.0}

    def execute(self, frame: Sequence[FrameType]) -> Sequence[FrameType]:
        return np.asarray(frame)  # pragma: no cover


@register_op(name="FzC", device=DeviceType.TPU, batch=8)
class _FzC(Kernel):
    def cost(self, shapes):
        return {"flops": 1.0, "bytes_in": 1.0, "bytes_out": 1.0}

    def execute(self, frame: Sequence[FrameType]) -> Sequence[FrameType]:
        return np.asarray(frame)  # pragma: no cover


@register_op(name="FzHost", device=DeviceType.CPU, batch=8)
class _FzHost(Kernel):
    def cost(self, shapes):
        return {"flops": 1.0, "bytes_in": 1.0, "bytes_out": 1.0}

    def execute(self, frame: Sequence[FrameType]) -> Sequence[FrameType]:
        return np.asarray(frame)  # pragma: no cover


@register_op(name="FzState", device=DeviceType.TPU, batch=8,
             bounded_state=0)
class _FzState(Kernel):
    def cost(self, shapes):
        return {"flops": 1.0, "bytes_in": 1.0, "bytes_out": 1.0}

    def execute(self, frame: Sequence[FrameType]) -> Sequence[FrameType]:
        return np.asarray(frame)  # pragma: no cover


@register_op(name="FzNoCost", device=DeviceType.TPU, batch=8)
class _FzNoCost(Kernel):
    def execute(self, frame: Sequence[FrameType]) -> Sequence[FrameType]:
        return np.asarray(frame)  # pragma: no cover


def _info(*mk):
    """Build Input -> mk[0] -> mk[1] -> ... -> Output and analyze it."""
    col = io.Input([FakeStream(24)])
    for f in mk:
        col = f(col)
    return A.analyze([io.Output(col, [FakeStream(0)])])


def _plan(info, **kw):
    kw.setdefault("probe", lambda n: None)
    return fusion.plan_chains(info, **kw)


# ---------------------------------------------------------------------------
# planner units
# ---------------------------------------------------------------------------

def test_plan_basic_chain():
    info = _info(lambda c: ops.FzA(frame=c), lambda c: ops.FzB(frame=c),
                 lambda c: ops.FzC(frame=c))
    chains = _plan(info)
    assert len(chains) == 1
    ch = chains[0]
    assert ch.member_names == ["FzA", "FzB", "FzC"]
    assert ch.chain_id == "FzA+FzB+FzC"
    assert ch.head.name == "FzA" and ch.tail.name == "FzC"
    assert ch.windows() == [0, 0, 0] and ch.width() == 1


def test_plan_breaks_at_host_op():
    info = _info(lambda c: ops.FzA(frame=c),
                 lambda c: ops.FzHost(frame=c),
                 lambda c: ops.FzB(frame=c))
    assert _plan(info) == []


def test_plan_breaks_at_stateful():
    info = _info(lambda c: ops.FzA(frame=c),
                 lambda c: ops.FzState(frame=c),
                 lambda c: ops.FzB(frame=c))
    assert _plan(info) == []


def test_plan_breaks_at_fuse_false():
    # fuse=False mid-run splits it; the two halves are singletons
    info = _info(lambda c: ops.FzA(frame=c),
                 lambda c: ops.FzB(frame=c, fuse=False),
                 lambda c: ops.FzC(frame=c))
    assert _plan(info) == []
    # fuse=False at the tail keeps the upstream pair
    info = _info(lambda c: ops.FzA(frame=c), lambda c: ops.FzB(frame=c),
                 lambda c: ops.FzC(frame=c, fuse=False))
    chains = _plan(info)
    assert [c.member_names for c in chains] == [["FzA", "FzB"]]


def test_plan_breaks_at_missing_cost():
    info = _info(lambda c: ops.FzA(frame=c),
                 lambda c: ops.FzNoCost(frame=c),
                 lambda c: ops.FzB(frame=c))
    assert _plan(info) == []


def test_plan_breaks_at_external_consumer():
    # FzB's output is read by BOTH FzC and the second Output: it must
    # materialize, so the chain ends at FzB
    col = io.Input([FakeStream(24)])
    a = ops.FzA(frame=col)
    b = ops.FzB(frame=a)
    c = ops.FzC(frame=b)
    info = A.analyze([io.Output(c, [FakeStream(0)]),
                      io.Output(b, [FakeStream(0)])])
    chains = _plan(info)
    assert [ch.member_names for ch in chains] == [["FzA", "FzB"]]


def test_plan_min_chain():
    info = _info(lambda c: ops.FzA(frame=c), lambda c: ops.FzB(frame=c))
    assert len(_plan(info)) == 1
    assert _plan(info, min_chain=3) == []
    old = fusion.fusion_min_chain()
    try:
        fusion.set_min_chain(3)
        assert _plan(info, min_chain=None) == []
        fusion.set_min_chain(0)  # clamps to 2: a singleton IS staged
        assert fusion.fusion_min_chain() == 2
    finally:
        fusion.set_min_chain(old)


def test_plan_cost_no_fuse():
    info = _info(lambda c: ops.FzA(frame=c), lambda c: ops.FzB(frame=c))
    # every member already judged compute-bound: no HBM win, stay staged
    assert _plan(info, probe=lambda n: "compute") == []
    # any memory-bound member keeps the chain
    assert len(_plan(
        info, probe=lambda n: "memory" if n.name == "FzB"
        else "compute")) == 1
    # unmeasured members fuse by default
    assert len(_plan(info, probe=lambda n: None)) == 1


def test_golden_chain_geometry():
    """The golden pipeline plans Resize+Blur+Histogram; HistDiff's
    [-1, 0] window keeps it OUT of the chain (a windowed op may only
    HEAD a chain — mid-chain it would make the fused program recompute
    every upstream member once per window element, where the staged
    stencil cache computes each intermediate row exactly once)."""
    col = io.Input([FakeStream(24)])
    r = ops.Resize(frame=col, width=[32], height=[24])
    b = ops.Blur(frame=r, kernel_size=3, sigma=1.0)
    h = ops.Histogram(frame=b)
    d = ops.HistDiff(frame=h)
    info = A.analyze([io.Output(d, [FakeStream(0)])])
    chains = _plan(info)
    assert len(chains) == 1
    ch = chains[0]
    assert ch.chain_id == "Resize+Blur+Histogram"
    assert ch.windows() == [0, 0, 0]
    assert ch.width() == 1
    assert "HistDiff" not in ch.member_names


def test_plan_windowed_op_only_heads_a_chain():
    """A stencil op extends no chain, but may start one: as the head
    its window composes into the chain's input gather (the same rows
    the staged path read)."""
    col = io.Input([FakeStream(24)])
    a = ops.FzA(frame=col)
    d = ops.HistDiff(frame=a)       # windowed: breaks the extension
    c = ops.FzB(frame=d)
    info = A.analyze([io.Output(c, [FakeStream(0)])])
    chains = _plan(info)
    # HistDiff itself heads a chain with FzB; FzA stays a singleton
    assert [ch.member_names for ch in chains] == [["HistDiff", "FzB"]]
    assert chains[0].windows() == [2, 0]
    assert chains[0].width() == 2


# ---------------------------------------------------------------------------
# end-to-end equivalence (fused vs staged, CPU backend)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def sc(tmp_path_factory):
    root = tmp_path_factory.mktemp("fusion")
    vid = str(root / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=W, height=H,
                         fps=24, keyint=12)
    client = Client(db_path=str(root / "db"))
    client.ingest_videos([("fz", vid)])
    yield client
    client.stop()


def _load(out):
    return list(out.load())


def _assert_rows_equal(a, b):
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        if isinstance(x, NullElement) or isinstance(y, NullElement):
            assert isinstance(x, NullElement) \
                and isinstance(y, NullElement), i
        elif isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
            assert np.array_equal(np.asarray(x), np.asarray(y)), i
        else:
            assert x == y, i


def _run_ab(sc, build, name, wp=8, io_=16):
    """Run the same graph staged (fusion off) and fused; return
    (staged_rows, fused_rows)."""
    outs = {}
    for mode, on in (("staged", False), ("fused", True)):
        fusion.set_enabled(on)
        try:
            frame = sc.io.Input([NamedVideoStream(sc, "fz")])
            col = build(sc, frame)
            out = NamedStream(sc, f"fz_{name}_{mode}")
            sc.run(sc.io.Output(col, [out]), PerfParams.manual(wp, io_),
                   cache_mode=CacheMode.Overwrite, show_progress=False)
            outs[mode] = _load(out)
        finally:
            fusion.set_enabled(True)
    return outs["staged"], outs["fused"]


def _golden(s, frame):
    r = s.ops.Resize(frame=frame, width=[32], height=[24])
    b = s.ops.Blur(frame=r, kernel_size=3, sigma=1.1)
    h = s.ops.Histogram(frame=b)
    return s.ops.HistDiff(frame=h)


def _op_counter(series: str):
    snap = registry().snapshot()
    out = {}
    for s in snap.get(series, {}).get("samples", []):
        lab = s["labels"]
        out[lab.get("op") or lab.get("chain")] = \
            out.get(lab.get("op") or lab.get("chain"), 0) + s["value"]
    return out


# rows straddle bucket boundaries: sub-smallest-bucket task (3), exact
# bucket (16), bucket+tail (21), full stream with ragged tail (50)
@pytest.mark.parametrize("rows", [3, 16, 21, N_FRAMES])
def test_fused_equivalence_golden_chain(sc, rows):
    def build(s, f):
        if rows < N_FRAMES:
            f = s.streams.Range(f, [(0, rows)])
        return _golden(s, f)

    staged, fused = _run_ab(sc, build, f"golden{rows}")
    assert len(fused) == rows
    _assert_rows_equal(staged, fused)


def test_fused_equivalence_stencil_head(sc):
    """Stencil member at the chain HEAD (OpticalFlow's [-1, 0] window
    feeds Blur): the composed gather reads the window once and the
    flow field never materializes."""
    def build(s, f):
        flow = s.ops.OpticalFlow(frame=s.streams.Range(f, [(0, 12)]))
        return s.ops.Blur(frame=flow, kernel_size=3, sigma=0.8)

    staged, fused = _run_ab(sc, build, "flowblur", wp=4)
    assert len(fused) == 12
    _assert_rows_equal(staged, fused)


def test_fused_equivalence_null_interleaved(sc):
    """Null rows propagate through the composed window: a tail row is
    null iff ANY transitively-read head row is null — identical to the
    staged member-by-member propagation."""
    def build(s, f):
        spaced = s.streams.RepeatNull(s.streams.Range(f, [(0, 6)]), [3])
        return _golden(s, spaced)

    staged, fused = _run_ab(sc, build, "nulls")
    assert sum(isinstance(e, NullElement) for e in staged) > 0
    _assert_rows_equal(staged, fused)


def test_fused_equivalence_gather_sampled(sc):
    def build(s, f):
        g = s.streams.Gather(f, [[0, 7, 8, 23, 24, 49]])
        return _golden(s, g)

    staged, fused = _run_ab(sc, build, "gather")
    assert len(fused) == 6
    _assert_rows_equal(staged, fused)


def test_fused_equivalence_multichip(sc, monkeypatch):
    """Virtual multi-chip staging (the PR 5 affinity lever): fused
    chains stage the head input to the instance's assigned chip and
    stay bit-exact."""
    monkeypatch.setenv("SCANNER_TPU_KERNEL_DEVICES", "all")
    staged, fused = _run_ab(sc, _golden, "mchip")
    assert len(fused) == N_FRAMES
    _assert_rows_equal(staged, fused)


def test_fusion_kill_switch_restores_staged_metrics(sc):
    """With fusion disabled the evaluator plans no chains and members
    dispatch individually — the chain id never shows up in op
    metrics."""
    before = _op_counter("scanner_tpu_op_rows_total")
    fusion.set_enabled(False)
    try:
        frame = sc.io.Input([NamedVideoStream(sc, "fz")])
        out = NamedStream(sc, "fz_kill")
        sc.run(sc.io.Output(_golden(sc, frame), [out]),
               PerfParams.manual(8, 16),
               cache_mode=CacheMode.Overwrite, show_progress=False)
    finally:
        fusion.set_enabled(True)
    after = _op_counter("scanner_tpu_op_rows_total")
    cid = "Resize+Blur+Histogram"
    assert after.get(cid, 0) == before.get(cid, 0)
    assert after.get("Resize", 0) > before.get("Resize", 0)


# ---------------------------------------------------------------------------
# chain-level attribution: one ladder, member'd ledger, warm chains
# ---------------------------------------------------------------------------

def test_one_ladder_per_chain_and_silent_members(sc):
    """A fused run mints recompile signatures under the CHAIN id only,
    bounded by the chain ladder; the chain members mint none and never
    dispatch.  HistDiff stays staged (windowed, non-head) and keeps its
    own row accounting."""
    cid = "Resize+Blur+Histogram"
    wp = 8
    before_rc = _op_counter("scanner_tpu_op_recompiles_total")
    before_rows = _op_counter("scanner_tpu_op_rows_total")
    frame = sc.io.Input([NamedVideoStream(sc, "fz")])
    out = NamedStream(sc, "fz_ladder")
    sc.run(sc.io.Output(_golden(sc, frame), [out]),
           PerfParams.manual(wp, 16),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    after_rc = _op_counter("scanner_tpu_op_recompiles_total")
    after_rows = _op_counter("scanner_tpu_op_rows_total")
    delta = after_rc.get(cid, 0) - before_rc.get(cid, 0)
    assert 0 < delta <= len(bucket_ladder(wp))
    for member in ("Resize", "Blur", "Histogram"):
        assert after_rc.get(member, 0) == before_rc.get(member, 0), member
        assert after_rows.get(member, 0) == before_rows.get(member, 0), \
            member
    assert after_rows.get(cid, 0) - before_rows.get(cid, 0) >= N_FRAMES
    # the staged tail op still dispatches under its own name
    assert after_rows.get("HistDiff", 0) - before_rows.get(
        "HistDiff", 0) >= N_FRAMES


def test_compile_ledger_records_members(sc):
    """observe_compiles entries for a fused chain carry the member op
    list (the fused-compile attribution satellite)."""
    was = _cs.enabled()
    _cs.set_enabled(True)
    try:
        frame = sc.io.Input([NamedVideoStream(sc, "fz")])
        out = NamedStream(sc, "fz_ledger")
        sc.run(sc.io.Output(_golden(sc, frame), [out]),
               PerfParams.manual(8, 16),
               cache_mode=CacheMode.Overwrite, show_progress=False)
    finally:
        _cs.set_enabled(was)
    cid = "Resize+Blur+Histogram"
    entries = [e for e in _cs.compile_ledger(10_000) if e["op"] == cid]
    assert entries, "no ledger entries under the chain id"
    assert all(e.get("members") == ["Resize", "Blur", "Histogram"]
               for e in entries)


def test_precompile_warms_chain(sc, monkeypatch):
    """The warm-up thread precompiles ONE chain ladder (not the member
    ladders): the precompile gauge appears under the chain id, the
    members stay unwarmed individually, and a geometry change INSIDE
    the chain (Resize head) is warmable — the chain traces through it
    from source-geometry head frames."""
    from scanner_tpu.engine.evaluate import TaskEvaluator
    from scanner_tpu.util.profiler import Profiler

    monkeypatch.setenv("SCANNER_TPU_PRECOMPILE", "1")
    cid = "Resize+Blur+Histogram"
    frame = sc.io.Input([NamedVideoStream(sc, "fz")])
    r = sc.ops.Resize(frame=frame, width=[32], height=[24])
    b = sc.ops.Blur(frame=r, kernel_size=3, sigma=1.1)
    h = sc.ops.Histogram(frame=b)
    outp = sc.io.Output(h, [NamedStream(sc, "fz_warm")])
    info = A.analyze([outp])
    te = TaskEvaluator(info, Profiler(), precompile=(H, W, 8))
    try:
        assert list(te.fused.values())[0].chain_id == cid
        assert te._precompile_thread is not None
        te._precompile_thread.join(timeout=60)
        assert not te._precompile_thread.is_alive()
        warmed = _op_counter("scanner_tpu_op_precompile_seconds")
        assert cid in warmed
        fki = list(te.fused.values())[0]
        assert fki._warm_state == "done"
        # members were never scheduled for individual warm-up
        for ki in te.kernels.values():
            assert ki._warm_state == "idle", ki.node.name
    finally:
        te.close()


def test_fusion_metrics_series_present(sc):
    """The fusion gauges register under their catalogued names and the
    planner sets the chains-planned gauge per chain id."""
    snap = registry().snapshot()
    for name in fusion.FUSION_SERIES:
        assert name in snap, name
    chains = {s["labels"]["chain"]: s["value"]
              for s in snap.get("scanner_tpu_fusion_chains_planned",
                                {}).get("samples", [])}
    assert chains.get("Resize+Blur+Histogram") == 3


# ---------------------------------------------------------------------------
# sharded gangs (slow): fused chains with a composed-stencil halo
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fused_equivalence_gang_sharded(tmp_path):
    """A fused chain with a stencil HEAD (OpticalFlow+Blur, composed
    windows [2, 0] -> 1 halo row) runs sharded over a real 2-worker
    gang: the composed-stencil back-reach past the shard boundary rides
    the halo exchange, the output is bit-exact vs single-host, and it
    stays bit-exact after the gang re-forms around a replaced worker."""
    from scanner_tpu.engine import gang as egang
    from scanner_tpu.engine.service import Master, Worker
    from scanner_tpu.util import metrics as _mx

    def halo():
        entry = _mx.registry().snapshot().get(
            "scanner_tpu_gang_shard_halo_bytes_total", {})
        return sum(s["value"] for s in entry.get("samples", []))

    def build(s):
        f = s.io.Input([NamedVideoStream(s, "fzg")])
        flow = s.ops.OpticalFlow(frame=f)
        return s.ops.Blur(frame=flow, kernel_size=3, sigma=1.1)

    def run_one(client, name, **perf_kw):
        out = NamedStream(client, name)
        client.run(client.io.Output(build(client), [out]),
                   PerfParams.manual(4, 8, **perf_kw),
                   cache_mode=CacheMode.Overwrite, show_progress=False)
        return _load(out)

    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=16, width=W, height=H,
                         fps=24, keyint=8)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("fzg", vid)])
    single = run_one(seed, "fzg_single")

    m = Master(db_path=db_path, no_workers_timeout=60.0)
    addr = f"localhost:{m.port}"
    old_t = egang.form_timeout_s()
    egang.set_form_timeout_s(6.0)
    workers = [Worker(addr, db_path=db_path) for _ in range(2)]
    sc2 = Client(db_path=db_path, master=addr)
    try:
        h0 = halo()
        sharded = run_one(sc2, "fzg_shard", gang_hosts=2)
        assert halo() - h0 > 0, \
            "composed-stencil shard back-reach must ride the halo"
        # re-form: replace one member, run the same fused graph again
        workers[0].stop()
        workers[0] = Worker(addr, db_path=db_path)
        reformed = run_one(sc2, "fzg_reform", gang_hosts=2)
    finally:
        sc2.stop()
        for w in workers:
            w.stop()
        m.stop()
        egang.set_form_timeout_s(old_t)
        seed.stop()
    _assert_rows_equal(single, sharded)
    _assert_rows_equal(single, reformed)
