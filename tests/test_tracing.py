"""Distributed tracing (util/tracing.py + the engine wiring).

Covers: the span API and flight recorder, traceparent propagation,
cross-host trace assembly (in-process AND spawned 2-worker clusters —
every task must carry an unbroken master→worker→stage→op chain under a
single per-job trace_id), the chaos interplay (an injected
`pipeline.eval` fault appears as a span event on the affected task's
timeline), straggler analytics, and the tracing-overhead guard on the
golden pipeline.
"""

import json
import os
import subprocess
import sys
import time
from typing import Any

import cloudpickle
import numpy as np
import pytest

from scanner_tpu import (CacheMode, Client, FrameType, Kernel, NamedStream,
                        NamedVideoStream, PerfParams, register_op)
import scanner_tpu.kernels  # noqa: F401
from scanner_tpu import video as scv
from scanner_tpu.engine.service import Master, Worker
from scanner_tpu.util import faults, tracing

# test kernels must travel to worker subprocesses inside the job spec
cloudpickle.register_pickle_by_value(sys.modules[__name__])

N_FRAMES = 48


@register_op(name="TraceHist")
class TraceHist(Kernel):
    def execute(self, frame: FrameType) -> Any:
        return np.asarray(frame).mean(axis=(0, 1))


# ---------------------------------------------------------------------------
# unit: span API, context, flight recorder
# ---------------------------------------------------------------------------

def test_traceparent_roundtrip():
    ctx = tracing.SpanContext(tracing.new_trace_id(),
                              tracing.new_span_id())
    back = tracing.parse_traceparent(ctx.traceparent())
    assert back is not None
    assert (back.trace_id, back.span_id) == (ctx.trace_id, ctx.span_id)
    # malformed headers must parse to None, never raise
    for bad in (None, "", "garbage", "00-zz-yy-01", 42,
                "00-" + "0" * 31 + "-" + "0" * 16 + "-01"):
        assert tracing.parse_traceparent(bad) is None


def test_span_nesting_and_ring():
    t = tracing.Tracer(node="unit", ring=128)
    with tracing.start_span(t, "outer", answer=42) as outer:
        with tracing.start_span(t, "inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            tracing.add_event("boom", k="v")
    recent = t.recent(10)
    names = [d["name"] for d in recent]
    assert names == ["outer", "inner"]  # newest first
    inner_d = recent[1]
    assert inner_d["events"][0]["name"] == "boom"
    assert inner_d["events"][0]["attrs"] == {"k": "v"}
    assert recent[0]["attrs"] == {"answer": 42}
    # spans_for_trace finds both
    assert len(t.spans_for_trace(outer.trace_id)) == 2


def test_ring_is_bounded():
    t = tracing.Tracer(node="unit", ring=64)
    for i in range(200):
        with tracing.start_span(t, f"s{i}"):
            pass
    assert len(t.recent(1000)) == 64


def test_export_drain():
    t = tracing.Tracer(node="unit", export=True, ring=64)
    with tracing.start_span(t, "a"):
        pass
    got = t.drain_export()
    assert [d["name"] for d in got] == ["a"]
    assert t.drain_export() == []  # drained


def test_disabled_records_nothing(monkeypatch):
    t = tracing.Tracer(node="unit", ring=64)
    tracing.set_enabled(False)
    try:
        with tracing.start_span(t, "x") as sp:
            assert sp is None
        assert tracing.current_traceparent() is None
        assert t.recent(10) == []
    finally:
        tracing.set_enabled(True)


def test_profiler_interval_becomes_span():
    """One instrumentation, two views: a Profiler.span inside an active
    trace context records BOTH an interval and a child trace span."""
    from scanner_tpu.util.profiler import Profiler
    t = tracing.Tracer(node="unit", ring=64)
    p = Profiler(level=1)
    with tracing.start_span(t, "task") as task:
        with p.span("load", level=0, task=3):
            pass
    assert [iv.name for iv in p.intervals()] == ["load"]
    spans = {d["name"]: d for d in t.recent(10)}
    assert set(spans) == {"task", "load"}
    assert spans["load"]["parent_id"] == task.span_id
    assert spans["load"]["attrs"] == {"task": 3}
    # outside any context: interval only, no span
    with p.span("save", level=0):
        pass
    assert len(t.recent(10)) == 2


def test_straggler_summary_and_verify_chain():
    t = tracing.Tracer(node="unit", ring=256)
    with tracing.start_span(t, "job") as root:
        for i, dur in enumerate((0.0, 0.0)):
            with tracing.start_span(t, "task", job=0, task=i):
                for stage in ("load", "evaluate", "save"):
                    with tracing.start_span(t, stage):
                        if stage == "evaluate":
                            with tracing.start_span(t,
                                                    "evaluate:TraceHist"):
                                pass
    spans = t.spans_for_trace(root.trace_id)
    s = tracing.straggler_summary(spans, top_n=5)
    assert s["per_stage"]["task"]["count"] == 2
    assert len(s["slowest_tasks"]) == 2
    assert s["slowest_tasks"][0]["trace_id"] == root.trace_id
    v = tracing.verify_chain(spans)
    assert v["tasks"] == 2 and v["complete"], v["broken"]
    # break the chain: drop the evaluate stage spans
    pruned = [d for d in spans if d["name"] != "evaluate"]
    v2 = tracing.verify_chain(pruned)
    assert not v2["complete"]
    # an empty trace must NOT audit as complete (a tracing outage would
    # otherwise pass the "100% of tasks chain" audit vacuously)
    assert not tracing.verify_chain([])["complete"]


def test_chrome_export_shape(tmp_path):
    t = tracing.Tracer(node="unit", ring=64)
    with tracing.start_span(t, "task", job=0) as sp:
        tracing.add_event("fault.injected", site="pipeline.eval")
    path = str(tmp_path / "t.json")
    tracing.write_chrome_trace(t.spans_for_trace(sp.trace_id), path,
                               device_events=[{"name": "xla", "ph": "X",
                                               "pid": 1000, "ts": 1.0,
                                               "dur": 2.0}])
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    xs = [e for e in evs if e.get("ph") == "X"]
    assert {e["name"] for e in xs} == {"task", "xla"}
    task_ev = next(e for e in xs if e["name"] == "task")
    assert task_ev["args"]["trace_id"] == sp.trace_id
    assert any(e.get("ph") == "i" and e["name"] == "fault.injected"
               for e in evs)
    assert any(e.get("ph") == "M" for e in evs)  # process/thread names


# ---------------------------------------------------------------------------
# cluster: cross-host assembly
# ---------------------------------------------------------------------------

@pytest.fixture()
def cluster(tmp_path):
    """Master + 2 in-process workers on ephemeral ports."""
    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=64, height=48,
                         fps=24, keyint=12)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("tr1", vid)])
    master = Master(db_path=db_path, no_workers_timeout=10.0)
    addr = f"localhost:{master.port}"
    workers = [Worker(addr, db_path=db_path) for _ in range(2)]
    sc = Client(db_path=db_path, master=addr)
    yield sc, master, workers, db_path, addr
    sc.stop()
    for w in workers:
        w.stop()
    master.stop()


def _run_hist(sc, out_name: str):
    frame = sc.io.Input([NamedVideoStream(sc, "tr1")])
    h = sc.ops.TraceHist(frame=frame)
    out = NamedStream(sc, out_name)
    jid = sc.run(sc.io.Output(h, [out]), PerfParams.manual(4, 8),
                 cache_mode=CacheMode.Overwrite, show_progress=False)
    return jid, out


def _assembled_spans(sc, jid):
    info = sc._job_traces[jid]
    reply = sc._cluster.get_trace(info["bulk_id"])
    spans = list(reply["spans"])
    spans.extend(tracing.default_tracer().spans_for_trace(
        info["trace_id"]))
    return info, reply, spans


def test_cluster_trace_roundtrip(cluster, tmp_path):
    """Every task of a 2-worker bulk carries a complete
    master→worker→stage→op span chain under the job's single trace_id,
    and Client.trace writes one merged file."""
    sc, _master, workers, _dbp, _addr = cluster
    jid, out = _run_hist(sc, "tr_roundtrip")
    assert out.len() == N_FRAMES
    info, reply, spans = _assembled_spans(sc, jid)
    assert reply["trace_id"] == info["trace_id"]
    v = tracing.verify_chain(spans)
    n_tasks = sc.job_status(info["bulk_id"])["total_tasks"]
    assert v["tasks"] == n_tasks
    assert v["complete"], v["broken"]
    assert v["trace_ids"] == [info["trace_id"]]
    # the chain crosses hosts: master assign spans + ≥1 worker node
    nodes = {d["node"] for d in spans}
    assert "master" in nodes
    assert any(n.startswith("worker") for n in nodes)
    by_name = {}
    for d in spans:
        by_name.setdefault(d["name"], []).append(d)
    assert len(by_name["master.assign"]) >= n_tasks
    # task spans parent into master.assign spans (the cross-host hop)
    assigns = {d["span_id"] for d in by_name["master.assign"]}
    for d in by_name["task"]:
        assert d["parent_id"] in assigns
    # merged file
    path = sc.trace(jid, str(tmp_path / "merged.json"))
    doc = json.load(open(path))
    assert any(e.get("name") == "task" for e in doc["traceEvents"])


def test_cluster_straggler_analytics(cluster):
    """GetJobStatus + /statusz surface per-stage stats and the top-N
    slowest tasks with trace ids, maintained incrementally from shipped
    spans."""
    sc, master, _workers, _dbp, _addr = cluster
    jid, _out = _run_hist(sc, "tr_straggle")
    info = sc._job_traces[jid]
    st = sc.job_status(info["bulk_id"])
    s = st["stragglers"]
    n_tasks = st["total_tasks"]
    assert s["per_stage"]["task"]["count"] == n_tasks
    for stage in ("load", "evaluate", "save"):
        assert s["per_stage"][stage]["count"] >= n_tasks
    assert s["slowest_tasks"]
    top = s["slowest_tasks"][0]
    assert top["trace_id"] == info["trace_id"]
    assert top["seconds"] >= s["slowest_tasks"][-1]["seconds"]
    # the same summary rides on /statusz (master-side bookkeeping)
    stz = master._statusz()
    assert stz["bulk"]["stragglers"]["slowest_tasks"]
    # and Client.stragglers is the API flavor
    assert sc.stragglers(jid)["per_stage"]["task"]["count"] == n_tasks


@pytest.mark.chaos
def test_chaos_fault_lands_on_task_span(cluster):
    """An injected pipeline.eval fault shows up as a `fault.injected`
    span event on the affected task's timeline (and the task completes
    via retry, bit-exact)."""
    sc, _master, _workers, _dbp, _addr = cluster
    faults.install("pipeline.eval:raise:n=1")
    try:
        jid, out = _run_hist(sc, "tr_chaos")
        n_fired = faults.fired("pipeline.eval")
    finally:
        faults.clear()
    assert out.len() == N_FRAMES
    assert n_fired == 1
    info, _reply, spans = _assembled_spans(sc, jid)
    hits = [(d, ev) for d in spans for ev in d.get("events", ())
            if ev["name"] == "fault.injected"]
    assert len(hits) == 1
    d, ev = hits[0]
    assert ev["attrs"]["site"] == "pipeline.eval"
    assert d["trace_id"] == info["trace_id"]
    # the event sits on the affected task's timeline: the span it landed
    # on is the task span or a descendant of exactly one task span
    by_id = {s["span_id"]: s for s in spans}
    cur = d
    while cur["name"] != "task" and cur.get("parent_id"):
        cur = by_id[cur["parent_id"]]
    assert cur["name"] == "task"
    # the injected detail names the same task the span claims
    a = cur.get("attrs") or {}
    assert ev["attrs"]["detail"] == f"task={a['job']},{a['task']}"
    # that attempt errored; a later attempt of the same task succeeded
    tasks = [s for s in spans if s["name"] == "task"
             and (s.get("attrs") or {}).get("task") == a["task"]]
    assert any(s["status"] == "error" for s in tasks)
    assert any(s["status"] == "ok" for s in tasks)


@pytest.mark.slow
def test_spawned_cluster_trace_roundtrip(tmp_path):
    """The acceptance shape: a SPAWNED 2-worker bulk (separate
    processes, spans only reachable via ShipSpans) produces one merged
    trace where 100% of tasks carry an unbroken chain under the job's
    trace_id."""
    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=64, height=48,
                         fps=24, keyint=12)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("tr1", vid)])
    master = Master(db_path=db_path, no_workers_timeout=30.0)
    addr = f"localhost:{master.port}"
    # spawned interpreters need the repo importable (the package is not
    # installed in the test env) and a CPU-pinned jax
    from scanner_tpu.util.jaxenv import cpu_only_env
    env = cpu_only_env()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    spawn = os.path.join(os.path.dirname(__file__), "spawn_worker.py")
    procs = [subprocess.Popen([sys.executable, spawn, addr, db_path],
                              env=env, stdout=subprocess.DEVNULL,
                              stderr=subprocess.DEVNULL)
             for _ in range(2)]
    sc = Client(db_path=db_path, master=addr)
    try:
        # generous: each spawned worker pays the full jax import, and
        # the slow lane runs this under whole-suite CPU contention
        deadline = time.time() + 300
        while time.time() < deadline \
                and sc.job_status().get("num_workers", 0) < 2:
            time.sleep(0.25)
        assert sc.job_status()["num_workers"] == 2
        jid, out = _run_hist(sc, "tr_spawned")
        assert out.len() == N_FRAMES
        info, reply, spans = _assembled_spans(sc, jid)
        v = tracing.verify_chain(spans)
        n_tasks = sc.job_status(info["bulk_id"])["total_tasks"]
        assert v["tasks"] == n_tasks
        assert v["complete"], v["broken"]
        nodes = {d["node"] for d in spans if d["name"] == "task"}
        assert len(nodes) == 2, f"tasks ran on {nodes}"
        path = sc.trace(jid, str(tmp_path / "spawned.json"))
        assert os.path.getsize(path) > 0
    finally:
        sc.stop()
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)
        master.stop()


# ---------------------------------------------------------------------------
# cross-host device traces (util/jaxprof.py)
# ---------------------------------------------------------------------------

def test_device_events_survive_crossing_hosts(tmp_path):
    """The satellite fix: a profile that ships to another host keeps its
    device timeline because the events are embedded into the record
    before shipping — the old behavior (only the trace *directory* path
    traveled) returned [] once the dir was gone."""
    import gzip
    import shutil

    from scanner_tpu.util import jaxprof

    trace_dir = tmp_path / "devtrace" / "plugins"
    trace_dir.mkdir(parents=True)
    events = [{"name": "fusion.1", "ph": "X", "pid": 1, "tid": 1,
               "ts": 100.0, "dur": 50.0},
              {"name": "$python_call", "ph": "X", "pid": 1, "tid": 2,
               "ts": 120.0, "dur": 5.0},
              {"name": "process_name", "ph": "M", "pid": 1,
               "args": {"name": "/device:TPU:0"}}]
    with gzip.open(trace_dir / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    rec = {"dir": str(tmp_path / "devtrace"), "t0": 1000.0, "t1": 1002.0}

    # the old failure mode: dir gone (shipped cross-host) -> no events
    gone = dict(rec, dir=str(tmp_path / "nonexistent"))
    assert jaxprof.load_device_events(gone) == []

    jaxprof.embed_device_events(rec)
    assert "events" in rec
    # embedded events are msgpack-able (they ride in PostProfile)
    from scanner_tpu.storage.metadata import pack, unpack
    rec2 = unpack(pack(rec))
    shutil.rmtree(tmp_path / "devtrace")  # the "other host" filesystem
    got = jaxprof.load_device_events(rec2)
    names = {e["name"] for e in got}
    assert "fusion.1" in names
    assert "$python_call" not in names  # python spans filtered at embed
    ev = next(e for e in got if e["name"] == "fusion.1")
    assert ev["ts"] == 100.0 + 1000.0 * 1e6  # shifted to host clock
    assert ev["pid"] >= jaxprof.DEVICE_PID_BASE
    # idempotent: embedding again is a no-op
    assert jaxprof.embed_device_events(rec2) is rec2


def test_device_events_embed_cap(tmp_path):
    """The embed cap keeps the longest events and records the drop."""
    import gzip

    from scanner_tpu.util import jaxprof

    d = tmp_path / "cap"
    d.mkdir()
    events = [{"name": f"op{i}", "ph": "X", "pid": 1, "ts": float(i),
               "dur": float(i)} for i in range(10)]
    events.append({"name": "process_name", "ph": "M", "pid": 1,
                   "args": {"name": "/device:TPU:0"}})
    with gzip.open(d / "x.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    rec = {"dir": str(d), "t0": 0.0}
    jaxprof.embed_device_events(rec, max_events=4)
    assert rec["events_dropped"] == 6
    kept = {e["name"] for e in rec["events"]}
    # longest-first among duration events; 'M' metadata (lane names) is
    # exempt from the cap — dropping it would leave bare pid numbers
    assert kept == {"op9", "op8", "op7", "op6", "process_name"}


# ---------------------------------------------------------------------------
# overhead guard
# ---------------------------------------------------------------------------

def test_span_overhead_micro():
    """The per-span cost stays in microseconds: recording must be cheap
    enough to leave on in production."""
    t = tracing.Tracer(node="bench", ring=1024)
    n = 5000
    with tracing.start_span(t, "root"):
        t0 = time.perf_counter()
        for _ in range(n):
            tok = tracing.begin_interval("s", None)
            tracing.end_interval(tok)
        per_span = (time.perf_counter() - t0) / n
    assert per_span < 200e-6, f"{per_span * 1e6:.1f}µs per span"
    # the disabled path is a flag check
    tracing.set_enabled(False)
    try:
        t0 = time.perf_counter()
        for _ in range(n):
            tracing.current_traceparent()
        per_call = (time.perf_counter() - t0) / n
    finally:
        tracing.set_enabled(True)
    assert per_call < 20e-6


def test_tracing_overhead_guard(tmp_path):
    """CI guard: tracing on vs off on the golden (histogram) pipeline.
    The acceptance budget is <5% wall; this 2-core CI box shows more
    run-to-run noise than that between two IDENTICAL runs, so the
    guard interleaves on/off pairs (killing warm-up drift) and bounds
    the median ratio at 1.5x — a real regression (per-task collector
    I/O, span explosion, a lock on the hot path) blows past that
    immediately, while scheduler noise does not."""
    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=64, height=48,
                         fps=24, keyint=12)
    sc = Client(db_path=db_path)
    sc.ingest_videos([("tr1", vid)])

    def run_once(i: int) -> float:
        frame = sc.io.Input([NamedVideoStream(sc, "tr1")])
        h = sc.ops.TraceHist(frame=frame)
        out = NamedStream(sc, f"tr_ovh_{i}")
        t0 = time.perf_counter()
        sc.run(sc.io.Output(h, [out]), PerfParams.manual(4, 8),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        return time.perf_counter() - t0

    run_once(99)  # warm (decode caches, jit, first-touch)
    on, off = [], []
    try:
        for k in range(3):
            tracing.set_enabled(True)
            on.append(run_once(k * 2))
            tracing.set_enabled(False)
            off.append(run_once(k * 2 + 1))
    finally:
        tracing.set_enabled(True)
    on_med, off_med = sorted(on)[1], sorted(off)[1]
    assert on_med <= off_med * 1.5 + 0.05, \
        f"tracing on {on_med:.3f}s vs off {off_med:.3f}s"
