"""Pallas kernel correctness under the interpreter (CPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from scanner_tpu.kernels import pallas_ops


@pytest.mark.skipif(not pallas_ops.HAVE_PALLAS, reason="no pallas")
def test_pallas_histogram_matches_numpy():
    rng = np.random.RandomState(0)
    vals = rng.randint(0, 16, (5, 1000)).astype(np.int32)
    got = np.asarray(pallas_ops.pallas_histogram(
        jnp.asarray(vals), bins=16, interpret=True))
    expect = np.stack([np.bincount(v, minlength=16) for v in vals])
    np.testing.assert_array_equal(got, expect)


@pytest.mark.skipif(not pallas_ops.HAVE_PALLAS, reason="no pallas")
def test_pallas_histogram_frames_matches_xla():
    from scanner_tpu.kernels.imgproc import _histogram_impl
    rng = np.random.RandomState(1)
    frames = jnp.asarray(rng.randint(0, 255, (3, 48, 64, 3), np.uint8))
    got = np.asarray(pallas_ops.histogram_frames(frames, interpret=True))
    expect = np.asarray(_histogram_impl(frames))
    np.testing.assert_array_equal(got, expect)


@pytest.mark.skipif(not pallas_ops.HAVE_PALLAS, reason="no pallas")
def test_pallas_histogram_padding_exact():
    # rows/pixels not multiples of the tile sizes; padding must not leak
    vals = jnp.asarray(np.full((3, 7), 2, np.int32))
    got = np.asarray(pallas_ops.pallas_histogram(vals, bins=4,
                                                 interpret=True))
    expect = np.zeros((3, 4), np.int32)
    expect[:, 2] = 7
    np.testing.assert_array_equal(got, expect)


def test_histogram_cmp_matches_bincount():
    """The TPU-fast compare+sum lowering is numerically identical to the
    bincount path (it is the default device path on TPU, PERF.md)."""
    import numpy as np

    from scanner_tpu.kernels.imgproc import (_histogram_cmp_impl,
                                             _histogram_impl)
    rng = np.random.default_rng(7)
    frames = rng.integers(0, 256, size=(5, 33, 41, 3), dtype=np.uint8)
    a = np.asarray(_histogram_impl(frames))
    b = np.asarray(_histogram_cmp_impl(frames))
    assert np.array_equal(a, b)
    assert b.dtype == np.int32
    assert b.sum() == 5 * 33 * 41 * 3
