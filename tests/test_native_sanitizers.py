"""Tier-1 sanitizer gate for the native decode library.

`make asan` in cpp/ rebuilds the scvid harness under AddressSanitizer
and runs every native check — the same harness `make test` runs, but
with heap/stack overruns fatal instead of silent (the unaligned-width
decode overrun fixed in PR 9 is exactly the class ASAN catches at the
write, not at the crash three frames later).  UBSAN/TSAN ride the same
Makefile (`make ubsan` / `make tsan`) but are left to the slow lane:
one sanitizer in tier-1 keeps the flags from rotting without tripling
the native build time.

Skips (does not fail) when the toolchain or the libav dev headers are
absent — CI images without g++ still run the Python tier-1 suite.
"""

import os
import shutil
import subprocess

import pytest

CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "cpp")


def _have_toolchain():
    cxx = os.environ.get("CXX", "g++")
    if shutil.which(cxx) is None or shutil.which("make") is None:
        return False
    # libav dev headers: probe the preprocessor rather than pkg-config
    # (the image installs headers without .pc files)
    probe = subprocess.run(
        [cxx, "-E", "-x", "c++", "-", "-o", os.devnull],
        input="#include <libavformat/avformat.h>\n",
        capture_output=True, text=True, cwd=CPP_DIR, timeout=60)
    return probe.returncode == 0


@pytest.mark.skipif(not os.path.isdir(CPP_DIR),
                    reason="cpp/ not present in this checkout")
def test_asan_harness_builds_and_passes():
    if not _have_toolchain():
        pytest.skip("no C++ toolchain / libav headers — native "
                    "sanitizer gate needs g++, make, libavformat-dev")
    res = subprocess.run(
        ["make", "asan"], cwd=CPP_DIR, capture_output=True,
        text=True, timeout=600,
        env={**os.environ, "ASAN_OPTIONS": "abort_on_error=1"})
    out = res.stdout + res.stderr
    assert res.returncode == 0, f"make asan failed:\n{out[-4000:]}"
    assert "all native checks passed" in out, out[-4000:]
    assert "AddressSanitizer" not in out, (
        "ASAN reported an error:\n" + out[-4000:])
