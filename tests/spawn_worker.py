"""Standalone worker process for fault-tolerance tests
(reference tests/spawn_worker.py)."""

import sys

from scanner_tpu.engine.service import start_worker

if __name__ == "__main__":
    master = sys.argv[1]
    db_path = sys.argv[2]
    port = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    start_worker(master, db_path=db_path, port=port, block=True)
