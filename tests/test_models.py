"""Model family tests: ops through the engine + sharded train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels  # noqa: F401
import scanner_tpu.models   # registers model ops
from scanner_tpu import video as scv
from scanner_tpu.models import make_sharded_train_step
from scanner_tpu.models.pose import heatmaps_to_keypoints
from scanner_tpu.parallel import make_mesh


@pytest.fixture(scope="module")
def sc(tmp_path_factory):
    root = tmp_path_factory.mktemp("models")
    vid = str(root / "v.mp4")
    scv.synthesize_video(vid, num_frames=32, width=128, height=128, fps=24,
                         keyint=8)
    client = Client(db_path=str(root / "db"))
    client.ingest_videos([("test1", vid)])
    yield client
    client.stop()


def _run(sc, col, name):
    out = NamedStream(sc, name)
    sc.run(sc.io.Output(col, [out]), PerfParams.manual(8, 16),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    return list(out.load())


def test_pose_detect_e2e(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 8)])
    pose = sc.ops.PoseDetect(frame=sampled)
    rows = _run(sc, pose, "pose_out")
    assert len(rows) == 8
    assert rows[0].shape == (17, 3)


def test_object_and_face_detect_e2e(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 4)])
    det = sc.ops.ObjectDetect(frame=sampled)
    rows = _run(sc, det, "det_out")
    assert len(rows) == 4
    # packed (top_k, 6) rows [y1,x1,y2,x2,score,valid]
    from scanner_tpu.models import unpack_detections
    d0 = unpack_detections(rows[0])
    assert np.asarray(rows[0]).shape[1] == 6
    assert "boxes" in d0 and d0["boxes"].shape[1:] == (4,)

    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 4)])
    fd = sc.ops.FaceDetect(frame=sampled)
    rows = _run(sc, fd, "face_out")
    assert len(rows) == 4


def test_face_embedding_e2e(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 6)])
    emb = sc.ops.FaceEmbedding(frame=sampled)
    rows = _run(sc, emb, "emb_out")
    assert len(rows) == 6
    assert rows[0].shape == (128,)
    np.testing.assert_allclose(np.linalg.norm(rows[0]), 1.0, rtol=1e-4)


def test_shot_detection_e2e(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    d = sc.ops.HistDiff(frame=frame)
    rows = _run(sc, d, "shots_out")
    assert len(rows) == 32
    assert all(isinstance(r, float) for r in rows)
    from scanner_tpu.kernels.shot import detect_shots
    detect_shots(np.asarray(rows))


def test_heatmaps_to_keypoints():
    heat = np.zeros((16, 16, 17), np.float32)
    heat[3, 7, 0] = 5.0
    kp = heatmaps_to_keypoints(heat)
    assert tuple(kp[0][:2]) == (7.0, 3.0)
    assert kp[0][2] == 5.0


@pytest.mark.slow  # multi-minute XLA compile of the full multi-chip train step on CPU
def test_sharded_train_step_dp_sp_tp():
    """Full multi-chip training step on the virtual 8-device mesh:
    dp=2 (batch) x sp=2 (ring-attention time) x tp=2 (channels+experts)."""
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    step, params, opt_state, (clip, target) = make_sharded_train_step(
        mesh, clip_shape=(4, 4, 64, 64, 3), width=32)
    params, opt_state, loss = step(params, opt_state, clip, target)
    params, opt_state, loss = step(params, opt_state, clip, target)
    assert np.isfinite(float(loss))


@pytest.mark.slow  # multi-minute XLA compile of the full multi-chip train step on CPU
def test_train_checkpoint_roundtrip(tmp_path):
    import jax
    from scanner_tpu.models.checkpoint import TrainCheckpointer
    from scanner_tpu.models import make_sharded_train_step
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    step, params, opt_state, (clip, target) = make_sharded_train_step(
        mesh, clip_shape=(2, 4, 32, 32, 3), width=32)
    params, opt_state, loss1 = step(params, opt_state, clip, target)
    ck = TrainCheckpointer(str(tmp_path / "ckpt"))
    ck.save(1, params, opt_state)
    assert ck.latest_step() == 1
    # restore onto the same shardings and take another step
    p2, o2, s = ck.restore(params, opt_state)
    p2, o2, loss2 = step(p2, o2, clip, target)
    assert s == 1 and float(loss2) <= float(loss1) * 1.5
    ck.close()


def test_params_npz_roundtrip(tmp_path):
    import jax
    from scanner_tpu.models import init_params
    from scanner_tpu.models.checkpoint import (export_params_npz,
                                               import_params_npz)
    _, params = init_params(jax.random.PRNGKey(3),
                            clip_shape=(1, 2, 32, 32, 3), width=8)
    p = str(tmp_path / "w.npz")
    export_params_npz(params, p)
    restored = import_params_npz(p, params)
    flat1 = jax.tree_util.tree_leaves(params)
    flat2 = jax.tree_util.tree_leaves(restored)
    assert all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(flat1, flat2))
    # width mismatch fails loudly, not silently
    _, wrong = init_params(jax.random.PRNGKey(3),
                           clip_shape=(1, 2, 32, 32, 3), width=16)
    with pytest.raises((ValueError, KeyError)):
        import_params_npz(p, wrong)


def test_pose_shipped_weights_localize(tmp_path):
    """E2E: PoseDetect restoring the SHIPPED weights localizes the blob in
    an encoded clip far better than chance (reference pose app semantics —
    real trained weights, not random init)."""
    import os
    from scanner_tpu import (CacheMode, Client, NamedStream,
                             NamedVideoStream, PerfParams)
    from scanner_tpu.models.pose_train import (SIZE, WIDTH,
                                               synth_blob_video)

    weights = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scanner_tpu", "models", "weights", "pose_blobnet_w8.npz")
    assert os.path.exists(weights), "shipped weights missing"

    vid = str(tmp_path / "blob.mp4")
    centers = synth_blob_video(vid, num_frames=16)
    sc = Client(db_path=str(tmp_path / "db"))
    try:
        movie = NamedVideoStream(sc, "blob", path=vid)
        poses = sc.ops.PoseDetect(frame=sc.io.Input([movie]), width=WIDTH,
                                  checkpoint_dir=weights)
        out = NamedStream(sc, "poses_out")
        sc.run(sc.io.Output(poses, [out]), PerfParams.estimate(),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        errs = []
        for i, kp in enumerate(out.load()):
            x, y = kp[0, 0] * 4, kp[0, 1] * 4
            errs.append(float(np.hypot(x - centers[i, 0],
                                       y - centers[i, 1])))
        assert len(errs) == 16
        # chance (uniform argmax over the heatmap) averages ~SIZE/2*0.76
        # ~= 18px here; the trained weights must be several times better
        assert np.mean(errs) < 5.0, f"mean error {np.mean(errs):.1f}px"
    finally:
        sc.stop()


def test_model_ops_checkpoint_restore(tmp_path):
    """Every model op restores exported weights (uniform weight path)."""
    import jax
    import jax.numpy as jnp
    from scanner_tpu.graph.ops import KernelConfig, registry
    from scanner_tpu.common import DeviceType
    from scanner_tpu.models.checkpoint import export_params_npz

    cfg = KernelConfig(device=DeviceType.TPU)
    for op_name, kw in [("ObjectDetect", dict(width=8)),
                        ("FaceDetect", dict(width=8)),
                        ("FaceEmbedding", dict(width=8, dim=16))]:
        spec = registry.get(op_name)
        k1 = spec.kernel_factory(cfg, **kw)
        p = str(tmp_path / f"{op_name}.npz")
        export_params_npz(k1.params, p)
        k2 = spec.kernel_factory(cfg, checkpoint_dir=p, **kw)
        leaves1 = jax.tree_util.tree_leaves(k1.params)
        leaves2 = jax.tree_util.tree_leaves(k2.params)
        assert all(np.array_equal(np.asarray(a), np.asarray(b))
                   for a, b in zip(leaves1, leaves2)), op_name
        # restored kernel runs
        frames = np.random.RandomState(0).randint(
            0, 255, (2, 64, 64, 3), np.uint8)
        out = k2.execute(frames)
        assert len(out) == 2


def test_data_parallel_inference_multichip():
    """Model kernels dp-shard inference across the devices the engine
    hands them; results match single-device exactly."""
    import jax
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices (virtual CPU mesh)")
    from scanner_tpu.common import DeviceType
    from scanner_tpu.graph.ops import KernelConfig, registry

    frames = np.random.RandomState(0).randint(
        0, 255, (8, 64, 64, 3), np.uint8)
    spec = registry.get("FaceEmbedding")
    k1 = spec.kernel_factory(
        KernelConfig(device=DeviceType.TPU), width=8, dim=16)
    k4 = spec.kernel_factory(
        KernelConfig(device=DeviceType.TPU,
                     devices=list(jax.devices()[:4])), width=8, dim=16)
    out1 = np.stack(k1.execute(frames))
    out4 = np.stack(k4.execute(frames))
    np.testing.assert_allclose(out1, out4, rtol=1e-5, atol=1e-6)
    # the sharded path really spans the chips
    sharded = jax.device_put(jnp.asarray(frames), k4._dp._data_sharding)
    assert len({s.device for s in sharded.addressable_shards}) == 4
    # odd batch pads to the device multiple and slices (still correct)
    odd = frames[:5]
    np.testing.assert_allclose(np.stack(k1.execute(odd)),
                               np.stack(k4.execute(odd)),
                               rtol=1e-5, atol=1e-6)


def test_detect_shipped_weights_localize(tmp_path):
    """E2E: ObjectDetect with the SHIPPED weights (restored by default at
    width 8) localizes synthetic scenes through the video codec path —
    reference object-detection app semantics (trained model by default,
    object_detection_tensorflow/main.py:16-23)."""
    from scanner_tpu import (CacheMode, Client, NamedStream,
                             NamedVideoStream, PerfParams)
    from scanner_tpu.models.detect_train import (WIDTH, box_iou,
                                                 synth_scene_video)
    from scanner_tpu.models.checkpoint import shipped_weights

    assert shipped_weights("detect_ssd_w8.npz"), "shipped weights missing"
    vid = str(tmp_path / "scenes.mp4")
    truth = synth_scene_video(vid, num_frames=12, seed=21)
    sc = Client(db_path=str(tmp_path / "db"))
    try:
        movie = NamedVideoStream(sc, "scenes", path=vid)
        dets = sc.ops.ObjectDetect(frame=sc.io.Input([movie]), width=WIDTH,
                                   score_thresh=0.3)
        out = NamedStream(sc, "dets_out")
        sc.run(sc.io.Output(dets, [out]), PerfParams.estimate(),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        hits = total = 0
        from scanner_tpu.models import unpack_detections
        for i, det in enumerate(out.load()):
            boxes = unpack_detections(det)["boxes"]
            for gt in truth[i]:
                total += 1
                if any(box_iou(gt, b) >= 0.3 for b in boxes):
                    hits += 1
        assert total >= 12
        assert hits >= 0.7 * total, f"recall {hits}/{total}"
    finally:
        sc.stop()


def test_face_shipped_weights_localize(tmp_path):
    """E2E: FaceDetect's shipped face-task weights localize face scenes
    (reference face_detection app semantics)."""
    from scanner_tpu import (CacheMode, Client, NamedStream,
                             NamedVideoStream, PerfParams)
    from scanner_tpu.models.detect_train import (WIDTH, box_iou,
                                                 render_face_scene,
                                                 synth_scene_video)
    from scanner_tpu.models.checkpoint import shipped_weights

    assert shipped_weights("face_ssd_w8.npz"), "shipped weights missing"
    vid = str(tmp_path / "faces.mp4")
    truth = synth_scene_video(vid, renderer=render_face_scene,
                              num_frames=12, seed=22)
    sc = Client(db_path=str(tmp_path / "db"))
    try:
        movie = NamedVideoStream(sc, "faces", path=vid)
        dets = sc.ops.FaceDetect(frame=sc.io.Input([movie]), width=WIDTH,
                                 score_thresh=0.3)
        out = NamedStream(sc, "faces_out")
        sc.run(sc.io.Output(dets, [out]), PerfParams.estimate(),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        hits = total = 0
        from scanner_tpu.models import unpack_detections
        for i, det in enumerate(out.load()):
            boxes = unpack_detections(det)["boxes"]
            for gt in truth[i]:
                total += 1
                if any(box_iou(gt, b) >= 0.3 for b in boxes):
                    hits += 1
        assert total >= 12
        assert hits >= 0.7 * total, f"recall {hits}/{total}"
    finally:
        sc.stop()


def test_embedding_shipped_weights_recall():
    """The shipped embedding separates identities: probe views match
    gallery views of the same procedural identity (recall@1) well above
    chance (1/8)."""
    import jax.numpy as jnp

    from scanner_tpu.graph.ops import KernelConfig
    from scanner_tpu.common import DeviceType
    from scanner_tpu.models.detect_train import WIDTH, render_identity
    from scanner_tpu.models.face import FaceEmbedding
    from scanner_tpu.models.checkpoint import shipped_weights

    assert shipped_weights("embed_w8.npz"), "shipped weights missing"
    k = FaceEmbedding(KernelConfig(device=DeviceType.CPU), width=WIDTH)
    rng = np.random.RandomState(99)
    idents = list(range(8))
    gallery = np.stack([render_identity(i, rng) for i in idents])
    probe = np.stack([render_identity(i, rng) for i in idents])
    g = np.stack(k.execute(gallery))
    p = np.stack(k.execute(probe))
    sim = p @ g.T                      # cosine (embeddings normalized)
    pred = sim.argmax(1)
    recall = float((pred == np.arange(8)).mean())
    assert recall >= 0.75, f"recall@1 {recall:.2f}"


@pytest.mark.slow  # multi-minute XLA compile of the full multi-chip train step on CPU
def test_attention_scheme_selection():
    """attn_scheme (or SCANNER_TPU_ATTN) selects the sequence-parallel
    attention for the sharded train step; all three schemes (XLA ring,
    pallas-flash ring, Ulysses all-to-all) train to the SAME losses over
    TWO steps from the same seed — the second step's loss depends on the
    first step's gradients, so this pins the backward pass too (incl.
    the pallas custom_vjp)."""
    from scanner_tpu.kernels.pallas_attention import HAVE_PALLAS
    from scanner_tpu.models import make_sharded_train_step
    from scanner_tpu.parallel import auto_axes, make_mesh

    schemes = ["ring", "ulysses"] + (["pallas"] if HAVE_PALLAS else [])
    losses = {}
    for scheme in schemes:
        mesh = make_mesh(auto_axes(8))
        step, params, opt_state, (clip, target) = make_sharded_train_step(
            mesh, clip_shape=(2, 8, 32, 32, 3), width=8,
            attn_scheme=scheme)
        params, opt_state, l1 = step(params, opt_state, clip, target)
        params, opt_state, l2 = step(params, opt_state, clip, target)
        losses[scheme] = (float(l1), float(l2))
        assert np.isfinite(losses[scheme]).all(), (scheme, losses[scheme])
        assert losses[scheme][1] < losses[scheme][0], \
            f"{scheme}: loss did not decrease {losses[scheme]}"
    # rel 1e-3: schemes reduce in different orders (ppermute chain vs
    # all-to-all vs pallas tiles), so f32 losses agree to ~1e-4 but not
    # bitwise; a broken backward diverges by orders of magnitude more
    for scheme in schemes[1:]:
        assert losses[scheme][0] == pytest.approx(losses["ring"][0],
                                                  rel=1e-3)
        assert losses[scheme][1] == pytest.approx(losses["ring"][1],
                                                  rel=1e-3)
    # unknown scheme fails loudly, not silently-ring
    with pytest.raises(ValueError, match="unknown attention scheme"):
        make_sharded_train_step(make_mesh(auto_axes(8)),
                                clip_shape=(2, 8, 32, 32, 3), width=8,
                                attn_scheme="flash")


def test_roi_align_matches_numpy_reference():
    """roi_align's bilinear samples agree with a direct numpy evaluation
    for identity, sub-region and out-of-range (clamped) boxes."""
    from scanner_tpu.models.segmentation import roi_align

    rng = np.random.RandomState(0)
    feat = rng.randn(1, 6, 5, 3).astype(np.float32)
    boxes = np.asarray([[[0.0, 0.0, 1.0, 1.0],
                         [0.2, 0.1, 0.7, 0.9],
                         [-0.2, 0.5, 1.3, 1.5]]], np.float32)
    S = 4
    got = np.asarray(roi_align(jnp.asarray(feat), jnp.asarray(boxes), S))

    fh, fw = feat.shape[1], feat.shape[2]
    for k, box in enumerate(boxes[0]):
        y1, x1, y2, x2 = box
        for i in range(S):
            for j in range(S):
                fy = (y1 + (y2 - y1) * (i + 0.5) / S) * fh - 0.5
                fx = (x1 + (x2 - x1) * (j + 0.5) / S) * fw - 0.5
                y0, x0 = int(np.floor(fy)), int(np.floor(fx))
                wy, wx = fy - y0, fx - x0
                c = lambda y, x: feat[0, min(max(y, 0), fh - 1),
                                      min(max(x, 0), fw - 1)]
                want = (c(y0, x0) * (1 - wy) * (1 - wx) +
                        c(y0, x0 + 1) * (1 - wy) * wx +
                        c(y0 + 1, x0) * wy * (1 - wx) +
                        c(y0 + 1, x0 + 1) * wy * wx)
                np.testing.assert_allclose(got[0, k, i, j], want,
                                           rtol=1e-5, atol=1e-5)


def test_instance_segment_e2e(sc):
    """InstanceSegment rows are packed (top_k, 6 + M*M) and unpack to
    boxes + boolean roi masks (reference detectron app shape contract)."""
    from scanner_tpu.models.segmentation import MASK_SIZE, TOP_K
    from scanner_tpu.models import unpack_instances

    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 4)])
    inst = sc.ops.InstanceSegment(frame=sampled)
    rows = _run(sc, inst, "seg_out")
    assert len(rows) == 4
    a = np.asarray(rows[0])
    assert a.shape == (TOP_K, 6 + MASK_SIZE * MASK_SIZE)
    r = unpack_instances(rows[0])
    assert r["masks"].shape[1:] == (MASK_SIZE, MASK_SIZE)
    assert r["masks"].dtype == bool


def test_seg_shipped_weights_segment(tmp_path):
    """E2E: InstanceSegment with the SHIPPED weights localizes synthetic
    shapes AND recovers their silhouettes — predicted masks must match
    the correct shape kind better than the wrong kind (a full-box mask
    cannot pass: IoU(box, inscribed ellipse) = pi/4).  Reference
    detectron app semantics (trained Mask R-CNN by default)."""
    from scanner_tpu.models import paste_masks, unpack_instances
    from scanner_tpu.models.checkpoint import shipped_weights
    from scanner_tpu.models.detect_train import WIDTH, box_iou
    from scanner_tpu.models.seg_train import (SIZE, full_gt_mask,
                                              synth_shape_video)

    assert shipped_weights("seg_w8.npz"), "shipped weights missing"
    vid = str(tmp_path / "shapes.mp4")
    truth = synth_shape_video(vid, num_frames=12, seed=31)
    sc2 = Client(db_path=str(tmp_path / "db"))
    try:
        movie = NamedVideoStream(sc2, "shapes", path=vid)
        inst = sc2.ops.InstanceSegment(frame=sc2.io.Input([movie]),
                                       width=WIDTH, score_thresh=0.3)
        out = NamedStream(sc2, "inst_out")
        sc2.run(sc2.io.Output(inst, [out]), PerfParams.estimate(),
                cache_mode=CacheMode.Overwrite, show_progress=False)
        matched = total = 0
        iou_correct, iou_wrong = [], []
        for i, row in enumerate(out.load()):
            r = unpack_instances(row)
            boxes, masks = r["boxes"], r["masks"]
            full = paste_masks(boxes, masks, SIZE, SIZE)
            gt_boxes, gt_kinds = truth[i]
            for gt_box, gt_kind in zip(gt_boxes, gt_kinds):
                total += 1
                cand = [j for j, b in enumerate(boxes)
                        if box_iou(gt_box, b) >= 0.3]
                if not cand:
                    continue
                matched += 1

                def iou_with(kind):
                    gm = full_gt_mask(gt_box, kind, SIZE, SIZE)
                    return max((full[j] & gm).sum() /
                               max((full[j] | gm).sum(), 1) for j in cand)

                iou_correct.append(iou_with(int(gt_kind)))
                iou_wrong.append(iou_with(1 - int(gt_kind)))
        assert total >= 12
        assert matched >= 0.7 * total, f"recall {matched}/{total}"
        mean_c = float(np.mean(iou_correct))
        mean_w = float(np.mean(iou_wrong))
        assert mean_c >= 0.55, f"mask IoU too low: {mean_c:.2f}"
        assert mean_c > mean_w + 0.05, (
            f"masks don't discriminate shape: correct {mean_c:.2f} "
            f"vs wrong-kind {mean_w:.2f}")
    finally:
        sc2.stop()


@pytest.mark.slow  # multi-minute XLA compile of the full multi-chip train step on CPU
def test_remat_train_step_matches():
    """remat=True (jax.checkpoint on backbone + temporal blocks) is the
    same math: first-step loss and the second-step loss after one update
    match the unremat'd model to f32 tolerance — only activation storage
    changes."""
    from scanner_tpu.parallel import auto_axes, make_mesh

    losses = {}
    for remat in (False, True):
        mesh = make_mesh(auto_axes(8))
        step, params, opt_state, (clip, target) = make_sharded_train_step(
            mesh, clip_shape=(2, 8, 32, 32, 3), width=8, remat=remat)
        params, opt_state, l1 = step(params, opt_state, clip, target)
        params, opt_state, l2 = step(params, opt_state, clip, target)
        losses[remat] = (float(l1), float(l2))
    # step-1 loss: same params, same forward -> identical
    assert losses[True][0] == pytest.approx(losses[False][0], rel=1e-5)
    # step-2 loss: grads recompute through bf16 blocks, so f32
    # accumulation order differs slightly (measured ~4e-4 rel); a broken
    # remat (wrong params/rng threading) diverges by orders more
    assert losses[True][1] == pytest.approx(losses[False][1], rel=1e-2)


def test_pp_params_convert_to_plain_serving():
    """Params trained on a pipeline mesh convert to the plain serving
    layout (and back) with BIT-IDENTICAL outputs in f32 — train with pp,
    serve with the engine kernels (pp_params_to_plain), or continue
    training shipped plain weights on a pp mesh (plain_params_to_pp)."""
    from scanner_tpu.models.pose import (VideoPoseNet, init_params,
                                         pp_params_to_plain,
                                         plain_params_to_pp)
    from scanner_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 2, "pp": 2})
    pp_model, pp_params = init_params(
        jax.random.PRNGKey(3), clip_shape=(1, 4, 32, 32, 3), width=8,
        pipeline_mesh=mesh, temporal_layers=2, dtype=jnp.float32)
    clip = (np.arange(np.prod((4, 4, 32, 32, 3))) % 251) \
        .astype(np.uint8).reshape(4, 4, 32, 32, 3)
    pp_out = np.asarray(jax.jit(pp_model.apply)(pp_params, clip))

    plain_model = VideoPoseNet(width=8, temporal_layers=2,
                               dtype=jnp.float32)
    plain_params = pp_params_to_plain(pp_params)
    plain_out = np.asarray(jax.jit(plain_model.apply)(plain_params, clip))
    np.testing.assert_array_equal(pp_out, plain_out)

    back = plain_params_to_pp(plain_params)
    back_out = np.asarray(jax.jit(pp_model.apply)(back, clip))
    np.testing.assert_array_equal(back_out, plain_out)
    # conversion is lossless both ways on the leaves too
    again = pp_params_to_plain(back)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), again,
        plain_params)


@pytest.mark.slow
def test_pp_trained_weights_serve_through_engine(tmp_path, sc):
    """The full pp workflow: one training step on a pipeline mesh ->
    convert the stacked stages to the plain layout -> export portable
    .npz -> PoseDetect(checkpoint_dir=...) serves it through the engine.
    Pins that pipeline-trained weights are first-class citizens of the
    kernel weight path."""
    from scanner_tpu.models import pp_params_to_plain
    from scanner_tpu.models.checkpoint import export_params_npz

    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 2, "pp": 2})
    step, params, opt_state, (clip, target) = make_sharded_train_step(
        mesh, clip_shape=(4, 4, 64, 64, 3), width=8)
    params, opt_state, loss = step(params, opt_state, clip, target)
    assert np.isfinite(float(loss))

    npz = str(tmp_path / "pp_trained_w8.npz")
    export_params_npz(pp_params_to_plain(params), npz)

    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 4)])
    pose = sc.ops.PoseDetect(frame=sampled, width=8, checkpoint_dir=npz)
    rows = _run(sc, pose, "pp_pose_out")
    assert len(rows) == 4 and rows[0].shape == (17, 3)
    assert all(np.isfinite(np.asarray(r)).all() for r in rows)


def test_unpack_and_paste_edge_cases():
    """Host-side mask utilities on degenerate inputs: all-invalid rows
    unpack to empty arrays, zero boxes paste to an empty stack, and a
    sub-pixel box still paints at least one pixel without crashing."""
    from scanner_tpu.models import paste_masks, unpack_instances
    from scanner_tpu.models.segmentation import MASK_SIZE, TOP_K

    row = np.zeros((TOP_K, 6 + MASK_SIZE * MASK_SIZE), np.float32)
    r = unpack_instances(row)  # every valid flag is 0
    assert r["boxes"].shape == (0, 4)
    assert r["scores"].shape == (0,)
    assert r["masks"].shape == (0, MASK_SIZE, MASK_SIZE)

    empty = paste_masks(r["boxes"], r["masks"], 32, 32)
    assert empty.shape == (0, 32, 32)

    boxes = np.asarray([[0.5, 0.5, 0.5001, 0.5001],   # sub-pixel
                        [-0.2, -0.2, 1.4, 1.4]],      # out of range
                       np.float32)
    masks = np.ones((2, MASK_SIZE, MASK_SIZE), bool)
    full = paste_masks(boxes, masks, 32, 32)
    assert full.shape == (2, 32, 32)
    assert full[0].sum() >= 1          # degenerate box still paints
    assert full[1].all()               # clipped full-frame box covers all
