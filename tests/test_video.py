import os
import struct

import numpy as np
import pytest

from scanner_tpu.common import ScannerException
from scanner_tpu import video as scv
from scanner_tpu.video.automata import VideoIndex


def expected_id(r, h, w):
    return scv.frame_pattern_id(scv.frame_pattern(r, h, w))


@pytest.fixture(scope="module")
def clip(tmp_path_factory):
    p = str(tmp_path_factory.mktemp("vids") / "clip.mp4")
    scv.synthesize_video(p, num_frames=90, width=128, height=96, fps=24,
                         keyint=12)
    return p


def test_synthesize_and_index(clip):
    vd = scv.ingest_file(clip, None)  # in-place index of the mp4
    assert vd.num_frames == 90
    assert vd.width == 128 and vd.height == 96
    assert vd.codec == "h264"
    assert len(vd.extradata) > 0
    # keyint=12 -> keyframes at 0,12,24,...
    assert vd.keyframe_indices[0] == 0
    assert len(vd.keyframe_indices) >= 90 // 12


def test_ingest_and_exact_decode(tmp_db, clip):
    scv.ingest_videos(tmp_db, [("clip", clip)])
    desc = tmp_db.table_descriptor("clip")
    assert desc.num_rows == 90
    assert desc.column_names() == ["index", "frame"]
    assert tmp_db.table_is_committed("clip")
    # index column contents
    idx = list(tmp_db.load_column("clip", "index"))
    assert struct.unpack("<q", idx[33])[0] == 33

    # exact frame reads across keyframe boundaries, unsorted with dup
    rows = [0, 13, 12, 40, 40, 89]
    frames = scv.load_frames(tmp_db, "clip", rows)
    assert frames.shape == (6, 96, 128, 3)
    for got, r in zip(frames, rows):
        assert scv.frame_pattern_id(got) == expected_id(r, 96, 128), \
            f"frame {r} mismatch"
    assert (frames[3] == frames[4]).all()


def test_unaligned_width_decode(tmp_db, tmp_path):
    """Regression: frame widths not a multiple of 16 corrupted the heap
    (tight-packed sws_scale RGB output overran SIMD row writes; noted
    in CHANGES.md PR 9, fixed via an aligned scratch surface in
    convert_frame).  A 90x70 clip must ingest and decode exactly, on
    both the rgb24 and the yuv420 wire paths."""
    w, h, n = 90, 70, 30
    p = str(tmp_path / "unaligned.mp4")
    scv.synthesize_video(p, num_frames=n, width=w, height=h, fps=24,
                         keyint=8)
    scv.ingest_videos(tmp_db, [("uclip", p)])
    rows = [0, 7, 8, 17, 29]
    frames = scv.load_frames(tmp_db, "uclip", rows)
    assert frames.shape == (len(rows), h, w, 3)
    for got, r in zip(frames, rows):
        assert scv.frame_pattern_id(got) == expected_id(r, h, w), \
            f"frame {r} mismatch at unaligned width"
    # yuv420 wire path (the planar copy/scratch flavor): decode the
    # same rows through a yuv decoder and convert host-side
    from scanner_tpu.storage.database import Database  # noqa: F401
    from scanner_tpu.video.automata import DecoderAutomata
    from scanner_tpu.storage import metadata as md
    from scanner_tpu.kernels.color import yuv420_to_rgb_host
    desc = tmp_db.table_descriptor("uclip")
    vd = scv.load_video_meta(tmp_db, "uclip", "frame")
    auto = DecoderAutomata(
        tmp_db.backend, vd, md.column_item_path(desc.id, "frame", 0),
        output_format="yuv420")
    try:
        yuv = auto.get_frames(rows)
    finally:
        auto.close()
    rgb = yuv420_to_rgb_host(np.asarray(yuv), h, w)
    assert rgb.shape == (len(rows), h, w, 3)
    for got, r in zip(rgb, rows):
        assert scv.frame_pattern_id(got) == expected_id(r, h, w), \
            f"yuv frame {r} mismatch at unaligned width"


def test_corpus_ingest_collects_per_video_failures(tmp_db, clip, tmp_path):
    """A corrupt file mid-list is reported in the failures list, not
    raised — the rest of the corpus still ingests (reference
    ingest.cpp:872-978 failed_videos)."""
    bad = str(tmp_path / "corrupt.mp4")
    with open(bad, "wb") as f:
        f.write(b"\x00\x01not a video at all" * 64)
    other = str(tmp_path / "other.mp4")
    scv.synthesize_video(other, num_frames=24, width=128, height=96)

    descs, failed = scv.ingest_videos(
        tmp_db, [("good1", clip), ("badv", bad), ("good2", other)])
    assert [d.name for d in descs] == ["good1", "good2"]
    assert len(failed) == 1
    assert failed[0][0] == bad and "ingest failed" in failed[0][1]
    # the failed video left no table behind; the good ones are committed
    assert not tmp_db.has_table("badv")
    assert tmp_db.table_is_committed("good1")
    assert tmp_db.table_descriptor("good2").num_rows == 24

    # a name collision is a caller error: raised up front, unless force=
    with pytest.raises(ScannerException, match="already exists"):
        scv.ingest_videos(tmp_db, [("good1", other)])
    with pytest.raises(ScannerException, match="duplicate table names"):
        scv.ingest_videos(tmp_db, [("dup", clip), ("dup", other)])
    descs2, failed2 = scv.ingest_videos(tmp_db, [("good1", other)],
                                        force=True)
    assert not failed2 and tmp_db.table_descriptor("good1").num_rows == 24


def test_inplace_ingest_decode(tmp_db, clip):
    scv.ingest_videos(tmp_db, [("clip_inplace", clip)], inplace=True)
    frames = scv.load_frames(tmp_db, "clip_inplace", [5, 60])
    for got, r in zip(frames, [5, 60]):
        assert scv.frame_pattern_id(got) == expected_id(r, 96, 128)


def test_full_sequential_decode(tmp_db, clip):
    scv.ingest_videos(tmp_db, [("clip2", clip)])
    frames = scv.load_frames(tmp_db, "clip2", list(range(90)))
    assert frames.shape == (90, 96, 128, 3)
    ids = [scv.frame_pattern_id(f) for f in frames]
    assert ids == [expected_id(r, 96, 128) for r in range(90)]


def test_plan_minimality(clip):
    vd = scv.ingest_file(clip, None)
    index = VideoIndex(vd)
    kfs = list(vd.keyframe_indices)

    def governing(r):
        return max(k for k in kfs if k <= r)

    # single frame mid-GOP: one run from its governing keyframe to the frame
    runs = index.plan([15])
    assert len(runs) == 1
    assert runs[0].start_dec == governing(15) and runs[0].end_dec == 15
    # distant frames: separate runs (no decode-through across the gap)
    runs = index.plan([0, 80], decode_through=4)
    assert len(runs) == 2
    assert runs[0].start_dec == 0 and runs[0].end_dec == 0
    assert runs[1].start_dec == governing(80)
    # near frames merge into one run
    runs = index.plan([10, 14], decode_through=64)
    assert len(runs) == 1
    assert runs[0].end_dec == 14


def test_out_of_range_row(tmp_db, clip):
    scv.ingest_videos(tmp_db, [("clip3", clip)])
    with pytest.raises(ScannerException):
        scv.load_frames(tmp_db, "clip3", [90])


def test_export_mp4_roundtrip(tmp_db, clip, tmp_path):
    scv.ingest_videos(tmp_db, [("clip4", clip)])
    out = str(tmp_path / "out.mp4")
    scv.export_mp4(tmp_db, "clip4", out)
    assert os.path.getsize(out) > 1000
    vd = scv.ingest_file(out, None)
    assert vd.num_frames == 90


@pytest.mark.parametrize("fps", [24.0, 12.5, 30000 / 1001])
def test_mux_preserves_frame_count_and_fps(tmp_path, fps):
    """Regression: without per-packet durations the mp4 edit list could
    exclude the final sample (lost frame at 12.5 fps) and avg_frame_rate
    was overestimated (24 fps clips reported ~25.04)."""
    from scanner_tpu.video import lib
    from scanner_tpu.video.ingest import frame_pattern
    p = str(tmp_path / "clip.mp4")
    enc = lib.Encoder(64, 48, fps=fps, keyint=12, crf=18)
    for i in range(24):
        enc.feed(frame_pattern(i, 48, 64))
    enc.flush()
    data, sizes, keys, pts, dts = enc.take_packets()
    lib.write_mp4(p, 64, 48, fps, "h264", enc.extradata, data, sizes, keys,
                  pts, dts)
    enc.close()
    vd = lib.ingest_file(p, str(tmp_path / "clip.pkts"))
    assert vd.num_frames == 24
    assert vd.fps == pytest.approx(fps, rel=1e-6)


def test_fps_to_rational():
    from scanner_tpu.video.lib import _fps_to_rational
    assert _fps_to_rational(24) == (24, 1)
    assert _fps_to_rational(12.5) == (25, 2)       # not NTSC-mangled
    assert _fps_to_rational(30000 / 1001) == (30000, 1001)
    assert _fps_to_rational(24000 / 1001) == (24000, 1001)


def test_encoder_decoder_roundtrip_lossless_geometry():
    enc = scv.Encoder(64, 48, fps=30, keyint=8)
    frames = np.stack([scv.frame_pattern(i, 48, 64) for i in range(20)])
    enc.feed(frames)
    enc.flush()
    data, sizes, keys, pts, dts = enc.take_packets()
    assert len(sizes) == 20
    assert keys[0] == 1
    dec = scv.Decoder("h264", enc.extradata, 64, 48)
    out = np.empty(20 * 48 * 64 * 3, np.uint8)
    n, h, w = dec.decode_run(data, sizes, np.ones(20, np.uint8), out)
    assert (n, h, w) == (20, 48, 64)
    out = out.reshape(20, 48, 64, 3)
    for i in range(20):
        assert scv.frame_pattern_id(out[i]) == expected_id(i, 48, 64)
    dec.close()
    enc.close()


# -- B-frame / reordered (pts != dts) streams ---------------------------
# Real-world encodes reorder: the display-order <-> decode-order maps in
# VideoIndex (dec_of_disp) are non-trivial.  Reference coverage:
# decoder_automata_test.cpp (seeks/discontinuities) + feeder
# discontinuity logic decoder_automata.cpp:238.

@pytest.fixture(scope="module")
def bclip(tmp_path_factory):
    p = str(tmp_path_factory.mktemp("vids") / "bclip.mp4")
    scv.synthesize_video(p, num_frames=48, width=64, height=48, fps=24,
                         keyint=8, bframes=2)
    return p


def test_bframe_stream_actually_reorders(bclip):
    vd = scv.ingest_file(bclip, None)
    assert vd.num_frames == 48
    pts = np.asarray(vd.sample_pts)
    # decode order != display order somewhere, else the fixture is moot
    assert not np.all(np.diff(pts) > 0), \
        "encoder produced no reordering; bframes knob broken"
    idx = VideoIndex(vd)
    assert not np.array_equal(idx.dec_of_disp, np.arange(48))
    # the display<->decode maps are mutually inverse permutations
    assert np.array_equal(idx.disp_of_dec[idx.dec_of_disp], np.arange(48))


def test_bframe_full_sequential_decode(tmp_db, bclip):
    scv.ingest_videos(tmp_db, [("bclip_seq", bclip)])
    frames = scv.load_frames(tmp_db, "bclip_seq", list(range(48)))
    ids = [scv.frame_pattern_id(f) for f in frames]
    assert ids == [expected_id(r, 48, 64) for r in range(48)], \
        "display-order delivery broken on a reordered stream"


def test_bframe_gather_near_gop_boundaries(tmp_db, bclip):
    """Isolated frames just before/at/after each keyframe (keyint=8):
    exactly where pts!=dts reordering bites the decode plan."""
    scv.ingest_videos(tmp_db, [("bclip_gop", bclip)])
    rows = [6, 7, 8, 9, 15, 16, 17, 31, 32, 40, 47]
    frames = scv.load_frames(tmp_db, "bclip_gop", rows)
    for got, r in zip(frames, rows):
        assert scv.frame_pattern_id(got) == expected_id(r, 48, 64), \
            f"frame {r} wrong on reordered stream"


def test_bframe_unsorted_with_duplicates(tmp_db, bclip):
    scv.ingest_videos(tmp_db, [("bclip_dup", bclip)])
    rows = [30, 7, 7, 45, 0, 23]
    frames = scv.load_frames(tmp_db, "bclip_dup", rows)
    assert (frames[1] == frames[2]).all()
    for got, r in zip(frames, rows):
        assert scv.frame_pattern_id(got) == expected_id(r, 48, 64)


def test_bframe_inplace_ingest(tmp_db, bclip):
    """In-place (external container) reads must also survive reordering."""
    scv.ingest_videos(tmp_db, [("bclip_inp", bclip)], inplace=True)
    rows = [5, 8, 20, 41]
    frames = scv.load_frames(tmp_db, "bclip_inp", rows)
    for got, r in zip(frames, rows):
        assert scv.frame_pattern_id(got) == expected_id(r, 48, 64)


def test_bframe_engine_gather_pipeline(tmp_db, bclip, tmp_path):
    """Full engine path (DAG analysis -> decode plan -> kernel -> sink)
    over a Gather of a reordered stream."""
    from scanner_tpu import (CacheMode, Client, NamedStream,
                            NamedVideoStream, PerfParams)
    import scanner_tpu.kernels  # noqa: F401

    sc = Client(db_path=str(tmp_path / "bdb"))
    try:
        movie = NamedVideoStream(sc, "bmovie", path=bclip)
        frames = sc.io.Input([movie])
        rows = [2, 8, 9, 15, 16, 30, 47]
        picked = sc.streams.Gather(frames, [rows])
        hist = sc.ops.Histogram(frame=picked)
        out = NamedStream(sc, "bhists")
        sc.run(sc.io.Output(hist, [out]), PerfParams.manual(4, 8),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        hists = list(out.load())
        assert len(hists) == len(rows)
        # cross-check against direct exact decode of the same rows
        direct = scv.load_frames(sc._db, "bmovie", rows)
        from scanner_tpu.kernels.imgproc import Histogram as HK
        for h, f in zip(hists, direct):
            expect = HK._histogram_np(f[None])[0]
            assert np.array_equal(np.stack(h), expect)
    finally:
        sc.stop()


# ---------------------------------------------------------------------------
# Open-GOP streams: non-IDR recovery-point keyframes whose leading B frames
# reference the PREVIOUS GOP.  Seeking to such a keyframe and counting
# emitted frames misdelivers; the pts-matched decode path
# (scvid_decode_run_pts + automata._decode_run_pts) detects undelivered
# timestamps and restarts from an earlier keyframe.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def oclip(tmp_path_factory):
    p = str(tmp_path_factory.mktemp("vids") / "oclip.mp4")
    scv.synthesize_video(p, num_frames=48, width=64, height=48, fps=24,
                         keyint=8, bframes=2, open_gop=True)
    return p


def test_open_gop_fixture_shape(oclip):
    """The fixture must really be open-GOP: reordered pts, and at least
    one keyframe with a leading frame (display index before the
    keyframe's own display position but decode index after it)."""
    vd = scv.ingest_file(oclip, None)
    assert vd.num_frames == 48
    idx = VideoIndex(vd)
    pts = np.asarray(vd.sample_pts)
    assert not np.all(np.diff(pts) > 0), "no reordering in open-GOP clip"
    leading = 0
    for kf_dec in np.asarray(vd.keyframe_indices)[1:]:
        kf_disp = idx.disp_of_dec[kf_dec]
        # frames decoded after the keyframe but displayed before it
        after = idx.disp_of_dec[kf_dec + 1:kf_dec + 4]
        leading += int(np.sum(after < kf_disp))
    assert leading > 0, (
        "fixture has no leading frames; open_gop knob produced closed GOPs")


def test_open_gop_full_sequential_decode(tmp_db, oclip):
    scv.ingest_videos(tmp_db, [("oclip_seq", oclip)])
    frames = scv.load_frames(tmp_db, "oclip_seq", list(range(48)))
    ids = [scv.frame_pattern_id(f) for f in frames]
    assert ids == [expected_id(r, 48, 64) for r in range(48)]


def test_open_gop_leading_frame_gathers(tmp_db, oclip):
    """Isolated requests for frames around every GOP boundary — incl. the
    leading B frames that are NOT decodable from their governing keyframe
    alone (the earlier-keyframe retry path)."""
    scv.ingest_videos(tmp_db, [("oclip_gop", oclip)])
    from scanner_tpu.video.ingest import load_video_meta
    vd = load_video_meta(tmp_db, "oclip_gop")
    idx = VideoIndex(vd)
    rows = set()
    for kf_dec in np.asarray(vd.keyframe_indices)[1:]:
        kf_disp = int(idx.disp_of_dec[kf_dec])
        for r in (kf_disp - 2, kf_disp - 1, kf_disp, kf_disp + 1):
            if 0 <= r < 48:
                rows.add(r)
    rows = sorted(rows)
    # one at a time: each request must be individually exact
    for r in rows:
        f = scv.load_frames(tmp_db, "oclip_gop", [r])
        assert scv.frame_pattern_id(f[0]) == expected_id(r, 48, 64), \
            f"frame {r} wrong near open-GOP boundary"


def test_open_gop_engine_pipeline(tmp_db, oclip, tmp_path):
    from scanner_tpu import (CacheMode, Client, NamedStream,
                            NamedVideoStream, PerfParams)
    import scanner_tpu.kernels  # noqa: F401

    sc = Client(db_path=str(tmp_path / "odb"))
    try:
        movie = NamedVideoStream(sc, "omovie", path=oclip)
        frames = sc.io.Input([movie])
        rows = [6, 7, 8, 9, 22, 23, 24, 38, 39, 40]
        picked = sc.streams.Gather(frames, [rows])
        hist = sc.ops.Histogram(frame=picked)
        out = NamedStream(sc, "ohists")
        sc.run(sc.io.Output(hist, [out]), PerfParams.manual(4, 8),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        hists = list(out.load())
        assert len(hists) == len(rows)
        direct = scv.load_frames(sc._db, "omovie", rows)
        from scanner_tpu.kernels.imgproc import Histogram as HK
        for h, f in zip(hists, direct):
            expect = HK._histogram_np(f[None])[0]
            assert np.array_equal(np.stack(h), expect)
    finally:
        sc.stop()


# ---------------------------------------------------------------------------
# VFR (variable frame rate) streams: display order and identity are defined
# by pts alone; sample durations vary.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def vfr_clip(tmp_path_factory):
    p = str(tmp_path_factory.mktemp("vids") / "vfr.mp4")
    # irregular (but strictly increasing) timestamps: 1,2,4,7,8,11,...
    rng = np.random.RandomState(11)
    gaps = rng.randint(1, 5, size=60)
    pts = np.cumsum(gaps) - gaps[0]
    scv.synthesize_video(p, num_frames=60, width=64, height=48, fps=24,
                         keyint=10, frame_pts=pts.tolist())
    return p, pts


def test_vfr_index_and_durations(vfr_clip):
    p, pts = vfr_clip
    vd = scv.ingest_file(p, None)
    assert vd.num_frames == 60
    got = np.sort(np.asarray(vd.sample_pts))
    # container timescale may rescale pts; spacing RATIOS must survive
    gaps_in = np.diff(pts).astype(np.float64)
    gaps_out = np.diff(got).astype(np.float64)
    ratio = gaps_out / gaps_in
    assert np.allclose(ratio, ratio[0]), "VFR spacing lost in mux/ingest"
    assert not np.allclose(gaps_out, gaps_out[0]), "fixture is CFR"


def test_vfr_exact_decode(tmp_db, vfr_clip):
    p, _ = vfr_clip
    scv.ingest_videos(tmp_db, [("vfr", p)])
    frames = scv.load_frames(tmp_db, "vfr", list(range(60)))
    ids = [scv.frame_pattern_id(f) for f in frames]
    assert ids == [expected_id(r, 48, 64) for r in range(60)]
    # sparse gather across keyframes
    rows = [0, 9, 10, 11, 29, 30, 59, 30]
    frames = scv.load_frames(tmp_db, "vfr", rows)
    for got, r in zip(frames, rows):
        assert scv.frame_pattern_id(got) == expected_id(r, 48, 64)


def test_vfr_bframe_combined(tmp_db, tmp_path_factory):
    """VFR + B-frames + open GOP together — the worst real-world shape."""
    p = str(tmp_path_factory.mktemp("vids") / "vfrb.mp4")
    rng = np.random.RandomState(13)
    gaps = rng.randint(1, 4, size=40)
    pts = (np.cumsum(gaps) - gaps[0])
    scv.synthesize_video(p, num_frames=40, width=64, height=48, fps=24,
                         keyint=8, bframes=2, open_gop=True,
                         frame_pts=pts.tolist())
    scv.ingest_videos(tmp_db, [("vfrb", p)])
    frames = scv.load_frames(tmp_db, "vfrb", list(range(40)))
    ids = [scv.frame_pattern_id(f) for f in frames]
    assert ids == [expected_id(r, 48, 64) for r in range(40)]
    rows = [7, 8, 9, 15, 16, 17, 31, 32, 39]
    for r in rows:
        f = scv.load_frames(tmp_db, "vfrb", [r])
        assert scv.frame_pattern_id(f[0]) == expected_id(r, 48, 64)


def test_false_keyframe_retry_recovers(tmp_db, bclip):
    """A stream whose index wrongly marks a mid-GOP frame as a seek point
    (stale/foreign index, non-compliant container): the first decode
    attempt fails to deliver the wanted timestamp (the decoder drops
    frames with missing references), and the automata retries from the
    previous TRUE keyframe — delivering bit-exact frames."""
    from scanner_tpu.storage import metadata as md
    from scanner_tpu.video.automata import DecoderAutomata
    from scanner_tpu.video.ingest import load_video_meta

    scv.ingest_videos(tmp_db, [("bclip_fake", bclip)])
    vd = load_video_meta(tmp_db, "bclip_fake")
    idx0 = VideoIndex(vd)
    item = md.column_item_path(tmp_db.table_descriptor("bclip_fake").id,
                               "frame", 0)
    clean_auto = DecoderAutomata(tmp_db.backend, vd, item)
    clean = clean_auto.get_frames(list(range(48)))
    clean_auto.close()

    fake_dec = 11  # mid-GOP (true keyframes are multiples of 8)
    assert fake_dec not in set(np.asarray(vd.keyframe_indices).tolist())
    vd.keyframe_indices = np.sort(np.append(vd.keyframe_indices, fake_dec))
    auto = DecoderAutomata(tmp_db.backend, vd, item)
    try:
        orig = auto.decoder.decode_run_pts
        attempts = []

        def spy(*a, **k):
            r = orig(*a, **k)
            attempts.append(bool(r[3].all()))
            return r
        auto.decoder.decode_run_pts = spy
        row = int(idx0.disp_of_dec[fake_dec]) + 2
        f = auto.get_frames([row])
        assert attempts[0] is False and attempts[-1] is True, attempts
        assert np.array_equal(f[0], clean[row]), \
            "retry delivered non-exact frame"
    finally:
        auto.close()


def test_open_gop_boundary_bit_exact(tmp_db, oclip):
    """Frames at/after a non-IDR recovery point must reconstruct
    BIT-EXACTLY when decoded from that recovery point (H.264 recovery
    contract) — a stronger check than the pattern id, which tolerates
    concealment artifacts."""
    from scanner_tpu.storage import metadata as md
    from scanner_tpu.video.automata import DecoderAutomata
    from scanner_tpu.video.ingest import load_video_meta

    scv.ingest_videos(tmp_db, [("oclip_exact", oclip)])
    vd = load_video_meta(tmp_db, "oclip_exact")
    idx = VideoIndex(vd)
    item = md.column_item_path(tmp_db.table_descriptor("oclip_exact").id,
                               "frame", 0)
    auto = DecoderAutomata(tmp_db.backend, vd, item)
    try:
        clean = auto.get_frames(list(range(48)))
        for kf_dec in np.asarray(vd.keyframe_indices)[1:]:
            kf_disp = int(idx.disp_of_dec[kf_dec])
            for r in (kf_disp, kf_disp + 1, kf_disp + 2):
                if r < 48:
                    f = auto.get_frames([r])
                    assert np.array_equal(f[0], clean[r]), \
                        f"frame {r} (recovery point {kf_disp}) not exact"
    finally:
        auto.close()


def test_corrupt_packet_fails_gracefully(tmp_db, tmp_path_factory):
    """Bitstream corruption surfaces as ScannerException (reference
    software decoder: report, don't crash) — never a hang or a silently
    wrong frame.  The engine then fails the task; the cluster's 3-strike
    blacklist isolates the poison stream (test_distributed.py)."""
    from scanner_tpu.storage import metadata as md
    from scanner_tpu.video.ingest import load_video_meta

    p = str(tmp_path_factory.mktemp("vids") / "corrupt.mp4")
    scv.synthesize_video(p, num_frames=48, width=64, height=48, fps=24,
                         keyint=8, bframes=2)
    scv.ingest_videos(tmp_db, [("corrupt", p)])
    vd = load_video_meta(tmp_db, "corrupt")
    kf = int(vd.keyframe_indices[2])
    off, sz = int(vd.sample_offsets[kf]), int(vd.sample_sizes[kf])
    item = md.column_item_path(tmp_db.table_descriptor("corrupt").id,
                               "frame", 0)
    blob = bytearray(tmp_db.backend.read(item))
    blob[off:off + sz] = b"\x00" * sz
    tmp_db.backend.write(item, bytes(blob))

    idx = VideoIndex(vd)
    want = int(idx.disp_of_dec[kf]) + 2  # inside the corrupted GOP
    with pytest.raises(ScannerException):
        scv.load_frames(tmp_db, "corrupt", [want])
    # frames before the corrupted GOP still decode exactly
    f = scv.load_frames(tmp_db, "corrupt", [3])
    assert scv.frame_pattern_id(f[0]) == expected_id(3, 48, 64)


def test_iter_frames_streaming(tmp_db, clip, monkeypatch):
    """iter_frames yields request-order frames in chunks, reusing ONE
    decoder handle across chunks (the client-side streaming read, hwang
    `as_hwang` analogue)."""
    from scanner_tpu.video import automata as A_
    from scanner_tpu.video.ingest import iter_frames

    built = []
    orig_init = A_.DecoderAutomata.__init__

    def counting_init(self, *a, **k):
        built.append(1)
        orig_init(self, *a, **k)
    monkeypatch.setattr(A_.DecoderAutomata, "__init__", counting_init)

    scv.ingest_videos(tmp_db, [("iterclip", clip)])
    rows = [0, 5, 13, 12, 40, 60, 60, 89]
    got = list(iter_frames(tmp_db, "iterclip", rows, chunk=3))
    assert sum(built) == 1, "decoder handle not reused across chunks"
    assert len(got) == len(rows)
    for f, r in zip(got, rows):
        assert scv.frame_pattern_id(f) == expected_id(r, 96, 128), r
    assert (got[5] == got[6]).all()


@pytest.mark.parametrize("codec,kw", [
    ("libx265", {}),
    ("mpeg4", {}),
    # the hard shape on a second codec: reordered (pts != dts) B frames
    # with open-GOP recovery points — the pts-matched decode path must
    # hold beyond H.264
    ("libx265", {"bframes": 2, "open_gop": True}),
])
def test_non_h264_codec_ingest_and_exact_decode(tmp_path, codec, kw):
    """The ingest index is codec-agnostic (demuxer-provided sample index,
    not an H.264 NAL parser — a deliberate relaxation of the reference's
    h264_byte_stream_index_creator): HEVC and MPEG-4 part 2 streams
    ingest, record their codec, and deliver exact gathers through the
    same decode plans as H.264."""
    from scanner_tpu.storage import Database, PosixStorage
    from scanner_tpu.video.ingest import (encode_frames_mp4, frame_pattern,
                                          ingest_videos, load_video_meta,
                                          open_automata)

    path = str(tmp_path / "clip.mp4")
    N, W, H = 40, 96, 64
    frames = [frame_pattern(i, H, W) for i in range(N)]
    encode_frames_mp4(path, frames, W, H, keyint=8, codec=codec, **kw)
    db = Database(PosixStorage(str(tmp_path / "db")))
    ingest_videos(db, [("clip", path)])
    vd = load_video_meta(db, "clip", "frame")
    assert vd.num_frames == N
    assert vd.codec == {"libx265": "hevc", "mpeg4": "mpeg4"}[codec]
    assert len(vd.keyframe_indices) >= N // 8  # GOP structure indexed
    auto = open_automata(db, "clip")
    try:
        seq = auto.get_frames(list(range(N)))
        gather = auto.get_frames([3, 9, 17, 31])
        for j, i in enumerate([3, 9, 17, 31]):
            np.testing.assert_array_equal(gather[j], seq[i])
        err = np.mean([np.abs(seq[i].astype(int) -
                              frames[i].astype(int)).mean()
                       for i in range(N)])
        assert err < 5.0, f"decode drifted from source ({err:.1f})"
    finally:
        auto.close()


@pytest.mark.parametrize("seed", range(8))
def test_decode_fuzz_random_streams_and_gathers(tmp_db, tmp_path, seed):
    """Randomized decode-exactness fuzz: random stream shapes (GOP
    length x B-frame depth x open-GOP x VFR x codec) against random
    gather patterns (unsorted, with duplicates), every delivered frame
    checked against the source pixels (codec drift bound + pattern id)
    and gathers for identity with the sequential decode.  The
    fixed-combo tests pin known-hard shapes; this composes them randomly
    so GOP-boundary/reorder bugs at unlucky combinations have nowhere to
    hide."""
    from scanner_tpu.video.ingest import (encode_frames_mp4, frame_pattern,
                                          frame_pattern_id, ingest_videos,
                                          open_automata)

    rng = np.random.RandomState(100 + seed)
    n = int(rng.randint(20, 70))
    keyint = int(rng.choice([4, 8, 12, 25]))
    bframes = int(rng.choice([0, 1, 2, 3]))
    open_gop = bool(rng.randint(0, 2)) and bframes > 0
    codec = "libx265" if rng.randint(0, 2) else "libx264"
    frame_pts = None
    if rng.randint(0, 2):
        # VFR: strictly increasing, irregular gaps
        frame_pts = np.cumsum(rng.randint(1, 4, n)).tolist()

    W_, H_ = 96, 64
    frames = [frame_pattern(i, H_, W_) for i in range(n)]
    path = str(tmp_path / "fuzz.mp4")
    encode_frames_mp4(path, frames, W_, H_, keyint=keyint, crf=14,
                      bframes=bframes, open_gop=open_gop,
                      frame_pts=frame_pts, codec=codec)
    ingest_videos(tmp_db, [("fuzz", path)])
    auto = open_automata(tmp_db, "fuzz")
    try:
        seq = auto.get_frames(list(range(n)))
        for i in range(n):
            shape = (f"seed {seed} (keyint={keyint} b={bframes} "
                     f"og={open_gop} vfr={frame_pts is not None} {codec})")
            assert frame_pattern_id(seq[i]) == i % 14, (
                f"{shape}: sequential frame {i} has wrong content")
            # pixel-level drift bound vs the SOURCE frame: catches an
            # off-by-full-period misdelivery the mod-14 id cannot
            err = np.abs(seq[i].astype(int) - frames[i].astype(int)).mean()
            assert err < 8.0, (
                f"{shape}: frame {i} drifted {err:.1f} from source")
        for _ in range(4):
            rows = rng.randint(0, n, size=int(rng.randint(1, 9))).tolist()
            got = auto.get_frames(rows)
            for j, r in enumerate(rows):
                np.testing.assert_array_equal(
                    got[j], seq[r],
                    err_msg=(f"seed {seed} gather {rows} row {r} "
                             f"(keyint={keyint} b={bframes} og={open_gop} "
                             f"vfr={frame_pts is not None} {codec})"))
    finally:
        auto.close()
