"""End-to-end engine tests — the equivalent of the reference's
tests/py_test.py executable spec (sampling, spacing, slicing, state,
stencil, python kernels, compression, multiple outputs...).
"""

import os
import pickle
import struct
import tempfile
import time
from typing import Any, Sequence

import numpy as np
import pytest

import scanner_tpu
from scanner_tpu import (CacheMode, Client, DeviceType, FrameType, Kernel,
                         NamedStream, NamedVideoStream, NullElement,
                         PerfParams, ScannerException, SliceList,
                         register_op)
import scanner_tpu.kernels  # registers Histogram/Resize/Blur/OpticalFlow
from scanner_tpu import video as scv
from scanner_tpu.storage import MemoryStorage, items

N_FRAMES = 96
W, H = 128, 96


@pytest.fixture(scope="module")
def sc(tmp_path_factory):
    root = tmp_path_factory.mktemp("engine")
    vid1 = str(root / "v1.mp4")
    vid2 = str(root / "v2.mp4")
    scv.synthesize_video(vid1, num_frames=N_FRAMES, width=W, height=H,
                         fps=24, keyint=12)
    scv.synthesize_video(vid2, num_frames=48, width=W, height=H, fps=24,
                         keyint=12)
    client = Client(db_path=str(root / "db"))
    client.ingest_videos([("test1", vid1), ("test2", vid2)])
    client.ingest_videos([("test1_inplace", vid1)], inplace=True)
    yield client
    client.stop()


def expected_id(r):
    return scv.frame_pattern_id(scv.frame_pattern(r, H, W))


# ---------------------------------------------------------------------------


def test_table_properties(sc):
    t = sc.table("test1")
    assert t.name() == "test1"
    assert t.num_rows() == N_FRAMES
    assert t.column_names() == ["index", "frame"]


def test_load_video_column(sc):
    for name in ["test1", "test1_inplace"]:
        frame = next(NamedVideoStream(sc, name).load())
        assert frame.shape == (H, W, 3)


def test_gather_video_column(sc):
    rows = [0, 10, 50, 90]
    frames = list(NamedVideoStream(sc, "test1").load(rows=rows))
    assert len(frames) == 4
    for f, r in zip(frames, rows):
        assert scv.frame_pattern_id(f) == expected_id(r)


def test_new_table(sc):
    sc.new_table("test", ["col1", "col2"],
                 [[b"r00", b"r01"], [b"r10", b"r11"]], overwrite=True)
    t = sc.table("test")
    assert t.num_rows() == 2
    assert next(t.column("col2").load()) == b"r01"


def test_summarize(sc):
    sc.summarize()


def test_histogram_e2e(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    hist = sc.ops.Histogram(frame=frame)
    out = NamedStream(sc, "hist_out")
    sc.run(sc.io.Output(hist, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    hists = list(out.load())
    assert len(hists) == N_FRAMES
    h0 = hists[0]
    assert len(h0) == 3 and h0[0].shape == (16,)
    assert int(h0[0].sum()) == W * H  # every pixel lands in one bin
    # frame 0 has R == 0 everywhere -> all R pixels in bin 0
    assert h0[0][0] == W * H


def test_sample(sc):
    def run_sampler(build, expected):
        frame = sc.io.Input([NamedVideoStream(sc, "test1")])
        sampled = build(frame)
        out = NamedVideoStream(sc, "sample_out")
        sc.run(sc.io.Output(sampled, [out]), PerfParams.estimate(),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        assert out.len() == expected

    run_sampler(lambda f: sc.streams.Stride(f, [{"stride": 8}]),
                (N_FRAMES + 7) // 8)
    run_sampler(lambda f: sc.streams.Range(f, [(0, 30)]), 30)
    run_sampler(lambda f: sc.streams.StridedRange(f, [(0, 90, 10)]), 9)
    run_sampler(lambda f: sc.streams.Gather(f, [[0, 50, 77]]), 3)


def test_sample_content_exact(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Gather(frame, [[3, 40, 71]])
    out = NamedVideoStream(sc, "gather_out")
    sc.run(sc.io.Output(sampled, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    got = list(out.load())
    for f, r in zip(got, [3, 40, 71]):
        assert scv.frame_pattern_id(f) == expected_id(r)


def test_space(sc):
    spacing = 8
    # Repeat
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    hist = sc.ops.Histogram(frame=frame)
    spaced = sc.streams.Repeat(hist, [spacing])
    out = NamedStream(sc, "space_out")
    sc.run(sc.io.Output(spaced, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    rows = list(out.load())
    assert len(rows) == N_FRAMES * spacing
    for i, hist_v in enumerate(rows):
        ref = rows[(i // spacing) * spacing]
        assert len(hist_v) == 3
        for c in range(3):
            assert (ref[c] == hist_v[c]).all()

    # RepeatNull
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    hist = sc.ops.Histogram(frame=frame)
    spaced = sc.streams.RepeatNull(hist, [spacing])
    out = NamedStream(sc, "space_null_out")
    sc.run(sc.io.Output(spaced, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    rows = list(out.load())
    assert len(rows) == N_FRAMES * spacing
    for i, v in enumerate(rows):
        if i % spacing == 0:
            assert not isinstance(v, NullElement)
            assert v[0].shape[0] == 16
        else:
            assert isinstance(v, NullElement)


# batch-capable (huge decl, no per-op override at construction), so the
# engine's work_packet_size chunking decides the call granularity
@register_op(batch=1 << 30)
class TestBatchRecorder(Kernel):
    """Records the batch sizes it is called with (work_packet_size probe)."""
    seen: list = []

    def execute(self, frame: Sequence[FrameType]) -> Sequence[bytes]:
        TestBatchRecorder.seen.append(len(frame))
        return [b"x"] * len(frame)


def test_work_packet_size_sets_compute_batch(sc):
    """PerfParams.work_packet_size is the XLA batch dimension for
    batch-capable kernels without an explicit per-op batch override."""
    for wps in (4, 8):
        TestBatchRecorder.seen = []
        frame = sc.io.Input([NamedVideoStream(sc, "test1")])
        sampled = sc.streams.Range(frame, [(0, 16)])
        t = sc.ops.TestBatchRecorder(frame=sampled)
        out = NamedStream(sc, f"wps_out_{wps}")
        sc.run(sc.io.Output(t, [out]),
               PerfParams.manual(wps, 16,
                                 pipeline_instances_per_node=1),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        assert list(out.load()) == [b"x"] * 16
        assert TestBatchRecorder.seen and \
            max(TestBatchRecorder.seen) == wps, \
            f"wps={wps}: kernel saw batches {TestBatchRecorder.seen}"


def test_queue_size_per_pipeline_plumbed(sc, monkeypatch):
    """queue_size_per_pipeline reaches the pipeline's stage queues."""
    captured = {}
    orig = type(sc._executor).run_pipeline

    def spy(self, info, source, **kw):
        captured["queue_size"] = kw.get("queue_size")
        return orig(self, info, source, **kw)

    monkeypatch.setattr(type(sc._executor), "run_pipeline", spy)
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    h = sc.ops.Histogram(frame=frame)
    out = NamedStream(sc, "qsize_out")
    sc.run(sc.io.Output(h, [out]),
           PerfParams.manual(8, 16, queue_size_per_pipeline=2),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert captured["queue_size"] == 2


def test_load_sparsity_threshold_controls_read_mode(tmp_db):
    """load_sparsity_threshold picks ranged reads vs whole-item reads."""

    class CountingStorage(MemoryStorage):
        def __init__(self):
            super().__init__()
            self.range_reads = 0
            self.full_reads = 0
            self._in_range = False

        def read(self, path):
            # MemoryStorage.read_range delegates to read(); only count
            # direct whole-blob reads
            if not self._in_range:
                self.full_reads += 1
            return super().read(path)

        def read_range(self, path, offset, size):
            self.range_reads += 1
            self._in_range = True
            try:
                return super().read_range(path, offset, size)
            finally:
                self._in_range = False

    s = CountingStorage()
    rows = [b"r%03d" % i for i in range(100)]
    items.write_item(s, "t", rows)
    # sparse request, high threshold -> ranged reads only
    s.range_reads = s.full_reads = 0
    got = items.read_item_rows(s, "t", [3, 97], sparsity_threshold=8)
    assert got == [b"r003", b"r097"]
    assert s.full_reads == 0 and s.range_reads > 0
    # high threshold -> dense crossover (whole-item read)
    s.range_reads = s.full_reads = 0
    got = items.read_item_rows(s, "t", [3, 97], sparsity_threshold=100)
    assert got == [b"r003", b"r097"]
    assert s.full_reads >= 1


def test_intermediate_columns_freed(sc):
    """The evaluator drops a column once its last consumer ran: a 4-op
    chain never holds more than the live frontier (bounding per-task
    memory; reference streams work packets through stages instead)."""
    from scanner_tpu.engine.evaluate import TaskEvaluator
    peaks = []
    orig = TaskEvaluator.execute_task

    def spy(self, jr, plan, batches):
        r = orig(self, jr, plan, batches)
        peaks.append(self.last_peak_columns)
        return r

    TaskEvaluator.execute_task = spy
    try:
        frame = sc.io.Input([NamedVideoStream(sc, "test1")])
        ranged = sc.streams.Range(frame, [(0, 16)])
        a = sc.ops.Blur(frame=ranged, kernel_size=3, sigma=0.5)
        b = sc.ops.Blur(frame=a, kernel_size=3, sigma=0.5)
        h = sc.ops.Histogram(frame=b)
        out = NamedStream(sc, "freed_out")
        sc.run(sc.io.Output(h, [out]), PerfParams.estimate(),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        assert len(list(out.load())) == 16
    finally:
        TaskEvaluator.execute_task = orig
    # graph columns: input, range, blur, blur, hist = 5 producers; the
    # frontier never needs more than 2 live columns at once
    assert peaks and max(peaks) <= 2, peaks


def test_null_rows_through_kernel(sc):
    """Regression: interleaved null/live rows inside one batch chunk must
    survive kernel output assembly (null propagation through a batched
    kernel after RepeatNull)."""
    spacing = 2
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    ranged = sc.streams.Range(frame, [(0, 8)])
    spaced = sc.streams.RepeatNull(ranged, [spacing])
    t = sc.ops.TestPyBatch(frame=spaced, batch=50)
    out = NamedStream(sc, "null_through_kernel_out")
    sc.run(sc.io.Output(t, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    rows = list(out.load())
    assert len(rows) == 8 * spacing
    for i, v in enumerate(rows):
        if i % spacing == 0:
            assert v == b"point"
        else:
            assert isinstance(v, NullElement)


def test_stream_args(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    resized = sc.ops.Resize(frame=frame, width=[64], height=[48])
    sampled = sc.streams.Range(resized, [(0, 10)])
    out = NamedVideoStream(sc, "resize_out")
    sc.run(sc.io.Output(sampled, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    frames = list(out.load())
    assert len(frames) == 10
    assert frames[0].shape == (48, 64, 3)


def test_slice(sc):
    input = NamedVideoStream(sc, "test1")
    frame = sc.io.Input([input])
    sliced = sc.streams.Slice(frame, partitions=[sc.partitioner.all(24)])
    unsliced = sc.streams.Unslice(sliced)
    out = NamedStream(sc, "slice_out")
    sc.run(sc.io.Output(unsliced, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert out.len() == input.len()


def test_overlapping_slice(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sliced = sc.streams.Slice(frame, partitions=[
        sc.partitioner.strided_ranges([(0, 15), (5, 25), (15, 35)], 1)])
    sampled = sc.streams.Range(sliced, ranges=[SliceList([
        {"start": 0, "end": 10},
        {"start": 5, "end": 15},
        {"start": 5, "end": 15},
    ])])
    unsliced = sc.streams.Unslice(sampled)
    out = NamedVideoStream(sc, "overlap_out")
    sc.run(sc.io.Output(unsliced, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert out.len() == 30
    got = list(out.load())
    # group 0 local 0..10 = source 0..10; group 1 local 5..15 = source
    # 10..20; group 2 local 5..15 = source 20..30
    expect_rows = list(range(0, 10)) + list(range(10, 20)) + \
        list(range(20, 30))
    for f, r in zip(got, expect_rows):
        assert scv.frame_pattern_id(f) == expected_id(r)


@register_op()
class TestSliceArgs(Kernel):
    def new_stream(self, arg=None):
        self.arg = arg

    def execute(self, frame: FrameType) -> Any:
        return self.arg


def test_slice_args(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sliced = sc.streams.Slice(frame, [sc.partitioner.ranges(
        [[0, 1], [1, 2], [2, 3]])])
    test = sc.ops.TestSliceArgs(frame=sliced,
                                arg=[SliceList([i for i in range(3)])])
    unsliced = sc.streams.Unslice(test)
    out = NamedStream(sc, "slice_args_out")
    sc.run(sc.io.Output(unsliced, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert list(out.load()) == [0, 1, 2]


@register_op(bounded_state=3)
class TestIncrementBounded(Kernel):
    def __init__(self, config):
        super().__init__(config)
        self.reset()

    def reset(self):
        self.x = 0

    def execute(self, ignore: FrameType) -> bytes:
        v = self.x
        self.x += 1
        return struct.pack("=q", v)


def test_bounded_state(sc):
    warmup = 3
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    increment = sc.ops.TestIncrementBounded(ignore=frame)
    sampled = sc.streams.Gather(increment, indices=[[0, 10, 25, 26, 27]])
    out = NamedStream(sc, "bounded_out")
    sc.run(sc.io.Output(sampled, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    expected = [0, warmup, warmup, warmup + 1, warmup + 2]
    got = [struct.unpack("=q", b)[0] for b in out.load()]
    assert got == expected


@register_op(unbounded_state=True)
class TestIncrementUnbounded(Kernel):
    def __init__(self, config):
        super().__init__(config)
        self.reset()

    def reset(self):
        self.x = 0

    def execute(self, ignore: FrameType) -> bytes:
        v = self.x
        self.x += 1
        return struct.pack("=q", v)


def test_unbounded_state(sc):
    input = NamedVideoStream(sc, "test1")
    frame = sc.io.Input([input])
    sliced = sc.streams.Slice(frame, partitions=[sc.partitioner.all(24)])
    increment = sc.ops.TestIncrementUnbounded(ignore=sliced)
    unsliced = sc.streams.Unslice(increment)
    out = NamedStream(sc, "unbounded_out")
    sc.run(sc.io.Output(unsliced, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert out.len() == input.len()
    got = [struct.unpack("=q", b)[0] for b in out.load()]
    # state resets at each slice-group boundary
    assert got == [i % 24 for i in range(N_FRAMES)]


def test_stencil(sc):
    input = NamedVideoStream(sc, "test1")

    def flow_job(build, expected_len):
        frame = sc.io.Input([input])
        col = build(frame)
        out = NamedStream(sc, "stencil_out")
        sc.run(sc.io.Output(col, [out]),
               PerfParams.estimate(pipeline_instances_per_node=1),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        assert out.len() == expected_len
        return list(out.load())

    rows = flow_job(
        lambda f: sc.ops.OpticalFlow(
            frame=sc.streams.Range(f, [(0, 1)]), stencil=[-1, 0]), 1)
    assert rows[0].shape == (H, W, 2)
    flow_job(lambda f: sc.ops.OpticalFlow(
        frame=sc.streams.Range(f, [(0, 1)]), stencil=[0, 1]), 1)
    flow_job(lambda f: sc.ops.OpticalFlow(
        frame=sc.streams.Range(f, [(0, 2)]), stencil=[0, 1]), 2)
    flow_job(lambda f: sc.streams.Range(
        sc.ops.OpticalFlow(frame=f, stencil=[-1, 0]), [(0, 1)]), 1)


def test_wider_than_packet_stencil(sc):
    input = NamedVideoStream(sc, "test1")
    frame = sc.io.Input([input])
    sampled = sc.streams.Range(frame, [(0, 3)])
    flow = sc.ops.OpticalFlow(frame=sampled, stencil=[0, 1])
    out = NamedStream(sc, "stencil_out2")
    sc.run(sc.io.Output(flow, [out]),
           PerfParams.manual(1, 1, pipeline_instances_per_node=1),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert out.len() == 3


@register_op()
class TestPy(Kernel):
    def __init__(self, config, kernel_arg):
        super().__init__(config)
        assert kernel_arg == 1
        self.x, self.y = 20, 20

    def new_stream(self, x=None, y=None):
        if x is not None:
            self.x, self.y = x, y

    def execute(self, frame: FrameType) -> Any:
        return {"x": self.x, "y": self.y}


def test_python_kernel(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 3)])
    test_out = sc.ops.TestPy(frame=sampled, kernel_arg=1, x=[0], y=[0])
    out = NamedStream(sc, "py_out")
    sc.run(sc.io.Output(test_out, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert next(out.load()) == {"x": 0, "y": 0}


def test_bind_op_args(sc):
    input = NamedVideoStream(sc, "test1")
    frame = sc.io.Input([input, input])
    sampled = sc.streams.Range(frame, [(0, 1), (0, 1)])
    test_out = sc.ops.TestPy(frame=sampled, kernel_arg=1, x=[1, 10],
                             y=[5, 50])
    outs = [NamedStream(sc, "py_out_0"), NamedStream(sc, "py_out_1")]
    sc.run(sc.io.Output(test_out, outs), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    for i, (x, y) in enumerate([(1, 5), (10, 50)]):
        assert next(outs[i].load()) == {"x": x, "y": y}


_fetch_counter_path = [None]


@register_op()
class ResourceTest(Kernel):
    def __init__(self, config, path):
        super().__init__(config)
        self.path = path

    def fetch_resources(self):
        with open(self.path, "r") as f:
            n = int(f.read())
        with open(self.path, "w") as f:
            f.write(str(n + 1))

    def setup_with_resources(self):
        with open(self.path, "r") as f:
            assert int(f.read()) == 1

    def execute(self, frame: FrameType) -> Any:
        return None


def test_fetch_resources(sc):
    with tempfile.NamedTemporaryFile(mode="w", suffix=".cnt",
                                     delete=False) as f:
        f.write("0")
        path = f.name
    try:
        frame = sc.io.Input([NamedVideoStream(sc, "test1")])
        sampled = sc.streams.Range(frame, [(0, 3)])
        t = sc.ops.ResourceTest(frame=sampled, path=path)
        out = NamedStream(sc, "fetch_out")
        sc.run(sc.io.Output(t, [out]), PerfParams.estimate(),
               cache_mode=CacheMode.Overwrite, show_progress=False,
               pipeline_instances=2)
        with open(path) as f:
            assert f.read() == "1"
    finally:
        os.unlink(path)


@register_op(batch=50)
class TestPyBatch(Kernel):
    def execute(self, frame: Sequence[FrameType]) -> Sequence[bytes]:
        return [b"point" for _ in range(len(frame))]


def test_python_batch_kernel(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 30)])
    t = sc.ops.TestPyBatch(frame=sampled, batch=50)
    out = NamedStream(sc, "batch_out")
    sc.run(sc.io.Output(t, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    rows = list(out.load())
    assert len(rows) == 30 and rows[0] == b"point"


@register_op(stencil=[0, 1])
class TestPyStencil(Kernel):
    def execute(self, frame: Sequence[FrameType]) -> bytes:
        assert len(frame) == 2
        return b"point"


def test_python_stencil_kernel(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 30)])
    t = sc.ops.TestPyStencil(frame=sampled)
    out = NamedStream(sc, "stencil_py_out")
    sc.run(sc.io.Output(t, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert len(list(out.load())) == 30


@register_op(stencil=[0, 1], batch=50)
class TestPyStencilBatch(Kernel):
    def execute(self, frame: Sequence[Sequence[FrameType]]
                ) -> Sequence[bytes]:
        assert len(frame[0]) == 2
        return [b"point" for _ in range(len(frame))]


def test_python_stencil_batch_kernel(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 30)])
    t = sc.ops.TestPyStencilBatch(frame=sampled, batch=50)
    out = NamedStream(sc, "stencil_batch_out")
    sc.run(sc.io.Output(t, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert len(list(out.load())) == 30


@register_op()
class TestPyVariadic(Kernel):
    def execute(self, *frame: FrameType) -> FrameType:
        assert len(frame) == 3
        return frame[0]


def test_py_variadic(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 10)])
    t = sc.ops.TestPyVariadic(sampled, sampled, sampled)
    out = NamedVideoStream(sc, "variadic_out")
    sc.run(sc.io.Output(t.lossless(), [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert len(list(out.load())) == 10


def test_multiple_outputs(sc):
    def run_job(r1, r2):
        frame = sc.io.Input([NamedVideoStream(sc, "test1")])
        s1 = sc.streams.Range(frame, [r1])
        s2 = sc.streams.Range(frame, [r2])
        o1 = sc.io.Output(s1, [NamedVideoStream(sc, "mp_1")])
        o2 = sc.io.Output(s2, [NamedVideoStream(sc, "mp_2")])
        sc.run([o1, o2], PerfParams.estimate(),
               cache_mode=CacheMode.Overwrite, show_progress=False)

    with pytest.raises(ScannerException):
        run_job((0, 30), (0, 15))

    run_job((0, 30), (30, 60))
    assert sc.table("mp_1").num_rows() == 30
    assert sc.table("mp_2").num_rows() == 30
    got = list(NamedVideoStream(sc, "mp_2").load(rows=[0]))
    assert scv.frame_pattern_id(got[0]) == expected_id(30)


def test_lossless_and_compress(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 30)])
    blurred = sc.ops.Blur(frame=sampled, kernel_size=3, sigma=0.1)
    out = NamedVideoStream(sc, "blur_out")
    sc.run(sc.io.Output(blurred.lossless(), [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    next(out.load())

    out2 = NamedVideoStream(sc, "blur_out2")
    sc.run(sc.io.Output(blurred.compress("video", bitrate=1024 * 1024),
                        [out2]),
           PerfParams.estimate(), cache_mode=CacheMode.Overwrite,
           show_progress=False)
    next(out2.load())


def test_save_mp4(sc, tmp_path):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    sampled = sc.streams.Range(frame, [(0, 30)])
    blurred = sc.ops.Blur(frame=sampled, kernel_size=3, sigma=0.1)
    out = NamedVideoStream(sc, "save_mp4_out")
    sc.run(sc.io.Output(blurred, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    p = str(tmp_path / "out.mp4")
    out.save_mp4(p)
    vd = scv.ingest_file(p, None)
    assert vd.num_frames == 30


def test_cache_mode(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    hist = sc.ops.Histogram(frame=frame)
    out = NamedStream(sc, "cache_out")
    sc.run(sc.io.Output(hist, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    with pytest.raises(ScannerException):
        frame = sc.io.Input([NamedVideoStream(sc, "test1")])
        hist = sc.ops.Histogram(frame=frame)
        sc.run(sc.io.Output(hist, [out]), PerfParams.estimate(),
               show_progress=False)
    # Ignore: skipped silently
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    hist = sc.ops.Histogram(frame=frame)
    sc.run(sc.io.Output(hist, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Ignore, show_progress=False)


def test_profiler(sc):
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    hist = sc.ops.Histogram(frame=frame)
    ghist = sc.streams.Gather(hist, [[0]])
    out = NamedStream(sc, "prof_out")
    job_id = sc.run(sc.io.Output(ghist, [out]), PerfParams.estimate(),
                    cache_mode=CacheMode.Overwrite, show_progress=False)
    profile = sc.get_profile(job_id)
    with tempfile.NamedTemporaryFile(suffix=".trace", delete=False) as f:
        path = f.name
    try:
        profile.write_trace(path)
        import json
        with open(path) as fh:
            trace = json.load(fh)
        assert len(trace["traceEvents"]) > 0
        stats = profile.statistics()
        assert any(k.startswith("evaluate") for k in stats)
    finally:
        os.unlink(path)


def test_auto_ingest(sc, tmp_path):
    p = str(tmp_path / "auto.mp4")
    scv.synthesize_video(p, num_frames=24, width=64, height=48, fps=24)
    stream = NamedVideoStream(sc, "auto_ingested", path=p)
    frame = sc.io.Input([stream])
    hist = sc.ops.Histogram(frame=frame)
    out = NamedStream(sc, "auto_hist")
    sc.run(sc.io.Output(hist, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert out.len() == 24


def test_crop_resize_two_input_op(sc):
    """CropResize consumes a frame column AND a per-row box column
    (multi-input op through the batched data path); crops land where the
    boxes say."""
    from typing import Any

    @register_op(name="TestQuadBox")
    def TestQuadBox(config, ignore: FrameType) -> Any:
        return np.asarray([0.0, 0.0, 0.5, 0.5], np.float32)  # TL quadrant

    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    ranged = sc.streams.Range(frame, [(0, 6)])
    box = sc.ops.TestQuadBox(ignore=ranged)
    crops = sc.ops.CropResize(frame=ranged, box=box, size=32)
    out = NamedStream(sc, "crop_out")
    sc.run(sc.io.Output(crops, [out]), PerfParams.estimate(),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    rows = list(out.load())
    assert len(rows) == 6 and rows[0].shape == (32, 32, 3)
    # the crop equals a resize of the frame's top-left quadrant
    src = next(iter(NamedVideoStream(sc, "test1").load(rows=[0])))
    tl = src[:src.shape[0] // 2, :src.shape[1] // 2]
    import jax.numpy as jnp
    from scanner_tpu.kernels.imgproc import _resize_impl
    expect = np.asarray(_resize_impl(jnp.asarray(tl[None]), 32, 32))[0]
    err = np.abs(rows[0].astype(int) - expect.astype(int)).mean()
    assert err < 3.0, f"crop mismatch, mean abs err {err}"


@register_op(name="StressJitter")
class StressJitter(Kernel):
    """Row identity with randomized micro-sleeps: maximizes thread
    interleavings across loader/evaluator/saver stages."""

    def execute(self, frame: FrameType) -> Any:
        import random
        time.sleep(random.random() * 0.004)
        return np.asarray(frame)[..., 0].mean()


def test_pipeline_concurrency_stress(tmp_path):
    """TSAN-style stress for the Python pipeline (the reference has no
    sanitizer coverage either — SURVEY §5 flags this as a first-class
    improvement): many tiny tasks through a deep pipeline (4 loaders x 4
    evaluator instances x 3 savers, 1-row work packets, queue depth 2),
    repeated; every row must arrive exactly once with correct content."""
    root = str(tmp_path)
    vid = os.path.join(root, "v.mp4")
    n = 72
    scv.synthesize_video(vid, num_frames=n, width=64, height=48, fps=24,
                         keyint=6)
    client = Client(db_path=os.path.join(root, "db"),
                    num_load_workers=4, num_save_workers=3)
    try:
        client.ingest_videos([("s", vid)])
        expect = None
        for trial in range(3):
            frames = client.io.Input([NamedVideoStream(client, "s")])
            out = NamedStream(client, f"stress_{trial}")
            client.run(
                client.io.Output(client.ops.StressJitter(frame=frames),
                                 [out]),
                PerfParams.manual(1, 2, pipeline_instances_per_node=4,
                                  queue_size_per_pipeline=2),
                cache_mode=CacheMode.Overwrite, show_progress=False)
            rows = list(out.load())
            assert len(rows) == n
            if expect is None:
                expect = rows
            else:
                # deterministic results regardless of interleaving
                assert rows == expect
        # content sanity: frame 0 R-mean ~0, row ids recoverable
        assert expect[0] < 4.0
        from scanner_tpu.video.ingest import frame_pattern
        want = [float(frame_pattern(i, 48, 64)[..., 0].mean())
                for i in range(n)]
        assert all(abs(a - b) < 6.0 for a, b in zip(expect, want))
    finally:
        client.stop()


def test_prestage_device_bound_analysis():
    """The loader pre-stages a source column host->device only when every
    first non-builtin consumer is a device kernel (executor.py
    _column_device_bound): staging a host-kernel input would force a
    device->host round-trip instead of saving one."""
    from scanner_tpu.engine.executor import LocalExecutor
    from scanner_tpu.graph import analysis as A
    from scanner_tpu.graph import ops as O
    from scanner_tpu.graph.streams_dsl import IOGenerator, StreamsGenerator

    @register_op(name="_DevK", device=DeviceType.TPU, batch=4)
    class _DevK(Kernel):
        def execute(self, frame: FrameType) -> Any:  # pragma: no cover
            return frame

    @register_op(name="_HostK")
    class _HostK(Kernel):
        def execute(self, frame: FrameType) -> Any:  # pragma: no cover
            return frame

    io = IOGenerator()
    streams = StreamsGenerator()
    ops = O.OpGenerator()

    class FakeStream:
        is_video = False

        def __init__(self, n):
            self.n = n

    import threading
    ex = LocalExecutor.__new__(LocalExecutor)
    ex._device_bound_cache = {}
    ex._device_bound_lock = threading.Lock()

    def input_id(info):
        return next(n.id for n in info.ops if n.name == O.INPUT_OP)

    # device kernel behind a builtin sampler: stage
    frames = io.Input([FakeStream(16)])
    ranged = streams.Range(frames, [(0, 8)])
    info = A.analyze([io.Output(ops._DevK(frame=ranged), [FakeStream(8)])])
    assert ex._column_device_bound(info, input_id(info)) is True

    # host kernel: don't stage
    ex._device_bound_cache = {}
    frames = io.Input([FakeStream(16)])
    info = A.analyze([io.Output(ops._HostK(frame=frames), [FakeStream(16)])])
    assert ex._column_device_bound(info, input_id(info)) is False

    # mixed consumers (device + host see the same column): don't stage
    ex._device_bound_cache = {}
    frames = io.Input([FakeStream(16)])
    d = ops._DevK(frame=frames)
    h = ops._HostK(frame=frames)
    info = A.analyze([io.Output(d, [FakeStream(16)]),
                      io.Output(h, [FakeStream(16)])])
    assert ex._column_device_bound(info, input_id(info)) is False


def test_prestage_pipeline_e2e(tmp_path, monkeypatch):
    """Run the pipeline with device staging active (accel check faked on
    the CPU backend): LOADERS pre-stage source columns as jax arrays (the
    evaluator would also stage lazily, so the loader-side staging is
    spied on directly), the evaluator chains them, results match the
    host path."""
    from scanner_tpu.engine import evaluate as EV
    from scanner_tpu.engine.executor import LocalExecutor
    monkeypatch.setattr(EV, "_BACKEND", "fake_accel")

    # spy: count tasks whose source column left the loader already staged
    staged_tasks = []
    orig_prestage = LocalExecutor._prestage_device_columns

    def spy_prestage(self, info, w, elements=None):
        orig_prestage(self, info, w, elements=elements)
        from scanner_tpu.engine.batch import _is_jax
        cols = w.elements if elements is None else elements
        if all(_is_jax(b.data) for b in cols.values()):
            staged_tasks.append(w.task_idx)
    monkeypatch.setattr(LocalExecutor, "_prestage_device_columns",
                        spy_prestage)

    @register_op(name="_DevMean", device=DeviceType.TPU, batch=8)
    class _DevMean(Kernel):
        def execute(self, frame: Sequence[FrameType]) -> Sequence[Any]:
            import jax.numpy as jnp
            assert not isinstance(frame, np.ndarray)  # staged on device
            return jnp.mean(jnp.asarray(frame, jnp.float32), axis=(1, 2, 3))

    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=24, width=64, height=48, fps=24,
                         keyint=8)
    client = Client(db_path=str(tmp_path / "db"), num_load_workers=2)
    try:
        client.ingest_videos([("v", vid)])
        frames = client.io.Input([NamedVideoStream(client, "v")])
        out = NamedStream(client, "m")
        client.run(client.io.Output(client.ops._DevMean(frame=frames),
                                    [out]),
                   PerfParams.manual(8, 16),
                   cache_mode=CacheMode.Overwrite, show_progress=False)
        rows = list(out.load())
        assert len(rows) == 24
        from scanner_tpu.video.ingest import frame_pattern
        want = [float(frame_pattern(i, 48, 64).astype(np.float32).mean())
                for i in range(24)]
        got = [float(r) for r in rows]
        # H.264 is lossy: compare means with a tolerance
        assert all(abs(a - b) < 4.0 for a, b in zip(got, want))
        # every task (24 rows / 16-row io packets = 2) left the loader
        # with its source column already on device; with work-packet
        # streaming the staging happens per chunk, so task ids repeat
        assert set(staged_tasks) == {0, 1}, staged_tasks
        assert len(staged_tasks) >= 2
    finally:
        client.stop()


def test_estimate_aligns_io_packets_to_keyint(sc, tmp_path):
    """PerfParams.estimate snaps io packets to the stream's keyframe
    interval so task boundaries land on keyframes — a mid-GOP task start
    re-decodes up to keyint-1 frames of GOP prefix for nothing."""
    # bframes>0 disables scenecut, so GOPs are exactly keyint=12 (the
    # plain fixture clips get extra scenecut I-frames from x264)
    vid = str(tmp_path / "gop12.mp4")
    scv.synthesize_video(vid, num_frames=72, width=W, height=H, fps=24,
                         keyint=12, bframes=1)
    sc.ingest_videos([("est_gop12", vid)])
    vs = NamedVideoStream(sc, "est_gop12")
    assert vs.estimate_keyint() == 12
    frames = sc.io.Input([vs])
    hist = sc.ops.Histogram(frame=frames)
    out = NamedStream(sc, "est_keyint")
    p = PerfParams.estimate()
    sc.run(sc.io.Output(hist, [out]), p,
           cache_mode=CacheMode.Overwrite, show_progress=False)
    assert p.io_packet_size % 12 == 0, p.io_packet_size
    assert p.io_packet_size % p.work_packet_size == 0
    assert len(list(out.load())) == 72


def test_no_pipelining_env(sc, monkeypatch):
    """SCANNER_TPU_NO_PIPELINING=1 (reference worker.cpp NO_PIPELINING)
    serializes the pipeline onto one thread with identical results."""
    import numpy as np

    from scanner_tpu import CacheMode, NamedStream, NamedVideoStream, PerfParams

    def run(name):
        frames = sc.io.Input([NamedVideoStream(sc, "test1")])
        hists = sc.ops.Histogram(frame=frames)
        out = NamedStream(sc, name)
        sc.run(sc.io.Output(hists, [out]), PerfParams.manual(8, 16),
               cache_mode=CacheMode.Overwrite, show_progress=False)
        return [np.asarray(r) for r in out.load()]

    monkeypatch.delenv("SCANNER_TPU_NO_PIPELINING", raising=False)
    piped = run("np_piped")
    monkeypatch.setenv("SCANNER_TPU_NO_PIPELINING", "1")
    serial = run("np_serial")
    assert len(piped) == len(serial)
    for a, b in zip(piped, serial):
        np.testing.assert_array_equal(a, b)
