"""GCS backend unit tests against an in-memory fake of the
google-cloud-storage client surface GcsStorage uses (reference storehouse
GCSStorage, scanner/util/storehouse.h)."""

import threading

import pytest

from scanner_tpu.common import StorageException
from scanner_tpu.storage import GcsStorage, make_storage, parse_gs_url


class _ApiError(Exception):
    def __init__(self, code):
        super().__init__(f"http {code}")
        self.code = code


class FakeBlob:
    def __init__(self, store, lock, name):
        self._store, self._lock, self.name = store, lock, name
        self.chunk_size = None

    @property
    def size(self):
        with self._lock:
            if self.name not in self._store:
                return None
            return len(self._store[self.name])

    def upload_from_string(self, data, content_type=None,
                           if_generation_match=None):
        with self._lock:
            if if_generation_match == 0 and self.name in self._store:
                raise _ApiError(412)
            self._store[self.name] = bytes(data)

    def download_as_bytes(self, start=None, end=None):
        with self._lock:
            if self.name not in self._store:
                raise _ApiError(404)
            data = self._store[self.name]
        if start is None:
            return data
        if start >= len(data):
            raise _ApiError(416)
        return data[start:(end + 1) if end is not None else None]

    def exists(self):
        with self._lock:
            return self.name in self._store

    def delete(self):
        with self._lock:
            if self.name not in self._store:
                raise _ApiError(404)
            del self._store[self.name]


class FakeBucket:
    def __init__(self, store, lock, name):
        self._store, self._lock, self.name = store, lock, name

    def blob(self, key):
        return FakeBlob(self._store, self._lock, key)

    def get_blob(self, key):
        with self._lock:
            if key not in self._store:
                return None
        return FakeBlob(self._store, self._lock, key)


class FakeGcsClient:
    def __init__(self):
        self._store = {}
        self._lock = threading.Lock()

    def bucket(self, name):
        return FakeBucket(self._store, self._lock, name)

    def list_blobs(self, bucket, prefix=""):
        with self._lock:
            names = sorted(k for k in self._store if k.startswith(prefix))
        return [FakeBlob(self._store, self._lock, n) for n in names]


@pytest.fixture()
def gcs():
    return GcsStorage("bkt", "db", client=FakeGcsClient())


def test_parse_gs_url():
    assert parse_gs_url("gs://bkt/a/b/") == ("bkt", "a/b")
    assert parse_gs_url("gs://bkt") == ("bkt", "")
    with pytest.raises(StorageException):
        parse_gs_url("/local/path")
    with pytest.raises(StorageException):
        parse_gs_url("gs://")


def test_roundtrip_and_ranged_reads(gcs):
    gcs.write("a/b.bin", b"hello world")
    assert gcs.read("a/b.bin") == b"hello world"
    assert gcs.read_range("a/b.bin", 6, 5) == b"world"
    assert gcs.read_range("a/b.bin", 6, 100) == b"world"  # clipped at EOF
    assert gcs.read_range("a/b.bin", 100, 5) == b""       # past EOF
    assert gcs.exists("a/b.bin")
    assert gcs.size("a/b.bin") == 11
    with pytest.raises(StorageException):
        gcs.read("missing")
    with pytest.raises(StorageException):
        gcs.size("missing")


def test_write_exclusive_first_writer_wins(gcs):
    assert gcs.write_exclusive("m", b"video") is True
    assert gcs.write_exclusive("m", b"pickle") is False
    assert gcs.read("m") == b"video"


def test_delete_and_listing(gcs):
    for i in range(3):
        gcs.write(f"t/{i}.bin", bytes([i]))
    gcs.write("other.bin", b"x")
    assert gcs.list_prefix("t") == ["t/0.bin", "t/1.bin", "t/2.bin"]
    gcs.delete("t/1.bin")
    gcs.delete("t/1.bin")  # idempotent
    assert gcs.list_prefix("t") == ["t/0.bin", "t/2.bin"]
    gcs.delete_prefix("t")
    assert gcs.list_prefix("t") == []
    assert gcs.exists("other.bin")


def test_prefix_component_boundary(gcs):
    """Regression: deleting table 5's prefix must not touch table 52 —
    object stores have no directories, so a raw string prefix would."""
    gcs.write("tables/5/output_0.bin", b"five")
    gcs.write("tables/52/output_0.bin", b"fifty-two")
    assert gcs.list_prefix("tables/5") == ["tables/5/output_0.bin"]
    gcs.delete_prefix("tables/5")
    assert not gcs.exists("tables/5/output_0.bin")
    assert gcs.read("tables/52/output_0.bin") == b"fifty-two"


def test_memory_prefix_component_boundary():
    from scanner_tpu.storage import MemoryStorage
    s = MemoryStorage()
    s.write("tables/5/a", b"x")
    s.write("tables/52/a", b"y")
    s.delete_prefix("tables/5")
    assert not s.exists("tables/5/a") and s.exists("tables/52/a")
    assert s.list_prefix("tables/5") == []


def test_make_storage_gcs_requires_bucket():
    with pytest.raises(StorageException):
        make_storage("gcs", db_path="/local/path")


def test_prefix_isolation():
    client = FakeGcsClient()
    a = GcsStorage("bkt", "dbA", client=client)
    b = GcsStorage("bkt", "dbB", client=client)
    a.write("x", b"a")
    b.write("x", b"b")
    assert a.read("x") == b"a" and b.read("x") == b"b"
    assert a.list_prefix("") == ["x"]


def test_make_storage_gs_url():
    client = FakeGcsClient()
    s = make_storage("posix", db_path="gs://bkt/some/db", client=client)
    assert isinstance(s, GcsStorage)
    assert s.prefix == "some/db"
    s2 = make_storage("gcs", bucket="bkt", prefix="p", client=client)
    assert isinstance(s2, GcsStorage)


def test_database_on_gcs():
    """The whole metadata/item layer runs against the GCS interface."""
    import numpy as np
    from scanner_tpu.storage import ColumnDescriptor, ColumnType, Database

    db = Database(make_storage("gcs", bucket="bkt", prefix="db",
                               client=FakeGcsClient()))
    desc = db.create_table(
        "t", [ColumnDescriptor("output", ColumnType.BYTES, codec="raw")],
        end_rows=[3], job_id=-1)
    from scanner_tpu.storage import items
    items.write_item(db.backend, f"tables/{desc.id}/output_0.bin",
                     [b"r0", b"r1", b"r2"])
    db.commit_table(desc.id)
    assert list(db.load_column("t", "output")) == [b"r0", b"r1", b"r2"]
    # sparse path exercises read_range against the fake
    assert items.read_item_rows(
        db.backend, f"tables/{desc.id}/output_0.bin", [2],
        sparsity_threshold=1) == [b"r2"]
    db.write_megafile()
    db2 = Database(db.backend)
    db2.load_megafile()
    assert db2.table_descriptor("t").num_rows == 3


def test_engine_pipeline_on_gcs(tmp_path):
    """Full engine flow (ingest -> graph -> sink -> decode readback)
    against the GCS interface via a gs:// db path."""
    from scanner_tpu import (CacheMode, Client, NamedStream,
                             NamedVideoStream, PerfParams)
    import scanner_tpu.kernels  # noqa: F401
    from scanner_tpu import video as scv

    vid = str(tmp_path / "clip.mp4")
    scv.synthesize_video(vid, num_frames=16, width=64, height=48, fps=24)
    fake = FakeGcsClient()
    sc = Client(db_path="gs://bkt/dbs/one",
                storage_options={"client": fake})
    try:
        movie = NamedVideoStream(sc, "t", path=vid)
        out = NamedStream(sc, "hists")
        sc.run(sc.io.Output(sc.ops.Histogram(
            frame=sc.io.Input([movie])), [out]),
            PerfParams.estimate(), cache_mode=CacheMode.Overwrite,
            show_progress=False)
        hists = list(out.load())
        assert len(hists) == 16 and hists[0][0].sum() == 64 * 48
        assert any(k.startswith("dbs/one/") for k in fake._store)
        # fresh client over the same bucket: metadata + frames read back
        with Client(db_path="gs://bkt/dbs/one",
                    storage_options={"client": fake}) as sc2:
            frames = list(NamedVideoStream(sc2, "t").load(rows=[0, 15]))
            assert frames[0].shape == (48, 64, 3)
    finally:
        sc.stop()


# -- fault injection: transient errors + short reads ---------------------

class FlakyBlob:
    """Wraps a FakeBlob; raises a transient error on every other call and
    optionally truncates ranged downloads to at most `max_range` bytes."""

    def __init__(self, inner, state, code, max_range=None):
        self._inner, self._state = inner, state
        self._code, self._max_range = code, max_range
        self.name = inner.name

    @property
    def chunk_size(self):
        return self._inner.chunk_size

    @chunk_size.setter
    def chunk_size(self, v):
        self._inner.chunk_size = v

    @property
    def size(self):
        return self._inner.size

    def _maybe_fail(self):
        self._state["calls"] += 1
        if self._state["calls"] % 2 == 1:
            self._state["failures"] += 1
            raise _ApiError(self._code)

    def upload_from_string(self, *a, **kw):
        self._maybe_fail()
        return self._inner.upload_from_string(*a, **kw)

    def download_as_bytes(self, start=None, end=None):
        self._maybe_fail()
        if (self._max_range is not None and start is not None
                and end is not None and end - start + 1 > self._max_range):
            end = start + self._max_range - 1  # truncated transfer
        return self._inner.download_as_bytes(start=start, end=end)

    def exists(self):
        self._maybe_fail()
        return self._inner.exists()

    def delete(self):
        self._maybe_fail()
        return self._inner.delete()


class FlakyGcsClient:
    def __init__(self, code=503, max_range=None):
        self._inner = FakeGcsClient()
        self.state = {"calls": 0, "failures": 0}
        self._code, self._max_range = code, max_range

    def _wrap(self, blob):
        return FlakyBlob(blob, self.state, self._code, self._max_range)

    def bucket(self, name):
        outer, inner_bucket = self, self._inner.bucket(name)

        class _B:
            name = inner_bucket.name

            def blob(self, key):
                return outer._wrap(inner_bucket.blob(key))

            def get_blob(self, key):
                outer.state["calls"] += 1
                if outer.state["calls"] % 2 == 1:
                    outer.state["failures"] += 1
                    raise _ApiError(outer._code)
                b = inner_bucket.get_blob(key)
                return None if b is None else outer._wrap(b)

        return _B()

    def list_blobs(self, bucket, prefix=""):
        self.state["calls"] += 1
        if self.state["calls"] % 2 == 1:
            self.state["failures"] += 1
            raise _ApiError(self._code)
        return [self._wrap(b)
                for b in self._inner.list_blobs(bucket, prefix=prefix)]


def _fast_gcs(client):
    return GcsStorage("bkt", "db", client=client,
                      backoff_base=0.001, backoff_cap=0.002)


@pytest.mark.parametrize("code", [429, 500, 503])
def test_gcs_transient_errors_are_retried(code):
    """Every other API call fails with a retryable code; all operations
    still succeed (storehouse retry parity)."""
    client = FlakyGcsClient(code=code)
    gcs = _fast_gcs(client)
    gcs.write("a/b.bin", b"hello world")
    assert gcs.read("a/b.bin") == b"hello world"
    assert gcs.read_range("a/b.bin", 6, 5) == b"world"
    assert gcs.exists("a/b.bin")
    assert gcs.size("a/b.bin") == 11
    assert gcs.list_prefix("a") == ["a/b.bin"]
    gcs.delete("a/b.bin")
    assert not gcs.exists("a/b.bin")
    assert client.state["failures"] > 0


def test_gcs_nontransient_errors_not_retried():
    client = FlakyGcsClient(code=403)  # permission denied: surface once
    gcs = _fast_gcs(client)
    with pytest.raises(_ApiError):
        gcs.write("a", b"x")
    assert client.state["failures"] == 1


def test_gcs_short_ranged_reads_are_completed():
    """Truncated ranged transfers are re-issued until the full range (or
    EOF) arrives."""
    client = FlakyGcsClient(max_range=4)
    gcs = _fast_gcs(client)
    payload = bytes(range(64))
    gcs.write("blob", payload)
    assert gcs.read_range("blob", 8, 32) == payload[8:40]
    assert gcs.read_range("blob", 48, 100) == payload[48:]  # EOF clip


def test_gcs_retry_exhaustion_raises():
    class AlwaysDown(FakeGcsClient):
        def bucket(self, name):
            class _B:
                def blob(self, key):
                    class _Blob:
                        name = key
                        chunk_size = None

                        def download_as_bytes(self, **kw):
                            raise _ApiError(503)

                    return _Blob()

            return _B()

    gcs = GcsStorage("bkt", "db", client=AlwaysDown(), retries=2,
                     backoff_base=0.001, backoff_cap=0.002)
    with pytest.raises(_ApiError):
        gcs.read("x")
