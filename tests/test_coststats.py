"""Compute-efficiency observability (util/coststats.py + wiring).

Covers the analytical cost-model exactness for stock kernels, roofline
classification math against synthetic device peaks, the XLA compile
ledger (observation, ring bounds, persistent-cache hit/miss labels),
the GetCompileLedger RPC round-trip + scanner_top/statusz surfaces,
and the acceptance e2e: the golden pipeline's ladder warm-up produces
one ledger entry per (op, device, bucket) with nonzero compile seconds
on a virtual multi-device host.
"""

import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

from scanner_tpu import (CacheMode, Client, NamedStream, NamedVideoStream,
                         PerfParams)
import scanner_tpu.kernels  # noqa: F401  (registers the stdlib ops)
from scanner_tpu.common import DeviceType
from scanner_tpu.engine.evaluate import bucket_ladder
from scanner_tpu.graph.ops import KernelConfig, registry
from scanner_tpu.util import coststats as cs
from scanner_tpu.util import metrics as _mx

HERE = os.path.dirname(os.path.abspath(__file__))
RUNNER = os.path.join(HERE, "coststats_runner.py")


def _kernel(name, **kw):
    import scanner_tpu.kernels  # noqa: F401
    cfg = KernelConfig(device=DeviceType.CPU)
    return registry.get(name).kernel_factory(cfg, **kw)


# ---------------------------------------------------------------------------
# analytical-cost exactness (the cost-model contract)
# ---------------------------------------------------------------------------

def test_histogram_cost_exact():
    k = _kernel("Histogram")
    d = k.cost([(8, 48, 64, 3)])
    px = 8 * 48 * 64 * 3
    assert d.bytes_in == px                      # uint8 frames, read once
    assert d.bytes_out == 8 * 3 * 16 * 4         # (b, C, bins) int32
    assert d.flops == px * (16 + 2)              # bins compares+adds + bin
    assert d.source == "hook"
    # per-row list input (host path): no analytical model, fall back
    assert k.cost([5]) is None


def test_crop_resize_cost_exact():
    k = _kernel("CropResize", size=32)
    d = k.cost([(4, 48, 64, 3), 4])
    out_px = 4 * 32 * 32 * 3
    assert d.flops == out_px * 8                 # 4 bilinear taps mul+add
    assert d.bytes_in == 4 * 48 * 64 * 3 + 4 * 16
    assert d.bytes_out == out_px


def test_blur_and_histdiff_cost_exact():
    k = _kernel("Blur", kernel_size=3)
    d = k.cost([(2, 16, 16, 3)])
    px = 2 * 16 * 16 * 3
    assert d.flops == px * 4 * 3                 # 2 separable passes
    assert d.bytes_in == px and d.bytes_out == px

    hd = _kernel("HistDiff")
    d2 = hd.cost([(2, 2, 8, 8, 3)])
    win_px = 2 * 2 * 8 * 8 * 3
    assert d2.flops == win_px * (16 + 2) + 2 * 2 * 3 * 16
    assert d2.bytes_in == win_px
    assert d2.bytes_out == 2 * 8


def test_optical_flow_cost_scales_with_window():
    k = _kernel("OpticalFlow")
    d = k.cost([(2, 2, 16, 16, 3)])
    from scanner_tpu.kernels.imgproc import HS_ITERS
    px = 2 * 16 * 16
    assert d.flops == px * (2 * 5 + 6 + HS_ITERS * 48)
    assert d.bytes_in == 2 * 2 * 16 * 16 * 3
    assert d.bytes_out == px * 2 * 4


# ---------------------------------------------------------------------------
# roofline classification
# ---------------------------------------------------------------------------

def test_classify_compute_vs_memory_bound():
    # synthetic roofline: ridge point at 100 FLOPs/byte
    cs.set_device_peaks("unit:rx", 1e12, 1e10)
    hot = cs.classify("unit:rx", flops=1e9, bytes_total=1e6, seconds=0.01)
    assert hot["bound"] == "compute"
    assert hot["flops_per_s"] == pytest.approx(1e11)
    assert hot["eff"] == pytest.approx(0.1)
    cold = cs.classify("unit:rx", flops=1e6, bytes_total=1e6,
                       seconds=0.001)
    assert cold["bound"] == "memory"
    assert cold["eff"] == pytest.approx(1e9 / 1e10)
    # FLOPs unknown -> memory-bound by definition (bandwidth roofline)
    bw = cs.classify("unit:rx", flops=None, bytes_total=1e6, seconds=0.01)
    assert bw["bound"] == "memory"
    assert cs.classify("unit:rx", None, 0.0, 0.01) is None
    assert cs.classify("unit:rx", 1e6, 1e6, 0.0) is None


def test_record_op_call_updates_gauges_and_table():
    cs.set_device_peaks("unit:rg", 1e12, 1e10)
    desc = cs.CostDescriptor(flops=2e6, bytes_in=1e4, bytes_out=100)
    r = cs.record_op_call("UnitOp", "unit:rg", 8, 8, 0.001, desc)
    assert r is not None and r["bound"] == "compute"
    rows = [o for o in cs.op_efficiency()
            if o["op"] == "UnitOp" and o["device"] == "unit:rg"]
    assert len(rows) == 1
    row = rows[0]
    assert row["bucket"] == 8 and row["calls"] == 1
    assert row["bound"] == "compute"
    assert row["efficiency"] == pytest.approx(2e9 / 1e12)
    assert row["cost_source"] == "hook"
    snap = _mx.registry().snapshot()
    eff = {json.dumps(s["labels"], sort_keys=True): s["value"]
           for s in snap["scanner_tpu_op_efficiency_ratio"]["samples"]}
    key = json.dumps({"bucket": "8", "device": "unit:rg",
                      "op": "UnitOp"}, sort_keys=True)
    assert eff[key] == pytest.approx(2e9 / 1e12)
    bound = {json.dumps(s["labels"], sort_keys=True): s["value"]
             for s in snap["scanner_tpu_op_compute_bound"]["samples"]}
    assert bound[key] == 1.0
    # disabled path records nothing
    cs.set_enabled(False)
    try:
        assert cs.record_op_call("UnitOp", "unit:rg", 8, 8, 0.001,
                                 desc) is None
    finally:
        cs.set_enabled(True)


# ---------------------------------------------------------------------------
# the compile ledger
# ---------------------------------------------------------------------------

def test_observe_compiles_records_ledger_entry():
    import jax
    import jax.numpy as jnp
    seen0 = cs.ledger_summary()["entries_seen"]
    with cs.observe_compiles("LedgerOp", "unit:lg", 8, "sig-e2e"):
        f = jax.jit(lambda x: (x * 2.0 + 1.0).sum())
        f(jnp.ones((8, 23))).block_until_ready()   # unique shape
    entries = [e for e in cs.compile_ledger() if e["op"] == "LedgerOp"]
    assert entries, "no compile observed"
    e = entries[-1]
    assert e["device"] == "unit:lg" and e["bucket"] == 8
    assert e["signature"] == "sig-e2e"
    assert e["compile_s"] > 0
    assert e["cache"] in ("hit", "miss", "uncached")
    assert cs.ledger_summary()["entries_seen"] > seen0
    # metrics counted it
    snap = _mx.registry().snapshot()
    total = sum(s["value"]
                for s in snap["scanner_tpu_compile_total"]["samples"]
                if s["labels"].get("op") == "LedgerOp")
    assert total >= 1
    # the executable's analytical cost fed the derived-default path
    d = cs.descriptor_for(_kernel("Histogram"), "LedgerOp", "unit:lg",
                          8, [np.ones((8, 23), np.float32)])
    # Histogram's hook rejects this shape -> falls to derived/observed
    assert d is not None and d.source in ("derived", "observed")


def test_observed_fallback_descriptor_uses_arg_bytes():
    class NoHook:
        def cost(self, shapes):
            return None

    d = cs.descriptor_for(NoHook(), "NeverCompiled", "unit:nf", 4,
                          [np.zeros((4, 10), np.float32)])
    assert d.source == "observed"
    assert d.bytes_in == 4 * 10 * 4
    assert d.flops is None


def test_ledger_ring_bounds():
    cs.set_ring_size(4)
    try:
        for i in range(7):
            ctx = cs._CompileCtx("RingOp", "unit:rr", i, f"s{i}")
            ctx.compiles.append((0.01, "uncached"))
            cs._record_compiles(ctx)
        ring = [e for e in cs.compile_ledger() if e["op"] == "RingOp"]
        assert len(ring) <= 4
        assert ring[-1]["bucket"] == 6          # newest kept
        assert cs.ledger_summary()["entries"] <= 4
    finally:
        cs.set_ring_size(1024)


def test_persistent_cache_hit_miss_labels(tmp_path):
    """With jax's persistent compilation cache configured, the first
    compile of a program records `miss` and a structurally identical
    second compile records `hit` — the classification the acceptance
    criteria require on ledger entries."""
    import jax
    import jax.numpy as jnp
    try:
        from jax._src import compilation_cache as _jcc
        _jcc.reset_cache  # noqa: B018 — probe the API
    except (ImportError, AttributeError):
        pytest.skip("jax compilation_cache.reset_cache unavailable")

    old_dir = jax.config.jax_compilation_cache_dir
    old_t = jax.config.jax_persistent_cache_min_compile_time_secs
    old_s = jax.config.jax_persistent_cache_min_entry_size_bytes
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    # the cache-used decision latches on the first compile of the
    # process (earlier suites compiled with no cache dir): re-probe
    _jcc.reset_cache()
    try:
        def make():
            def cache_probe(x):
                return (x * 3.5 - 1.25).sum()
            return jax.jit(cache_probe)

        with cs.observe_compiles("CacheOp", "unit:cc", 1, "first"):
            make()(jnp.ones((31,))).block_until_ready()
        with cs.observe_compiles("CacheOp", "unit:cc", 1, "second"):
            make()(jnp.ones((31,))).block_until_ready()
    finally:
        jax.config.update("jax_compilation_cache_dir", old_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          old_t)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          old_s)
        _jcc.reset_cache()  # un-latch for the suites that follow
    entries = {e["signature"]: e for e in cs.compile_ledger()
               if e["op"] == "CacheOp"}
    assert entries["first"]["cache"] == "miss", entries
    assert entries["second"]["cache"] == "hit", entries
    rate = cs.ledger_summary()["cache_hit_rate"]
    assert rate is not None and 0.0 < rate <= 1.0


# ---------------------------------------------------------------------------
# engine wiring: local e2e + cluster RPC round-trip
# ---------------------------------------------------------------------------

N_FRAMES = 36  # wp=8, io=16: full chunks of 8 plus a 4-row tail task


def _synth(tmp_path, name, w=64, h=56):
    # unique geometry so the jit signatures are cold in this process
    # however many suites ran Histogram before us.  Widths stay
    # multiples of 16: the native decoder's tight-packed RGB output
    # overflows sws_scale's SIMD row writes on unaligned widths (a
    # pre-existing scvid issue, not an efficiency-plane one)
    from scanner_tpu import video as scv
    vid = str(tmp_path / f"{name}.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=w, height=h,
                         fps=24, keyint=8)
    return vid


def test_local_dispatch_ledger_and_efficiency(tmp_path, monkeypatch):
    """Local-mode golden pipeline with forced device staging: every
    dispatch-site compile lands in the ledger with a cache label, the
    roofline table classifies Histogram, and Client.compile_report()
    serves both under nodes["client"]."""
    monkeypatch.setenv("SCANNER_TPU_KERNEL_DEVICES", "all")
    vid = _synth(tmp_path, "local")
    sc = Client(db_path=str(tmp_path / "db"))
    sc.ingest_videos([("csv", vid)])
    frame = sc.io.Input([NamedVideoStream(sc, "csv")])
    out = NamedStream(sc, "cs_local")
    sc.run(sc.io.Output(sc.ops.Histogram(frame=frame), [out]),
           PerfParams.manual(8, 16), cache_mode=CacheMode.Overwrite,
           show_progress=False)
    rows = list(out.load())
    assert len(rows) == N_FRAMES

    entries = [e for e in cs.compile_ledger()
               if e["op"] == "Histogram" and "56, 64" in e["signature"]]
    assert entries, "dispatch-site compiles missing from the ledger"
    buckets = {e["bucket"] for e in entries}
    # steady-state chunks run at bucket 8; the 4-row tail at bucket 4
    assert buckets == {4, 8}, entries
    for e in entries:
        assert e["compile_s"] > 0
        assert e["cache"] in ("hit", "miss", "uncached")
        assert e["compiles"] >= 1

    eff = [o for o in cs.op_efficiency() if o["op"] == "Histogram"]
    assert eff, "no roofline rows for Histogram"
    for o in eff:
        assert o["bound"] in ("compute", "memory")
        assert o["efficiency"] > 0
        assert o["cost_source"] == "hook"

    rep = sc.compile_report()
    assert "client" in rep["nodes"]
    crep = rep["nodes"]["client"]
    assert crep["summary"]["compiles"] >= len(entries)
    assert any(o["op"] == "Histogram" for o in crep["op_efficiency"])
    sc.stop()


@pytest.fixture
def eff_cluster(tmp_path, monkeypatch):
    """Master (with /statusz) + 1 worker + client over an ingested
    video, device staging forced so the efficiency plane records."""
    monkeypatch.setenv("SCANNER_TPU_KERNEL_DEVICES", "all")
    from scanner_tpu.engine.service import Master, Worker

    db_path = str(tmp_path / "db")
    vid = _synth(tmp_path, "cluster", w=96, h=48)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("csc", vid)])
    master = Master(db_path=db_path, no_workers_timeout=10.0,
                    metrics_port=0)
    addr = f"localhost:{master.port}"
    worker = Worker(addr, db_path=db_path, pipeline_instances=2)
    sc = Client(db_path=db_path, master=addr)
    yield sc, master, worker, addr
    sc.stop()
    worker.stop()
    master.stop()


def test_cluster_compile_report_rpc_and_surfaces(eff_cluster):
    """GetCompileLedger RPC round-trip: master + worker nodes in
    Client.compile_report(), the /statusz Efficiency panel, and
    scanner_top --json carrying compile + ops keys."""
    sc, master, _worker, addr = eff_cluster
    frame = sc.io.Input([NamedVideoStream(sc, "csc")])
    out = NamedStream(sc, "cs_cluster")
    sc.run(sc.io.Output(sc.ops.Histogram(frame=frame), [out]),
           PerfParams.manual(8, 16), cache_mode=CacheMode.Overwrite,
           show_progress=False)
    assert len(list(out.load())) == N_FRAMES

    rep = sc.compile_report()
    nodes = rep["nodes"]
    assert "master" in nodes
    workers = [n for n in nodes if n.startswith("worker")]
    assert workers, nodes
    wrep = nodes[workers[0]]
    assert set(wrep) == {"ledger", "summary", "op_efficiency"}
    # the worker (same process here, as in the memstats cluster) saw
    # the Histogram compiles; the ledger labels every one
    assert any(e["op"] == "Histogram" for e in wrep["ledger"])
    assert all(e["cache"] in ("hit", "miss", "uncached")
               for e in wrep["ledger"])

    # /statusz Efficiency panel (master role)
    port = master.metrics_server.port
    st = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/statusz", timeout=10).read())
    assert "efficiency" in st
    assert st["efficiency"]["enabled"] is True
    assert "compile" in st["efficiency"]
    assert isinstance(st["efficiency"]["ops"], list)

    # scanner_top --json: compile + ops keys per node
    from scanner_tpu.util.jaxenv import cpu_only_env
    env = cpu_only_env()
    env["PYTHONPATH"] = os.path.dirname(HERE) + os.pathsep + \
        env.get("PYTHONPATH", "")
    tool = os.path.join(os.path.dirname(HERE), "tools", "scanner_top.py")
    r = subprocess.run(
        [sys.executable, tool, "--master", addr, "--json"],
        env=env, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stderr
    doc = json.loads(r.stdout)
    wn = doc["nodes"][workers[0]]
    assert "compile" in wn and "hit_rate" in wn["compile"]
    assert "ops" in wn
    if wn["ops"]:
        o = next(iter(wn["ops"].values()))
        assert {"bucket", "efficiency", "compute_bound",
                "flops_per_s", "bytes_per_s"} <= set(o)
    # the human table grew the efficiency section
    r2 = subprocess.run(
        [sys.executable, tool, "--master", addr, "--once"],
        env=env, capture_output=True, text=True, timeout=180)
    assert r2.returncode == 0, r2.stderr
    if wn["ops"]:
        assert "EFF%" in r2.stdout and "XCACHE" in r2.stdout

    # scanner_cost: the dedicated report CLI against the same master
    cost_tool = os.path.join(os.path.dirname(HERE), "tools",
                             "scanner_cost.py")
    r3 = subprocess.run(
        [sys.executable, cost_tool, "--master", addr, "--json"],
        env=env, capture_output=True, text=True, timeout=180)
    assert r3.returncode == 0, r3.stderr
    doc3 = json.loads(r3.stdout)
    assert "master" in doc3["nodes"]
    r4 = subprocess.run(
        [sys.executable, cost_tool, "--master", addr],
        env=env, capture_output=True, text=True, timeout=180)
    assert r4.returncode == 0, r4.stderr
    assert "compiles in" in r4.stdout


# ---------------------------------------------------------------------------
# bench_history: the per-direction baseline gate
# ---------------------------------------------------------------------------

def test_bench_history_baseline_gate(tmp_path):
    """bench_history --write-baselines banks the stable
    baseline_metrics keys; a later round that regresses a metric
    against its declared direction beyond the threshold exits 1."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_history_under_test",
        os.path.join(os.path.dirname(HERE), "tools", "bench_history.py"))
    bh = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bh)

    def write_round(p99, eff, hit):
        with open(tmp_path / "BENCH_r01.json", "w") as f:
            json.dump({"parsed": {"metric": "m", "value": 10.0}}, f)
        with open(tmp_path / "BENCH_DETAIL.json", "w") as f:
            json.dump([{"config": "baseline_metrics", "metrics": {
                "task_latency_p99_s": {"value": p99, "better": "lower"},
                "op_efficiency_mean": {"value": eff, "better": "higher"},
                "compile_cache_hit_rate": {"value": hit,
                                           "better": "higher"},
            }}], f)

    write_round(p99=2.0, eff=0.5, hit=0.9)
    assert bh.main(["--dir", str(tmp_path), "--write-baselines"]) == 0
    base = bh.load_baselines(str(tmp_path))
    assert base["task_latency_p99_s"]["value"] == 2.0
    # same numbers: clean
    assert bh.main(["--dir", str(tmp_path)]) == 0
    # latency p99 doubles (lower-is-better): gate trips
    write_round(p99=4.0, eff=0.5, hit=0.9)
    assert bh.main(["--dir", str(tmp_path)]) == 1
    # efficiency halves (higher-is-better): gate trips
    write_round(p99=2.0, eff=0.2, hit=0.9)
    assert bh.main(["--dir", str(tmp_path)]) == 1
    # a metric going unmeasured (None) must NOT page
    write_round(p99=2.0, eff=None, hit=None)
    assert bh.main(["--dir", str(tmp_path)]) == 0
    # improvements never page
    write_round(p99=1.0, eff=0.9, hit=1.0)
    assert bh.main(["--dir", str(tmp_path)]) == 0


# ---------------------------------------------------------------------------
# acceptance e2e: warm-up ladder ledger on a virtual multi-device host
# ---------------------------------------------------------------------------

def test_warmup_ladder_compile_ledger_per_device(tmp_path):
    """The golden pipeline's bucket-ladder warm-up on a 2-device
    virtual host produces one compile-ledger entry per (op, device,
    bucket) with nonzero compile seconds, and every observed compile
    carries a cache label — the acceptance criterion."""
    from scanner_tpu import video as scv
    from scanner_tpu.util.jaxenv import cpu_only_env

    vid = str(tmp_path / "warm.mp4")
    scv.synthesize_video(vid, num_frames=32, width=64, height=44,
                         fps=24, keyint=8)
    out = str(tmp_path / "cs.json")
    env = cpu_only_env(n_devices=2)
    env["PYTHONPATH"] = os.path.dirname(HERE) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env["SCANNER_TPU_KERNEL_DEVICES"] = "all"
    env["SCANNER_TPU_PRECOMPILE"] = "1"
    r = subprocess.run(
        [sys.executable, RUNNER, vid, out],
        env=env, cwd=HERE, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0 and "COSTSTATS_OK" in r.stdout, \
        f"runner failed (rc={r.returncode}):\n{r.stderr[-3000:]}"
    with open(out) as f:
        res = json.load(f)
    assert res["n_devices"] == 2
    assert res["n_rows"] == 32

    warm = [e for e in res["ledger"]
            if e["op"] == "Histogram"
            and str(e["signature"]).startswith("warmup:")]
    ladder = bucket_ladder(8)  # wp=8 in the runner
    want = {(f"cpu:{d}", b) for d in (0, 1) for b in ladder}
    got = {(e["device"], e["bucket"]) for e in warm}
    assert got == want, (got, want)
    for e in warm:
        assert e["compile_s"] > 0, e
        assert e["cache"] in ("hit", "miss", "uncached")
    # 100% of observed compiles are accounted: the summary's compile
    # count equals the per-entry sum, none dropped from the ring
    total = sum(e["compiles"] for e in res["ledger"])
    assert res["summary"]["compiles"] == total
    assert res["summary"]["entries_seen"] == len(res["ledger"])
    # the roofline table classified the op
    eff = [o for o in res["op_efficiency"] if o["op"] == "Histogram"]
    assert eff and all(o["bound"] in ("compute", "memory") for o in eff)
    # and the local-mode report carries the same plane
    assert "client" in res["report"]["nodes"]
