"""Live telemetry subsystem (util/metrics.py + endpoints + scanner-top).

Covers the registry primitives (concurrency, bucket edges, exposition
golden output), the series-name lint that keeps dashboards from drifting,
and the full serving path: /metrics + /healthz + /statusz against a live
in-process master, the master-aggregated Client.metrics() view, and the
scanner_top --once CLI.
"""

import json
import os
import re
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import pytest

from scanner_tpu.util.metrics import (DEFAULT_BUCKETS, MetricsError,
                                      MetricsRegistry, MetricsServer,
                                      merge_snapshots, registry,
                                      render_prometheus)

N_FRAMES = 24


# ---------------------------------------------------------------------------
# registry primitives
# ---------------------------------------------------------------------------

def test_counter_concurrency():
    """N threads hammering one counter (and one labeled child) lose no
    increments — the per-thread-cell fast path is race-free."""
    r = MetricsRegistry()
    c = r.counter("scanner_tpu_t_total", "t")
    lc = r.counter("scanner_tpu_tl_total", "t", labels=["k"])
    child = lc.labels(k="x")
    n_threads, per_thread = 8, 20000

    def hammer():
        for _ in range(per_thread):
            c.inc()
            child.inc(2)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c._default.value() == n_threads * per_thread
    assert child.value() == 2 * n_threads * per_thread


def test_histogram_bucket_edges():
    """Prometheus buckets are upper-INCLUSIVE: v == le lands in that
    bucket; above the last upper lands in +Inf."""
    r = MetricsRegistry()
    h = r.histogram("scanner_tpu_t_seconds", "t", buckets=[0.1, 1.0, 5.0])
    for v in (0.1, 1.0, 5.0):     # exactly on the edges
        h.observe(v)
    h.observe(0.0999)             # below first
    h.observe(5.0001)             # above last -> +Inf
    s = h._default.value()
    assert s["buckets"] == [2, 1, 1, 1]
    assert s["count"] == 5
    assert abs(s["sum"] - (0.1 + 1.0 + 5.0 + 0.0999 + 5.0001)) < 1e-9


def test_histogram_concurrency():
    r = MetricsRegistry()
    h = r.histogram("scanner_tpu_t_seconds", "t", buckets=[1.0])

    def hammer():
        for i in range(5000):
            h.observe(0.5 if i % 2 else 2.0)

    threads = [threading.Thread(target=hammer) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = h._default.value()
    assert s["count"] == 30000
    assert s["buckets"] == [15000, 15000]


def test_dead_thread_cells_fold_into_retained_total():
    """Cells of finished threads fold into a retained total at read
    time: a worker spawning fresh stage threads per run leaks neither
    memory nor scrape cost, and no increments are lost."""
    r = MetricsRegistry()
    c = r.counter("scanner_tpu_t_total", "t")
    h = r.histogram("scanner_tpu_t_seconds", "t", buckets=[1.0])
    for _ in range(20):
        t = threading.Thread(target=lambda: (c.inc(5), h.observe(0.5)))
        t.start()
        t.join()
    assert c._default.value() == 100
    assert h._default.value()["count"] == 20
    # dead cells were folded away, not accumulated
    assert len(c._default._cells) == 0
    assert len(h._default._cells) == 0
    c.inc()  # the live (this) thread still counts
    assert c._default.value() == 101


def test_gauge_clear_function_respects_new_owner():
    """A finished pipeline may only detach the queue-depth sampler it
    installed itself — not a newer owner's."""
    r = MetricsRegistry()
    g = r.gauge("scanner_tpu_t_depth", "t")
    mine, theirs = (lambda: 1), (lambda: 2)
    g.set_function(mine)
    g.set_function(theirs)          # a newer pipeline re-binds
    assert g.clear_function(mine) is False
    assert g._default.value() == 2  # still the new owner's sampler
    assert g.clear_function(theirs) is True
    assert g._default.value() == 0.0


def test_remove_labels_drops_child_series():
    """Departed label values (e.g. dead worker ids) can be pruned so a
    long-lived master's scrape output doesn't grow without bound."""
    r = MetricsRegistry()
    g = r.gauge("scanner_tpu_t_age", "t", labels=["worker"])
    g.labels(worker="0").set(1)
    g.labels(worker="1").set(2)
    g.remove_labels(worker="0")
    labels = [s["labels"] for s in
              r.snapshot()["scanner_tpu_t_age"]["samples"]]
    assert labels == [{"worker": "1"}]
    with pytest.raises(MetricsError):
        g.remove_labels(nope="0")


def test_gauge_set_function_and_fallback():
    r = MetricsRegistry()
    g = r.gauge("scanner_tpu_t_depth", "t")
    g.set(3)
    assert g._default.value() == 3
    g.set_function(lambda: 7)
    assert g._default.value() == 7
    g.set_function(lambda: 1 / 0)   # a scrape bug must not raise
    assert g._default.value() == 0.0
    g.set_function(None)
    assert g._default.value() == 3


def test_registry_idempotent_and_mismatch():
    r = MetricsRegistry()
    a = r.counter("scanner_tpu_t_total", "t")
    assert r.counter("scanner_tpu_t_total", "t") is a
    with pytest.raises(MetricsError):
        r.gauge("scanner_tpu_t_total", "t")          # kind mismatch
    with pytest.raises(MetricsError):
        r.counter("scanner_tpu_t_total", "t", labels=["x"])  # labels
    with pytest.raises(MetricsError):
        r.counter("Bad-Name", "t")                   # name pattern
    with pytest.raises(MetricsError):
        r.counter("scanner_tpu_nohelp_total", "  ")  # empty help


def test_prometheus_exposition_golden():
    """Exact text-exposition output: HELP/TYPE lines, label escaping,
    cumulative histogram buckets, _sum/_count."""
    r = MetricsRegistry()
    c = r.counter("scanner_tpu_g_total", "Counter help.", labels=["op"])
    c.labels(op='He said "hi"\n').inc(3)
    g = r.gauge("scanner_tpu_g_depth", "Gauge help.")
    g.set(2.5)
    h = r.histogram("scanner_tpu_g_seconds", "Hist help.",
                    buckets=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(2.0)
    assert render_prometheus(r.snapshot()) == (
        "# HELP scanner_tpu_g_depth Gauge help.\n"
        "# TYPE scanner_tpu_g_depth gauge\n"
        "scanner_tpu_g_depth 2.5\n"
        "# HELP scanner_tpu_g_seconds Hist help.\n"
        "# TYPE scanner_tpu_g_seconds histogram\n"
        'scanner_tpu_g_seconds_bucket{le="0.1"} 1\n'
        'scanner_tpu_g_seconds_bucket{le="1"} 2\n'
        'scanner_tpu_g_seconds_bucket{le="+Inf"} 3\n'
        "scanner_tpu_g_seconds_sum 2.55\n"
        "scanner_tpu_g_seconds_count 3\n"
        "# HELP scanner_tpu_g_total Counter help.\n"
        "# TYPE scanner_tpu_g_total counter\n"
        'scanner_tpu_g_total{op="He said \\"hi\\"\\n"} 3\n')


def test_merge_snapshots_adds_node_labels():
    r1, r2 = MetricsRegistry(), MetricsRegistry()
    r1.counter("scanner_tpu_t_total", "t").inc(1)
    r2.counter("scanner_tpu_t_total", "t").inc(5)
    merged = merge_snapshots({"master": r1.snapshot(),
                              "worker0": r2.snapshot()})
    samples = merged["scanner_tpu_t_total"]["samples"]
    by_node = {s["labels"]["node"]: s["value"] for s in samples}
    assert by_node == {"master": 1, "worker0": 5}


# ---------------------------------------------------------------------------
# series-name lint: dashboards break silently on metric-name drift
# ---------------------------------------------------------------------------

def test_registered_series_names_lint():
    """The naming/help/catalog contract now lives in scanner-check's
    contract pass (SC301/SC302, scanner_tpu/analysis/static/) — one
    source of truth, also enforced by the tier-1 gate in
    tests/test_static_analysis.py.  This thin wrapper runs just those
    codes over the package, then keeps the RUNTIME half the static pass
    cannot see: that the series dashboards depend on really register at
    import."""
    from scanner_tpu.analysis.static import run_analysis

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = run_analysis([os.path.join(repo, "scanner_tpu")],
                            root=repo, select=["SC301", "SC302"])
    assert not findings, "metric contract violations:\n" + "\n".join(
        f.format() for f in findings)

    # pull in every instrumented module so their module-level metrics
    # are registered
    import scanner_tpu.engine.batch       # noqa: F401
    import scanner_tpu.engine.evaluate    # noqa: F401
    import scanner_tpu.engine.executor    # noqa: F401
    import scanner_tpu.engine.rpc         # noqa: F401
    import scanner_tpu.engine.service     # noqa: F401
    import scanner_tpu.storage.gcs        # noqa: F401
    import scanner_tpu.storage.items      # noqa: F401
    import scanner_tpu.util.faults        # noqa: F401
    import scanner_tpu.util.profiler      # noqa: F401
    import scanner_tpu.util.retry         # noqa: F401

    metrics = registry().metrics()
    assert len(metrics) >= 20, [m.name for m in metrics]
    # the shape-stability series (docs/observability.md catalog) must
    # exist: padding waste and ladder-precompile time ride alongside the
    # recompile proxy
    names = {m.name for m in metrics}
    assert {"scanner_tpu_op_recompiles_total",
            "scanner_tpu_op_pad_rows_total",
            "scanner_tpu_op_precompile_seconds"} <= names
    # the robustness series (docs/robustness.md): chaos-fire evidence,
    # crc-detected corruption, strike-free transient requeues, drains
    assert {"scanner_tpu_faults_injected_total",
            "scanner_tpu_item_corruptions_total",
            "scanner_tpu_transient_retries_total",
            "scanner_tpu_worker_drains_total"} <= names


# ---------------------------------------------------------------------------
# endpoints against a live in-process cluster
# ---------------------------------------------------------------------------

@pytest.fixture()
def metrics_cluster(tmp_path):
    """Master (with /metrics enabled) + 1 worker + client, plus an
    ingested test video."""
    from scanner_tpu import Client
    from scanner_tpu import video as scv
    from scanner_tpu.engine.service import Master, Worker

    db_path = str(tmp_path / "db")
    vid = str(tmp_path / "v.mp4")
    scv.synthesize_video(vid, num_frames=N_FRAMES, width=64, height=48,
                         fps=24, keyint=12)
    seed = Client(db_path=db_path)
    seed.ingest_videos([("test1", vid)])
    master = Master(db_path=db_path, no_workers_timeout=10.0,
                    metrics_port=0)
    addr = f"localhost:{master.port}"
    worker = Worker(addr, db_path=db_path)
    sc = Client(db_path=db_path, master=addr)
    yield sc, master, worker, addr
    sc.stop()
    worker.stop()
    master.stop()


def _run_histogram(sc, out_name: str) -> None:
    from scanner_tpu import CacheMode, NamedStream, NamedVideoStream, \
        PerfParams
    import scanner_tpu.kernels  # noqa: F401  (registers Histogram)
    frame = sc.io.Input([NamedVideoStream(sc, "test1")])
    h = sc.ops.Histogram(frame=frame)
    out = NamedStream(sc, out_name)
    sc.run(sc.io.Output(h, [out]), PerfParams.manual(4, 8),
           cache_mode=CacheMode.Overwrite, show_progress=False)


def test_metrics_endpoint_end_to_end(metrics_cluster):
    """After a bulk job: GET /metrics returns valid Prometheus text with
    >= 20 distinct scanner_tpu_* series, /healthz and /statusz answer,
    and Client.metrics() returns the master-aggregated cluster view
    including a worker's series."""
    sc, master, worker, _addr = metrics_cluster
    _run_histogram(sc, "mx_out")

    port = master.metrics_server.port
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
    # sample lines only (skip # HELP/# TYPE); a series = name+labels
    series = {line.split(" ")[0] for line in text.splitlines()
              if line.startswith("scanner_tpu_")}
    assert len(series) >= 20, sorted(series)
    families = {s.split("{")[0] for s in series}
    # the headline catalog is present
    for fam in ("scanner_tpu_stage_queue_depth",
                "scanner_tpu_stage_seconds_total",
                "scanner_tpu_decoded_frames_total",
                "scanner_tpu_h2d_bytes_total",
                "scanner_tpu_master_workers_active",
                "scanner_tpu_master_tasks_completed_total",
                "scanner_tpu_rpc_latency_seconds_bucket",
                "scanner_tpu_op_rows_total"):
        assert fam in families, f"{fam} missing from /metrics"

    hz = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=10).read())
    assert hz["ok"] is True and hz["role"] == "master"

    st = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/statusz", timeout=10).read())
    assert st["role"] == "master"
    assert st["bulk"]["tasks_done"] == st["bulk"]["total_tasks"]
    assert set(st["bulk"]["stage_fps"]) == {"load", "evaluate", "save"}
    assert any(w["active"] for w in st["workers"])

    # 404 path
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/nope", timeout=10)

    # cluster-wide merged view over the GetMetrics RPC
    snap = sc.metrics()
    nodes = {s["labels"].get("node")
             for e in snap.values() for s in e["samples"]}
    assert "master" in nodes
    assert any(n and n.startswith("worker") for n in nodes), nodes
    assert "scanner_tpu_decoded_frames_total" in snap
    # the merged view renders as valid exposition too
    assert "scanner_tpu_master_workers_active" in render_prometheus(snap)


def test_metrics_server_off_by_default(tmp_path):
    """No metrics_port -> no listener anywhere (the acceptance default:
    telemetry serving must be strictly opt-in)."""
    from scanner_tpu import Client
    from scanner_tpu.engine.service import Master, Worker

    master = Master(db_path=str(tmp_path / "db"), no_workers_timeout=5.0)
    worker = Worker(f"localhost:{master.port}",
                    db_path=str(tmp_path / "db"))
    sc = Client(db_path=str(tmp_path / "db"))
    try:
        assert master.metrics_server is None
        assert worker.metrics_server is None
        assert sc._metrics_server is None
    finally:
        sc.stop()
        worker.stop()
        master.stop()


def test_client_local_metrics_and_endpoint(tmp_path):
    """Local (in-process) mode: Client(metrics_port=0) serves its own
    registry and Client.metrics() returns the node-labeled snapshot."""
    from scanner_tpu import Client

    sc = Client(db_path=str(tmp_path / "db"), metrics_port=0)
    try:
        port = sc._metrics_server.port
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "scanner_tpu_process_start_time_seconds" in text
        snap = sc.metrics()
        nodes = {s["labels"].get("node")
                 for e in snap.values() for s in e["samples"]}
        assert nodes == {"client"}
    finally:
        sc.stop()


def test_scanner_top_once_smoke(metrics_cluster):
    """scanner_top --once against a live master: exits 0 and renders the
    job line + per-node table."""
    sc, _master, _worker, addr = metrics_cluster
    _run_histogram(sc, "top_out")

    from scanner_tpu.util.jaxenv import cpu_only_env
    env = cpu_only_env()
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "scanner_top.py")
    r = subprocess.run(
        [sys.executable, tool, "--master", addr, "--once"],
        env=env, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stderr
    assert "NODE" in r.stdout
    assert "bulk:" in r.stdout
    assert re.search(r"worker\d", r.stdout), r.stdout

    # unreachable master -> exit code 2, not a hang or traceback
    r2 = subprocess.run(
        [sys.executable, tool, "--master", "localhost:1", "--once"],
        env=env, capture_output=True, text=True, timeout=180)
    assert r2.returncode == 2


def test_profiler_counters_mirror_into_metrics():
    """Profiler.count events appear in the live registry under
    scanner_tpu_profiler_events_total{event=...} — traces and live
    metrics cannot disagree on counts."""
    from scanner_tpu.util.profiler import Profiler

    before = _profiler_event_value("mirror_probe")
    p = Profiler()
    p.count("mirror_probe", 3)
    assert _profiler_event_value("mirror_probe") == before + 3
    assert p.counters["mirror_probe"] == 3


def _profiler_event_value(event: str) -> float:
    snap = registry().snapshot()
    entry = snap.get("scanner_tpu_profiler_events_total", {"samples": []})
    return sum(s["value"] for s in entry["samples"]
               if s["labels"].get("event") == event)


def test_retry_metrics_and_giveup_warning(caplog):
    """util/retry.py routes attempts through the registry and logs the
    final give-up at WARNING with the accumulated wait."""
    import logging

    from scanner_tpu.util.retry import call_with_backoff

    def site_value():
        snap = registry().snapshot()
        entry = snap.get("scanner_tpu_retry_attempts_total",
                         {"samples": []})
        return sum(s["value"] for s in entry["samples"]
                   if s["labels"].get("site") == "unit_test")

    before = site_value()
    sleeps = []
    with caplog.at_level(logging.WARNING, logger="scanner_tpu"):
        with pytest.raises(ConnectionError):
            call_with_backoff(
                _always_fail, is_transient=lambda e: True, retries=3,
                base=0.001, cap=0.002, sleep=sleeps.append,
                label="unit_test")
    assert site_value() == before + 3
    assert len(sleeps) == 3
    assert "giving up" in caplog.text
    assert "unit_test" in caplog.text
    assert "accumulated" in caplog.text

    # retries=0 callers (e.g. wait_for_server poll loops) stay quiet
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="scanner_tpu"):
        with pytest.raises(ConnectionError):
            call_with_backoff(_always_fail, is_transient=lambda e: True,
                              retries=0, label="unit_test")
    assert "giving up" not in caplog.text


def _always_fail():
    raise ConnectionError("nope")
