"""Health & SLO engine suite (scanner_tpu/util/health.py).

Three layers:
  * units — the histogram-quantile estimator, the [alerts] rule clause
    grammar, and every rule form (threshold, rate, quantile,
    multi-window burn, ratio, composite backpressure) driven over a
    private registry with synthetic clocks, so firing/hold-down/resolve
    transitions are deterministic;
  * the serving surface — /healthz roll-up shape + status codes,
    /readyz drain behavior, /alertz;
  * chaos-style e2e (the acceptance test) — an injected pipeline.save
    delay on an in-process cluster fires `stage_backpressure` (visible
    via Client.health(), /alertz and the transitions counter) and
    resolves, while the identical fault-free run stays `ok` with zero
    alerts; heartbeat loss degrades the master's /healthz.
"""

import json
import os
import struct
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import cloudpickle
import pytest

from scanner_tpu import (CacheMode, Client, Kernel, NamedStream,
                         PerfParams, register_op)
from scanner_tpu.engine.service import Master, Worker
from scanner_tpu.util import faults
from scanner_tpu.util import health
from scanner_tpu.util import metrics as _mx
from scanner_tpu.util.metrics import (MetricsRegistry, MetricsServer,
                                      histogram_quantile,
                                      snapshot_histogram_quantiles)

# test kernels travel to worker subprocesses inside the job spec
cloudpickle.register_pickle_by_value(sys.modules[__name__])

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_ROWS = 48


def _pk(v: int) -> bytes:
    return struct.pack("<q", v)


@register_op(name="HealthDouble")
class HealthDouble(Kernel):
    def execute(self, x: bytes) -> bytes:
        return _pk(2 * struct.unpack("<q", x)[0])


EXPECT = [_pk(2 * (100 + i)) for i in range(N_ROWS)]


def _counter(name: str, **labels) -> float:
    entry = _mx.registry().snapshot().get(name, {})
    for s in entry.get("samples", []):
        if s["labels"] == labels:
            return s["value"]
    return 0.0


def _get_json(url: str):
    """(status_code, parsed body) — a 503 is an answer, not an error."""
    try:
        r = urllib.request.urlopen(url, timeout=10)
        return r.getcode(), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# histogram quantile estimation (util/metrics.py — shared helper)
# ---------------------------------------------------------------------------

def test_histogram_quantile_interpolates_within_bucket():
    # 10 observations all inside (1, 2]: p50 lands mid-bucket
    assert histogram_quantile([1, 2, 4], [0, 10, 0, 0], 0.5) == 1.5
    # spread across buckets: p75 of 4+4 obs -> inside the second bucket
    v = histogram_quantile([1, 2], [4, 4, 0], 0.75)
    assert 1.0 < v <= 2.0
    assert v == pytest.approx(1.5)


def test_histogram_quantile_edge_buckets():
    # everything in the FIRST bucket: interpolates from edge 0
    assert histogram_quantile([2, 4], [8, 0, 0], 0.5) == \
        pytest.approx(1.0)
    # everything in the +Inf bucket clamps to the top finite bound
    assert histogram_quantile([1, 2, 4], [0, 0, 0, 5], 0.99) == 4.0
    # q=1.0 stays within the last occupied bucket
    assert histogram_quantile([1, 2], [0, 6, 0], 1.0) == 2.0


def test_histogram_quantile_empty_histogram():
    assert histogram_quantile([1, 2], [0, 0, 0], 0.5) is None
    assert histogram_quantile([], [], 0.5) is None


def test_snapshot_histogram_quantiles_shapes():
    reg = MetricsRegistry()
    h = reg.histogram("scanner_tpu_t_lat_seconds", "x", buckets=(1, 5))
    assert snapshot_histogram_quantiles(reg.snapshot(),
                                        "scanner_tpu_t_lat_seconds") == {}
    assert snapshot_histogram_quantiles(reg.snapshot(), "nosuch") == {}
    for v in (0.2, 0.4, 0.6, 2.0):
        h.observe(v)
    out = snapshot_histogram_quantiles(reg.snapshot(),
                                       "scanner_tpu_t_lat_seconds",
                                       qs=(0.5, 0.99))
    assert out["count"] == 4
    assert out["mean_s"] == pytest.approx(0.8)
    assert 0 < out["p50_s"] <= 1.0
    assert 1.0 < out["p99_s"] <= 5.0


# ---------------------------------------------------------------------------
# rule grammar
# ---------------------------------------------------------------------------

def test_parse_rules_grammar():
    rules = health.parse_rules(
        "evalq:value(scanner_tpu_stage_queue_depth{stage=evaluate})>=8"
        ":for=5:severity=critical;"
        "slow_rpc:p99(scanner_tpu_rpc_latency_seconds)>0.5:window=120;"
        "hbm:value(scanner_tpu_device_hbm_bytes_in_use"
        "/scanner_tpu_device_hbm_limit_bytes)>0.9:by=device;"
        "req_slo:burn(scanner_tpu_task_latency_seconds)>2"
        ":objective=5:budget=0.01:short=30:window=300")
    assert [r.name for r in rules] == ["evalq", "slow_rpc", "hbm",
                                      "req_slo"]
    assert rules[0].match == {"stage": "evaluate"}
    assert rules[0].for_seconds == 5 and rules[0].severity == "critical"
    assert rules[1].form == "p99" and rules[1].window == 120
    assert rules[2].ratio_to == "scanner_tpu_device_hbm_limit_bytes"
    assert rules[2].by == ("device",)
    assert rules[3].objective == 5 and rules[3].budget == 0.01
    assert rules[3].short_window == 30 and rules[3].window == 300
    assert health.parse_rules("") == []
    for bad in (
            "noexpr",                                      # no clause
            "r:exp!ode(scanner_tpu_x)>1",                  # bad form
            "r:value(not_a_series)>1",                     # bad series
            "r:value(scanner_tpu_x)>1:zz=3",               # unknown opt
            "r:value(scanner_tpu_x)>1:severity=panic",     # bad severity
            "r:value(scanner_tpu_x)>1:window=soon",        # bad number
            "BAD NAME:value(scanner_tpu_x)>1"):            # bad name
        with pytest.raises(health.HealthConfigError):
            health.parse_rules(bad)


def test_default_rules_are_valid_and_quiet_on_empty_registry():
    names = [r.name for r in health.DEFAULT_RULES]
    assert len(names) == len(set(names))
    for r in health.DEFAULT_RULES:
        r.validate()
    eng = health.HealthEngine(reg=MetricsRegistry(),
                              rules=health.default_rules(), interval=0.1)
    assert eng.tick(100.0) == []
    assert eng.tick(105.0) == []
    st = eng.status_dict()
    assert st["status"] == "ok" and st["firing"] == []


# ---------------------------------------------------------------------------
# rule forms (private registry, synthetic clock)
# ---------------------------------------------------------------------------

def test_threshold_hold_down_fire_and_resolve():
    reg = MetricsRegistry()
    g = reg.gauge("scanner_tpu_t_depth", "x", labels=["stage"])
    rule = health.AlertRule(
        name="t_hold", series="scanner_tpu_t_depth", form="value",
        op=">=", value=3, by=("stage",), for_seconds=2.0,
        severity="critical")
    eng = health.HealthEngine(reg=reg, rules=[rule], interval=0.1)
    g.labels(stage="save").set(5)
    assert eng.tick(100.0) == []               # pending, not fired yet
    assert eng.status_dict()["status"] == "ok"
    assert eng.tick(101.0) == []               # still inside hold-down
    trans = eng.tick(102.5)                    # 2.5s >= for
    assert [t["state"] for t in trans] == ["firing"]
    assert trans[0]["labels"] == {"stage": "save"}
    st = eng.status_dict()
    assert st["status"] == "unhealthy"         # critical severity
    assert st["reasons"] == ["t_hold[stage=save]"]
    # transitions counter + firing gauge went live
    assert _counter("scanner_tpu_alerts_transitions_total",
                    rule="t_hold", state="firing") == 1
    assert _counter("scanner_tpu_alerts_firing",
                    rule="t_hold", severity="critical") == 1
    g.labels(stage="save").set(1)
    trans = eng.tick(103.0)
    assert [t["state"] for t in trans] == ["resolved"]
    assert eng.status_dict()["status"] == "ok"
    assert _counter("scanner_tpu_alerts_transitions_total",
                    rule="t_hold", state="resolved") == 1
    assert _counter("scanner_tpu_alerts_firing",
                    rule="t_hold", severity="critical") == 0
    # a dip below for_seconds never fires
    g.labels(stage="save").set(5)
    assert eng.tick(104.0) == []
    g.labels(stage="save").set(0)
    assert eng.tick(105.0) == []


def test_vanished_series_resolves_firing_alert():
    reg = MetricsRegistry()
    g = reg.gauge("scanner_tpu_t_age", "x", labels=["worker"])
    rule = health.AlertRule(
        name="t_gone", series="scanner_tpu_t_age", form="value",
        op=">", value=4, by=("worker",))
    eng = health.HealthEngine(reg=reg, rules=[rule], interval=0.1)
    g.labels(worker="3").set(9)
    trans = eng.tick(100.0)
    assert [t["state"] for t in trans] == ["firing"]
    # the master drops a deactivated worker's gauge child entirely
    for m in reg.metrics():
        if m.name == "scanner_tpu_t_age":
            m.remove_labels(worker="3")
    trans = eng.tick(101.0)
    assert [t["state"] for t in trans] == ["resolved"]
    assert eng.status_dict()["status"] == "ok"


def test_rate_rule_windowed():
    reg = MetricsRegistry()
    c = reg.counter("scanner_tpu_t_recompiles_total", "x")
    rule = health.AlertRule(
        name="t_rate", series="scanner_tpu_t_recompiles_total",
        form="rate", op=">", value=2.0, window=10.0)
    eng = health.HealthEngine(reg=reg, rules=[rule], interval=0.1)
    assert eng.tick(100.0) == []       # single sample: no rate yet
    c.inc(5)                           # 5 in 5s = 1/s: under threshold
    assert eng.tick(105.0) == []
    c.inc(40)                          # 45 over 10s = 4.5/s: over
    trans = eng.tick(110.0)
    assert [t["state"] for t in trans] == ["firing"]
    # counter stops climbing -> windowed rate decays -> resolves
    trans = eng.tick(121.0)
    assert [t["state"] for t in trans] == ["resolved"]


def test_quantile_rule_over_window():
    reg = MetricsRegistry()
    h = reg.histogram("scanner_tpu_t_rpc_seconds", "x",
                      buckets=(0.1, 0.5, 2.0))
    rule = health.AlertRule(
        name="t_p99", series="scanner_tpu_t_rpc_seconds", form="p99",
        op=">", value=0.5, window=30.0)
    eng = health.HealthEngine(reg=reg, rules=[rule], interval=0.1)
    for _ in range(100):
        h.observe(0.05)
    assert eng.tick(100.0) == []       # p99 ~ 0.1: quiet
    assert eng.tick(105.0) == []
    for _ in range(50):
        h.observe(1.5)                 # now a third of the window is slow
    trans = eng.tick(110.0)
    assert [t["state"] for t in trans] == ["firing"]
    # 40s later the slow observations age OUT of the 30s window (no new
    # traffic: the bucket delta is empty, the alert resolves)
    trans = eng.tick(150.0)
    assert [t["state"] for t in trans] == ["resolved"]


def test_burn_rate_multi_window_semantics():
    def mk():
        reg = MetricsRegistry()
        h = reg.histogram("scanner_tpu_t_lat2_seconds", "x",
                          buckets=(0.1, 1.0, 10.0))
        rule = health.AlertRule(
            name="t_burn", series="scanner_tpu_t_lat2_seconds",
            form="burn", op=">", value=2.0, objective=1.0, budget=0.1,
            short_window=10.0, window=60.0, severity="critical")
        return reg, h, health.HealthEngine(reg=reg, rules=[rule],
                                           interval=0.1)

    # sustained burn: 30% of every batch over the objective, in both
    # windows -> fires (30% > 2.0 x 10% budget)
    _reg, h, eng = mk()
    fired = []
    for i in range(15):
        for _ in range(7):
            h.observe(0.05)
        for _ in range(3):
            h.observe(5.0)
        fired += eng.tick(100.0 + 5 * i)
    assert [t["state"] for t in fired] == ["firing"]
    # recovery: traffic goes clean -> the short window empties of bad
    # observations -> resolves
    for i in range(4):
        for _ in range(10):
            h.observe(0.05)
        fired += eng.tick(180.0 + 5 * i)
    assert [t["state"] for t in fired] == ["firing", "resolved"]

    # a short spike does NOT fire: the short window burns but the long
    # window's error share stays under the threshold
    _reg, h, eng = mk()
    out = []
    for i in range(12):                    # 60s of clean traffic
        for _ in range(10):
            h.observe(0.05)
        out += eng.tick(100.0 + 5 * i)
    for _ in range(3):                     # one bad batch
        h.observe(5.0)
    out += eng.tick(160.0)
    out += eng.tick(161.0)
    assert out == []


def test_ratio_rule_hbm_pressure_shape():
    reg = MetricsRegistry()
    use = reg.gauge("scanner_tpu_t_hbm_bytes", "x", labels=["device"])
    lim = reg.gauge("scanner_tpu_t_hbm_limit_bytes", "x",
                    labels=["device"])
    rule = health.AlertRule(
        name="t_hbm", series="scanner_tpu_t_hbm_bytes",
        ratio_to="scanner_tpu_t_hbm_limit_bytes",
        form="value", op=">", value=0.9, by=("device",))
    eng = health.HealthEngine(reg=reg, rules=[rule], interval=0.1)
    lim.labels(device="tpu:0").set(100)
    lim.labels(device="tpu:1").set(100)
    use.labels(device="tpu:0").set(50)
    use.labels(device="tpu:1").set(95)
    trans = eng.tick(100.0)
    assert [(t["state"], t["labels"]) for t in trans] == \
        [("firing", {"device": "tpu:1"})]
    # a device with no limit sample never divides by zero
    use.labels(device="tpu:2").set(99)
    assert eng.tick(101.0) == []


def test_backpressure_watermark_and_imbalance_branches():
    reg = MetricsRegistry()
    q = reg.gauge("scanner_tpu_stage_queue_depth", "x", labels=["stage"])
    tasks = reg.counter("scanner_tpu_stage_tasks_total", "x",
                        labels=["stage"])
    rule = health.AlertRule(
        name="t_bp", series="scanner_tpu_stage_queue_depth",
        form="backpressure", op=">=", value=3, by=("stage",),
        window=10.0, for_seconds=0.0)
    eng = health.HealthEngine(reg=reg, rules=[rule], interval=0.1)
    # watermark branch: deep queue alone fires
    q.labels(stage="save").set(4)
    q.labels(stage="evaluate").set(0)
    trans = eng.tick(100.0)
    assert [(t["state"], t["labels"]) for t in trans] == \
        [("firing", {"stage": "save"})]
    q.labels(stage="save").set(0)
    trans = eng.tick(101.0)
    assert [t["state"] for t in trans] == ["resolved"]
    # imbalance branch: a standing backlog (depth 1 < watermark) plus a
    # producer completing >1.5x faster than the stage
    q.labels(stage="save").set(1)
    tasks.labels(stage="evaluate").inc(0)    # create children
    tasks.labels(stage="save").inc(0)
    eng.tick(102.0)
    tasks.labels(stage="evaluate").inc(100)
    tasks.labels(stage="save").inc(10)
    trans = eng.tick(108.0)
    assert [(t["state"], t["labels"]) for t in trans] == \
        [("firing", {"stage": "save"})]
    # backlog clears -> resolves even though the rate window still
    # remembers the imbalance
    q.labels(stage="save").set(0)
    trans = eng.tick(109.0)
    assert [t["state"] for t in trans] == ["resolved"]


def test_rollup_severity_mapping_and_alertz():
    reg = MetricsRegistry()
    g = reg.gauge("scanner_tpu_t_sev", "x", labels=["which"])
    rules = [
        health.AlertRule(name="t_warn", series="scanner_tpu_t_sev",
                         form="value", op=">", value=0,
                         match={"which": "w"}, severity="warning"),
        health.AlertRule(name="t_crit", series="scanner_tpu_t_sev",
                         form="value", op=">", value=0,
                         match={"which": "c"}, severity="critical"),
    ]
    eng = health.HealthEngine(reg=reg, rules=rules, interval=0.1)
    g.labels(which="w").set(0)
    g.labels(which="c").set(0)
    eng.tick(100.0)
    assert eng.status_dict()["status"] == "ok"
    g.labels(which="w").set(1)
    eng.tick(101.0)
    assert eng.status_dict()["status"] == "degraded"
    g.labels(which="c").set(1)
    eng.tick(102.0)
    st = eng.status_dict()
    assert st["status"] == "unhealthy"
    assert {f["rule"] for f in st["firing"]} == {"t_warn", "t_crit"}
    az = eng.alertz_dict()
    assert az["status"] == "unhealthy"
    assert {r["name"] for r in az["rule_table"]} == {"t_warn", "t_crit"}


def test_user_rules_ride_alongside_defaults():
    reg = MetricsRegistry()
    g = reg.gauge("scanner_tpu_t_user", "x")
    eng = health.HealthEngine(reg=reg, rules=health.default_rules(),
                              interval=0.1)
    eng.set_user_rules(health.parse_rules(
        "my_rule:value(scanner_tpu_t_user)>5:severity=critical"))
    assert "my_rule" in [r.name for r in eng.rules()]
    g.set(9)
    trans = eng.tick(100.0)
    assert [(t["rule"], t["state"]) for t in trans] == \
        [("my_rule", "firing")]
    # replacing the user rules resolves the removed rule's firing
    # state on the spot — it must not degrade the roll-up forever
    res_base = _counter("scanner_tpu_alerts_transitions_total",
                        rule="my_rule", state="resolved")
    eng.set_user_rules([])
    assert eng.status_dict()["status"] == "ok"
    assert eng.status_dict()["firing"] == []
    assert _counter("scanner_tpu_alerts_transitions_total",
                    rule="my_rule", state="resolved") == res_base + 1
    assert _counter("scanner_tpu_alerts_firing",
                    rule="my_rule", severity="critical") == 0


def test_burn_requires_real_long_window_history():
    """A young engine (uptime < the long window) must NOT collapse
    both burn windows onto the same short delta: a spike right after
    startup is not a sustained burn."""
    reg = MetricsRegistry()
    h = reg.histogram("scanner_tpu_t_lat3_seconds", "x",
                      buckets=(0.1, 1.0, 10.0))
    rule = health.AlertRule(
        name="t_young_burn", series="scanner_tpu_t_lat3_seconds",
        form="burn", op=">", value=2.0, objective=1.0, budget=0.1,
        short_window=10.0, window=60.0, severity="critical")
    eng = health.HealthEngine(reg=reg, rules=[rule], interval=0.1)
    eng.tick(100.0)
    for _ in range(7):
        h.observe(0.05)
    for _ in range(3):
        h.observe(5.0)      # 30% bad — would fire if windows collapsed
    assert eng.tick(105.0) == []
    assert eng.tick(115.0) == []     # still < 60s of history
    assert eng.status_dict()["status"] == "ok"


def test_merge_status_worst_of_and_node_prefixes():
    merged = health.merge_status({
        "master": {"status": "ok", "reasons": [], "firing": []},
        "worker0": {"status": "degraded",
                    "reasons": ["stage_backpressure[stage=save]"],
                    "firing": [{"rule": "stage_backpressure",
                                "severity": "warning",
                                "labels": {"stage": "save"}}]},
        "worker1": {"status": "unhealthy",
                    "reasons": ["hbm_pressure[device=tpu:0]"],
                    "firing": [{"rule": "hbm_pressure",
                                "severity": "critical",
                                "labels": {"device": "tpu:0"}}]},
    })
    assert merged["status"] == "unhealthy"
    assert "worker0:stage_backpressure[stage=save]" in merged["reasons"]
    assert "worker1:hbm_pressure[device=tpu:0]" in merged["reasons"]
    assert {(f["node"], f["rule"]) for f in merged["firing"]} == \
        {("worker0", "stage_backpressure"), ("worker1", "hbm_pressure")}


# ---------------------------------------------------------------------------
# serving surface: /healthz roll-up, /readyz drain, /alertz
# ---------------------------------------------------------------------------

def test_healthz_reflects_rollup_and_readyz_drains():
    state = {"status": "ok", "reasons": []}
    draining = {"v": False}
    srv = MetricsServer(port=0, health=lambda: dict(state),
                        ready=lambda: not draining["v"],
                        alertz=lambda: {"status": state["status"],
                                        "firing": [], "rule_table": []},
                        healthz=lambda: {"role": "worker"})
    base = f"http://127.0.0.1:{srv.port}"
    try:
        code, hz = _get_json(base + "/healthz")
        assert code == 200
        # backward-compatible shape PLUS the roll-up
        assert hz["ok"] is True and hz["role"] == "worker"
        assert hz["status"] == "ok" and hz["reasons"] == []
        code, rz = _get_json(base + "/readyz")
        assert code == 200 and rz["ready"] is True

        # degraded: still alive (200), status visible
        state["status"] = "degraded"
        state["reasons"] = ["stage_backpressure[stage=save]"]
        code, hz = _get_json(base + "/healthz")
        assert code == 200 and hz["ok"] is True
        assert hz["status"] == "degraded"
        assert hz["reasons"] == ["stage_backpressure[stage=save]"]

        # unhealthy: /healthz STAYS 200 (liveness — a restart cannot
        # fix a workload alert) with ok False in the body; /readyz is
        # the surface that goes 503 so routing stops
        state["status"] = "unhealthy"
        code, hz = _get_json(base + "/healthz")
        assert code == 200 and hz["ok"] is False
        assert hz["status"] == "unhealthy"
        code, rz = _get_json(base + "/readyz")
        assert code == 503 and rz["ready"] is False

        # draining: NOT ready, still alive — the SIGTERM contract
        state["status"] = "ok"
        draining["v"] = True
        code, hz = _get_json(base + "/healthz")
        assert code == 200 and hz["ok"] is True
        code, rz = _get_json(base + "/readyz")
        assert code == 503 and rz["ready"] is False

        code, az = _get_json(base + "/alertz")
        assert code == 200 and "rule_table" in az
    finally:
        srv.stop()


def test_worker_drain_not_ready_still_alive(tmp_path):
    """The real Worker wiring: drain() flips /readyz to 503 while
    /healthz stays 200 (k8s stops routing, doesn't kill)."""
    db = str(tmp_path / "db")
    master = Master(db_path=db, no_workers_timeout=10.0)
    worker = Worker(f"localhost:{master.port}", db_path=db,
                    metrics_port=0, metrics_host="127.0.0.1")
    base = f"http://127.0.0.1:{worker.metrics_server.port}"
    try:
        code, hz = _get_json(base + "/healthz")
        assert code == 200 and hz["ok"] is True and not hz["draining"]
        code, rz = _get_json(base + "/readyz")
        assert code == 200
        worker.drain()
        code, hz = _get_json(base + "/healthz")
        assert code == 200 and hz["ok"] is True and hz["draining"]
        code, rz = _get_json(base + "/readyz")
        assert code == 503 and rz["ready"] is False
    finally:
        worker.stop()
        master.stop()


# ---------------------------------------------------------------------------
# chaos-style e2e (the acceptance criteria)
# ---------------------------------------------------------------------------

@pytest.fixture()
def health_cluster(tmp_path):
    """Master (with /metrics+/alertz enabled) + 2 in-process workers
    over a packed-int source table, health engine on a fast clock."""
    health.set_interval(0.1)
    db_path = str(tmp_path / "db")
    seed = Client(db_path=db_path)
    seed.new_table("health_src", ["output"],
                   [[_pk(100 + i)] for i in range(N_ROWS)])
    master = Master(db_path=db_path, no_workers_timeout=30.0,
                    metrics_port=0, metrics_host="127.0.0.1")
    addr = f"localhost:{master.port}"
    workers = [Worker(addr, db_path=db_path) for _ in range(2)]
    sc = Client(db_path=db_path, master=addr)
    yield sc, master, workers, addr
    faults.clear()
    sc.stop()
    for w in workers:
        w.stop()
    master.stop()
    health.set_interval(1.0)


def _run_golden(sc, out_name: str):
    col = sc.io.Input([NamedStream(sc, "health_src")])
    col = sc.ops.HealthDouble(x=col)
    out = NamedStream(sc, out_name)
    sc.run(sc.io.Output(col, [out]), PerfParams.manual(2, 2),
           cache_mode=CacheMode.Overwrite, show_progress=False)
    return [bytes(r) for r in out.load()]


def _wait_until(pred, timeout=20.0, dt=0.1):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return True
        time.sleep(dt)
    return False


@pytest.mark.chaos
def test_save_delay_fires_backpressure_then_resolves(health_cluster):
    """The acceptance chaos test: a pipeline.save delay fault induces
    stage backpressure -> the `stage_backpressure` alert fires with
    stage=save labels (Client.health(), /alertz, transitions counter)
    and resolves after the backlog drains; output stays bit-exact; the
    identical fault-free run reports ok with zero firing alerts."""
    sc, master, _workers, _addr = health_cluster
    fire_base = _counter("scanner_tpu_alerts_transitions_total",
                         rule="stage_backpressure", state="firing")

    # every save stalls 0.8s: evaluators outrun savers, the save queue
    # hits its watermark and stays there
    faults.install("pipeline.save:delay:seconds=0.8")
    rows_box = []
    t = threading.Thread(
        target=lambda: rows_box.append(_run_golden(sc, "bp_out")))
    t.start()
    saw = {}

    def firing_now():
        h = sc.health()
        for f in h.get("firing", []):
            if f["rule"] == "stage_backpressure" \
                    and (f.get("labels") or {}).get("stage") == "save":
                saw.update(f)
                return True
        return False

    assert _wait_until(firing_now, timeout=30.0), \
        "stage_backpressure[stage=save] never fired under a " \
        "save-delay fault"
    assert saw["labels"] == {"stage": "save"}, saw
    assert saw["severity"] == "warning"
    assert sc.health()["status"] in ("degraded", "unhealthy")

    # visible on /alertz too (the master's endpoint; in-process
    # cluster components share the process engine)
    code, az = _get_json(
        f"http://127.0.0.1:{master.metrics_server.port}/alertz")
    assert code == 200
    assert any(f["rule"] == "stage_backpressure"
               for f in az.get("firing", [])), az

    t.join(timeout=120)
    assert not t.is_alive()
    assert rows_box and rows_box[0] == EXPECT   # bit-exact through it
    assert faults.fired("pipeline.save") > 0    # the fault really fired
    assert _counter("scanner_tpu_alerts_transitions_total",
                    rule="stage_backpressure",
                    state="firing") > fire_base

    # the fault plan clears; the drained pipeline's queue gauge reads 0
    # and the alert resolves
    faults.clear()
    res_base = _counter("scanner_tpu_alerts_transitions_total",
                        rule="stage_backpressure", state="resolved")

    def resolved():
        h = sc.health()
        return not any(f["rule"] == "stage_backpressure"
                       for f in h.get("firing", []))

    assert _wait_until(resolved, timeout=20.0), \
        "stage_backpressure never resolved after the fault cleared"
    assert _counter("scanner_tpu_alerts_transitions_total",
                    rule="stage_backpressure",
                    state="resolved") >= res_base

    # clean golden run: zero backpressure alerts fire, health ends ok
    fire_base2 = _counter("scanner_tpu_alerts_transitions_total",
                          rule="stage_backpressure", state="firing")
    rows = _run_golden(sc, "bp_clean_out")
    assert rows == EXPECT
    assert _counter("scanner_tpu_alerts_transitions_total",
                    rule="stage_backpressure",
                    state="firing") == fire_base2
    assert _wait_until(lambda: sc.health()["status"] == "ok",
                       timeout=20.0), sc.health()
    assert sc.health()["firing"] == []


@pytest.mark.chaos
def test_heartbeat_loss_degrades_master_healthz(tmp_path):
    """Worker heartbeat loss -> `worker_heartbeat_stale` fires on the
    master -> /healthz transitions out of ok; the stale scan then
    deactivates the worker (its gauge child is dropped) and health
    recovers."""
    health.set_interval(0.1)
    db = str(tmp_path / "db")
    master = Master(db_path=db, no_workers_timeout=30.0,
                    metrics_port=0, metrics_host="127.0.0.1")
    worker = Worker(f"localhost:{master.port}", db_path=db)
    base = f"http://127.0.0.1:{master.metrics_server.port}"
    try:
        # healthy first: heartbeats land, age stays ~1s
        assert _wait_until(
            lambda: _get_json(base + "/healthz")[1]["status"] == "ok",
            timeout=10.0)
        # now every beat is dropped at the injection site
        faults.install("worker.heartbeat:raise")

        def not_ok():
            code, hz = _get_json(base + "/healthz")
            return hz.get("status") != "ok" and any(
                r.startswith("worker_heartbeat_stale")
                for r in hz.get("reasons", []))

        assert _wait_until(not_ok, timeout=15.0), \
            "heartbeat loss never degraded /healthz"
        # the stale scan deactivates the worker at WORKER_STALE_AFTER;
        # its heartbeat-age gauge child is removed and health recovers
        assert _wait_until(
            lambda: _get_json(base + "/healthz")[1]["status"] == "ok",
            timeout=15.0), "health never recovered after stale removal"
    finally:
        faults.clear()
        worker.stop()
        master.stop()
        health.set_interval(1.0)


# ---------------------------------------------------------------------------
# satellites: GetJobStatus health field, statusz panel, bench history
# ---------------------------------------------------------------------------

def test_job_status_and_statusz_carry_health(health_cluster):
    sc, master, _workers, _addr = health_cluster
    _run_golden(sc, "hs_out")
    st = sc.job_status()
    assert "health" in st and "status" in st["health"]
    code, statusz = _get_json(
        f"http://127.0.0.1:{master.metrics_server.port}/statusz")
    assert code == 200
    assert "health" in statusz and "status" in statusz["health"]
    # the cluster roll-up names its nodes
    h = sc.health()
    assert set(h) >= {"status", "reasons", "firing", "nodes"}
    assert "master" in h["nodes"]


def test_bench_history_trajectory_and_regression(tmp_path):
    """The checked-in BENCH_r01..r05 trajectory prints and exits 0; a
    synthetic same-source regression exits 1."""
    tool = os.path.join(REPO, "tools", "bench_history.py")
    r = subprocess.run([sys.executable, tool, "--dir", REPO],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "5 rounds" in r.stdout
    assert "histogram" in r.stdout

    def write_round(n, value, source=None):
        parsed = {"metric": "m_x", "value": value,
                  "unit": "frames/sec/chip"}
        if source:
            parsed["source"] = source
        with open(os.path.join(str(tmp_path),
                               f"BENCH_r{n:02d}.json"), "w") as f:
            json.dump({"n": n, "rc": 0, "parsed": parsed}, f)

    write_round(1, 100.0)
    write_round(2, 50.0)               # 50% drop, same source
    r = subprocess.run([sys.executable, tool, "--dir", str(tmp_path)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 1
    assert "REGRESSIONS" in r.stdout

    # a capture-source change resets the baseline: no regression
    write_round(3, 20.0, source="opportunistic_capture")
    r = subprocess.run([sys.executable, tool, "--dir", str(tmp_path)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout

    # --json view
    r = subprocess.run([sys.executable, tool, "--dir", str(tmp_path),
                        "--json"], capture_output=True, text=True,
                       timeout=60)
    doc = json.loads(r.stdout)
    assert doc["rounds"] == [1, 2, 3]
    assert "m_x" in doc["metrics"]

    # empty dir -> exit 2
    empty = tmp_path / "empty"
    empty.mkdir()
    r = subprocess.run([sys.executable, tool, "--dir", str(empty)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 2
