# Tests always run on a virtual 8-device CPU mesh so multi-chip sharding
# logic is exercised without TPU hardware (the ambient environment may point
# JAX_PLATFORMS at a real chip — override it).  bench.py does NOT import
# this — it runs on the real chip.
from scanner_tpu.util.jaxenv import force_cpu_platform

force_cpu_platform(n_devices=8)

import pytest  # noqa: E402


@pytest.fixture()
def tmp_db(tmp_path):
    from scanner_tpu.storage import Database, PosixStorage
    return Database(PosixStorage(str(tmp_path / "db")))


@pytest.fixture()
def ledger_leak_guard():
    """Opt-in leak guard (util/memstats.py allocation ledger): snapshot
    the live device-buffer ledger entries before the test and FAIL if
    entries registered during the test are still live afterwards — a
    staging leak the chaos suite could only crash on becomes a direct
    assertion.  Release is finalizer-driven, so collect a few times
    before judging (cycles + jax's deferred drops)."""
    import gc

    from scanner_tpu.util import memstats

    gc.collect()
    before = {e["id"] for e in memstats.entries()}
    yield memstats
    leaked = []
    for _ in range(4):
        gc.collect()
        # kind=cache entries are the frame cache's resident pages and
        # fill fragments (engine/framecache.py): pool-owned memory with
        # its own LRU/pressure eviction — deliberate residency, not a
        # staging leak
        leaked = [e for e in memstats.entries()
                  if e["id"] not in before and e["kind"] != "cache"]
        if not leaked:
            break
    assert not leaked, (
        f"engine left {len(leaked)} registered device buffer(s) in the "
        f"allocation ledger: {leaked[:5]}")
