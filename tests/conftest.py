# Tests always run on a virtual 8-device CPU mesh so multi-chip sharding
# logic is exercised without TPU hardware (the ambient environment may point
# JAX_PLATFORMS at a real chip — override it).  bench.py does NOT import
# this — it runs on the real chip.
from scanner_tpu.util.jaxenv import force_cpu_platform

force_cpu_platform(n_devices=8)

import pytest  # noqa: E402


@pytest.fixture()
def tmp_db(tmp_path):
    from scanner_tpu.storage import Database, PosixStorage
    return Database(PosixStorage(str(tmp_path / "db")))
