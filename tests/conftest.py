import os

# Tests always run on a virtual 8-device CPU mesh so multi-chip sharding
# logic is exercised without TPU hardware (the ambient environment may point
# JAX_PLATFORMS at a real chip — override it).  bench.py does NOT import
# this — it runs on the real chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# the axon TPU plugin's sitecustomize overrides jax_platforms via jax.config
# at interpreter start; force it back to cpu-only for tests
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_db(tmp_path):
    from scanner_tpu.storage import Database, PosixStorage
    return Database(PosixStorage(str(tmp_path / "db")))
